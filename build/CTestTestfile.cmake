# CMake generated Testfile for 
# Source directory: /root/repo/core
# Build directory: /root/repo/build
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test(core_test "/root/repo/build/core_test")
set_tests_properties(core_test PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/core/CMakeLists.txt;39;add_test;/root/repo/core/CMakeLists.txt;0;")
