"""Benchmark: PREPARE+COMMIT signature verifications/sec on one host.

The north-star metric (BASELINE.json): the reference intended per-message
Ed25519 checks on every PREPARE/COMMIT (left as TODOs, reference
src/behavior.rs:127,:185); this framework batches a window of quorum
certificates into one XLA launch sharded across every local device.

Architecture (ISSUE 7): the accelerator is owned by a PERSISTENT verify
service (scripts/verifyd.py), not by the bench. The service initializes
the backend once per deploy, AOT-warms every pad-ladder window shape, and
answers a readiness handshake; the bench:

  1. DETECT: probe PBFT_VERIFY_SERVICE (default 127.0.0.1:7600) with a
     short deadline. A ready service is driven over the 128-byte-triple
     protocol from several coalescing connections — ZERO timed seconds
     on backend init or compile; cold/warm startup costs are read from
     the service's status and reported separately.
  2. LAUNCH-ONCE: no service but accelerator indicators present (or
     PBFT_BENCH_LAUNCH_SERVICE=1) -> spawn verifyd, wait for readiness
     under PBFT_SERVICE_WARM_BUDGET_S (the once-per-deploy cold start,
     paid OUTSIDE the timed region), bench it, stop it. A wedged PJRT
     tunnel costs one bounded wait — the old 8 x 60 s in-process probe
     loop (BENCH_r05's 480 s tax) is gone.
  3. FALLBACK: otherwise measure the framework's production CPU arm
     (native C++ pool; XLA:CPU as last resort) and tag the result
     "cpu-native-fallback" / "cpu-fallback" — a real number, never 0.0.

Methodology, service arm: the timed region counts verdict bytes returned
for submitted windows (request -> merged coalesced window -> sharded XLA
launch -> per-connection verdict slices), after one untimed warmup
round-trip per connection. The service's own verify is data-dependent
per item; verdict bitmaps are validated against the known-planted
invalid signature. In-process XLA arms (PBFT_BENCH_CPU / --tpu-worker)
keep the chained-jit methodology: K kernel applications chained inside
one jit so async dispatch and launch caching cannot fake the number.

Baseline for vs_baseline: the reference publishes no numbers and does not
compile (SURVEY.md §6); BASELINE.json's target is >= 50,000 verifies/sec on
one TPU host, so vs_baseline = value / 50_000.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"backend"[, "devices", "note", "error", ...]}.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)
from pbft_tpu.utils.cache import host_keyed_cache_dir  # noqa: E402 (jax-free)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    host_keyed_cache_dir(os.path.join(_REPO, ".jax_cache")),
)

_METRIC = "ed25519_sig_verifies_per_sec"


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _emit(
    per_sec: float,
    backend: str,
    note: str | None = None,
    extra: dict | None = None,
) -> None:
    result = {
        "metric": _METRIC,
        "value": round(per_sec, 1),
        "unit": "signatures/sec",
        "vs_baseline": round(per_sec / 50_000.0, 3),
        "backend": backend,
    }
    if extra:
        result.update(extra)
    if note:
        result["note"] = note
    print(json.dumps(result))


def _fail(stage: str, err: str) -> None:
    """Fail fast but still emit the one JSON line the driver parses."""
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": 0.0,
                "unit": "signatures/sec",
                "vs_baseline": 0.0,
                "error": f"{stage}: {err}",
            }
        ),
        flush=True,
    )
    os._exit(1)


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        for name in list(getattr(xla_bridge, "_backend_factories", {})):
            if name != "cpu":
                xla_bridge._backend_factories.pop(name)
    except Exception as e:
        _log(f"cpu forcing incomplete: {e}")


def _probe_tpu(
    timeout_s: float, attempts: int, gap_s: float, budget_s: float | None = None
) -> bool:
    """One-shot TPU reachability probe in disposable subprocesses.

    No longer part of bench.py's own flow (the verify service's readiness
    handshake replaced the in-bench probe loop, ISSUE 7) — kept for the
    round-long watchers (scripts/tpu_watch.py, scripts/tpu_evidence.py)
    that poll for tunnel windows across a whole round. A wedged tunnel
    hangs ``jax.devices()`` beyond any in-process watchdog; subprocesses
    are killable.
    """
    import subprocess

    code = "import jax; d = jax.devices(); print(len(d), d[0].platform)"
    gap = gap_s
    loop_t0 = time.perf_counter()
    for attempt in range(1, attempts + 1):
        if budget_s is not None and time.perf_counter() - loop_t0 >= budget_s:
            _log(f"tpu probe: budget {budget_s:.0f}s exhausted")
            return False
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            _log(f"tpu probe {attempt}/{attempts}: timeout after {timeout_s:.0f}s")
            out = None
        if out is not None and out.returncode == 0:
            info = out.stdout.strip()
            # `jax.devices()` silently falls back to CPU when no
            # accelerator plugin is present — that is NOT a healthy TPU.
            platform = info.split()[-1] if info else ""
            if platform == "cpu":
                _log(f"tpu probe {attempt}/{attempts}: only CPU visible ({info})")
                return False
            _log(
                f"tpu probe {attempt}/{attempts}: ok in "
                f"{time.perf_counter() - t0:.1f}s ({info})"
            )
            return True
        if out is not None:
            tail = (out.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
            _log(f"tpu probe {attempt}/{attempts}: rc={out.returncode} {tail[0]}")
        if attempt < attempts:
            time.sleep(gap)
            gap = min(gap * 2.0, 60.0)
    return False


def _tpu_indicators() -> list:
    """Environment signals that a TPU could plausibly be reachable.

    A service launch only makes sense when a chip might exist; when the
    environment already rules one out (no accelerator device nodes, no
    tunnel/proxy configuration), spinning up a JAX service just delays
    the inevitable CPU fallback (the BENCH_r05 lesson: 480 s of probing
    that the environment had already answered). A bare libtpu *module*
    is not an indicator — the image bakes it in everywhere; without
    device nodes it cannot drive anything.
    """
    import glob

    found = []
    plat = os.environ.get("JAX_PLATFORMS", "")
    if "tpu" in plat or "proxy" in plat:
        found.append(f"JAX_PLATFORMS={plat}")
    for var in sorted(os.environ):
        if var.startswith(("TPU_", "PJRT_")):
            found.append(var)
    for dev in glob.glob("/dev/accel*"):
        found.append(dev)
    if os.path.exists("/dev/vfio"):
        found.append("/dev/vfio")
    return found


def _init_backend(timeout_s: float):
    """Initialize the backend under a watchdog.

    Tunneled PJRT plugins can hang during init (round-1 vs round-2 bench
    history: identical code, rc=1 then rc=0). The probe runs in a daemon
    thread; on timeout we emit the diagnostic JSON and exit instead of
    eating the caller's whole timeout budget.
    """
    result: dict = {}

    def probe():
        try:
            import jax

            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ["JAX_COMPILATION_CACHE_DIR"],
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 - reported via result
            result["error"] = repr(e)

    for attempt in (1, 2):
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            _fail("backend-init", f"timeout after {timeout_s}s")
        if "devices" in result:
            return result["devices"]
        _log(f"backend init attempt {attempt} failed: {result.get('error')}")
        result.clear()
        time.sleep(2.0)
    _fail("backend-init", "both init attempts failed")


def _native_mod():
    """The native C++ core module, or None if unbuilt/unavailable."""
    try:
        from pbft_tpu import native

        if native.available():
            return native
    except Exception as e:  # pragma: no cover
        _log(f"native core unavailable ({e!r})")
    return None


def _signed_pool(batch: int):
    """(pubs, msgs, sigs) uint8 arrays: a 64-triple signed pool tiled to
    the batch, with sigs[batch//2] corrupted (the batch-reject path must
    not cost extra). Verification cost is independent of uniqueness;
    prefer the native C++ signer."""
    from pbft_tpu.crypto import ref

    pool = 64
    pubs = np.zeros((pool, 32), np.uint8)
    msgs = np.zeros((pool, 32), np.uint8)
    sigs = np.zeros((pool, 64), np.uint8)
    native = _native_mod()
    if native is not None:
        signer_pub, signer_sign = native.public_key, native.sign
        _log("signer: native C++ core")
    else:
        signer_pub, signer_sign = ref.public_key, ref.sign
        _log("signer: Python oracle")
    for i in range(pool):
        seed = bytes([i + 1, 0x42]) * 16
        msg = os.urandom(32)
        pubs[i] = np.frombuffer(signer_pub(seed), np.uint8)
        msgs[i] = np.frombuffer(msg, np.uint8)
        sigs[i] = np.frombuffer(signer_sign(seed, msg), np.uint8)
    reps = (batch + pool - 1) // pool
    bp = np.tile(pubs, (reps, 1))[:batch]
    bm = np.tile(msgs, (reps, 1))[:batch]
    bs = np.tile(sigs, (reps, 1))[:batch]
    bs[batch // 2, 7] ^= 0xFF
    return bp, bm, bs


def _native_rate(native, items, target_secs: float) -> float:
    """Sustained verifies/sec over repeated full-batch calls."""
    batch = len(items)
    done = 0
    t0 = time.perf_counter()
    elapsed = 0.0
    while elapsed < target_secs or done == 0:
        native.verify_batch(items)
        done += batch
        elapsed = time.perf_counter() - t0
    return done / elapsed


def _native_fallback(
    target_secs: float, reason: str | None, backend: str = "cpu-native-fallback"
) -> bool:
    """Measure the framework's production CPU verifier arm (the native C++
    backend pbftd uses) — no JAX involvement at all. Measures BOTH the
    single-thread rate and the pooled rate (core/verify_pool.cc at
    PBFT_VERIFY_THREADS, default hardware concurrency) and reports the
    pooled number as the headline with the scaling recorded alongside.
    Returns False if the native core isn't available (caller then tries
    XLA:CPU)."""
    native = _native_mod()
    if native is None:
        return False
    # Same batch as the TPU arm. The spec corrupts one signature per
    # batch (below), so exactly one RLC window pays the bisect; the fixed
    # bisect cost amortizes over the batch.
    batch = int(os.environ.get("PBFT_BENCH_BATCH", "4096"))
    bp, bm, bs = _signed_pool(batch)
    items = [(bytes(bp[i]), bytes(bm[i]), bytes(bs[i])) for i in range(batch)]
    out = native.verify_batch(items)
    if sum(out) != batch - 1 or out[batch // 2]:
        _fail("native-verdicts", f"wrong bitmap: sum={sum(out)}")
    want_threads = int(os.environ.get("PBFT_VERIFY_THREADS", "0"))
    native.set_verify_threads(1)
    single = _native_rate(native, items, max(1.0, target_secs / 2))
    _log(f"native CPU arm (1 thread): {single:.0f} verifies/sec")
    native.set_verify_threads(want_threads)  # 0 = hardware concurrency
    threads = native.verify_threads()
    if threads > 1:
        # Pooled/serial verdict parity on the bench batch itself before
        # trusting the pooled rate.
        if native.verify_batch(items) != out:
            _fail("native-verdicts", "pooled verdicts diverge from serial")
        pooled = _native_rate(native, items, target_secs)
    else:
        pooled = single
    _log(
        f"native CPU arm: {pooled:.0f} verifies/sec pooled "
        f"({threads} threads; {pooled / single:.2f}x single-thread)"
    )
    _emit(
        pooled,
        backend,
        reason,
        extra={
            "threads": threads,
            "single_thread_per_sec": round(single, 1),
            "pooled_per_sec": round(pooled, 1),
            "pool_speedup": round(pooled / single, 2),
        },
    )
    return True


def _service_target() -> str:
    return os.environ.get("PBFT_VERIFY_SERVICE", "127.0.0.1:7600")


def _probe_service(target: str) -> dict | None:
    """Short-deadline JSON status probe of a running verify service."""
    from pbft_tpu.net.verify_service import probe_status_json

    return probe_status_json(target, timeout=2.0)


def _launch_service(budget_s: float):
    """Spawn verifyd ONCE and wait (bounded) for readiness.

    This is the once-per-deploy cold start — backend init + the pad
    ladder's AOT warmup — paid entirely OUTSIDE the timed region. A
    wedged PJRT tunnel costs exactly ``budget_s`` before the kill and
    CPU fallback (the whole 8 x 60 s probe loop this replaces).

    Returns (proc, target, status, cold_start_s) with proc=None on
    failure (the subprocess is killed before returning).
    """
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    target = f"127.0.0.1:{port}"
    cmd = [
        sys.executable,
        os.path.join(_REPO, "scripts", "verifyd.py"),
        "--port",
        str(port),
        "--backend",
        "jax",
    ]
    _log(f"launching verify service: {' '.join(cmd)}")
    # stdout is OURS for the one result line: the daemon's announcements
    # go to stderr-land (devnull; its warnings inherit our stderr).
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL)
    from pbft_tpu.net.verify_service import probe_status_json

    t0 = time.perf_counter()
    status = None
    while time.perf_counter() - t0 < budget_s:
        if proc.poll() is not None:
            _log(f"verify service exited rc={proc.returncode} during warmup")
            return None, target, None, 0.0
        status = probe_status_json(target, timeout=2.0)
        if status is not None and status.get("state") == "ready":
            cold = time.perf_counter() - t0
            _log(f"verify service ready in {cold:.1f}s: {status}")
            return proc, target, status, cold
        if status is not None and status.get("state") == "cpu-only":
            # The daemon found no usable accelerator (warm_error says
            # why); its CPU arm would only re-measure our own fallback
            # with a socket in the middle.
            _log(f"verify service came up cpu-only: {status}")
            break
        time.sleep(2.0)
    _stop_service(proc)
    _log(
        f"verify service not ready after {time.perf_counter() - t0:.0f}s; "
        "killed"
    )
    return None, target, None, 0.0


def _stop_service(proc) -> None:
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except Exception:  # noqa: BLE001 - wedged teardown
        proc.kill()


def _run_service_bench(
    target: str, status: dict, target_secs: float, cold_start_s: float | None
) -> None:
    """Drive a ready verify service: several connections submit windows
    concurrently (the coalescing dispatcher merges them into sharded XLA
    launches), timed AFTER one untimed warmup round-trip per connection —
    zero timed seconds on backend init or compile."""
    import socket

    batch = int(os.environ.get("PBFT_BENCH_BATCH", "1024"))
    conns = int(os.environ.get("PBFT_BENCH_SERVICE_CONNS", "4"))
    # Per-roundtrip socket deadline: generous (a warmed TPU launch is
    # milliseconds; XLA:CPU control arms take seconds per window).
    io_timeout = float(os.environ.get("PBFT_BENCH_SERVICE_TIMEOUT", "300"))
    bp, bm, bs = _signed_pool(batch)
    payload = (batch).to_bytes(4, "big") + b"".join(
        bytes(bp[i]) + bytes(bm[i]) + bytes(bs[i]) for i in range(batch)
    )
    host, port = target.rsplit(":", 1)

    def roundtrip(sock) -> int:
        sock.sendall(payload)
        got = 0
        while got < batch:
            chunk = sock.recv(batch - got)
            if not chunk:
                raise ConnectionError("service closed mid-verdicts")
            got += len(chunk)
        return got

    socks = []
    try:
        t0 = time.perf_counter()
        for _ in range(conns):
            sock = socket.create_connection(
                (host, int(port)), timeout=io_timeout
            )
            # Warmup round-trip: validates the verdict bitmap end to end
            # and keeps connect + first-window effects out of the timed
            # region. (The service compiled at startup; this is not a
            # compile, just the pipeline filling.)
            sock.sendall(payload)
            out = b""
            while len(out) < batch:
                chunk = sock.recv(batch - len(out))
                if not chunk:
                    raise ConnectionError("service closed during warmup")
                out += chunk
            if sum(out) != batch - 1 or out[batch // 2]:
                _fail("service-verdicts", f"wrong bitmap: sum={sum(out)}")
            socks.append(sock)
        warm_start_s = time.perf_counter() - t0
        _log(f"service warm-start ({conns} conns): {warm_start_s:.2f}s")

        done = [0] * conns
        errors: list = []
        stop_at = time.perf_counter() + target_secs

        def worker(idx: int, sock) -> None:
            try:
                while time.perf_counter() < stop_at or done[idx] == 0:
                    done[idx] += roundtrip(sock)
            except Exception as e:  # noqa: BLE001 - reported below
                errors.append(repr(e))

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=worker, args=(i, s), daemon=True)
            for i, s in enumerate(socks)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=target_secs * 10 + 120)
        elapsed = time.perf_counter() - t0
        if errors:
            _fail("service-timed-region", "; ".join(errors[:3]))
        per_sec = sum(done) / elapsed
    finally:
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
    warm_stats = status.get("warm_stats", {})
    extra = {
        "devices": status.get("devices", 0),
        "service_state": status.get("state"),
        "connections": conns,
        "batch": batch,
        "warm_start_s": round(warm_start_s, 3),
        "steady_state_per_sec": round(per_sec, 1),
        "service_cold_compile_s": warm_stats.get("cold_compile_s"),
        "service_warm_load_s": warm_stats.get("warm_load_s"),
    }
    if cold_start_s is not None:
        # We launched the service this run: spawn -> ready wall time
        # (backend init + warmup), paid once per deploy, never timed.
        extra["cold_start_s"] = round(cold_start_s, 1)
    _log(
        f"service steady state: {per_sec:.0f} verifies/sec over "
        f"{conns} connections ({elapsed:.2f}s timed)"
    )
    _emit(per_sec, "verify-service", None, extra=extra)


def main() -> None:
    target_secs = float(os.environ.get("PBFT_BENCH_SECS", "5.0"))
    if "--tpu-worker" in sys.argv:
        _run_xla_bench("tpu", None, target_secs)
        return
    if os.environ.get("PBFT_BENCH_NATIVE"):
        # Direct native-arm run (no TPU probing): the pooled C++ verifier,
        # reported as "cpu-native" with threads + single-vs-pooled rates.
        if not _native_fallback(target_secs, None, backend="cpu-native"):
            _fail("native", "native core unavailable")
        return
    if os.environ.get("PBFT_BENCH_CONSENSUS"):
        # Consensus-protocol entry (ISSUE 4): drive the f=1 firehose
        # through real pbftd daemons and report requests/sec alongside
        # rounds/sec plus the measured mean batch occupancy —
        # PBFT_BATCH_MAX_ITEMS / PBFT_BATCH_FLUSH_US select the batching
        # knobs (1/0 = the pre-batching protocol).
        import tempfile

        from pbft_tpu.bench.harness import run_native_config

        # Per-request latency waterfall (ISSUE 9): the run traces every
        # replica into a scratch dir and joins the client-side
        # send/quorum stamps against request_rx/batch_sealed/
        # consensus_span — requests_per_sec ships WITH its segment
        # breakdown (client queue, batch wait, prepared, committed,
        # execute, reply; p50/p95/p99 each).
        with tempfile.TemporaryDirectory(prefix="pbft-bench-traces-") as td:
            res = run_native_config(
                1,  # firehose f=1
                requests=int(os.environ.get("PBFT_BENCH_REQUESTS", "960")),
                pipeline=int(os.environ.get("PBFT_BENCH_PIPELINE", "64")),
                batch_max_items=int(os.environ.get("PBFT_BATCH_MAX_ITEMS", "1")),
                batch_flush_us=int(os.environ.get("PBFT_BATCH_FLUSH_US", "0")),
                trace_dir=td,
            )
        print(
            json.dumps(
                {
                    "metric": "pbft_requests_per_sec",
                    "value": res.requests_per_sec,
                    "unit": "requests/sec",
                    "rounds_per_sec": res.rounds_per_sec,
                    "mean_batch": res.mean_batch,
                    "batch_max_items": res.batch_max_items,
                    "batch_flush_us": res.batch_flush_us,
                    "reply_p50_ms": res.reply_p50_ms,
                    "reply_p95_ms": res.reply_p95_ms,
                    "reply_p99_ms": res.reply_p99_ms,
                    "segments_ms": res.latency_segments_ms,
                    "backend": "consensus-native",
                }
            )
        )
        return
    if (
        os.environ.get("PBFT_BENCH_CPU")
        and "PBFT_VERIFY_SERVICE" not in os.environ
    ):
        # Explicit in-process XLA:CPU arm (kernel-on-XLA:CPU control; the
        # chained-jit compile alone is minutes at the default batch). An
        # EXPLICIT service target wins even here: operators with a warmed
        # service still get the zero-compile timed region. A cpu-pinned
        # shell (JAX_PLATFORMS=cpu) is NOT routed here — it means "no
        # accelerator", and the production CPU arm below (native pool)
        # is the honest fast measurement for that environment.
        os.environ["JAX_PLATFORMS"] = "cpu"
        _force_cpu()
        _run_xla_bench("cpu", None, target_secs)
        return

    # Accelerator path (ISSUE 7): a persistent verify service owns the
    # chip. Detect a running one first (zero startup cost in this run);
    # else launch one ONCE when the environment suggests a chip could
    # exist (or PBFT_BENCH_LAUNCH_SERVICE=1 forces it), with the whole
    # cold start bounded by PBFT_SERVICE_WARM_BUDGET_S and paid outside
    # the timed region. No in-process probe loop in either case.
    target = _service_target()
    status = _probe_service(target)
    proc, cold_start_s = None, None
    if status is None:
        # A cpu-pinned shell rules an accelerator out up front: don't
        # spin up a JAX service just to discover CpuDevice (the engine
        # would then sink minutes into XLA:CPU ladder compiles).
        cpu_pinned = os.environ.get("JAX_PLATFORMS") == "cpu"
        indicators = [] if cpu_pinned else _tpu_indicators()
        if indicators or os.environ.get("PBFT_BENCH_LAUNCH_SERVICE"):
            if indicators:
                _log(f"tpu indicators: {', '.join(indicators)}")
            budget = float(os.environ.get("PBFT_SERVICE_WARM_BUDGET_S", "900"))
            proc, target, status, cold_start_s = _launch_service(budget)
        else:
            why = (
                "shell pins JAX_PLATFORMS=cpu"
                if cpu_pinned
                else "no accelerator indicators"
            )
            _log(
                f"verify service: none reachable and {why} — native CPU "
                "fallback (set PBFT_BENCH_LAUNCH_SERVICE=1 to force a "
                "service launch)"
            )
    else:
        _log(f"verify service at {target}: {status}")
    if status is not None and status.get("state") in ("ready", "cpu-only"):
        try:
            _run_service_bench(target, status, target_secs, cold_start_s)
            return
        finally:
            _stop_service(proc)
    _stop_service(proc)
    fallback_reason = "no ready verify service; CPU fallback"
    # If the round-long watcher (scripts/tpu_watch.py) already captured an
    # on-chip kernel number during a tunnel window, point the artifact's
    # note at it: the fallback VALUE stays the honest live measurement,
    # but the reader should know driver-visible on-chip evidence exists.
    tag = os.environ.get("PBFT_ROUND_TAG", "r5")  # tpu_watch.py --tag
    rel = os.path.join("benchmarks", f"tpu_{tag}_kernel_xla.json")
    if os.path.exists(os.path.join(_REPO, rel)):
        try:
            with open(os.path.join(_REPO, rel)) as fh:
                cap = json.load(fh)
            if isinstance(cap, dict):
                fallback_reason += (
                    f"; same-round on-chip capture exists: "
                    f"{cap.get('value')} {cap.get('unit', 'sig/s')} ({rel})"
                )
        except (OSError, ValueError):
            pass
    _log(fallback_reason)
    if _native_fallback(target_secs, fallback_reason):
        return
    # Last resort: TPU unreachable AND native core unbuilt — measure
    # the XLA:CPU backend at a small batch rather than emit 0.0. The
    # conv field-mul compiles ~10x faster on XLA:CPU, and batch 64
    # keeps compile ~1 minute (measured).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PBFT_FIELD_MUL", "conv")
    os.environ.setdefault("PBFT_BENCH_BATCH", "64")
    os.environ.setdefault("PBFT_BENCH_CHAIN", "4")
    _force_cpu()
    _run_xla_bench("cpu-fallback", fallback_reason, target_secs)


def _run_xla_bench(backend: str, fallback_reason: str | None, target_secs: float) -> None:
    devices = _init_backend(float(os.environ.get("PBFT_BENCH_INIT_TIMEOUT", "180")))
    if backend == "tpu" and (not devices or devices[0].platform == "cpu"):
        # jax.devices() silently falls back to XLA:CPU when the plugin
        # fails AFTER the probe passed; a CPU number must never be
        # reported under the "tpu" tag.
        _fail("backend-init", f"tpu worker got non-TPU devices: {devices}")

    import jax
    import jax.numpy as jnp
    from jax import lax

    from pbft_tpu.crypto.batch import verify_batch
    from pbft_tpu.crypto.ed25519 import verify_kernel

    batch = int(os.environ.get("PBFT_BENCH_BATCH", "4096"))
    chain_k = int(os.environ.get("PBFT_BENCH_CHAIN", "16"))
    _log(f"devices: {devices}; batch={batch} chain={chain_k}")
    bp, bm, bs = _signed_pool(batch)

    try:
        t0 = time.perf_counter()
        out = np.asarray(verify_batch(bp, bm, bs))
        compile_s = time.perf_counter() - t0
        if out.sum() != batch - 1 or out[batch // 2]:
            _fail("verdicts", f"wrong bitmap: sum={int(out.sum())}")
        _log(f"verify_batch compile+transfer+first: {compile_s:.1f}s; verdicts OK")
    except Exception as e:  # noqa: BLE001
        _fail("first-batch", repr(e))

    # Timed region: K data-dependent kernel applications per jit call.
    @jax.jit
    def chained(p, m, s):
        def body(carry, _):
            m2, acc = carry
            ok = verify_kernel(p, m2, s)
            # optimization_barrier ties the next iteration's message input
            # to THIS iteration's verdicts in the HLO dependency graph, so
            # XLA cannot hoist the (otherwise loop-invariant) verify out of
            # the scan body or collapse the chain. (A zero-valued XOR trick
            # gets constant-folded; the barrier is the supported tool.)
            m3, acc = lax.optimization_barrier((m2, acc + ok.astype(jnp.int32)))
            return (m3, acc), ()
        (_, acc), _ = lax.scan(
            body, (m, jnp.zeros((m.shape[0],), jnp.int32)), None, length=chain_k
        )
        return acc

    try:
        t0 = time.perf_counter()
        dp, dm, ds = jax.device_put(bp), jax.device_put(bm), jax.device_put(bs)
        jax.block_until_ready((dp, dm, ds))
        _log(f"host->device transfer: {time.perf_counter() - t0:.2f}s")
        t0 = time.perf_counter()
        acc = np.asarray(chained(dp, dm, ds))
        _log(f"chained compile+first: {time.perf_counter() - t0:.1f}s")
        if int(acc[0]) != chain_k or int(acc[batch // 2]) != 0:
            _fail("chained-verdicts", f"acc[0]={int(acc[0])}")
        chains = 0
        t0 = time.perf_counter()
        elapsed = 0.0
        while elapsed < target_secs or chains == 0:
            np.asarray(chained(dp, dm, ds))
            chains += 1
            elapsed = time.perf_counter() - t0
        per_sec = chains * chain_k * batch / elapsed
        _log(f"{chains} chains x {chain_k} batches of {batch} in {elapsed:.2f}s")
    except Exception as e:  # noqa: BLE001
        _fail("timed-region", repr(e))

    _emit(per_sec, backend, fallback_reason)


if __name__ == "__main__":
    main()
