"""Benchmark: PREPARE+COMMIT signature verifications/sec on one chip.

The north-star metric (BASELINE.json): the reference intended per-message
Ed25519 checks on every PREPARE/COMMIT (left as TODOs, reference
src/behavior.rs:127,:185); this framework batches a window of quorum
certificates into one XLA launch. The bench drives the batched JAX verifier
with realistic consensus traffic shapes (32-byte signed digests, mixed
valid/invalid) and reports sustained verifications/sec.

Baseline for vs_baseline: the reference publishes no numbers and does not
compile (SURVEY.md §6); BASELINE.json's target is >= 50,000 verifies/sec on
one TPU host, so vs_baseline = value / 50_000.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    if os.environ.get("PBFT_BENCH_CPU") or os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU smoke-test mode: keep the TPU PJRT plugin (registered by the
        # environment at interpreter startup) from initializing.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
        try:
            from jax._src import xla_bridge

            for name in list(getattr(xla_bridge, "_backend_factories", {})):
                if name != "cpu":
                    xla_bridge._backend_factories.pop(name)
        except Exception:
            pass
    import pbft_tpu  # noqa: F401  (enables x64 before jax init)
    import jax

    from pbft_tpu.crypto import ref
    from pbft_tpu.crypto.batch import verify_batch

    batch = int(os.environ.get("PBFT_BENCH_BATCH", "4096"))
    target_secs = float(os.environ.get("PBFT_BENCH_SECS", "5.0"))
    _log(f"devices: {jax.devices()}; batch={batch}")

    # Build a pool of unique signed triples and tile to the batch size
    # (verification cost is independent of uniqueness; signing in the pure
    # Python oracle is slow, so keep the pool small — or use the native
    # C++ signer when the toolchain has built it).
    pool = 64
    pubs = np.zeros((pool, 32), np.uint8)
    msgs = np.zeros((pool, 32), np.uint8)
    sigs = np.zeros((pool, 64), np.uint8)
    signer_pub = None
    signer_sign = None
    try:
        from pbft_tpu import native

        if native.available():
            signer_pub, signer_sign = native.public_key, native.sign
            _log("signer: native C++ core")
    except Exception as e:  # pragma: no cover
        _log(f"native core unavailable ({e}); using Python oracle signer")
    if signer_pub is None:
        signer_pub, signer_sign = ref.public_key, ref.sign
    for i in range(pool):
        seed = bytes([i + 1, 0x42]) * 16
        msg = os.urandom(32)
        pubs[i] = np.frombuffer(signer_pub(seed), np.uint8)
        msgs[i] = np.frombuffer(msg, np.uint8)
        sigs[i] = np.frombuffer(signer_sign(seed, msg), np.uint8)
    reps = (batch + pool - 1) // pool
    bp = np.tile(pubs, (reps, 1))[:batch]
    bm = np.tile(msgs, (reps, 1))[:batch]
    bs = np.tile(sigs, (reps, 1))[:batch]
    # Corrupt one signature: the batch-reject path must not cost extra.
    bs[batch // 2, 7] ^= 0xFF

    t0 = time.perf_counter()
    out = np.asarray(jax.block_until_ready(verify_batch(bp, bm, bs)))
    compile_s = time.perf_counter() - t0
    assert out.sum() == batch - 1, "verifier verdicts wrong"
    assert not out[batch // 2], "corrupted signature not rejected"
    _log(f"compile+first batch: {compile_s:.1f}s; verdicts OK")

    # Timed region: steady-state batches.
    iters = 0
    t0 = time.perf_counter()
    elapsed = 0.0
    while elapsed < target_secs:
        jax.block_until_ready(verify_batch(bp, bm, bs))
        iters += 1
        elapsed = time.perf_counter() - t0
    per_sec = iters * batch / elapsed
    _log(f"{iters} batches of {batch} in {elapsed:.2f}s")

    print(
        json.dumps(
            {
                "metric": "ed25519_sig_verifies_per_sec",
                "value": round(per_sec, 1),
                "unit": "signatures/sec",
                "vs_baseline": round(per_sec / 50_000.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
