"""Benchmark: PREPARE+COMMIT signature verifications/sec on one chip.

The north-star metric (BASELINE.json): the reference intended per-message
Ed25519 checks on every PREPARE/COMMIT (left as TODOs, reference
src/behavior.rs:127,:185); this framework batches a window of quorum
certificates into one XLA launch. The bench drives the batched JAX verifier
with realistic consensus traffic shapes (32-byte signed digests, mixed
valid/invalid) and reports sustained verifications/sec.

Methodology: K kernel applications are CHAINED inside one jit (each
iteration's input depends on the previous verdicts) and the result is read
back to the host — so neither async dispatch nor any backend-side caching
of repeated identical launches can fake the number. Inputs are
device-resident during the timed region: host->device transfer over this
dev environment's tunneled PJRT link costs ~250ms/batch, which measures
the tunnel, not the TPU; transfer time is logged to stderr separately.

Robustness (the same script must survive a moody tunnel): persistent
compile cache, a watchdog around backend init that fails fast with a
diagnostic JSON line instead of hanging, and a result line even if only a
single timed chain completes. The round-3 lesson (BENCH_r03.json captured
a CPU fallback because two 75 s probes hit a multi-hour tunnel wedge): the
tunnel can wedge at ANY point, including mid-bench, and a wedged PJRT call
hangs the process uninterruptibly. So the orchestrator in this process
never touches the backend at all:

  1. PROBE: `jax.devices()` in disposable subprocesses — default 8
     attempts x 60 s with backoff gaps between them (~13 min worst
     case, well inside the driver budget).
  2. RUN: the whole TPU bench (backend init, compile, timed region) runs
     in a KILLABLE WORKER SUBPROCESS (`bench.py --tpu-worker`) under a
     timeout; a mid-bench wedge kills the worker and the orchestrator
     re-probes and retries instead of dying.
  3. FALLBACK: only after the full probe+retry budget is spent does it
     fall back to the framework's CPU verifier arm (native C++ Ed25519
     when built, else XLA:CPU at a small batch) and report a real
     measured number tagged "backend": "cpu-native-fallback" /
     "cpu-fallback" instead of a useless 0.0 artifact.

Baseline for vs_baseline: the reference publishes no numbers and does not
compile (SURVEY.md §6); BASELINE.json's target is >= 50,000 verifies/sec on
one TPU host, so vs_baseline = value / 50_000.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"backend"[, "note", "error"]}.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)
from pbft_tpu.utils.cache import host_keyed_cache_dir  # noqa: E402 (jax-free)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    host_keyed_cache_dir(os.path.join(_REPO, ".jax_cache")),
)

_METRIC = "ed25519_sig_verifies_per_sec"


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _emit(
    per_sec: float,
    backend: str,
    note: str | None = None,
    extra: dict | None = None,
) -> None:
    result = {
        "metric": _METRIC,
        "value": round(per_sec, 1),
        "unit": "signatures/sec",
        "vs_baseline": round(per_sec / 50_000.0, 3),
        "backend": backend,
    }
    if extra:
        result.update(extra)
    if note:
        result["note"] = note
    print(json.dumps(result))


def _fail(stage: str, err: str) -> None:
    """Fail fast but still emit the one JSON line the driver parses."""
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": 0.0,
                "unit": "signatures/sec",
                "vs_baseline": 0.0,
                "error": f"{stage}: {err}",
            }
        ),
        flush=True,
    )
    os._exit(1)


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        for name in list(getattr(xla_bridge, "_backend_factories", {})):
            if name != "cpu":
                xla_bridge._backend_factories.pop(name)
    except Exception as e:
        _log(f"cpu forcing incomplete: {e}")


def _probe_tpu(
    timeout_s: float, attempts: int, gap_s: float, budget_s: float | None = None
) -> bool:
    """Probe TPU backend init in disposable subprocesses.

    A wedged tunnel hangs ``jax.devices()`` beyond any in-process watchdog's
    ability to clean up (the probe thread leaks, and a second in-process
    attempt just queues behind the same wedged client init). Subprocesses
    are killable, and a tunnel that is merely slow/mid-restart often comes
    back between attempts.

    ``budget_s`` caps the WHOLE probe loop (attempts + backoff gaps): the
    BENCH_r05 lesson was 8 x 60 s of probing before the inevitable CPU
    fallback — a dead tunnel should cost minutes, not the round's budget.
    """
    import subprocess

    code = "import jax; d = jax.devices(); print(len(d), d[0].platform)"
    gap = gap_s
    loop_t0 = time.perf_counter()
    for attempt in range(1, attempts + 1):
        if budget_s is not None:
            spent = time.perf_counter() - loop_t0
            if spent >= budget_s:
                _log(
                    f"tpu probe: budget {budget_s:.0f}s exhausted after "
                    f"{attempt - 1} attempts ({spent:.0f}s)"
                )
                return False
        t0 = time.perf_counter()
        attempt_timeout = timeout_s
        if budget_s is not None:
            attempt_timeout = min(
                timeout_s, max(5.0, budget_s - (time.perf_counter() - loop_t0))
            )
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=attempt_timeout,
            )
        except subprocess.TimeoutExpired:
            _log(
                f"tpu probe {attempt}/{attempts}: timeout after "
                f"{attempt_timeout:.0f}s"
            )
            out = None
        if out is not None and out.returncode == 0:
            info = out.stdout.strip()
            # `jax.devices()` silently falls back to CPU when no
            # accelerator plugin is present — that is NOT a healthy TPU.
            platform = info.split()[-1] if info else ""
            if platform == "cpu":
                _log(f"tpu probe {attempt}/{attempts}: only CPU visible ({info})")
                return False
            _log(
                f"tpu probe {attempt}/{attempts}: ok in "
                f"{time.perf_counter() - t0:.1f}s ({info})"
            )
            return True
        if out is not None:
            tail = (out.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
            _log(f"tpu probe {attempt}/{attempts}: rc={out.returncode} {tail[0]}")
        if attempt < attempts:
            time.sleep(gap)
            gap = min(gap * 2.0, 60.0)
    return False


def _tpu_indicators() -> list:
    """Environment signals that a TPU could plausibly be reachable.

    The probe loop exists for a tunnel that might come back; when the
    environment already rules a chip out (no accelerator device nodes, no
    tunnel/proxy configuration), 8 x 60 s of probing just delays the
    inevitable CPU fallback (the BENCH_r05 lesson: 480 s spent learning
    what the environment already said). A bare libtpu *module* is not an
    indicator — the image bakes it in everywhere; without device nodes it
    cannot drive anything.
    """
    import glob

    found = []
    plat = os.environ.get("JAX_PLATFORMS", "")
    if "tpu" in plat or "proxy" in plat:
        found.append(f"JAX_PLATFORMS={plat}")
    for var in sorted(os.environ):
        if var.startswith(("TPU_", "PJRT_")):
            found.append(var)
    for dev in glob.glob("/dev/accel*"):
        found.append(dev)
    if os.path.exists("/dev/vfio"):
        found.append("/dev/vfio")
    return found


def _init_backend(timeout_s: float):
    """Initialize the backend under a watchdog.

    Tunneled PJRT plugins can hang during init (round-1 vs round-2 bench
    history: identical code, rc=1 then rc=0). The probe runs in a daemon
    thread; on timeout we emit the diagnostic JSON and exit instead of
    eating the caller's whole timeout budget.
    """
    result: dict = {}

    def probe():
        try:
            import jax

            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ["JAX_COMPILATION_CACHE_DIR"],
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 - reported via result
            result["error"] = repr(e)

    for attempt in (1, 2):
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            _fail("backend-init", f"timeout after {timeout_s}s")
        if "devices" in result:
            return result["devices"]
        _log(f"backend init attempt {attempt} failed: {result.get('error')}")
        result.clear()
        time.sleep(2.0)
    _fail("backend-init", "both init attempts failed")


def _native_mod():
    """The native C++ core module, or None if unbuilt/unavailable."""
    try:
        from pbft_tpu import native

        if native.available():
            return native
    except Exception as e:  # pragma: no cover
        _log(f"native core unavailable ({e!r})")
    return None


def _signed_pool(batch: int):
    """(pubs, msgs, sigs) uint8 arrays: a 64-triple signed pool tiled to
    the batch, with sigs[batch//2] corrupted (the batch-reject path must
    not cost extra). Verification cost is independent of uniqueness;
    prefer the native C++ signer."""
    from pbft_tpu.crypto import ref

    pool = 64
    pubs = np.zeros((pool, 32), np.uint8)
    msgs = np.zeros((pool, 32), np.uint8)
    sigs = np.zeros((pool, 64), np.uint8)
    native = _native_mod()
    if native is not None:
        signer_pub, signer_sign = native.public_key, native.sign
        _log("signer: native C++ core")
    else:
        signer_pub, signer_sign = ref.public_key, ref.sign
        _log("signer: Python oracle")
    for i in range(pool):
        seed = bytes([i + 1, 0x42]) * 16
        msg = os.urandom(32)
        pubs[i] = np.frombuffer(signer_pub(seed), np.uint8)
        msgs[i] = np.frombuffer(msg, np.uint8)
        sigs[i] = np.frombuffer(signer_sign(seed, msg), np.uint8)
    reps = (batch + pool - 1) // pool
    bp = np.tile(pubs, (reps, 1))[:batch]
    bm = np.tile(msgs, (reps, 1))[:batch]
    bs = np.tile(sigs, (reps, 1))[:batch]
    bs[batch // 2, 7] ^= 0xFF
    return bp, bm, bs


def _native_rate(native, items, target_secs: float) -> float:
    """Sustained verifies/sec over repeated full-batch calls."""
    batch = len(items)
    done = 0
    t0 = time.perf_counter()
    elapsed = 0.0
    while elapsed < target_secs or done == 0:
        native.verify_batch(items)
        done += batch
        elapsed = time.perf_counter() - t0
    return done / elapsed


def _native_fallback(
    target_secs: float, reason: str | None, backend: str = "cpu-native-fallback"
) -> bool:
    """Measure the framework's production CPU verifier arm (the native C++
    backend pbftd uses) — no JAX involvement at all. Measures BOTH the
    single-thread rate and the pooled rate (core/verify_pool.cc at
    PBFT_VERIFY_THREADS, default hardware concurrency) and reports the
    pooled number as the headline with the scaling recorded alongside.
    Returns False if the native core isn't available (caller then tries
    XLA:CPU)."""
    native = _native_mod()
    if native is None:
        return False
    # Same batch as the TPU arm. The spec corrupts one signature per
    # batch (below), so exactly one RLC window pays the bisect; the fixed
    # bisect cost amortizes over the batch.
    batch = int(os.environ.get("PBFT_BENCH_BATCH", "4096"))
    bp, bm, bs = _signed_pool(batch)
    items = [(bytes(bp[i]), bytes(bm[i]), bytes(bs[i])) for i in range(batch)]
    out = native.verify_batch(items)
    if sum(out) != batch - 1 or out[batch // 2]:
        _fail("native-verdicts", f"wrong bitmap: sum={sum(out)}")
    want_threads = int(os.environ.get("PBFT_VERIFY_THREADS", "0"))
    native.set_verify_threads(1)
    single = _native_rate(native, items, max(1.0, target_secs / 2))
    _log(f"native CPU arm (1 thread): {single:.0f} verifies/sec")
    native.set_verify_threads(want_threads)  # 0 = hardware concurrency
    threads = native.verify_threads()
    if threads > 1:
        # Pooled/serial verdict parity on the bench batch itself before
        # trusting the pooled rate.
        if native.verify_batch(items) != out:
            _fail("native-verdicts", "pooled verdicts diverge from serial")
        pooled = _native_rate(native, items, target_secs)
    else:
        pooled = single
    _log(
        f"native CPU arm: {pooled:.0f} verifies/sec pooled "
        f"({threads} threads; {pooled / single:.2f}x single-thread)"
    )
    _emit(
        pooled,
        backend,
        reason,
        extra={
            "threads": threads,
            "single_thread_per_sec": round(single, 1),
            "pooled_per_sec": round(pooled, 1),
            "pool_speedup": round(pooled / single, 2),
        },
    )
    return True


def _run_worker(timeout_s: float) -> dict | None:
    """Run the full TPU bench in a killable subprocess.

    Returns the worker's JSON result dict, or None when the worker wedged
    (killed at timeout) or produced no parseable result line. The worker's
    stderr is inherited so its progress lands in this process's stderr.
    """
    import subprocess

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--tpu-worker"],
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:  # pragma: no cover - unkillable child
            out = ""
        _log(f"tpu worker: killed after {timeout_s:.0f}s")
        # A worker that printed its result and THEN wedged in teardown
        # (interpreter-exit PJRT cleanup over a dead tunnel) still counts:
        # don't throw away a completed measurement.
        result = _parse_result(out)
        if result is not None:
            _log("tpu worker: result line recovered from killed worker")
        return result
    result = _parse_result(out)
    if result is None:
        _log(f"tpu worker: rc={proc.returncode}, no JSON result line")
    return result


def _parse_result(out: str | None) -> dict | None:
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main() -> None:
    target_secs = float(os.environ.get("PBFT_BENCH_SECS", "5.0"))
    if "--tpu-worker" in sys.argv:
        _run_xla_bench("tpu", None, target_secs)
        return
    if os.environ.get("PBFT_BENCH_NATIVE"):
        # Direct native-arm run (no TPU probing): the pooled C++ verifier,
        # reported as "cpu-native" with threads + single-vs-pooled rates.
        if not _native_fallback(target_secs, None, backend="cpu-native"):
            _fail("native", "native core unavailable")
        return
    if os.environ.get("PBFT_BENCH_CONSENSUS"):
        # Consensus-protocol entry (ISSUE 4): drive the f=1 firehose
        # through real pbftd daemons and report requests/sec alongside
        # rounds/sec plus the measured mean batch occupancy —
        # PBFT_BATCH_MAX_ITEMS / PBFT_BATCH_FLUSH_US select the batching
        # knobs (1/0 = the pre-batching protocol).
        from pbft_tpu.bench.harness import run_native_config

        res = run_native_config(
            1,  # firehose f=1
            requests=int(os.environ.get("PBFT_BENCH_REQUESTS", "960")),
            pipeline=int(os.environ.get("PBFT_BENCH_PIPELINE", "64")),
            batch_max_items=int(os.environ.get("PBFT_BATCH_MAX_ITEMS", "1")),
            batch_flush_us=int(os.environ.get("PBFT_BATCH_FLUSH_US", "0")),
        )
        print(
            json.dumps(
                {
                    "metric": "pbft_requests_per_sec",
                    "value": res.requests_per_sec,
                    "unit": "requests/sec",
                    "rounds_per_sec": res.rounds_per_sec,
                    "mean_batch": res.mean_batch,
                    "batch_max_items": res.batch_max_items,
                    "batch_flush_us": res.batch_flush_us,
                    "backend": "consensus-native",
                }
            )
        )
        return
    if os.environ.get("PBFT_BENCH_CPU") or os.environ.get("JAX_PLATFORMS") == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        _force_cpu()
        _run_xla_bench("cpu", None, target_secs)
        return

    # TPU path: probe in disposable subprocesses, then run the bench in a
    # killable worker; retry (with a short re-probe) if the worker wedges.
    # PBFT_TPU_PROBE_BUDGET_S caps the whole probe loop (BENCH_r05 burned
    # 8 x 60 s before the inevitable fallback) — and, when set explicitly,
    # forces probing even where the environment shows no chip indicators.
    probe_budget_env = os.environ.get("PBFT_TPU_PROBE_BUDGET_S")
    probe_budget = float(probe_budget_env or "240")
    indicators = _tpu_indicators()
    if not indicators and probe_budget_env is None:
        _log(
            "tpu probe: skipped entirely — no accelerator device nodes or "
            "tunnel indicators in the environment (set "
            "PBFT_TPU_PROBE_BUDGET_S to force probing)"
        )
        probed = False
    else:
        if indicators:
            _log(f"tpu indicators: {', '.join(indicators)}")
        probed = _probe_tpu(
            timeout_s=float(os.environ.get("PBFT_BENCH_PROBE_TIMEOUT", "60")),
            attempts=int(os.environ.get("PBFT_BENCH_PROBES", "8")),
            gap_s=float(os.environ.get("PBFT_BENCH_PROBE_GAP", "10")),
            budget_s=probe_budget,
        )
    if probed:
        worker_timeout = float(os.environ.get("PBFT_BENCH_WORKER_TIMEOUT", "600"))
        tpu_attempts = int(os.environ.get("PBFT_BENCH_TPU_ATTEMPTS", "3"))
        for attempt in range(1, tpu_attempts + 1):
            result = _run_worker(worker_timeout)
            if result and not result.get("error") and result.get("value", 0) > 0:
                print(json.dumps(result))
                return
            _log(f"tpu worker attempt {attempt}/{tpu_attempts} failed: {result}")
            # Only transient failures (wedge-kill -> None, or backend init
            # trouble) are worth a retry; a deterministic in-bench error
            # (wrong verdicts, kernel exception) will just fail identically
            # two more expensive times.
            err = (result or {}).get("error", "")
            if result is not None and not err.startswith("backend-init"):
                break
            if attempt < tpu_attempts and not _probe_tpu(
                timeout_s=60.0, attempts=3, gap_s=15.0,
                budget_s=min(90.0, probe_budget),
            ):
                break
    fallback_reason = "tpu bench never completed; CPU fallback"
    # If the round-long watcher (scripts/tpu_watch.py) already captured an
    # on-chip kernel number during a tunnel window, point the artifact's
    # note at it: the fallback VALUE stays the honest live measurement,
    # but the reader should know driver-visible on-chip evidence exists.
    tag = os.environ.get("PBFT_ROUND_TAG", "r5")  # tpu_watch.py --tag
    rel = os.path.join("benchmarks", f"tpu_{tag}_kernel_xla.json")
    if os.path.exists(os.path.join(_REPO, rel)):
        try:
            with open(os.path.join(_REPO, rel)) as fh:
                cap = json.load(fh)
            if isinstance(cap, dict):
                fallback_reason += (
                    f"; same-round on-chip capture exists: "
                    f"{cap.get('value')} {cap.get('unit', 'sig/s')} ({rel})"
                )
        except (OSError, ValueError):
            pass
    _log(fallback_reason)
    if _native_fallback(target_secs, fallback_reason):
        return
    # Last resort: TPU unreachable AND native core unbuilt — measure
    # the XLA:CPU backend at a small batch rather than emit 0.0. The
    # conv field-mul compiles ~10x faster on XLA:CPU, and batch 64
    # keeps compile ~1 minute (measured).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PBFT_FIELD_MUL", "conv")
    os.environ.setdefault("PBFT_BENCH_BATCH", "64")
    os.environ.setdefault("PBFT_BENCH_CHAIN", "4")
    _force_cpu()
    _run_xla_bench("cpu-fallback", fallback_reason, target_secs)


def _run_xla_bench(backend: str, fallback_reason: str | None, target_secs: float) -> None:
    devices = _init_backend(float(os.environ.get("PBFT_BENCH_INIT_TIMEOUT", "180")))
    if backend == "tpu" and (not devices or devices[0].platform == "cpu"):
        # jax.devices() silently falls back to XLA:CPU when the plugin
        # fails AFTER the probe passed; a CPU number must never be
        # reported under the "tpu" tag.
        _fail("backend-init", f"tpu worker got non-TPU devices: {devices}")

    import jax
    import jax.numpy as jnp
    from jax import lax

    from pbft_tpu.crypto.batch import verify_batch
    from pbft_tpu.crypto.ed25519 import verify_kernel

    batch = int(os.environ.get("PBFT_BENCH_BATCH", "4096"))
    chain_k = int(os.environ.get("PBFT_BENCH_CHAIN", "16"))
    _log(f"devices: {devices}; batch={batch} chain={chain_k}")
    bp, bm, bs = _signed_pool(batch)

    try:
        t0 = time.perf_counter()
        out = np.asarray(verify_batch(bp, bm, bs))
        compile_s = time.perf_counter() - t0
        if out.sum() != batch - 1 or out[batch // 2]:
            _fail("verdicts", f"wrong bitmap: sum={int(out.sum())}")
        _log(f"verify_batch compile+transfer+first: {compile_s:.1f}s; verdicts OK")
    except Exception as e:  # noqa: BLE001
        _fail("first-batch", repr(e))

    # Timed region: K data-dependent kernel applications per jit call.
    @jax.jit
    def chained(p, m, s):
        def body(carry, _):
            m2, acc = carry
            ok = verify_kernel(p, m2, s)
            # optimization_barrier ties the next iteration's message input
            # to THIS iteration's verdicts in the HLO dependency graph, so
            # XLA cannot hoist the (otherwise loop-invariant) verify out of
            # the scan body or collapse the chain. (A zero-valued XOR trick
            # gets constant-folded; the barrier is the supported tool.)
            m3, acc = lax.optimization_barrier((m2, acc + ok.astype(jnp.int32)))
            return (m3, acc), ()
        (_, acc), _ = lax.scan(
            body, (m, jnp.zeros((m.shape[0],), jnp.int32)), None, length=chain_k
        )
        return acc

    try:
        t0 = time.perf_counter()
        dp, dm, ds = jax.device_put(bp), jax.device_put(bm), jax.device_put(bs)
        jax.block_until_ready((dp, dm, ds))
        _log(f"host->device transfer: {time.perf_counter() - t0:.2f}s")
        t0 = time.perf_counter()
        acc = np.asarray(chained(dp, dm, ds))
        _log(f"chained compile+first: {time.perf_counter() - t0:.1f}s")
        if int(acc[0]) != chain_k or int(acc[batch // 2]) != 0:
            _fail("chained-verdicts", f"acc[0]={int(acc[0])}")
        chains = 0
        t0 = time.perf_counter()
        elapsed = 0.0
        while elapsed < target_secs or chains == 0:
            np.asarray(chained(dp, dm, ds))
            chains += 1
            elapsed = time.perf_counter() - t0
        per_sec = chains * chain_k * batch / elapsed
        _log(f"{chains} chains x {chain_k} batches of {batch} in {elapsed:.2f}s")
    except Exception as e:  # noqa: BLE001
        _fail("timed-region", repr(e))

    _emit(per_sec, backend, fallback_reason)


if __name__ == "__main__":
    main()
