"""pbft_tpu — a TPU-native Practical Byzantine Fault Tolerance framework.

Built from scratch with the capability surface of the reference
``ameya-deshmukh/pbft`` (Rust + libp2p normal-case PBFT; see SURVEY.md):
PRE-PREPARE -> PREPARE -> COMMIT with a JSON-over-TCP client front-end —
re-designed TPU-first:

- ``pbft_tpu.crypto``    — the hot path: batched Ed25519 signature verification
  as a single ``jax.vmap``'d XLA launch (SHA-512 + GF(2^255-19) field kernels),
  plus a pure-Python reference oracle.
- ``pbft_tpu.consensus`` — the deterministic replica state machine with *real*
  quorums (2f prepares, 2f+1 commits; the reference stubbed these to >= 1,
  reference src/behavior.rs:181,:208,:222), logs keyed by (view, seq) for all
  three phases (fixing reference src/state.rs:23), watermarks and the
  exactly-once timestamp guard (reference src/behavior.rs:391-398).
- ``pbft_tpu.parallel``  — sharding the verification batch over a
  ``jax.sharding.Mesh`` (data-parallel over the signature axis, scaling to
  multi-chip/multi-host via XLA collectives).
- ``pbft_tpu.net``       — client gateway contract (JSON request in, dial-back
  reply out; reference src/client_handler.rs) and the cluster launcher.

All crypto kernels use native 32-bit arithmetic (int32 8-bit limbs, uint32
SHA-512 word halves) — the TPU vector unit's native width — so this package
neither needs nor touches jax x64 mode.
"""

__version__ = "0.1.0"
