"""No blocking calls inside ``async def`` (ISSUE 8 tentpole, leg 3a).

The asyncio runtime's event loop is the Python twin of net.cc's poll()
loop: ONE blocking call inside a coroutine stalls every replica duty —
verify batching, view-change timers, the chaos delay pump — exactly the
wedge class the C++ side guards with deadlines. This pass walks the AST
of every module in ``pbft_tpu/net/`` and flags calls that are known to
block when they appear inside an ``async def`` body:

    time.sleep                    (asyncio.sleep is the loop-safe spelling)
    subprocess.run/call/check_*   (use asyncio.create_subprocess_*)
    os.system
    socket.create_connection      (use loop.sock_connect / open_connection)
    <sock>.recv/recv_into/accept/connect/sendall  un-awaited socket method
                                  calls (use loop.sock_* or streams)
    open(...)                     blocking file I/O on the loop

Nested ``def`` bodies inside an ``async def`` are NOT flagged (a sync
helper defined in a coroutine runs wherever it is called — commonly via
run_in_executor); ``await loop.run_in_executor(None, time.sleep, ...)``
passes the callable without calling it, so it never trips the pass.
"""

from __future__ import annotations

import ast
import pathlib
from typing import List, Optional, Tuple

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

# (module, attribute) calls that block the loop.
BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("os", "system"),
    ("socket", "create_connection"),
}
# Method names that block when called on a raw socket-ish object inside a
# coroutine. Narrow on purpose: generic enough names (read/write/send)
# would drown the pass in false positives on asyncio streams.
BLOCKING_METHODS = {"recv", "recv_into", "recvfrom", "accept", "connect",
                    "sendall"}
# Bare calls that block (file I/O on the loop).
BLOCKING_BARE_CALLS = {"open"}


def _call_signature(node: ast.Call) -> Optional[Tuple[str, str]]:
    func = node.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    return None


def _blocking_reason(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name) and func.id in BLOCKING_BARE_CALLS:
        return f"{func.id}()"
    sig = _call_signature(node)
    if sig is None:
        return None
    if sig in BLOCKING_MODULE_CALLS:
        return f"{sig[0]}.{sig[1]}"
    # obj.recv(...) etc: flag unless obj is a module from the allow-set
    # (asyncio.X, loop helpers are Attribute chains and never match).
    if sig[1] in BLOCKING_METHODS and sig[0] not in ("asyncio", "loop"):
        return f"{sig[0]}.{sig[1]}"
    return None


class _AsyncWalker(ast.NodeVisitor):
    def __init__(self, path: pathlib.Path, errors: List[str]):
        self.path = path
        self.errors = errors
        self.async_stack: List[str] = []

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.async_stack.append(node.name)
        self.generic_visit(node)
        self.async_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A sync def nested in a coroutine is a new (non-loop) context.
        saved, self.async_stack = self.async_stack, []
        self.generic_visit(node)
        self.async_stack = saved

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.async_stack = self.async_stack, []
        self.generic_visit(node)
        self.async_stack = saved

    def visit_Call(self, node: ast.Call) -> None:
        if self.async_stack:
            reason = _blocking_reason(node)
            if reason:
                self.errors.append(
                    f"async-blocking: {self.path.name}:{node.lineno}: "
                    f"blocking call {reason} inside async def "
                    f"'{self.async_stack[-1]}'")
        self.generic_visit(node)


def check_file(path: pathlib.Path) -> List[str]:
    errors: List[str] = []
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError as exc:
        return [f"async-blocking: {path.name}: unparseable: {exc}"]
    _AsyncWalker(path, errors).visit(tree)
    return errors


def files_scanned(root: pathlib.Path = REPO) -> List[pathlib.Path]:
    return sorted((root / "pbft_tpu" / "net").glob("*.py"))


def check(root: pathlib.Path = REPO) -> List[str]:
    errors: List[str] = []
    for path in files_scanned(root):
        errors.extend(check_file(path))
    return errors
