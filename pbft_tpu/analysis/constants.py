"""Cross-runtime constant conformance (ISSUE 8 tentpole, leg 2).

The C++ core and the asyncio runtime must agree on every hand-mirrored
wire and protocol constant — the 0xB2 binary magic, the message type
tags, the protocol version set, the ClusterConfig defaults, the RLC
window width, the verify-service pad ladder. Castro & Liskov's safety
argument assumes replicas compute identical digests; a one-byte drift in
any of these forks the accept set silently. tests/test_wire_codec.py
fuzzes the DYNAMIC behavior; this pass is the static complement — it
parses both source trees (C++ by regex over declarations, Python by AST)
and fails the build when the values diverge.

Policy (README "Static analysis & sanitizers"): a new cross-runtime
constant is added to BOTH runtimes and to ``PAIRS`` below in the same
commit, or the lint fails the build.

Every check reads files relative to ``root`` so tests/test_lint.py can
run the pass against a shadow tree with one deliberately divergent value.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, List, Optional, Tuple, Union

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

Value = Union[int, str, tuple]

# (label, (C++ file, declaration name), (Python file, binding name)).
# C++ names are matched against `<name> = <value>[;,]` declarations
# (enumerators, constexprs, struct-member defaults alike); Python names
# against any `<name> = <literal>` / `<name>: T = <literal>` binding.
PAIRS: List[Tuple[str, Tuple[str, str], Tuple[str, str]]] = [
    ("wire binary magic",
     ("core/messages.h", "kBinaryMagic"),
     ("pbft_tpu/consensus/messages.py", "WIRE_BINARY_MAGIC")),
    ("binary codec name",
     ("core/messages.h", "kCodecBinary2"),
     ("pbft_tpu/consensus/messages.py", "CODEC_BINARY2")),
    ("binary tag: client-request",
     ("core/messages.cc", "kBinClientRequest"),
     ("pbft_tpu/consensus/messages.py", "_BIN_CLIENT_REQUEST")),
    ("binary tag: pre-prepare",
     ("core/messages.cc", "kBinPrePrepare"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PRE_PREPARE")),
    ("binary tag: prepare",
     ("core/messages.cc", "kBinPrepare"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PREPARE")),
    ("binary tag: commit",
     ("core/messages.cc", "kBinCommit"),
     ("pbft_tpu/consensus/messages.py", "_BIN_COMMIT")),
    ("binary tag: checkpoint",
     ("core/messages.cc", "kBinCheckpoint"),
     ("pbft_tpu/consensus/messages.py", "_BIN_CHECKPOINT")),
    ("binary tag: batched pre-prepare",
     ("core/messages.cc", "kBinPrePrepareBatch"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PRE_PREPARE_BATCH")),
    ("binary max batch",
     ("core/messages.cc", "kBinMaxBatch"),
     ("pbft_tpu/consensus/messages.py", "_BIN_MAX_BATCH")),
    # MAC-vector frame variants (ISSUE 14): the five authenticated
    # codes, the lane-vector bound, the tag length, the KDF/domain
    # labels, and the auth-mode offer name — one byte of drift here and
    # a mixed-runtime mac link rejects every frame.
    ("binary tag: pre-prepare (MAC)",
     ("core/messages.cc", "kBinPrePrepareMac"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PRE_PREPARE_MAC")),
    ("binary tag: prepare (MAC)",
     ("core/messages.cc", "kBinPrepareMac"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PREPARE_MAC")),
    ("binary tag: commit (MAC)",
     ("core/messages.cc", "kBinCommitMac"),
     ("pbft_tpu/consensus/messages.py", "_BIN_COMMIT_MAC")),
    ("binary tag: checkpoint (MAC)",
     ("core/messages.cc", "kBinCheckpointMac"),
     ("pbft_tpu/consensus/messages.py", "_BIN_CHECKPOINT_MAC")),
    ("binary tag: batched pre-prepare (MAC)",
     ("core/messages.cc", "kBinPrePrepareBatchMac"),
     ("pbft_tpu/consensus/messages.py", "_BIN_PRE_PREPARE_BATCH_MAC")),
    ("MAC vector bound",
     ("core/messages.cc", "kMacVectorMax"),
     ("pbft_tpu/consensus/messages.py", "_MAC_VECTOR_MAX")),
    ("MAC tag length",
     ("core/secure.h", "kMacTagLen"),
     ("pbft_tpu/net/secure.py", "MAC_TAG_LEN")),
    ("MAC domain-separation label",
     ("core/secure.h", "kMacContext"),
     ("pbft_tpu/net/secure.py", "MAC_CONTEXT")),
    ("MAC auth-mode offer name",
     ("core/secure.h", "kAuthModeMac"),
     ("pbft_tpu/net/secure.py", "AUTH_MODE_MAC")),
    # Tentative-reply flag (ISSUE 14): the signed JSON member both
    # runtimes omit-when-zero — a renamed/mis-cased field would fork
    # every tentative reply's signable bytes.
    ("tentative-reply field tag",
     ("core/messages.h", "kTentativeField"),
     ("pbft_tpu/consensus/messages.py", "TENTATIVE_FIELD")),
    ("protocol version (current)",
     ("core/secure.h", "kProtocolVersion"),
     ("pbft_tpu/net/secure.py", "PROTOCOL_VERSION")),
    ("protocol version (batch)",
     ("core/secure.h", "kProtocolVersionBatch"),
     ("pbft_tpu/net/secure.py", "PROTOCOL_VERSION_BATCH")),
    ("protocol version (bin2)",
     ("core/secure.h", "kProtocolVersionBin2"),
     ("pbft_tpu/net/secure.py", "PROTOCOL_VERSION_BIN2")),
    ("protocol version (legacy)",
     ("core/secure.h", "kProtocolVersionLegacy"),
     ("pbft_tpu/net/secure.py", "PROTOCOL_VERSION_LEGACY")),
    # The fixed RLC window width. The Python mirror lives in the parity
    # suite (tests/test_verify_pool.py WINDOW): the test that PINS
    # thread-count-independent accept sets must pin the right width.
    ("ed25519 RLC window items",
     ("core/ed25519.h", "kEd25519RlcWindowItems"),
     ("tests/test_verify_pool.py", "WINDOW")),
    # ClusterConfig defaults: a replica constructed from a sparse
    # network.json must behave identically in either runtime.
    ("ClusterConfig default: watermark_window",
     ("core/replica.h", "watermark_window"),
     ("pbft_tpu/consensus/config.py", "watermark_window")),
    ("ClusterConfig default: checkpoint_interval",
     ("core/replica.h", "checkpoint_interval"),
     ("pbft_tpu/consensus/config.py", "checkpoint_interval")),
    ("ClusterConfig default: batch_pad",
     ("core/replica.h", "batch_pad"),
     ("pbft_tpu/consensus/config.py", "batch_pad")),
    ("ClusterConfig default: verify_flush_us",
     ("core/replica.h", "verify_flush_us"),
     ("pbft_tpu/consensus/config.py", "verify_flush_us")),
    ("ClusterConfig default: verify_flush_items",
     ("core/replica.h", "verify_flush_items"),
     ("pbft_tpu/consensus/config.py", "verify_flush_items")),
    ("ClusterConfig default: batch_max_items",
     ("core/replica.h", "batch_max_items"),
     ("pbft_tpu/consensus/config.py", "batch_max_items")),
    ("ClusterConfig default: batch_flush_us",
     ("core/replica.h", "batch_flush_us"),
     ("pbft_tpu/consensus/config.py", "batch_flush_us")),
    # Admission control (ISSUE 12): per-client in-flight cap + global
    # backlog watermark — a sparse network.json must disable both
    # identically in either runtime.
    ("ClusterConfig default: admission_inflight",
     ("core/replica.h", "admission_inflight"),
     ("pbft_tpu/consensus/config.py", "admission_inflight")),
    ("ClusterConfig default: admission_backlog",
     ("core/replica.h", "admission_backlog"),
     ("pbft_tpu/consensus/config.py", "admission_backlog")),
    # Multi-core replica core (ISSUE 13): a sparse network.json must mean
    # the classic single-threaded loop in both runtimes.
    ("ClusterConfig default: net_threads",
     ("core/replica.h", "net_threads"),
     ("pbft_tpu/consensus/config.py", "net_threads")),
    # Fast-path modes (ISSUE 14): a sparse network.json must mean
    # signature mode + committed-only replies in both runtimes.
    ("ClusterConfig default: fastpath",
     ("core/replica.h", "fastpath"),
     ("pbft_tpu/consensus/config.py", "fastpath")),
    ("ClusterConfig default: tentative",
     ("core/replica.h", "tentative"),
     ("pbft_tpu/consensus/config.py", "tentative")),
    # Durable replica recovery (ISSUE 15): the WAL's on-disk format is
    # byte-identical across runtimes (a pbftd-written log must replay in
    # the asyncio runtime's tooling and vice versa) — magic, version,
    # record tags, and vote kinds are all hand-mirrored; and a sparse
    # network.json must mean no-WAL + fsync-on identically in both.
    ("WAL file magic",
     ("core/wal.h", "kWalMagic"),
     ("pbft_tpu/consensus/wal.py", "WAL_MAGIC")),
    ("WAL format version",
     ("core/wal.h", "kWalVersion"),
     ("pbft_tpu/consensus/wal.py", "WAL_VERSION")),
    ("WAL record tag: view",
     ("core/wal.h", "kWalRecView"),
     ("pbft_tpu/consensus/wal.py", "WAL_REC_VIEW")),
    ("WAL record tag: vote",
     ("core/wal.h", "kWalRecVote"),
     ("pbft_tpu/consensus/wal.py", "WAL_REC_VOTE")),
    ("WAL record tag: checkpoint",
     ("core/wal.h", "kWalRecCheckpoint"),
     ("pbft_tpu/consensus/wal.py", "WAL_REC_CHECKPOINT")),
    ("WAL vote kind: pre-prepare",
     ("core/wal.h", "kWalVotePrePrepare"),
     ("pbft_tpu/consensus/wal.py", "WAL_VOTE_PRE_PREPARE")),
    ("WAL vote kind: prepare",
     ("core/wal.h", "kWalVotePrepare"),
     ("pbft_tpu/consensus/wal.py", "WAL_VOTE_PREPARE")),
    ("WAL vote kind: commit",
     ("core/wal.h", "kWalVoteCommit"),
     ("pbft_tpu/consensus/wal.py", "WAL_VOTE_COMMIT")),
    ("ClusterConfig default: wal_dir",
     ("core/replica.h", "wal_dir"),
     ("pbft_tpu/consensus/config.py", "wal_dir")),
    ("ClusterConfig default: wal_fsync",
     ("core/replica.h", "wal_fsync"),
     ("pbft_tpu/consensus/config.py", "wal_fsync")),
    # ISSUE 12: forwarded-request retention (view-change re-aim) bound —
    # same eviction point in both runtimes or their storm behavior forks.
    ("forwarded-request retention bound",
     ("core/replica.h", "kMaxForwardedRetained"),
     ("pbft_tpu/consensus/replica.py", "MAX_FORWARDED_RETAINED")),
    # Verify-service readiness handshake record shape.
    ("verify-service status version",
     ("core/verifier.cc", "kStatusVersionLint"),  # custom, see below
     ("pbft_tpu/net/service.py", "STATUS_VERSION")),
    # Gateway tier (ISSUE 10): the routing-token prefix both runtimes
    # switch the reply path on, and the bounded-queue/route-cache sizes
    # the backpressure and fan-back fallback policies share.
    ("gateway client-token prefix",
     ("core/net.h", "kGatewayClientPrefix"),
     ("pbft_tpu/net/gateway.py", "GATEWAY_CLIENT_PREFIX")),
    ("max per-connection outbound bytes",
     ("core/net.cc", "kMaxConnOutbound"),
     ("pbft_tpu/net/server.py", "MAX_CONN_OUTBOUND")),
    ("gateway route-cache bound",
     ("core/net.cc", "kMaxGatewayRoutes"),
     ("pbft_tpu/net/server.py", "MAX_GATEWAY_ROUTES")),
    # ISSUE 16 health introspection: the health-document schema version
    # both runtimes stamp into their /status surface, and the detector
    # thresholds every gate (pbft_top, endurance_soak, chaos harnesses)
    # shares — a fork here makes a mixed-runtime cluster's health reads
    # incomparable.
    ("health document version",
     ("core/net.h", "kHealthDocVersion"),
     ("pbft_tpu/utils/trace_schema.py", "HEALTH_DOC_VERSION")),
    ("health stall threshold seconds",
     ("core/net.h", "kHealthStallSeconds"),
     ("pbft_tpu/analysis/health.py", "HEALTH_STALL_SECONDS")),
    ("health snapshot interval seconds",
     ("core/net.h", "kHealthSnapshotIntervalS"),
     ("pbft_tpu/analysis/health.py", "HEALTH_SNAPSHOT_INTERVAL_S")),
]

# Files consulted by extractors that are not simple name pairs.
EXTRA_FILES = [
    "core/net.h",
    "core/secure.cc",
    "pbft_tpu/consensus/simulation.py",
    "pbft_tpu/crypto/batch.py",
]


def files_scanned() -> List[str]:
    """Repo-relative paths this pass reads (tests build shadow trees)."""
    out = []
    for _, (cxx, _), (py, _) in PAIRS:
        out.extend([cxx, py])
    out.extend(EXTRA_FILES)
    seen: Dict[str, None] = {}
    for p in out:
        seen.setdefault(p)
    return list(seen)


# -- C++ extraction (regex over declarations) --------------------------------

def _parse_cxx_value(raw: str) -> Optional[Value]:
    raw = raw.strip()
    # bool defaults (e.g. `bool tentative = false;`): compare as 0/1 —
    # Python-side `False` literals extract as bool, and False == 0.
    if raw == "false":
        return 0
    if raw == "true":
        return 1
    m = re.fullmatch(r'"([^"]*)"', raw)
    if m:
        return m.group(1)
    m = re.fullmatch(r"(0[xX][0-9a-fA-F]+|\d+)\s*[uUlL]*\s*<<\s*(\d+)", raw)
    if m:
        return int(m.group(1), 0) << int(m.group(2))
    m = re.fullmatch(r"(0[xX][0-9a-fA-F]+|\d+)[uUlL]*", raw)
    if m:
        return int(m.group(1), 0)
    return None


def cxx_const(path: pathlib.Path, name: str) -> Optional[Value]:
    """The value of `name = <value>[;,]` in a C++ source/header: covers
    constexpr declarations, enumerators, and struct-member defaults."""
    text = path.read_text()
    hits = set()
    for m in re.finditer(
            r"\b" + re.escape(name) + r"\s*=\s*([^;,\n]+)[;,]", text):
        v = _parse_cxx_value(m.group(1))
        if v is not None:
            hits.add(v)
    if len(hits) > 1:
        raise ValueError(f"{path.name}: {name} bound to multiple values {hits}")
    return next(iter(hits)) if hits else None


# -- Python extraction (AST over bindings) -----------------------------------

def _literal(node: ast.AST) -> Optional[Value]:
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, str, bytes)):
        v = node.value
        return v.decode("latin-1") if isinstance(v, bytes) else v
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.right, ast.Constant)):
        return node.left.value << node.right.value
    if isinstance(node, ast.Tuple):
        items = [_literal(e) for e in node.elts]
        if all(i is not None for i in items):
            return tuple(items)
    return None


def py_const(path: pathlib.Path, name: str) -> Optional[Value]:
    """The literal bound to `name` anywhere in the module (module level,
    class attribute, or dataclass field annotation-assignment)."""
    tree = ast.parse(path.read_text())
    hits = set()
    for node in ast.walk(tree):
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            target, value = node.target.id, node.value
        if target != name or value is None:
            continue
        v = _literal(value)
        if v is not None:
            hits.add(v)
    if len(hits) > 1:
        raise ValueError(f"{path.name}: {name} bound to multiple values {hits}")
    return next(iter(hits)) if hits else None


# -- the pass ----------------------------------------------------------------

def _check_pair(root: pathlib.Path, label: str, cxx_spec, py_spec,
                errors: List[str]) -> None:
    cxx_file, cxx_name = cxx_spec
    py_file, py_name = py_spec
    cxx_path = root / cxx_file
    py_path = root / py_file
    for p in (cxx_path, py_path):
        if not p.exists():
            errors.append(f"constants: {label}: missing file {p}")
            return
    try:
        if cxx_name == "kStatusVersionLint":
            # The readiness probe's version byte: verifier.cc checks it
            # inline (`status[2] != 1`) rather than naming a constant.
            m = re.search(r"status\[2\]\s*!=\s*(\d+)", cxx_path.read_text())
            cxx_val: Optional[Value] = int(m.group(1)) if m else None
        else:
            cxx_val = cxx_const(cxx_path, cxx_name)
        py_val = py_const(py_path, py_name)
    except (ValueError, SyntaxError) as exc:
        errors.append(f"constants: {label}: {exc}")
        return
    if cxx_val is None:
        errors.append(
            f"constants: {label}: {cxx_name} not found in {cxx_file}")
        return
    if py_val is None:
        errors.append(f"constants: {label}: {py_name} not found in {py_file}")
        return
    if cxx_val != py_val:
        errors.append(
            f"constants: {label}: C++ {cxx_file}:{cxx_name} = {cxx_val!r} "
            f"!= Python {py_file}:{py_name} = {py_val!r}")


def _check_chaos_seed(root: pathlib.Path, errors: List[str]) -> None:
    """net.h's default chaos RNG seed and the simulator's seed-mix
    constant are the same magic value by design (one chaos namespace)."""
    net_h = (root / "core/net.h").read_text()
    sim = (root / "pbft_tpu/consensus/simulation.py").read_text()
    m_cxx = re.search(r"chaos_rng_\{(0[xX][0-9a-fA-F]+|\d+)\}", net_h)
    m_py = re.search(
        r"chaos_rng\s*=\s*random\.Random\([^\n]*\^\s*(0[xX][0-9a-fA-F]+|\d+)\)",
        sim)
    if not m_cxx:
        errors.append("constants: chaos seed: default not found in core/net.h")
        return
    if not m_py:
        errors.append(
            "constants: chaos seed: mix constant not found in simulation.py")
        return
    if int(m_cxx.group(1), 0) != int(m_py.group(1), 0):
        errors.append(
            f"constants: chaos seed: net.h {m_cxx.group(1)} != "
            f"simulation.py {m_py.group(1)}")


def _check_pad_ladder(root: pathlib.Path, errors: List[str]) -> None:
    """Pad-ladder shape: ascending, topped by the service merge cap
    (service.py MAX_WINDOW) and the C++ async-budget clamp (verifier.cc)
    — three independent spellings of the largest XLA window shape."""
    ladder = py_const(root / "pbft_tpu/crypto/batch.py", "_PAD_LADDER")
    if not isinstance(ladder, tuple) or not ladder:
        errors.append("constants: pad ladder: _PAD_LADDER not found/parsed "
                      "in crypto/batch.py")
        return
    if list(ladder) != sorted(ladder):
        errors.append(f"constants: pad ladder {ladder} is not ascending")
    top = ladder[-1]
    max_window = py_const(root / "pbft_tpu/net/service.py", "MAX_WINDOW")
    if max_window != top:
        errors.append(
            f"constants: pad ladder top {top} != service.py MAX_WINDOW "
            f"{max_window}")
    vcc = (root / "core/verifier.cc").read_text()
    m = re.search(
        r"async_budget_items_\s*>\s*(\d+)\)\s*async_budget_items_\s*=\s*(\d+)",
        vcc)
    if not m:
        errors.append(
            "constants: pad ladder: async-budget clamp not found in "
            "core/verifier.cc")
    elif int(m.group(1)) != top or int(m.group(2)) != top:
        errors.append(
            f"constants: pad ladder top {top} != verifier.cc async-budget "
            f"clamp {m.group(1)}/{m.group(2)}")


def _check_status_magic(root: pathlib.Path, errors: List[str]) -> None:
    """service.py STATUS_MAGIC vs the byte checks in verifier.cc."""
    magic = py_const(root / "pbft_tpu/net/service.py", "STATUS_MAGIC")
    vcc = (root / "core/verifier.cc").read_text()
    m = re.search(r"status\[0\]\s*!=\s*'(.)'\s*\|\|\s*status\[1\]\s*!=\s*'(.)'",
                  vcc)
    if not isinstance(magic, str) or len(magic) != 2:
        errors.append("constants: status magic: STATUS_MAGIC not found/2-byte "
                      "in service.py")
        return
    if not m:
        errors.append("constants: status magic: byte checks not found in "
                      "core/verifier.cc")
        return
    if m.group(1) + m.group(2) != magic:
        errors.append(
            f"constants: status magic: verifier.cc checks "
            f"{m.group(1) + m.group(2)!r} != service.py STATUS_MAGIC "
            f"{magic!r}")


def _check_version_set(root: pathlib.Path, errors: List[str]) -> None:
    """secure.py's _COMPATIBLE_VERSIONS must be exactly the four version
    constants (which the pairwise checks pin to the C++ spellings); the
    C++ compatible set in secure.cc is the same four names by check."""
    path = root / "pbft_tpu/net/secure.py"
    tree = ast.parse(path.read_text())
    consts = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = _literal(node.value)
            if v is not None:
                consts[node.targets[0].id] = v
    compatible = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_COMPATIBLE_VERSIONS" and \
                isinstance(node.value, ast.Tuple):
            names = [e.id for e in node.value.elts if isinstance(e, ast.Name)]
            compatible = {consts.get(n) for n in names}
    want = {consts.get("PROTOCOL_VERSION"),
            consts.get("PROTOCOL_VERSION_BATCH"),
            consts.get("PROTOCOL_VERSION_BIN2"),
            consts.get("PROTOCOL_VERSION_LEGACY")}
    if compatible is None:
        errors.append(
            "constants: version set: _COMPATIBLE_VERSIONS not found in "
            "secure.py")
    elif compatible != want:
        errors.append(
            f"constants: version set: _COMPATIBLE_VERSIONS {compatible} != "
            f"the four protocol versions {want}")
    # C++ side: secure.cc must admit exactly the four named constants.
    scc = (root / "core/secure.cc")
    if scc.exists():
        text = scc.read_text()
        for name in ("kProtocolVersion", "kProtocolVersionBatch",
                     "kProtocolVersionBin2", "kProtocolVersionLegacy"):
            if not re.search(r"ver\s*!=\s*" + name, text):
                errors.append(
                    f"constants: version set: secure.cc compatible-set check "
                    f"does not name {name}")


def check(root: pathlib.Path = REPO) -> List[str]:
    """All conformance checks; [] when the runtimes agree."""
    errors: List[str] = []
    for label, cxx_spec, py_spec in PAIRS:
        _check_pair(root, label, cxx_spec, py_spec, errors)
    try:
        _check_chaos_seed(root, errors)
        _check_pad_ladder(root, errors)
        _check_status_magic(root, errors)
        _check_version_set(root, errors)
    except FileNotFoundError as exc:
        errors.append(f"constants: missing file: {exc}")
    return errors
