"""Metrics/trace-event conformance lint (ISSUE 8 tentpole, leg 3b).

The generalized successor of scripts/check_trace_schema.py (now a thin
shim over this module): every trace-event and metric emitter in BOTH
runtimes is statically extracted and diffed against the single manifest,
``pbft_tpu/utils/trace_schema.py``.

Per emitter:

- Python emitters (net/server.py, net/service.py, net/verify_service.py,
  utils/metrics.py): every ``tracer.event("name", field=...)`` call is
  parsed from the AST — the event name must be in the manifest with this
  file listed as an emitter, its keyword fields a subset of
  required|optional, every required field present. Every
  ``registry.counter/gauge/histogram("name")`` lookup must name a
  manifest metric of that type.
- GENERALIZED sweep (new in ISSUE 8): every other module under
  ``pbft_tpu/`` is scanned for ``.counter/.gauge/.histogram("pbft_...")``
  lookups — an unregistered metric name anywhere in the package fails
  the lint, not just in the declared emitter files.
- C++ emitter (core/net.cc): event names extracted from the
  ``\\"ev\\":\\"<name>\\"`` tokens in its format strings — exact two-way
  match against the manifest's net.cc events, field tokens checked both
  directions.
- C++ metric tables (core/metrics.cc): kCounterNames/kGaugeNames/
  kHistogramNames must match the manifest's net.cc metric sets
  name-for-name and type-for-type; kLatencyBuckets/kSizeBuckets must
  equal LATENCY_BUCKETS_S/BATCH_SIZE_BUCKETS value-for-value.
- Phase names passed to phase_hook in consensus/replica.py and
  core/replica.cc must be exactly the manifest PHASES.

Everything reads relative to ``root`` (the manifest too, loaded by file
path) so tests/test_lint.py can run the pass against a shadow tree with
a deliberately unregistered metric.
"""

from __future__ import annotations

import ast
import importlib.util
import pathlib
import re
from typing import Dict, List

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

PY_EMITTERS = {
    "server.py": pathlib.Path("pbft_tpu/net/server.py"),
    "service.py": pathlib.Path("pbft_tpu/net/service.py"),
    "verify_service.py": pathlib.Path("pbft_tpu/net/verify_service.py"),
    # The client emits its half of the latency waterfall (client_request
    # send/first-reply/quorum stamps, ISSUE 9) — held to the same
    # manifest contract as the replica runtimes.
    "client.py": pathlib.Path("pbft_tpu/net/client.py"),
    # The gateway tier (ISSUE 10): clients-open gauge, forwarded counter,
    # and the shared backpressure counter — same manifest contract.
    "gateway.py": pathlib.Path("pbft_tpu/net/gateway.py"),
}
# utils/metrics.py emits consensus_span on behalf of server.py (the spans
# object is wired there); lint it under the server.py emitter identity.
PY_EMITTER_ALIASES = {
    pathlib.Path("pbft_tpu/utils/metrics.py"): "server.py",
}
NET_CC = pathlib.Path("core/net.cc")
METRICS_CC = pathlib.Path("core/metrics.cc")
PY_REPLICA = pathlib.Path("pbft_tpu/consensus/replica.py")
CC_REPLICA = pathlib.Path("core/replica.cc")
MANIFEST = pathlib.Path("pbft_tpu/utils/trace_schema.py")


def load_manifest(root: pathlib.Path):
    """Import the manifest module FROM root (not the installed package),
    so a shadow tree lints against its own manifest copy."""
    spec = importlib.util.spec_from_file_location(
        "_pbft_lint_trace_schema", root / MANIFEST)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def files_scanned(root: pathlib.Path = REPO) -> List[pathlib.Path]:
    fixed = [root / p for p in PY_EMITTERS.values()]
    fixed += [root / p for p in PY_EMITTER_ALIASES]
    fixed += [root / p for p in (NET_CC, METRICS_CC, PY_REPLICA, CC_REPLICA,
                                 MANIFEST)]
    return fixed + _sweep_files(root)


def _sweep_files(root: pathlib.Path) -> List[pathlib.Path]:
    """The generalized-sweep targets: every pbft_tpu module that is not
    already a declared emitter (those get the stricter per-emitter lint)."""
    known = {root / p for p in PY_EMITTERS.values()}
    known |= {root / p for p in PY_EMITTER_ALIASES}
    out = []
    for path in sorted((root / "pbft_tpu").rglob("*.py")):
        if path in known or "__pycache__" in path.parts:
            continue
        out.append(path)
    return out


def _event_calls(path: pathlib.Path):
    """(event_name, keyword_field_set, has_dynamic_kwargs, lineno) for
    every .event(...) call; a conditional name (IfExp) yields one entry
    per branch."""
    tree = ast.parse(path.read_text())
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "event"):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        names = []
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            names = [arg.value]
        elif isinstance(arg, ast.IfExp):
            for side in (arg.body, arg.orelse):
                if isinstance(side, ast.Constant) and isinstance(
                        side.value, str):
                    names.append(side.value)
        if not names:
            continue
        fields = set()
        dynamic = False
        for kw in node.keywords:
            if kw.arg is None:
                dynamic = True  # **fields: contents checked at the call site
            else:
                fields.add(kw.arg)
        for name in names:
            out.append((name, fields, dynamic, node.lineno))
    return out


def _metric_lookups(path: pathlib.Path):
    """(kind, name, lineno) for registry.counter/gauge/histogram("...")."""
    tree = ast.parse(path.read_text())
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr in ("counter", "gauge", "histogram")
        ):
            continue
        if node.args and isinstance(node.args[0], ast.Constant):
            val = node.args[0].value
            if isinstance(val, str):
                out.append((func.attr, val, node.lineno))
    return out


def check(root: pathlib.Path = REPO) -> List[str]:
    errors: List[str] = []
    trace_schema = load_manifest(root)
    schemas = trace_schema.EVENT_SCHEMAS
    metrics = trace_schema.METRIC_SCHEMAS

    # -- Python trace events -------------------------------------------------
    py_seen: Dict[str, set] = {}  # emitter -> set of event names
    files = [(em, root / p) for em, p in PY_EMITTERS.items()] + [
        (em, root / p) for p, em in PY_EMITTER_ALIASES.items()
    ]
    for emitter, path in files:
        for name, fields, dynamic, line in _event_calls(path):
            loc = f"{path.name}:{line}"
            schema = schemas.get(name)
            if schema is None:
                errors.append(f"{loc}: event {name!r} not in manifest")
                continue
            if emitter not in schema["emitters"]:
                errors.append(
                    f"{loc}: {emitter} is not a manifest emitter of {name!r}"
                )
            allowed = schema["required"] | schema["optional"]
            # ts/ev are stamped by Tracer.event itself.
            extra = fields - allowed
            if extra:
                errors.append(
                    f"{loc}: event {name!r} has unknown fields {sorted(extra)}"
                )
            if not dynamic:
                missing = schema["required"] - fields - {"ts", "ev"}
                if missing:
                    errors.append(
                        f"{loc}: event {name!r} missing required fields "
                        f"{sorted(missing)}"
                    )
            py_seen.setdefault(emitter, set()).add(name)
    for name, schema in schemas.items():
        for emitter in schema["emitters"] & set(PY_EMITTERS):
            if name not in py_seen.get(emitter, set()):
                errors.append(
                    f"{emitter}: manifest event {name!r} is never emitted"
                )

    # -- Python metric lookups (declared emitters) ---------------------------
    py_metrics_seen: Dict[str, set] = {}
    for emitter, path in files:
        for kind, name, line in _metric_lookups(path):
            loc = f"{path.name}:{line}"
            if name not in metrics:
                errors.append(f"{loc}: metric {name!r} not in manifest")
                continue
            want, emitters = metrics[name]
            if kind != want:
                errors.append(
                    f"{loc}: metric {name!r} looked up as {kind}, "
                    f"manifest says {want}"
                )
            if emitter not in emitters:
                errors.append(
                    f"{loc}: {emitter} is not a manifest emitter of {name!r}"
                )
            py_metrics_seen.setdefault(emitter, set()).add(name)
    # ConsensusSpans (utils/metrics.py, wired into server.py) records the
    # phase histograms through the PHASE_HISTOGRAMS table rather than
    # string literals — credit those to server.py from the manifest table
    # itself (drift there is drift in the manifest, not the emitter).
    py_metrics_seen.setdefault("server.py", set()).update(
        trace_schema.PHASE_HISTOGRAMS.values()
    )
    for name, (kind, emitters) in metrics.items():
        for emitter in emitters & set(PY_EMITTERS):
            if name not in py_metrics_seen.get(emitter, set()):
                errors.append(
                    f"{emitter}: manifest metric {name!r} is never recorded"
                )

    # -- generalized sweep: unregistered metric names anywhere -----------------
    # Only pbft_-prefixed literals are considered (collections.Counter and
    # friends share the method names); declared emitters were already held
    # to the stricter emitter/type contract above.
    for path in _sweep_files(root):
        try:
            lookups = _metric_lookups(path)
        except SyntaxError as exc:
            errors.append(f"{path.name}: unparseable: {exc}")
            continue
        for kind, name, line in lookups:
            if not name.startswith("pbft_"):
                continue
            rel = path.relative_to(root)
            if name not in metrics:
                errors.append(
                    f"{rel}:{line}: metric {name!r} not in manifest")
            elif metrics[name][0] != kind:
                errors.append(
                    f"{rel}:{line}: metric {name!r} looked up as {kind}, "
                    f"manifest says {metrics[name][0]}")

    # -- C++ trace events (net.cc) ------------------------------------------
    cc = (root / NET_CC).read_text()
    cc_events = set(re.findall(r'\\"ev\\":\\"(\w+)\\"', cc))
    want_cc = {n for n, s in schemas.items() if "net.cc" in s["emitters"]}
    for name in cc_events - want_cc:
        errors.append(f"net.cc: event {name!r} not a manifest net.cc event")
    for name in want_cc - cc_events:
        errors.append(f"net.cc: manifest event {name!r} is never emitted")
    cc_fields = set(re.findall(r'\\"(\w+)\\":', cc))
    known_cc_fields = set()
    for name in want_cc:
        known_cc_fields |= schemas[name]["required"] | schemas[name]["optional"]
    for f in cc_fields - known_cc_fields - cc_events:
        errors.append(f"net.cc: JSON field {f!r} not in any net.cc event schema")
    for name in want_cc:
        for f in schemas[name]["required"] - {"ts", "ev"}:
            # consensus_span assembles its optional-phase fields from a
            # plain string-literal names array, so accept either the
            # \"field\": format-string token or a bare "field" literal.
            if f not in cc_fields and f'"{f}"' not in cc:
                errors.append(
                    f"net.cc: required field {f!r} of event {name!r} "
                    "never appears in a format string"
                )

    # -- C++ metric name tables + buckets (metrics.cc) -----------------------
    mc = (root / METRICS_CC).read_text()

    def array_strings(var):
        m = re.search(re.escape(var) + r"\[\]\s*=\s*\{(.*?)\};", mc, re.S)
        return re.findall(r'"([^"]+)"', m.group(1)) if m else None

    want_native = {
        kind: {n for n, (k, em) in metrics.items() if k == kind and "net.cc" in em}
        for kind in ("counter", "gauge", "histogram")
    }
    for var, kind in (
        ("kCounterNames", "counter"),
        ("kGaugeNames", "gauge"),
        ("kHistogramNames", "histogram"),
    ):
        got = array_strings(var)
        if got is None:
            errors.append(f"metrics.cc: table {var} not found")
            continue
        if set(got) != want_native[kind]:
            errors.append(
                f"metrics.cc: {var} = {sorted(got)} != manifest {kind}s "
                f"{sorted(want_native[kind])}"
            )

    def array_numbers(var):
        m = re.search(re.escape(var) + r"\s*=\s*\{(.*?)\};", mc, re.S)
        if not m:
            return None
        return [float(x) for x in re.findall(r"[0-9.]+", m.group(1))]

    for var, want in (
        ("kLatencyBuckets", list(trace_schema.LATENCY_BUCKETS_S)),
        ("kSizeBuckets", [float(x) for x in trace_schema.BATCH_SIZE_BUCKETS]),
    ):
        got = array_numbers(var)
        if got != want:
            errors.append(f"metrics.cc: {var} = {got} != manifest {want}")

    # -- phase names in both replicas ----------------------------------------
    for path, pattern in (
        (root / PY_REPLICA, r'hook\("(\w+)"'),
        (root / CC_REPLICA, r'phase_hook\("(\w+)"'),
    ):
        got = set(re.findall(pattern, path.read_text()))
        if got != set(trace_schema.PHASES):
            errors.append(
                f"{path.name}: phase_hook phases {sorted(got)} != manifest "
                f"PHASES {sorted(trace_schema.PHASES)}"
            )
    return errors
