"""Static-analysis passes over BOTH runtimes (ISSUE 8 tentpole).

The codebase is two concurrent implementations of one protocol — a C++
core and an asyncio runtime — held together by hand-mirrored constants
and a shared metrics/trace manifest. Runtime fuzz (test_wire_codec.py)
guards the dynamic behavior; this package is the static complement:

    constants       cross-runtime constant conformance (wire magic,
                    message tags, protocol versions, config defaults,
                    RLC window, pad ladder, status handshake)
    async-blocking  no blocking calls inside ``async def`` in pbft_tpu/net
    metrics         every metric/trace emitter matches the manifest
                    (generalized successor of scripts/check_trace_schema)
    sockets         TCP_NODELAY / SO_REUSEADDR at every stream-socket
                    creation site in both runtimes (ISSUE 10)

Entry point: ``scripts/pbft_lint.py`` (wired into tier-1 by
tests/test_lint.py). Every pass takes a ``root`` so the tests can run
them against shadow trees with deliberate violations.
"""

from __future__ import annotations

import pathlib
from typing import Callable, Dict, List

from . import async_blocking, constants, metrics_lint, sockets

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

PASSES: Dict[str, Callable[[pathlib.Path], List[str]]] = {
    "constants": constants.check,
    "async-blocking": async_blocking.check,
    "metrics": metrics_lint.check,
    "sockets": sockets.check,
}


def run_all(root: pathlib.Path = REPO, passes=None) -> Dict[str, List[str]]:
    """pass name -> error list (empty = clean). Unknown names raise."""
    selected = list(PASSES) if passes is None else list(passes)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        raise ValueError(f"unknown passes {unknown}; have {sorted(PASSES)}")
    return {name: PASSES[name](root) for name in selected}


def scanned_files(root: pathlib.Path = REPO) -> List[pathlib.Path]:
    """Every file any pass reads, absolute, deduplicated — the set a
    shadow tree (tests/test_lint.py) must copy for all passes to run."""
    paths = [root / rel for rel in constants.files_scanned()]
    paths += async_blocking.files_scanned(root)
    paths += metrics_lint.files_scanned(root)
    paths += sockets.files_scanned(root)
    out, seen = [], set()
    for p in paths:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out
