"""Cluster-health detectors over timestamped snapshot sequences (ISSUE 16).

Pure functions — no sockets, no clocks. The input is a *history*: a list
of snapshots, each ``{"t": <seconds, monotonic-ish float>, "replicas":
{<rid>: <health document>}}`` where the health document is the dict both
runtimes serve on ``/status`` (core/net.cc ``metrics_json`` /
pbft_tpu/net/server.py ``metrics()``; shape stamped by
``health_version``). Collectors — ``scripts/pbft_top.py``,
``scripts/endurance_soak.py``, the chaos harnesses' ``--health-gate`` —
build histories however they like (live HTTP polls, simulator state,
parsed logs) and hand them here, so every gate in the repo trips on the
same definitions.

Each detector returns a list of *verdicts* (empty = healthy):

    {"detector": <name>, "replica": <rid or None>,
     "reason": <one sentence>, "evidence": {<window facts>}}

The detectors (thresholds are parameters; the shared defaults are the
constants-lint-paired values mirrored by core/net.h):

silent-stall        pending work (verify inbox + sealed-but-unexecuted +
                    forwarded-but-unreplied requests) while executed_upto
                    stays flat for >= stall_seconds. This is the liveness
                    failure completion-pct can't see mid-run (Castro &
                    Liskov §4.5: a correct cluster must keep executing
                    while work pends).
resource-leak       robust positive slope (Theil-Sen median of pairwise
                    slopes) on rss_bytes / open_fds / wal_disk_bytes
                    after a warmup prefix, AND projected growth over the
                    window above an absolute floor — slope alone would
                    trip on allocator noise, floors alone on one big
                    transient.
divergence          two replicas report the same committed_upto with
                    different chain digests. The committed chain is
                    deterministic per sequence, so ANY mismatch at an
                    equal floor is a safety violation, not a lag.
stuck-view-change   in_view_change held across >= stall_seconds while the
                    view number never advances — the cluster is burning
                    timeouts without converging on a new primary.
queue-saturation    verify-inbox depth at or above a watermark for the
                    whole sustain window — upstream of a stall: work is
                    arriving faster than it can ever drain.

A resource reading of 0 means "no data" (/proc absent), never a
baseline; such points are skipped.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# Shared thresholds/defaults (constants lint pairs with core/net.h:
# kHealthStallSeconds, kHealthSnapshotIntervalS). The stall threshold is
# deliberately whole seconds: last-progress clocks on both runtimes are
# quantized to the observation cadence.
HEALTH_STALL_SECONDS = 5
HEALTH_SNAPSHOT_INTERVAL_S = 2

# Leak floors: the projected growth over the post-warmup window that
# turns a positive slope into a verdict. RSS breathes with allocator
# arenas and fds with transient dials; the WAL compacts at stable
# checkpoints so its steady-state file size is bounded, but one
# checkpoint interval of appends can sit on disk between compactions.
LEAK_RSS_FLOOR_BYTES = 48 << 20
LEAK_FDS_FLOOR = 16
LEAK_WAL_FLOOR_BYTES = 8 << 20

QUEUE_SATURATION_DEPTH = 512


def _verdict(detector: str, replica, reason: str, evidence: dict) -> dict:
    return {
        "detector": detector,
        "replica": replica,
        "reason": reason,
        "evidence": evidence,
    }


def _series(history: List[dict], rid, key) -> List[tuple]:
    """[(t, value)] for one replica's field across the history (snapshots
    where the replica or the field is missing are skipped — a dead or
    pre-v16 replica contributes no points, it does not zero-fill)."""
    out = []
    for snap in history:
        doc = snap.get("replicas", {}).get(rid)
        if doc is None or key not in doc:
            continue
        out.append((snap["t"], doc[key]))
    return out


def _rids(history: List[dict]) -> list:
    seen = {}
    for snap in history:
        for rid in snap.get("replicas", {}):
            seen[rid] = True
    return list(seen)


def theil_sen_slope(points: List[tuple]) -> Optional[float]:
    """Median of all pairwise slopes — one wild reading cannot fake (or
    hide) a trend, unlike least squares. None with < 2 usable points."""
    slopes = []
    for i in range(len(points)):
        t0, v0 = points[i]
        for t1, v1 in points[i + 1:]:
            if t1 == t0:
                continue
            slopes.append((v1 - v0) / (t1 - t0))
    if not slopes:
        return None
    slopes.sort()
    mid = len(slopes) // 2
    if len(slopes) % 2:
        return slopes[mid]
    return (slopes[mid - 1] + slopes[mid]) / 2.0


def _pending_work(doc: dict) -> int:
    """The replica-local evidence that SOMETHING should be executing.
    waiting_requests matters: with a muted primary, a backup's inbox
    drains (it verified and forwarded) while the request sits unreplied
    in its progress-timer map — that is exactly the silent stall."""
    return (
        int(doc.get("inbox_depth", 0))
        + int(doc.get("sealed_unexecuted", 0))
        + int(doc.get("waiting_requests", 0))
    )


def detect_silent_stall(
    history: List[dict], stall_seconds: float = HEALTH_STALL_SECONDS
) -> List[dict]:
    out = []
    for rid in _rids(history):
        exec_series = _series(history, rid, "executed_upto")
        if len(exec_series) < 2:
            continue
        # Scan for the longest suffix window with flat executed_upto and
        # pending work at every point in it (a momentarily-empty queue
        # resets the clock: the replica may simply be idle).
        window: List[tuple] = []  # (t, executed, pending)
        for snap in history:
            doc = snap.get("replicas", {}).get(rid)
            if doc is None or "executed_upto" not in doc:
                continue
            executed = doc["executed_upto"]
            pending = _pending_work(doc)
            if window and (executed != window[-1][1] or pending == 0):
                window = []
            window.append((snap["t"], executed, pending))
            if (
                len(window) >= 2
                and window[0][2] > 0
                and window[-1][0] - window[0][0] >= stall_seconds
            ):
                out.append(_verdict(
                    "silent-stall", rid,
                    "pending work with executed_upto flat for "
                    f"{window[-1][0] - window[0][0]:.1f}s",
                    {
                        "executed_upto": executed,
                        "pending": pending,
                        "flat_seconds": round(window[-1][0] - window[0][0], 3),
                        "window_start_t": window[0][0],
                        "window_end_t": window[-1][0],
                    },
                ))
                break  # one verdict per replica
    return out


def detect_resource_leak(
    history: List[dict],
    warmup_frac: float = 0.25,
    min_points: int = 6,
    floors: Optional[Dict[str, float]] = None,
) -> List[dict]:
    if floors is None:
        floors = {
            "rss_bytes": LEAK_RSS_FLOOR_BYTES,
            "open_fds": LEAK_FDS_FLOOR,
            "wal_disk_bytes": LEAK_WAL_FLOOR_BYTES,
        }
    out = []
    for rid in _rids(history):
        for key, floor in floors.items():
            pts = [(t, v) for t, v in _series(history, rid, key) if v > 0]
            if len(pts) < min_points:
                continue
            pts = pts[int(len(pts) * warmup_frac):]  # drop warmup prefix
            if len(pts) < 2:
                continue
            span = pts[-1][0] - pts[0][0]
            if span <= 0:
                continue
            slope = theil_sen_slope(pts)
            if slope is None or slope <= 0:
                continue
            projected = slope * span
            if projected < floor:
                continue
            out.append(_verdict(
                "resource-leak", rid,
                f"{key} climbing ~{slope:.1f}/s over {span:.0f}s "
                f"(projected +{projected:.0f} > floor {floor:.0f})",
                {
                    "metric": key,
                    "slope_per_s": slope,
                    "window_seconds": round(span, 3),
                    "projected_growth": round(projected, 1),
                    "floor": floor,
                    "first": pts[0][1],
                    "last": pts[-1][1],
                },
            ))
    return out


def detect_divergence(history: List[dict]) -> List[dict]:
    out = []
    reported = set()  # (rid_a, rid_b, seq) pairs already verdicted
    for snap in history:
        docs = snap.get("replicas", {})
        by_floor: Dict[int, list] = {}
        for rid, doc in docs.items():
            if "chain_digest" not in doc:
                continue
            floor = doc.get("committed_upto", 0)
            if floor > 0:
                by_floor.setdefault(floor, []).append((rid, doc["chain_digest"]))
        for floor, entries in by_floor.items():
            digests = {}
            for rid, digest in entries:
                digests.setdefault(digest, []).append(rid)
            if len(digests) <= 1:
                continue
            groups = sorted(digests.items(), key=lambda kv: -len(kv[1]))
            key = (floor, tuple(sorted(r for _, rids in groups for r in rids)))
            if key in reported:
                continue
            reported.add(key)
            out.append(_verdict(
                "divergence", None,
                f"chain digests disagree at committed_upto={floor}",
                {
                    "committed_upto": floor,
                    "t": snap["t"],
                    "groups": [
                        {"chain_digest": d, "replicas": sorted(map(str, rs))}
                        for d, rs in groups
                    ],
                },
            ))
    return out


def detect_stuck_view_change(
    history: List[dict], stall_seconds: float = HEALTH_STALL_SECONDS
) -> List[dict]:
    out = []
    for rid in _rids(history):
        window: List[tuple] = []  # (t, view)
        for snap in history:
            doc = snap.get("replicas", {}).get(rid)
            if doc is None or "in_view_change" not in doc:
                continue
            if not doc["in_view_change"]:
                window = []
                continue
            view = doc.get("view", 0)
            if window and view != window[-1][1]:
                window = []  # the view DID move: progress, restart clock
            window.append((snap["t"], view))
            if (
                len(window) >= 2
                and window[-1][0] - window[0][0] >= stall_seconds
            ):
                out.append(_verdict(
                    "stuck-view-change", rid,
                    "in view change without installing for "
                    f"{window[-1][0] - window[0][0]:.1f}s",
                    {
                        "view": view,
                        "stuck_seconds": round(window[-1][0] - window[0][0], 3),
                        "window_start_t": window[0][0],
                    },
                ))
                break
    return out


def detect_queue_saturation(
    history: List[dict],
    depth: int = QUEUE_SATURATION_DEPTH,
    sustain_seconds: float = HEALTH_STALL_SECONDS,
) -> List[dict]:
    out = []
    for rid in _rids(history):
        window: List[tuple] = []  # (t, depth)
        for snap in history:
            doc = snap.get("replicas", {}).get(rid)
            if doc is None or "inbox_depth" not in doc:
                continue
            if doc["inbox_depth"] < depth:
                window = []
                continue
            window.append((snap["t"], doc["inbox_depth"]))
            if (
                len(window) >= 2
                and window[-1][0] - window[0][0] >= sustain_seconds
            ):
                out.append(_verdict(
                    "queue-saturation", rid,
                    f"verify inbox >= {depth} for "
                    f"{window[-1][0] - window[0][0]:.1f}s",
                    {
                        "depth": window[-1][1],
                        "watermark": depth,
                        "sustained_seconds": round(
                            window[-1][0] - window[0][0], 3
                        ),
                    },
                ))
                break
    return out


ALL_DETECTORS = (
    detect_silent_stall,
    detect_resource_leak,
    detect_divergence,
    detect_stuck_view_change,
    detect_queue_saturation,
)


def run_detectors(
    history: List[dict],
    stall_seconds: float = HEALTH_STALL_SECONDS,
    leak_floors: Optional[Dict[str, float]] = None,
    saturation_depth: int = QUEUE_SATURATION_DEPTH,
) -> List[dict]:
    """All detectors over one history; the concatenated verdicts (empty =
    healthy). The shared thresholds fan out to each detector's knob."""
    verdicts: List[dict] = []
    verdicts += detect_silent_stall(history, stall_seconds=stall_seconds)
    verdicts += detect_resource_leak(history, floors=leak_floors)
    verdicts += detect_divergence(history)
    verdicts += detect_stuck_view_change(history, stall_seconds=stall_seconds)
    verdicts += detect_queue_saturation(
        history, depth=saturation_depth, sustain_seconds=stall_seconds
    )
    return verdicts
