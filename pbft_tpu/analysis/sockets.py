"""Socket-option conformance lint (ISSUE 10 satellite).

Every TCP stream socket in BOTH runtimes must be tuned at creation:
``TCP_NODELAY`` on data sockets (one Nagle stall on a 100-byte consensus
frame dwarfs a whole round) and ``SO_REUSEADDR`` on listeners (restart
races). The PR 10 audit fixed every site; this pass keeps a NEW dial or
accept site from silently regressing latency:

- C++ (core library sources, test drivers excluded): every
  ``socket(AF_INET, SOCK_STREAM...)`` creation and every ``accept(``
  call must be followed, within a few lines, by a call to
  ``tune_stream_socket`` / ``tune_listen_socket`` (core/net.h) — the two
  canonical spellings of the options. AF_UNIX and SOCK_DGRAM sockets are
  exempt (no Nagle / not streams).
- Python (pbft_tpu/net): every ``socket.create_connection(`` and every
  ``socket.socket(..., SOCK_STREAM)`` must be followed, within a few
  lines, by a ``TCP_NODELAY`` setsockopt (asyncio transports set it
  automatically since 3.6, so only raw-socket sites are scanned).
  ``socketserver`` handlers spell it ``disable_nagle_algorithm = True``
  or set the option in ``setup`` — both count, same window.

Like every pass here, reads relative to ``root`` so tests/test_lint.py
can run it against a shadow tree with a deliberately untuned site.
"""

from __future__ import annotations

import pathlib
import re
from typing import List

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

# Library sources only: the test drivers (core_test.cc, race_stress.cc)
# open throwaway loopback sockets where a missed option costs nothing.
CXX_FILES = [
    "core/net.cc",
    "core/verifier.cc",
    "core/secure.cc",
    "core/pbftd.cc",
    "core/discovery.cc",
]
PY_GLOB = "pbft_tpu/net/*.py"

# How many lines after the creation site the tuning call must appear in.
WINDOW = 8

_CXX_STREAM_SOCKET = re.compile(r"socket\s*\(\s*AF_INET\s*,\s*SOCK_STREAM")
_CXX_ACCEPT = re.compile(r"=\s*(?:::)?accept\s*\(")
_CXX_TUNE = re.compile(r"tune_(?:stream|listen)_socket\s*\(")

_PY_DIAL = re.compile(r"socket\.create_connection\s*\(")
_PY_STREAM_SOCKET = re.compile(r"socket\.socket\s*\([^)\n]*SOCK_STREAM")
_PY_TUNE = re.compile(r"TCP_NODELAY|disable_nagle_algorithm\s*=\s*True")


def _window_ok(lines: List[str], i: int, tune: re.Pattern) -> bool:
    return any(tune.search(line) for line in lines[i : i + WINDOW + 1])


def files_scanned(root: pathlib.Path = REPO) -> List[pathlib.Path]:
    out = [root / p for p in CXX_FILES]
    out += sorted(root.glob(PY_GLOB))
    return [p for p in out if p.exists()]


def check(root: pathlib.Path = REPO) -> List[str]:
    errors: List[str] = []
    for rel in CXX_FILES:
        path = root / rel
        if not path.exists():
            continue
        lines = path.read_text().splitlines()
        for i, line in enumerate(lines):
            site = None
            if _CXX_STREAM_SOCKET.search(line):
                site = "stream socket()"
            elif _CXX_ACCEPT.search(line) and "AF_UNIX" not in line:
                site = "accept()"
            if site and not _window_ok(lines, i, _CXX_TUNE):
                errors.append(
                    f"{rel}:{i + 1}: {site} without "
                    f"tune_stream_socket/tune_listen_socket within "
                    f"{WINDOW} lines (ISSUE 10 socket discipline)"
                )
    for path in sorted(root.glob(PY_GLOB)):
        rel = path.relative_to(root)
        lines = path.read_text().splitlines()
        for i, line in enumerate(lines):
            site = None
            if _PY_DIAL.search(line):
                site = "socket.create_connection"
            elif _PY_STREAM_SOCKET.search(line) and "AF_UNIX" not in line:
                site = "stream socket.socket"
            if site and not _window_ok(lines, i, _PY_TUNE):
                errors.append(
                    f"{rel}:{i + 1}: {site} without TCP_NODELAY within "
                    f"{WINDOW} lines (ISSUE 10 socket discipline)"
                )
    return errors
