"""Structured JSONL tracing for the replica runtimes.

Events are single JSON lines: {"ts": <monotonic>, "ev": <name>, ...fields}.
Disabled (no-op, one attribute check) unless a sink is set — tracing must
never tax the batching hot loop the way the reference's println!-in-poll
did (reference src/handler.rs:265,:269; SURVEY.md §5).
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Optional


class Tracer:
    def __init__(self, sink: Optional[IO[str]] = None):
        self.sink = sink
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.sink is not None

    def event(self, ev: str, **fields) -> None:
        if self.sink is None:
            return
        rec = {"ts": round(time.monotonic(), 6), "ev": ev}
        rec.update(fields)
        # default=str: a non-JSON-serializable field value (a stray bytes
        # digest, an enum, a numpy scalar) degrades to its str() form
        # instead of throwing in the batching hot loop.
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        with self._lock:
            self.sink.write(line)
            self.sink.flush()


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def set_trace_file(path: Optional[str]) -> Tracer:
    """Route global tracing to a JSONL file (None disables); closes any
    previously set sink. Raises OSError if the file cannot be opened."""
    global _tracer
    old_sink = _tracer.sink
    _tracer = Tracer(open(path, "a") if path else None)
    if old_sink is not None:
        old_sink.close()
    return _tracer
