"""pbft_tpu.utils — structured logging / tracing / metrics.

The reference's observability was ~110 println! calls, several inside the
poll hot loop (SURVEY.md §5 — a real throughput hazard); here tracing is
structured JSONL events behind a level check, off by default, and never
in the per-signature hot path (batch boundaries only), and metrics are a
Prometheus-style registry with the same one-attribute-check-when-disabled
discipline (utils/metrics.py). Event/metric names are contracted across
both runtimes by utils/trace_schema.py.
"""

from .flight import FlightRecorder
from .metrics import (
    ConsensusSpans,
    MetricsRegistry,
    count_open_fds,
    file_size_bytes,
    read_rss_bytes,
    start_metrics_server,
)
from .trace import Tracer, get_tracer, set_trace_file

__all__ = [
    "ConsensusSpans",
    "FlightRecorder",
    "MetricsRegistry",
    "Tracer",
    "count_open_fds",
    "file_size_bytes",
    "get_tracer",
    "read_rss_bytes",
    "set_trace_file",
    "start_metrics_server",
]
