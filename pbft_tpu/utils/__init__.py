"""pbft_tpu.utils — structured logging / tracing.

The reference's observability was ~110 println! calls, several inside the
poll hot loop (SURVEY.md §5 — a real throughput hazard); here tracing is
structured JSONL events behind a level check, off by default, and never
in the per-signature hot path (batch boundaries only).
"""

from .trace import Tracer, get_tracer, set_trace_file

__all__ = ["Tracer", "get_tracer", "set_trace_file"]
