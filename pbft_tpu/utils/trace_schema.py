"""THE schema manifest for trace events and metrics — single source of truth.

Both runtimes (pbft_tpu/net/server.py + net/service.py in Python,
core/net.cc in C++) emit JSONL trace events and Prometheus metrics whose
names and field sets must stay identical, or a mixed-runtime cluster's
traces stop merging and its scrapes stop aggregating. This module is the
contract; scripts/check_trace_schema.py lints every emitter against it
(wired into tier-1 via tests/test_trace_schema.py), and core/metrics.cc
mirrors the metric table (checked by the same lint).

Event schema entries:
    required  fields every event of this name must carry
    optional  fields an emitter may add
    emitters  the source files allowed to emit this event name

Changing an event or metric here without updating every emitter (or vice
versa) fails the lint — that is the point.
"""

from __future__ import annotations

# -- trace events (JSONL lines: {"ts": .., "ev": <name>, ...fields}) --------

EVENT_SCHEMAS = {
    "verify_batch": {
        "required": {"ts", "ev", "replica", "size", "rejected", "secs"},
        "optional": {"view", "executed", "requests"},
        "emitters": {"server.py", "service.py", "net.cc"},
    },
    "verify_window_failed": {
        "required": {"ts", "ev", "replica", "size", "requests", "rejected", "secs"},
        "optional": set(),
        "emitters": {"service.py"},
    },
    "verify_batch_error": {
        "required": {"ts", "ev", "replica", "size", "secs"},
        "optional": set(),
        "emitters": {"service.py"},
    },
    "view_change_start": {
        "required": {"ts", "ev", "replica", "pending_view", "backoff"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    # One span per executed (view, seq): absolute monotonic stamps for each
    # consensus phase this replica observed. "request" is primary-only (a
    # backup's first sighting is the pre-prepare); stamps are comparable
    # across processes on one host (CLOCK_MONOTONIC is per-boot).
    "consensus_span": {
        "required": {"ts", "ev", "replica", "view", "seq", "pre_prepare", "executed"},
        "optional": {"request", "prepared", "committed"},
        "emitters": {"server.py", "net.cc"},
    },
    # The wedged-async-verifier bound (ADVICE.md core/net.cc item): the
    # inflight launch overran its deadline, the connection was dropped and
    # the batch re-verified on the CPU safety net.
    "verify_deadline_fired": {
        "required": {"ts", "ev", "replica", "size", "age_secs"},
        "optional": set(),
        "emitters": {"net.cc"},
    },
    # -- request-level latency waterfall (ISSUE 9) --------------------------
    #
    # Requests are uniquely keyed by (client, req_ts) and batches by
    # (view, seq); batch_sealed carries the [client, req_ts] pairs it
    # sealed, so client-side send/recv stamps join to replica-side
    # consensus spans purely in post-processing — zero wire changes
    # (scripts/consensus_timeline.py --waterfall).
    "request_rx": {
        "required": {"ts", "ev", "replica", "client", "req_ts"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    # The primary sealed its open batch under a sequence number. wait_s is
    # how long the first request sat in the open batch (the "batch wait"
    # waterfall segment); reqs is the ordered [[client, req_ts], ...] join
    # key list.
    "batch_sealed": {
        "required": {"ts", "ev", "replica", "view", "seq", "batch", "wait_s"},
        "optional": {"reqs"},
        "emitters": {"server.py", "net.cc"},
    },
    "reply_tx": {
        "required": {"ts", "ev", "replica", "client", "req_ts", "view"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    # -- view-change spans (ROADMAP item 4) ---------------------------------
    #
    # view_timer_fired (the runtime's progress timer expired) ->
    # view_change_sent (the replica broadcast VIEW-CHANGE toward
    # pending_view) -> new_view_installed (it entered the view). Ordering
    # is machine-checked by consensus_timeline.py --check-invariants
    # (consensus/invariants.py check_view_events).
    "view_timer_fired": {
        "required": {"ts", "ev", "replica", "view", "backoff"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    "view_change_sent": {
        "required": {"ts", "ev", "replica", "pending_view"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    "new_view_installed": {
        "required": {"ts", "ev", "replica", "view"},
        "optional": set(),
        "emitters": {"server.py", "net.cc"},
    },
    # Client-side half of the waterfall (net/client.py write_trace): send /
    # first-reply / f+1-quorum monotonic stamps per (client, req_ts).
    # Comparable to replica stamps on one host (CLOCK_MONOTONIC).
    # "overloaded" counts explicit admission-control rejections the client
    # absorbed for this request (ISSUE 12) — distinct from silent timeouts.
    "client_request": {
        "required": {"ts", "ev", "client", "req_ts", "send"},
        "optional": {"first_reply", "quorum", "overloaded"},
        "emitters": {"client.py"},
    },
}

# -- metrics (Prometheus text format at --metrics-port) ---------------------
#
# name -> (type, emitters). Replica runtimes (server.py, net.cc) must emit
# the full replica set with IDENTICAL names so a mixed-runtime cluster
# scrapes uniformly; the verifier service emits the verify subset.

METRIC_SCHEMAS = {
    "pbft_frames_in_total": ("counter", {"server.py", "net.cc"}),
    "pbft_executed_total": ("counter", {"server.py", "net.cc"}),
    "pbft_view_changes_total": ("counter", {"server.py", "net.cc"}),
    "pbft_verify_batches_total": ("counter", {"server.py", "service.py", "net.cc"}),
    "pbft_verify_items_total": ("counter", {"server.py", "service.py", "net.cc"}),
    "pbft_verify_rejected_total": ("counter", {"server.py", "service.py", "net.cc"}),
    "pbft_verify_deadline_fired_total": ("counter", {"net.cc"}),
    "pbft_verify_queue_depth": ("gauge", {"server.py", "service.py", "net.cc"}),
    "pbft_verify_inflight_age_seconds": ("gauge", {"server.py", "service.py", "net.cc"}),
    # Native verify-pool surface (core/verify_pool.cc): pool width, windows
    # queued by the last dispatch, lifetime busy/(wall*threads) ratio, and
    # the per-dispatch RLC window width. C++ runtime only — the Python
    # replica's parallelism lives in the JAX mesh, not a thread pool.
    "pbft_verify_pool_threads": ("gauge", {"net.cc"}),
    "pbft_verify_pool_queue_depth": ("gauge", {"net.cc"}),
    "pbft_verify_pool_utilization": ("gauge", {"net.cc"}),
    "pbft_verify_pool_window_size": ("histogram", {"net.cc"}),
    # Wire-codec surface (ISSUE 3): outbound frames per payload codec,
    # plus the serialize-once invariant counter — encodes are counted per
    # BROADCAST (lazy, at most once per codec), never per peer, so in a
    # single-codec cluster pbft_broadcast_encodes_total tracks the
    # broadcast count instead of broadcasts x peers.
    "pbft_codec_binary_frames_total": ("counter", {"server.py", "net.cc"}),
    "pbft_codec_json_frames_total": ("counter", {"server.py", "net.cc"}),
    "pbft_broadcast_encodes_total": ("counter", {"server.py", "net.cc"}),
    # Batching surface (ISSUE 4): requests executed vs three-phase
    # instances executed (their ratio is the batch amplification), and
    # the per-accepted-pre-prepare batch occupancy histogram. Note
    # pbft_executed_total counts per SEQUENCE (span closes), so it tracks
    # pbft_consensus_rounds_total, not requests.
    "pbft_requests_executed_total": ("counter", {"server.py", "net.cc"}),
    "pbft_consensus_rounds_total": ("counter", {"server.py", "net.cc"}),
    # Chaos/fault-injection surface (ISSUE 5): behaviors the --fault mode
    # actually fired (corrupted signatures, equivocating pre-prepares,
    # muted sends, stutter replays) and outbound frames the seeded
    # --chaos-drop-pct link dropped. Both zero on a healthy replica — a
    # nonzero value in production is an alarm, in a chaos test it is the
    # proof the injection ran.
    "pbft_faults_injected_total": ("counter", {"server.py", "net.cc"}),
    "pbft_chaos_dropped_total": ("counter", {"server.py", "net.cc"}),
    # Persistent verify-service surface (ISSUE 7): XLA launches the
    # coalescing dispatcher actually shipped, items per launch window,
    # and how many client connections each merged window carried. The
    # warm/cold compile gauges record the once-per-deploy startup cost
    # (cold = traced+compiled shapes, warm = serialized-executable or
    # cache reloads) so the bench can report it OUTSIDE the timed
    # region. Registered in core/metrics.cc too (eager registration:
    # every runtime exposes the same series set, zero-valued where the
    # lifecycle can't happen).
    "pbft_verify_service_launches_total": (
        "counter",
        {"service.py", "net.cc"},
    ),
    "pbft_verify_service_window_size": (
        "histogram",
        {"service.py", "net.cc"},
    ),
    "pbft_verify_service_coalesced_clients": (
        "histogram",
        {"service.py", "net.cc"},
    ),
    "pbft_verify_service_cold_compile_seconds": (
        "gauge",
        {"verify_service.py", "net.cc"},
    ),
    "pbft_verify_service_warm_compile_seconds": (
        "gauge",
        {"verify_service.py", "net.cc"},
    ),
    # Scale-out surface (ISSUE 10). Replica side: live sockets, event-loop
    # readiness wakeups (epoll_wait/poll returns in C++; stream read
    # completions in asyncio), bounded-outbound drops + partial-write
    # backpressure episodes, and client requests received over gateway
    # links. Gateway side (pbft_tpu/net/gateway.py): downstream client
    # connections open and requests forwarded upstream — the tier's
    # multiplexing ratio is gateway_clients_open vs the replicas'
    # connections_open.
    "pbft_connections_open": ("gauge", {"server.py", "net.cc"}),
    "pbft_epoll_wakeups_total": ("counter", {"server.py", "net.cc"}),
    "pbft_write_backpressure_events_total": (
        "counter",
        {"server.py", "net.cc", "gateway.py"},
    ),
    "pbft_gateway_clients_open": ("gauge", {"gateway.py"}),
    "pbft_gateway_forwarded_total": (
        "counter",
        {"gateway.py", "server.py", "net.cc"},
    ),
    # Perf-under-faults surface (ISSUE 12). Backoff level: the view
    # timer's current exponential multiplier (1 = fresh, doubles per
    # consecutive no-progress expiry, §4.5.2) — a sustained high level is
    # a cluster failing to converge. Overload rejections: client requests
    # answered with an explicit {"type":"overloaded"} instead of being
    # queued into the tail (admission control: per-client in-flight caps
    # + the global backlog watermark; gateway and both replica runtimes).
    # Gateway failovers: a gateway-fabric link had to be replaced — a
    # client failing over to another gateway (GatewayClient), a gateway
    # re-dialing a dead replica link (ClientGateway), or a replica losing
    # a live gateway link (both runtimes).
    "pbft_view_timer_backoff_level": ("gauge", {"server.py", "net.cc"}),
    # Multi-core surface (ISSUE 13). Loop threads: event-loop shards the
    # replica runs (pbftd net_threads; always 1 on the single-loop
    # asyncio runtime). Offload depth: aggregate occupancy of the
    # per-shard crypto-pipeline queues (AEAD seal/open + codec work held
    # off the loop threads). Cross-thread wakes: eventfd/pipe wakes
    # crossing the loop-shard / crypto-pipeline / consensus boundaries —
    # the handoff cost the sharding pays for its parallelism. The asyncio
    # runtime emits the latter two as zeros for series-set parity.
    "pbft_net_loop_threads": ("gauge", {"server.py", "net.cc"}),
    "pbft_crypto_offload_queue_depth": ("gauge", {"server.py", "net.cc"}),
    "pbft_cross_thread_wakes_total": ("counter", {"server.py", "net.cc"}),
    "pbft_overload_rejections_total": (
        "counter",
        {"gateway.py", "server.py", "net.cc"},
    ),
    "pbft_gateway_failovers_total": (
        "counter",
        {"gateway.py", "server.py", "net.cc"},
    ),
    # Fast-path surface (ISSUE 14, protocol 1.3.0). MAC frames: outbound
    # normal-case frames authenticated by a per-link session-MAC vector
    # instead of hot-path signature verification (zero in signature mode
    # and against pre-1.3.0 peers). Tentative executions: sequences
    # executed at PREPARED (one commit round-trip early); rollbacks:
    # tentative sequences undone by a view change / certified-checkpoint
    # catch-up — nonzero rollbacks with zero client-visible divergence is
    # exactly the §5.3 story the chaos matrix checks.
    "pbft_mac_frames_total": ("counter", {"server.py", "net.cc"}),
    "pbft_tentative_executions_total": ("counter", {"server.py", "net.cc"}),
    "pbft_tentative_rollbacks_total": ("counter", {"server.py", "net.cc"}),
    # Durable-recovery surface (ISSUE 15). WAL appends: records written
    # to the write-ahead log (votes, view transitions, stable
    # checkpoints); fsyncs: group-commit fsync syscalls (one per emit
    # boundary with pending records — NOT one per message; zero with
    # wal_fsync off); bytes: file bytes written (appends + compactions).
    # Recovery seconds: wall time of the last WAL replay + state
    # reinstall (gauge; 0 = this life started fresh).
    "pbft_wal_appends_total": ("counter", {"server.py", "net.cc"}),
    "pbft_wal_fsyncs_total": ("counter", {"server.py", "net.cc"}),
    "pbft_wal_bytes_total": ("counter", {"server.py", "net.cc"}),
    "pbft_recovery_seconds": ("gauge", {"server.py", "net.cc"}),
    # Health-introspection surface (ISSUE 16). Resource gauges a soak can
    # gate flat: resident set (/proc/self/statm x page size), open file
    # descriptors (/proc/self/fd entries), and the WAL file's on-disk
    # byte size (0 with WAL off). Progress gauges a stall detector can
    # watch: seconds since executed_upto last advanced (as observed at
    # scrape/refresh time) and the verify-inbox depth. All five refresh
    # lazily when the status/metrics surface is rendered — a dead-idle
    # replica pays nothing for them.
    "pbft_process_rss_bytes": ("gauge", {"server.py", "net.cc"}),
    "pbft_open_fds": ("gauge", {"server.py", "net.cc"}),
    "pbft_wal_disk_bytes": ("gauge", {"server.py", "net.cc"}),
    "pbft_last_progress_seconds": ("gauge", {"server.py", "net.cc"}),
    "pbft_inbox_depth": ("gauge", {"server.py", "net.cc"}),
    "pbft_batch_size": ("histogram", {"server.py", "net.cc"}),
    "pbft_verify_batch_size": ("histogram", {"server.py", "service.py", "net.cc"}),
    "pbft_verify_seconds": ("histogram", {"server.py", "service.py", "net.cc"}),
    "pbft_phase_pre_prepare_seconds": ("histogram", {"server.py", "net.cc"}),
    "pbft_phase_prepare_seconds": ("histogram", {"server.py", "net.cc"}),
    "pbft_phase_commit_seconds": ("histogram", {"server.py", "net.cc"}),
    "pbft_phase_reply_seconds": ("histogram", {"server.py", "net.cc"}),
    "pbft_request_reply_seconds": ("histogram", {"server.py", "net.cc"}),
}

# Fixed histogram bucket upper edges (le semantics: v <= edge). Shared by
# both runtimes — core/metrics.cc mirrors these values; the lint compares.
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# The consensus phases in protocol order. "request" exists only on the
# primary (it assigns the sequence number); every replica sees the rest.
PHASES = ("request", "pre_prepare", "prepared", "committed", "executed")

# -- black-box flight recorder (ISSUE 9) -------------------------------------
#
# Both runtimes keep a fixed-size ring of compact binary records
# (core/flight.{h,cc} lock-free atomics; pbft_tpu/utils/flight.py a
# bounded deque) dumped to a file on SIGTERM/fatal/invariant-failure and
# decoded by scripts/flight_dump.py. The on-disk format is shared:
#
#   header  FLIGHT_MAGIC (8B) + u32le version + u32le record count
#   record  u64le t_ns, u16le event id, i16le peer, i32le view, i32le seq
#
# Event ids are the cross-runtime contract below; core/flight.h mirrors
# them (enum FlightEvent). The "request" consensus phase records as
# batch_sealed (the primary's sequence assignment IS the seal).
FLIGHT_MAGIC = b"PBFTBBX1"
FLIGHT_VERSION = 1
FLIGHT_RECORD_SIZE = 20
FLIGHT_EVENTS = {
    1: "request_rx",
    2: "batch_sealed",
    3: "pre_prepare",
    4: "prepared",
    5: "committed",
    6: "executed",
    7: "reply_tx",
    8: "view_timer_fired",
    9: "view_change_sent",
    10: "new_view_installed",
    11: "verify_batch",
    # Perf-under-faults coverage (ISSUE 12): the view timer's backoff
    # level changed (seq = new level), a client request was answered with
    # an explicit overload rejection (seq = request timestamp), and a
    # gateway-fabric link was replaced (peer = replica id / gateway index
    # where meaningful).
    12: "backoff_level",
    13: "overload_rejected",
    14: "gateway_failover",
    # Fast-path coverage (ISSUE 14): a reply left at PREPARED (seq = the
    # request timestamp), and a tentative-suffix rollback on view change
    # / certified-checkpoint catch-up (seq = sequences rolled back).
    15: "tentative_reply",
    16: "tentative_rollback",
    # Durable recovery (ISSUE 15): WAL replay began (view = persisted
    # view, seq = the stable-checkpoint floor) and recovery finished
    # (seq = the recovered executed_upto). core/flight.h mirrors the ids.
    17: "recovery_started",
    18: "recovery_complete",
}
FLIGHT_EVENT_IDS = {name: i for i, name in FLIGHT_EVENTS.items()}

# -- health document (ISSUE 16) ----------------------------------------------
#
# Both runtimes extend their metrics_json/metrics() status surface into a
# versioned health document: resource readings (rss_bytes, open_fds,
# wal_disk_bytes), progress watermarks (inbox_depth, sealed_unexecuted,
# waiting_requests, last_progress_seconds, uptime_seconds) and identity
# digests (chain_digest, state_digest) alongside the existing counters.
# health_version stamps the document shape so pbft_top and the detector
# library (pbft_tpu/analysis/health.py) can refuse snapshots from a
# runtime speaking a different schema. core/net.h mirrors the value
# (kHealthDocVersion — constants lint pair "health document version").
HEALTH_DOC_VERSION = 1

# phase-transition -> the latency histogram it feeds (observed at
# "executed" time from the span's stamps).
PHASE_HISTOGRAMS = {
    ("request", "pre_prepare"): "pbft_phase_pre_prepare_seconds",
    ("pre_prepare", "prepared"): "pbft_phase_prepare_seconds",
    ("prepared", "committed"): "pbft_phase_commit_seconds",
    ("committed", "executed"): "pbft_phase_reply_seconds",
}


def histogram_buckets(name: str):
    """The fixed bucket edges for a manifest histogram."""
    if METRIC_SCHEMAS[name][0] != "histogram":
        raise ValueError(f"{name} is not a histogram")
    if name in (
        "pbft_verify_batch_size",
        "pbft_verify_pool_window_size",
        "pbft_batch_size",
        "pbft_verify_service_window_size",
        "pbft_verify_service_coalesced_clients",
    ):
        return BATCH_SIZE_BUCKETS
    return LATENCY_BUCKETS_S
