"""Host-keyed persistent compile cache location.

XLA:CPU AOT cache entries embed the compiling machine's CPU features;
loading an entry compiled on a better-featured host only WARNS at load
time but can SIGILL at execution time. The multichip dryrun is the one
gate that must never flake, and its workspace (including `.jax_cache/`)
can move between hosts — so the cache directory is keyed by the host's
identity AND its CPU description: a foreign cache lands under a
different key and is simply never read. The cost of a key mismatch is a
cold recompile, never a crash.

Why both components (MULTICHIP_r05 postmortem): keying by the
`/proc/cpuinfo` feature flags alone was not enough — XLA's *target*
feature set is derived from the CPU model (e.g. `+prefer-no-gather` on
some microarchitectures), so two hosts can report byte-identical flag
lists yet compile incompatible AOT artifacts, and the r05 log duly
spewed `cpu_aot_loader` feature-mismatch warnings threatening SIGILL.
The key therefore folds in (a) a stable host id (`/etc/machine-id`,
falling back to the hostname) and (b) the machine type + CPU model name
+ feature flags. Same host, same kernel → same key → warm cache; any
move or CPU change → new key → cold but safe.

This module must stay importable without touching jax (bench.py and
__graft_entry__.py compute the cache path before backend init).
"""

from __future__ import annotations

import hashlib
import os
import platform


def _cpuinfo_fields(*names: str) -> str:
    """First occurrence of each named /proc/cpuinfo field, joined."""
    found = {n: "" for n in names}
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                key = line.split(":")[0].strip()
                if key in found and not found[key]:
                    found[key] = line.split(":", 1)[1].strip()
                if all(found.values()):
                    break
    except OSError:
        pass  # non-Linux: machine type + host id still separate real moves
    return "|".join(found[n] for n in names)


def _host_id() -> str:
    """A stable identifier for THIS host (not the workspace)."""
    for path in ("/etc/machine-id", "/var/lib/dbus/machine-id"):
        try:
            with open(path) as fh:
                hid = fh.read().strip()
            if hid:
                return hid
        except OSError:
            continue
    return platform.node()


def host_cache_key() -> str:
    """12-hex digest of host id + machine type + CPU model + features.

    ``PBFT_CACHE_HOST_KEY`` overrides the computed key (tests pin it to
    exercise warm-restart behavior deterministically)."""
    override = os.environ.get("PBFT_CACHE_HOST_KEY")
    if override:
        return override
    cpu = _cpuinfo_fields("model name", "flags", "Features")
    return hashlib.blake2b(
        f"{_host_id()}|{platform.machine()}|{cpu}".encode(), digest_size=6
    ).hexdigest()


def host_keyed_cache_dir(root: str) -> str:
    """<root>/<host_cache_key()>, e.g. .jax_cache/a1b2c3d4e5f6."""
    return os.path.join(root, host_cache_key())
