"""Host-feature-keyed persistent compile cache location.

XLA:CPU AOT cache entries embed the compiling machine's CPU features;
loading an entry compiled on a better-featured host only WARNS at load
time but can SIGILL at execution time. The multichip dryrun is the one
gate that must never flake, and its workspace (including `.jax_cache/`)
can move between hosts — so the cache directory is keyed by the host's
machine type + CPU feature flags: a foreign cache lands under a
different key and is simply never read. The cost of a feature mismatch
is a cold recompile, never a crash.

This module must stay importable without touching jax (bench.py and
__graft_entry__.py compute the cache path before backend init).
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_cache_key() -> str:
    """12-hex digest of this host's machine type + CPU feature flags."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.split(":")[0].strip() in ("flags", "Features"):
                    flags = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass  # non-Linux: machine type alone still separates real moves
    return hashlib.blake2b(
        f"{platform.machine()}|{flags}".encode(), digest_size=6
    ).hexdigest()


def host_keyed_cache_dir(root: str) -> str:
    """<root>/<host_cache_key()>, e.g. .jax_cache/a1b2c3d4e5f6."""
    return os.path.join(root, host_cache_key())
