"""Per-request latency waterfalls (ISSUE 9): join client-side
send/first-reply/quorum stamps with replica-side trace events into
per-request segment breakdowns — with zero wire-format changes.

The join keys already exist: requests are unique per (client, req_ts) and
batches per (view, seq). The primary's ``batch_sealed`` event carries the
ordered [client, req_ts] list it sealed, so:

    client send --(client_queue)--> primary request_rx
               --(batch_wait)-----> batch_sealed          (view, seq)
               --(prepared)-------> consensus_span.prepared
               --(committed)------> consensus_span.committed
               --(execute)--------> consensus_span.executed
               --(reply)----------> client quorum (f+1 matching replies)

All stamps are CLOCK_MONOTONIC, comparable across processes on one host.
Consumers: ``scripts/consensus_timeline.py --waterfall`` (offline, from
trace files) and ``pbft_tpu/bench/harness.py`` (in-process, from client
handles + the run's trace dir) — one join implementation for both.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, Iterable, List, Optional

SEGMENTS = ("client_queue", "batch_wait", "prepared", "committed",
            "execute", "reply")
QUANTILES = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


def percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def load_jsonl(paths: Iterable) -> List[dict]:
    """Best-effort JSONL loader (skips unparseable lines, like the
    trace_report loader)."""
    events = []
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(e, dict):
                    events.append(e)
    return events


def client_records_from_events(events: Iterable[dict]) -> List[dict]:
    """Extract ``client_request`` events (net/client.py write_trace) into
    the record shape build_waterfall takes."""
    out = []
    for e in events:
        if e.get("ev") != "client_request":
            continue
        row = {k: e[k] for k in ("client", "req_ts", "send") if k in e}
        if len(row) < 3:
            continue
        for k in ("first_reply", "quorum"):
            if isinstance(e.get(k), (int, float)):
                row[k] = e[k]
        out.append(row)
    return out


def build_waterfall(
    replica_events: Iterable[dict], client_records: Iterable[dict]
) -> Dict:
    """The join. Returns::

        {"requests": joined count, "clients": client record count,
         "mean_batch": mean sealed-batch occupancy,
         "e2e_ms": {p50, p95, p99, count},
         "segments_ms": {segment: {p50, p95, p99, count}, ...}}

    Requests missing a piece of evidence (an un-traced replica, a span
    evicted mid-run) contribute the segments they do have — partial
    coverage degrades percentile sample counts, never correctness.
    """
    # (client, req_ts) -> earliest request_rx stamp (the primary's first
    # sighting; forwards arrive later and must not win).
    rx: Dict = {}
    # (client, req_ts) -> (view, seq); (view, seq) -> seal info.
    seat: Dict = {}
    seals: Dict = {}
    # (view, seq, replica) -> consensus_span stamps.
    spans: Dict = {}
    batch_sizes: List[int] = []
    for e in replica_events:
        ev = e.get("ev")
        if ev == "request_rx":
            key = (e.get("client"), e.get("req_ts"))
            ts = e.get("ts")
            if None in key or not isinstance(ts, (int, float)):
                continue
            if key not in rx or ts < rx[key]:
                rx[key] = ts
        elif ev == "batch_sealed":
            try:
                view, seq = int(e["view"]), int(e["seq"])
            except (KeyError, TypeError, ValueError):
                continue
            seals[(view, seq)] = e
            if isinstance(e.get("batch"), int):
                batch_sizes.append(e["batch"])
            for pair in e.get("reqs") or ():
                if isinstance(pair, (list, tuple)) and len(pair) == 2:
                    seat[(pair[0], pair[1])] = (view, seq)
        elif ev == "consensus_span":
            try:
                key = (int(e["view"]), int(e["seq"]), int(e["replica"]))
            except (KeyError, TypeError, ValueError):
                continue
            spans[key] = e

    durs: Dict[str, List[float]] = {s: [] for s in SEGMENTS}
    e2e: List[float] = []
    joined = 0
    records = list(client_records)
    for rec in records:
        key = (rec.get("client"), rec.get("req_ts"))
        send = rec.get("send")
        arrived = rx.get(key)
        slot = seat.get(key)
        seal = seals.get(slot) if slot is not None else None
        span = None
        if slot is not None and seal is not None:
            # The sealing replica's span is the authoritative per-phase
            # clock (its "request" stamp IS the seal).
            span = spans.get((slot[0], slot[1], seal.get("replica")))
        if arrived is None and span is None and seal is None:
            continue
        joined += 1

        def seg(name: str, a: Optional[float], b: Optional[float]) -> None:
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                durs[name].append(max(0.0, b - a))

        sealed_ts = seal.get("ts") if seal else None
        seg("client_queue", send, arrived)
        seg("batch_wait", arrived, sealed_ts)
        if span is not None:
            seg("prepared", span.get("pre_prepare"), span.get("prepared"))
            seg("committed", span.get("prepared"), span.get("committed"))
            seg("execute", span.get("committed"), span.get("executed"))
            seg("reply", span.get("executed"), rec.get("quorum"))
        if isinstance(send, (int, float)) and isinstance(
            rec.get("quorum"), (int, float)
        ):
            e2e.append(max(0.0, rec["quorum"] - send))

    def stats_ms(vals: List[float]) -> Dict:
        vals = sorted(vals)
        out = {name: round(percentile(vals, q) * 1e3, 3)
               for name, q in QUANTILES}
        out["count"] = len(vals)
        return out

    return {
        "requests": joined,
        "clients": len(records),
        "mean_batch": (
            round(sum(batch_sizes) / len(batch_sizes), 2)
            if batch_sizes
            else 0.0
        ),
        "e2e_ms": stats_ms(e2e),
        "segments_ms": {s: stats_ms(durs[s]) for s in SEGMENTS},
    }


def render(wf: Dict) -> str:
    """Human-readable waterfall table."""
    lines = [
        "per-request latency waterfall: %d requests joined "
        "(%d client records, mean batch %.2f)"
        % (wf["requests"], wf["clients"], wf["mean_batch"])
    ]
    lines.append(
        f"  {'segment':<14}{'p50 ms':>10}{'p95 ms':>10}{'p99 ms':>10}"
        f"{'samples':>10}"
    )
    for name in SEGMENTS + ("e2e",):
        st = wf["e2e_ms"] if name == "e2e" else wf["segments_ms"][name]
        lines.append(
            f"  {name:<14}{st['p50']:>10.2f}{st['p95']:>10.2f}"
            f"{st['p99']:>10.2f}{st['count']:>10}"
        )
    return "\n".join(lines)


def from_trace_dir(paths) -> Dict:
    """Build a waterfall straight from trace files/dirs (client_request
    events mixed in with replica events — the harness writes both)."""
    files = []
    for arg in paths:
        p = pathlib.Path(arg)
        if p.is_dir():
            files.extend(sorted(p.glob("*.jsonl")) + sorted(p.glob("*/*.jsonl")))
        else:
            files.append(p)
    events = load_jsonl(files)
    return build_waterfall(events, client_records_from_events(events))
