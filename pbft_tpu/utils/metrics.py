"""Metrics registry + consensus-phase spans for the replica runtimes.

Same discipline as ``Tracer`` (trace.py): every record path is a plain
attribute check when disabled, and the enabled fast path is lock-free for
the single writer that owns the runtime (the asyncio loop in server.py,
the dispatcher in service.py, the poll thread in pbftd). A concurrent
scrape thread reads ints/floats that are each updated atomically under
CPython's GIL; a scrape may observe a histogram mid-update (count ahead of
sum by one observation) — Prometheus tolerates that, a lock in the hot
loop would not be tolerable (the println!-in-poll lesson, SURVEY.md §5).

Metric names, types, and bucket edges come from trace_schema.py — the
cross-runtime contract that core/metrics.cc mirrors and
scripts/check_trace_schema.py enforces.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from . import trace_schema
from .trace import Tracer


# -- process resource readers (ISSUE 16 health document) ---------------------
#
# C++ mirror: read_rss_bytes/count_open_fds in core/net.cc. Both prefer
# /proc/self (live resident set, not the ru_maxrss high-water mark) and
# return 0 where /proc is absent — the detectors treat a zero reading as
# "no data", never as a leak baseline.

def read_rss_bytes() -> int:
    """Current resident set in bytes (/proc/self/statm field 2 x page)."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except (ImportError, OSError):
            return 0


def count_open_fds() -> int:
    """Open file descriptors for this process (/proc/self/fd entries)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def file_size_bytes(path: Optional[str]) -> int:
    """On-disk size of ``path`` (0 when unset/absent) — the WAL gauge."""
    if not path:
        return 0
    try:
        return os.stat(path).st_size
    except OSError:
        return 0


class Counter:
    __slots__ = ("name", "enabled", "value")

    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if not self.enabled:
            return
        self.value += n


class Gauge:
    __slots__ = ("name", "enabled", "value")

    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled
        self.value = 0.0

    def set(self, v: float) -> None:
        if not self.enabled:
            return
        self.value = v


class Histogram:
    """Fixed-bucket histogram. ``edges`` are upper bounds (le semantics:
    an observation lands in the first bucket with v <= edge); counts has
    one extra slot for +Inf. Rendered cumulatively (Prometheus contract)."""

    __slots__ = ("name", "enabled", "edges", "counts", "sum", "count")

    def __init__(self, name: str, edges: Tuple[float, ...], enabled: bool):
        self.name = name
        self.enabled = enabled
        self.edges = tuple(edges)
        self.counts = [0] * (len(self.edges) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        if not self.enabled:
            return
        self.counts[bisect.bisect_left(self.edges, v)] += 1
        self.sum += v
        self.count += 1


class MetricsRegistry:
    """Holds one instance of each metric; renders Prometheus text format.

    ``labels`` are constant labels stamped on every sample (the replica id,
    so a mixed-runtime cluster's scrapes aggregate per replica). Metrics
    are looked up by manifest name; unknown names raise — drift from
    trace_schema.py must fail loudly, not mint ad-hoc series."""

    def __init__(self, labels: Optional[Dict[str, str]] = None, enabled: bool = True):
        self.labels = dict(labels or {})
        self.enabled = enabled
        self._metrics: Dict[str, object] = {}

    _TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def _get(self, name: str, want_type: str):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, self._TYPES[want_type]):
                raise KeyError(f"{name} is not a manifest {want_type}")
            return m
        mtype = trace_schema.METRIC_SCHEMAS.get(name, (None,))[0]
        if mtype != want_type:
            raise KeyError(f"{name} is not a manifest {want_type}")
        if want_type == "counter":
            m = Counter(name, self.enabled)
        elif want_type == "gauge":
            m = Gauge(name, self.enabled)
        else:
            m = Histogram(name, trace_schema.histogram_buckets(name), self.enabled)
        self._metrics[name] = m
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, "counter")

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge")

    def histogram(self, name: str) -> Histogram:
        return self._get(name, "histogram")

    def preregister(self, emitter: Optional[str] = None) -> None:
        """Create every manifest metric (zero-valued) up front — scrape
        uniformity with the C++ registry, which registers eagerly: a mixed
        cluster must expose the SAME series set from every replica, even
        for events that haven't happened yet (view changes) or can't
        happen in this runtime (the async-verifier deadline). ``emitter``
        restricts to that source's manifest subset (the service)."""
        for name, (kind, emitters) in trace_schema.METRIC_SCHEMAS.items():
            if emitter is None or emitter in emitters:
                self._get(name, kind)

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = enabled
        for m in self._metrics.values():
            m.enabled = enabled

    # -- rendering -----------------------------------------------------------

    def _label_str(self, extra: str = "") -> str:
        parts = [f'{k}="{v}"' for k, v in sorted(self.labels.items())]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @staticmethod
    def _fmt(v: float) -> str:
        if isinstance(v, int) or (isinstance(v, float) and v == int(v)):
            return str(int(v))
        return repr(v)

    def render_prometheus(self) -> str:
        """Prometheus exposition text, deterministically ordered by name."""
        out = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Counter):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name}{self._label_str()} {m.value}")
            elif isinstance(m, Gauge):
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name}{self._label_str()} {self._fmt(m.value)}")
            else:
                out.append(f"# TYPE {name} histogram")
                cum = 0
                for edge, c in zip(m.edges, m.counts):
                    cum += c
                    le = 'le="%s"' % self._fmt(edge)
                    out.append(f"{name}_bucket{self._label_str(le)} {cum}")
                cum += m.counts[-1]
                inf = 'le="+Inf"'
                out.append(f"{name}_bucket{self._label_str(inf)} {cum}")
                out.append(f"{name}_sum{self._label_str()} {self._fmt(round(m.sum, 9))}")
                out.append(f"{name}_count{self._label_str()} {m.count}")
        return "\n".join(out) + "\n"


class ConsensusSpans:
    """Per-(view, seq) consensus-phase spans, fed by Replica.phase_hook.

    The replica state machine stays clock-free (its determinism is what
    makes it testable): it only reports *transitions*; this tracker stamps
    them with the runtime's monotonic clock. At the "executed" transition
    the span closes: phase latencies go to the manifest histograms and one
    ``consensus_span`` trace event carries the absolute stamps (comparable
    across processes on one host — CLOCK_MONOTONIC is per-boot), which is
    what scripts/consensus_timeline.py merges across replicas.

    Bounded: at most ``max_open`` open spans; a slot that never executes
    (view abandoned, replica crashed mid-protocol) is evicted oldest-first
    rather than leaking.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        tracer: Optional[Tracer] = None,
        replica: int = -1,
        clock: Callable[[], float] = time.monotonic,
        max_open: int = 4096,
    ):
        self.registry = registry
        self.tracer = tracer
        self.replica = replica
        self.clock = clock
        self.max_open = max_open
        self._open: "OrderedDict[Tuple[int, int], Dict[str, float]]" = OrderedDict()
        self._hists = {
            pair: registry.histogram(name)
            for pair, name in trace_schema.PHASE_HISTOGRAMS.items()
        }
        self._e2e = registry.histogram("pbft_request_reply_seconds")
        self._executed = registry.counter("pbft_executed_total")

    def on_phase(self, phase: str, view: int, seq: int) -> None:
        now = self.clock()
        key = (view, seq)
        span = self._open.get(key)
        if span is None:
            if phase == "executed":
                return  # span evicted or never opened: nothing to close
            if len(self._open) >= self.max_open:
                self._open.popitem(last=False)
            span = self._open[key] = {}
        span.setdefault(phase, now)
        if phase != "executed":
            return
        del self._open[key]
        self._executed.inc()
        for (a, b), hist in self._hists.items():
            ta, tb = span.get(a), span.get(b)
            if ta is not None and tb is not None:
                hist.observe(max(0.0, tb - ta))
        start = span.get("request", span.get("pre_prepare"))
        if start is not None:
            self._e2e.observe(max(0.0, now - start))
        if self.tracer is not None and self.tracer.enabled:
            fields = {
                p: round(t, 6) for p, t in span.items() if p in trace_schema.PHASES
            }
            self.tracer.event(
                "consensus_span", replica=self.replica, view=view, seq=seq, **fields
            )


def start_metrics_server(
    registry: MetricsRegistry, port: int, host: str = "127.0.0.1",
    status_fn: Optional[Callable[[], dict]] = None,
):
    """Serve ``registry`` as Prometheus text on ``/metrics`` (any path,
    really — scrapers vary) from a daemon thread. Returns the HTTPServer;
    the bound port is ``server.server_address[1]`` (useful with port=0).
    Works for both runtimes' Python processes: the asyncio replica server
    and the threaded verifier service — registry reads are GIL-atomic.

    With ``status_fn``, GET /status serves its dict as JSON — the health
    document (ISSUE 16; C++ mirror: net.cc serve_metrics_ready routes
    /status to metrics_json). status_fn runs on the scrape thread: it
    must only read GIL-atomic runtime state, same contract as the
    registry reads."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server contract
            if status_fn is not None and self.path.startswith("/status"):
                body = _json.dumps(status_fn()).encode()
                ctype = "application/json"
            else:
                body = registry.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stdout
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
