"""Black-box flight recorder — the Python mirror of core/flight.{h,cc}.

A bounded ring of the last N protocol events (request_rx, batch_sealed,
phase transitions, reply_tx, view-change spans), kept in memory for the
process's whole life and dumped to a compact binary file on
SIGTERM/fatal/invariant-failure. Unlike the JSONL tracer — which only
helps for replicas that lived long enough to flush — the black box is
what a chaos soak or sanitizer kill recovers from the dead process.

Record path discipline matches the Tracer/metrics rule: one attribute
check when disabled, no locks (deque.append is atomic under the GIL; a
concurrent dump may miss the newest record, never corrupt one).

The on-disk format (trace_schema.FLIGHT_MAGIC/FLIGHT_EVENTS) is shared
byte-for-byte with the C++ recorder; scripts/flight_dump.py decodes both.
"""

from __future__ import annotations

import os
import signal
import struct
import time
from collections import deque
from typing import Dict, List, Optional

from . import trace_schema

_HEADER = struct.Struct("<8sII")  # magic, version, record count
_RECORD = struct.Struct("<QHhii")  # t_ns, event id, peer, view, seq
assert _RECORD.size == trace_schema.FLIGHT_RECORD_SIZE

# The consensus-phase hook's names, mapped onto flight event ids: the
# primary's "request" transition (sequence assignment) IS the batch seal.
_PHASE_EVENTS = {
    "request": "batch_sealed",
    "pre_prepare": "pre_prepare",
    "prepared": "prepared",
    "committed": "committed",
    "executed": "executed",
}


def _i32(v: int) -> int:
    v = int(v) & 0xFFFFFFFF
    return v - 0x100000000 if v >= 0x80000000 else v


def _i16(v: int) -> int:
    v = int(v) & 0xFFFF
    return v - 0x10000 if v >= 0x8000 else v


class FlightRecorder:
    """Fixed-capacity ring of (t_ns, event, peer, view, seq) records."""

    __slots__ = ("enabled", "capacity", "_ring")

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.capacity = capacity
        self.enabled = enabled
        self._ring: deque = deque(maxlen=capacity)

    def record(
        self,
        ev,
        view: int = 0,
        seq: int = 0,
        peer: int = -1,
        t_ns: Optional[int] = None,
    ) -> None:
        if not self.enabled:
            return
        if isinstance(ev, str):
            ev = trace_schema.FLIGHT_EVENT_IDS.get(ev, 0)
        self._ring.append(
            (
                time.monotonic_ns() if t_ns is None else int(t_ns),
                int(ev) & 0xFFFF,
                _i16(peer),
                _i32(view),
                _i32(seq),
            )
        )

    def record_phase(self, phase: str, view: int, seq: int) -> None:
        """Replica.phase_hook adapter (phase, view, seq)."""
        if not self.enabled:
            return
        name = _PHASE_EVENTS.get(phase)
        if name is not None:
            self.record(name, view=view, seq=seq)

    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self) -> List[tuple]:
        return list(self._ring)

    def encode(self) -> bytes:
        recs = self.snapshot()
        out = [
            _HEADER.pack(
                trace_schema.FLIGHT_MAGIC, trace_schema.FLIGHT_VERSION, len(recs)
            )
        ]
        out.extend(_RECORD.pack(*r) for r in recs)
        return b"".join(out)

    def dump(self, path: str) -> int:
        """Write the binary dump; returns the record count."""
        data = self.encode()
        with open(path, "wb") as fh:
            fh.write(data)
        return (len(data) - _HEADER.size) // _RECORD.size


def encode_records(records) -> bytes:
    """Re-encode decoded records (t_ns, ev, peer, view, seq) — the
    byte-exact round-trip check the overhead-guard test pins."""
    out = [
        _HEADER.pack(
            trace_schema.FLIGHT_MAGIC, trace_schema.FLIGHT_VERSION, len(records)
        )
    ]
    out.extend(_RECORD.pack(*r) for r in records)
    return b"".join(out)


def decode_bytes(data: bytes) -> List[Dict]:
    """Decode a dump into [{t_ns, ev, event, peer, view, seq}, ...].
    Raises ValueError on a bad magic/version or truncated record."""
    if len(data) < _HEADER.size:
        raise ValueError("flight dump truncated before header")
    magic, version, count = _HEADER.unpack_from(data, 0)
    if magic != trace_schema.FLIGHT_MAGIC:
        raise ValueError(f"not a flight dump (magic {magic!r})")
    if version != trace_schema.FLIGHT_VERSION:
        raise ValueError(f"unknown flight dump version {version}")
    need = _HEADER.size + count * _RECORD.size
    if len(data) < need:
        raise ValueError(
            f"flight dump truncated: header claims {count} records, "
            f"{(len(data) - _HEADER.size) // _RECORD.size} present"
        )
    out = []
    off = _HEADER.size
    for _ in range(count):
        t_ns, ev, peer, view, seq = _RECORD.unpack_from(data, off)
        off += _RECORD.size
        out.append(
            {
                "t_ns": t_ns,
                "ev": ev,
                "event": trace_schema.FLIGHT_EVENTS.get(ev, f"unknown-{ev}"),
                "peer": peer,
                "view": view,
                "seq": seq,
            }
        )
    return out


def decode_file(path: str) -> List[Dict]:
    with open(path, "rb") as fh:
        return decode_bytes(fh.read())


def install_signal_dump(recorder: FlightRecorder, path: str) -> None:
    """Dump the black box when the process is terminated (SIGTERM/SIGINT)
    — the flight-data-recorder contract: a replica killed mid-soak still
    ships its last N protocol events. The handler exits with the
    conventional 128+signum status after writing the dump."""

    def _handler(signum, frame):  # noqa: ARG001 - signal contract
        try:
            recorder.dump(path)
        finally:
            os._exit(128 + signum)

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
