"""Multi-host scaling for the batch verifier (ICI/DCN; scaling-book recipe).

The consensus transport stays on the host network (C++ asio-style TCP /
the asyncio runtime — SURVEY.md §5: consensus-critical small messages
never route through the TPU fabric). What scales over the accelerator
fabric is the *verification burden*: when a cluster's signature volume
exceeds one host, hosts feed process-local shards of the global
(pubkey, digest, sig) batch and the same `quorum_certify` psum produces
globally-replicated per-round verdicts — XLA routes the all-reduce over
ICI within a slice and DCN across slices.

Usage (one JAX process per host):

    import jax
    jax.distributed.initialize()          # coordinator env vars per host
    mesh = global_mesh()                  # all devices, 1-D batch axis
    certify = quorum_certify(mesh, num_rounds=R)
    pubs = host_shard_to_global(mesh, local_pubs)   # etc.
    result = certify(pubs, msgs, sigs, round_ids, thresholds)
    # result.certified is replicated: every host reads the same verdicts.

Single-process (one host, N chips) needs no initialize(); the same code
runs unchanged — that is the configuration the driver's dryrun and the
unit tests exercise.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from .verifier import make_mesh


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with explicit args or env-var discovery.

    No-op when jax.distributed is already initialized or when running a
    single process (num_processes == 1 and no cluster env markers). The
    already-initialized check must NOT touch jax.process_count()/
    jax.devices(): those initialize the XLA backend, after which
    jax.distributed.initialize() refuses to run."""
    import os

    if jax.distributed.is_initialized():
        return
    # Env-var discovery: jax's own coordinator variables mark a multi-host
    # launch even when the caller passes no explicit args (e.g. a launcher
    # exports them per host). Single-process is only assumed when neither
    # explicit args nor these markers are present.
    env_discovery = any(
        os.environ.get(k)
        for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES")
    )
    if (
        num_processes in (None, 1)
        and coordinator_address is None
        and not env_discovery
    ):
        return  # single-process deployment: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis: str = "batch"):
    """1-D mesh over every device of every process (the verification
    batch is pure data-parallel, so 1-D is the right shape at any scale)."""
    return make_mesh(axis=axis)


def host_shard_to_global(mesh, local: np.ndarray) -> jax.Array:
    """Assemble a globally-sharded array from this host's shard.

    Each process passes its process-local rows (equal count per process);
    the result is one global array sharded over the mesh's batch axis,
    ready for quorum_certify. Under a single process this is just
    device_put with the batch sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    global_shape = (local.shape[0] * jax.process_count(),) + local.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local, global_shape)


def partition_items(
    items: Sequence, process_id: Optional[int] = None, num: Optional[int] = None
):
    """Deterministic round-robin split of a batch across hosts: host k
    verifies items k, k+N, k+2N, … — every host computes the same split
    from the same batch, no coordination message needed."""
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num is None else num
    return list(items[pid::n])
