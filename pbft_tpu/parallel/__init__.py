"""pbft_tpu.parallel — sharding the crypto hot path over a device mesh.

The reference's only concurrency was one OS process per replica plus libp2p
substreams (SURVEY.md §2 "Parallelism strategies: none"); the rebuild's
scaling axis is the *signature batch*. This package shards that batch over a
``jax.sharding.Mesh`` (data-parallel over the batch axis) and aggregates
per-round quorum counts with XLA collectives (``psum`` over ICI), so one
verification launch scales from one chip to a pod slice without touching the
consensus core.
"""

from .verifier import (
    QuorumResult,
    batch_sharding,
    compile_sharded,
    make_mesh,
    sharded_verify,
    verify_many_auto,
    verify_many_sharded,
    quorum_certify,
    round_step,
)
from .multihost import (
    global_mesh,
    host_shard_to_global,
    initialize_distributed,
    partition_items,
)

__all__ = [
    "QuorumResult",
    "batch_sharding",
    "compile_sharded",
    "make_mesh",
    "sharded_verify",
    "verify_many_auto",
    "verify_many_sharded",
    "quorum_certify",
    "round_step",
    "global_mesh",
    "host_shard_to_global",
    "initialize_distributed",
    "partition_items",
]
