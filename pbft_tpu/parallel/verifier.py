"""Mesh-sharded batch verification and distributed quorum certification.

Design (TPU-first, scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- One logical axis, ``"batch"``: signature triples are embarrassingly
  parallel, so the (B, …) tensors are sharded over it and the Ed25519 kernel
  runs shard-local with zero communication (``sharded_verify``).
- The *consensus* reduction — "does round r have >= its quorum threshold of
  valid signatures?" — is the only cross-shard computation. ``quorum_certify``
  computes shard-local per-round one-hot counts and ``psum``s them over the
  mesh, so every device holds the global per-round verdict after one small
  all-reduce riding ICI. This is the TPU-era analogue of the reference's
  per-message quorum predicates (reference src/behavior.rs:177-182,:199-223),
  evaluated for a whole window of rounds in one launch.
- Multi-host: the same code runs under ``jax.distributed`` — the Mesh spans
  all processes' devices and each host feeds its process-local shard
  (``jax.make_array_from_process_local_data``); psum then rides ICI/DCN.

Everything is static-shape: B (padded batch) and R (rounds window) are fixed
per compilation; pad slots carry round_id = R (a dummy row that is sliced
off), so changing batch occupancy never recompiles.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto.ed25519 import verify_kernel

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map as _shard_map_mod

    shard_map = _shard_map_mod  # type: ignore[assignment]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


def make_mesh(
    n_devices: Optional[int] = None, axis: str = "batch", devices=None
) -> Mesh:
    """1-D device mesh over the batch axis.

    The verifier's parallelism is pure data-parallel over signatures, so a
    1-D mesh is the right shape; n_devices defaults to all local devices.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def sharded_verify(
    mesh: Mesh, axis: str = "batch", donate: bool = False, kernel=None
):
    """jit'd (B,32),(B,32),(B,64) uint8 -> (B,) bool, batch-sharded.

    Shard-local compute only — XLA partitions the vmapped kernel with no
    collectives. B must be divisible by the mesh size. ``donate=True``
    marks the three input buffers donated so XLA reuses their device
    memory across launches (the verify service re-stages every window, so
    its inputs are dead the moment the launch reads them). ``kernel``
    overrides the Ed25519 kernel (tests substitute a cheap stand-in to
    exercise the serving plumbing without a minutes-long compile).
    """
    spec = NamedSharding(mesh, P(axis))
    kern = kernel or verify_kernel

    def fn(pubs, msgs, sigs):
        pubs = jax.lax.with_sharding_constraint(pubs, spec)
        msgs = jax.lax.with_sharding_constraint(msgs, spec)
        sigs = jax.lax.with_sharding_constraint(sigs, spec)
        return kern(pubs, msgs, sigs)

    return jax.jit(fn, donate_argnums=(0, 1, 2) if donate else ())


def batch_sharding(mesh: Mesh, axis: str = "batch") -> NamedSharding:
    """The (B, …) input sharding the verify launches expect — callers
    ``jax.device_put`` against it to stage a window ahead of the launch."""
    return NamedSharding(mesh, P(axis))


def compile_sharded(
    mesh: Mesh,
    size: int,
    axis: str = "batch",
    donate: bool = True,
    kernel=None,
):
    """AOT-compile the sharded verifier for one fixed window size.

    ``jax.jit(...).lower(...).compile()`` ahead of first traffic: the
    persistent verify service warms every `_PAD_LADDER` shape at startup
    so no request ever pays tracing or compilation (the persistent
    on-disk cache makes the warm-restart compile cache-hit cheap; the
    serialized-executable export in net/verify_service.py skips even
    tracing). Returns a ``jax.stages.Compiled`` expecting inputs placed
    with :func:`batch_sharding`.
    """
    if size % mesh.devices.size:
        raise ValueError(
            f"window {size} not divisible by mesh size {mesh.devices.size}"
        )
    spec = NamedSharding(mesh, P(axis))
    fn = sharded_verify(mesh, axis, donate=donate, kernel=kernel)
    return fn.lower(
        jax.ShapeDtypeStruct((size, 32), jnp.uint8, sharding=spec),
        jax.ShapeDtypeStruct((size, 32), jnp.uint8, sharding=spec),
        jax.ShapeDtypeStruct((size, 64), jnp.uint8, sharding=spec),
    ).compile()


# One compiled sharded verifier per process: the serving path below is
# called per batching window, and rebuilding the jit per call would
# retrace every window. Lock guards the lazy init — handler threads
# (service.py) and executor threads (server.py) may race the first call.
_SERVING = None  # (mesh, fn)
_SERVING_LOCK = threading.Lock()


def verify_many_sharded(items, pad_to: Optional[int] = None):
    """Host serving API: list of (pub32, msg32, sig64) byte triples ->
    list[bool], the padded batch sharded over this host's LOCAL devices
    (multi-host slices shard per-process; the global-mesh path needs
    make_array_from_process_local_data — see module docstring).

    The multi-chip deployment path for the verifier service / asyncio
    runtime: same call shape as crypto.batch.verify_many — and the same
    body, via its ``launch`` hook — but the single XLA launch is
    data-parallel across the mesh. NOTE: an explicit ``pad_to`` is
    rounded UP to the nearest multiple of the local device count when not
    already divisible. Verdicts are identical to the single-device path
    (tests/test_parallel.py pins equivalence).
    """
    from ..crypto import batch as _batch

    if not items:
        return []
    global _SERVING
    with _SERVING_LOCK:
        if _SERVING is None:
            mesh = make_mesh(devices=jax.local_devices())
            _SERVING = (mesh, sharded_verify(mesh))
        mesh, fn = _SERVING
    return _batch.verify_many(
        items, pad_to=pad_to, launch=fn, size_multiple=mesh.devices.size
    )


def verify_many_auto(items, pad_to: Optional[int] = None):
    """The serving-path selector: mesh-sharded over this host's local
    devices when there are several, the plain single-device launch
    otherwise. Every jax-arm consumer (verifier service, asyncio runtime,
    simulation) routes here so the deployment choice lives in one place."""
    if jax.local_device_count() > 1:
        return verify_many_sharded(items, pad_to=pad_to)
    from ..crypto import batch as _batch

    return _batch.verify_many(items, pad_to=pad_to)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QuorumResult:
    """Global (replicated) outputs of one quorum-certification launch."""

    valid: jax.Array  # (B,) bool  per-signature verdicts (batch-sharded)
    counts: jax.Array  # (R,) int32 valid-signature count per round
    certified: jax.Array  # (R,) bool  counts >= thresholds


def quorum_certify(mesh: Mesh, num_rounds: int, axis: str = "batch"):
    """Distributed quorum certification: verify + psum per-round counts.

    Returns a jit'd function
        (pubs (B,32), msgs (B,32), sigs (B,64), round_ids (B,), thresholds (R,))
        -> QuorumResult
    where round_ids[i] in [0, R) assigns signature i to a consensus round
    (view, seq) slot; pad slots use round_id >= R and are dropped. Each
    device verifies its batch shard, builds shard-local per-round counts,
    and one psum over the mesh replicates the global counts — the quorum
    predicate for a whole window of rounds in a single collective.
    """
    R = num_rounds

    def local(pubs, msgs, sigs, round_ids, thresholds):
        ok = verify_kernel(pubs, msgs, sigs)
        # Shard-local counts; dummy segment R swallows pad slots.
        rid = jnp.clip(round_ids.astype(jnp.int32), 0, R)
        counts = jax.ops.segment_sum(
            ok.astype(jnp.int32), rid, num_segments=R + 1
        )[:R]
        counts = jax.lax.psum(counts, axis)
        return ok, counts, counts >= thresholds

    # check_vma=False: the crypto kernel's lax loops carry broadcast curve
    # constants whose varying-axis annotation the checker can't infer.
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(axis), P(), P()),
        check_vma=False,
    )

    @jax.jit
    def certify(pubs, msgs, sigs, round_ids, thresholds):
        valid, counts, certified = fn(
            jnp.asarray(pubs, jnp.uint8),
            jnp.asarray(msgs, jnp.uint8),
            jnp.asarray(sigs, jnp.uint8),
            jnp.asarray(round_ids, jnp.int32),
            jnp.asarray(thresholds, jnp.int32),
        )
        return QuorumResult(valid=valid, counts=counts, certified=certified)

    return certify


def round_step(mesh: Mesh, num_rounds: int, axis: str = "batch"):
    """The framework's full distributed step, jitted over the mesh.

    One consensus *window* step = verify every queued PREPARE/COMMIT
    signature (batch-sharded over the mesh) + certify every round's quorum
    (psum collective) + fold the certified rounds into a running state
    digest chain (the execution analogue: replicas apply committed ops in
    sequence order, reference src/behavior.rs:383-410). This is what
    ``__graft_entry__.dryrun_multichip`` compiles and runs on an N-device
    mesh, and what the multi-chip bench drives.
    """
    certify = quorum_certify(mesh, num_rounds, axis)
    state_spec = NamedSharding(mesh, P())

    @jax.jit
    def step(state_digest, pubs, msgs, sigs, round_ids, thresholds):
        res = certify(pubs, msgs, sigs, round_ids, thresholds)
        # Chain certified rounds into the replicated state digest: a
        # data-independent fold (certified rounds contribute their count;
        # uncertified contribute 0) keeps the step fully static-shape.
        contrib = jnp.where(
            res.certified, res.counts, jnp.zeros_like(res.counts)
        )
        mixed = jnp.concatenate(
            [state_digest.astype(jnp.int32), contrib], axis=0
        )
        new_state = jax.lax.with_sharding_constraint(
            jnp.cumsum(mixed)[-state_digest.shape[0] :].astype(jnp.int32)
            % jnp.int32(2**31 - 1),
            state_spec,
        )
        return new_state, res

    return step
