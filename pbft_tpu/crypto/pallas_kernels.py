"""Pallas TPU kernels for the Ed25519 hot path.

Why these exist: the XLA pipeline in field.py/ed25519.py expresses every
field multiply as its own HLO op (a depthwise conv + carry chain). XLA
fuses the elementwise carries, but the convs break fusion, so the ~2,200
sequential multiplies of one verification each round-trip their (B, 32)
operands through HBM. These kernels hold whole multiply *chains* in VMEM:

- ``inv`` / ``pow_p58`` — the ~254-squaring exponent ladders of
  compress/decompress as ONE kernel launch each;
- ``ladder`` — the full 128-iteration Shamir double-scalar ladder
  (2 doublings + 1 table addition per step, the dominant ~85% of a
  verify) as one kernel, with the 16-entry point table VMEM-resident.

Layout: kernels are **limb-major** — a field element batch is a (32, TB)
int32 tile (limbs on sublanes, batch on lanes), so every carry/fold is a
sublane rotate of a fully-populated 128-lane vector. The public wrappers
transpose at the boundary (one (B,32)->(32,B) transpose per kernel call,
amortized over hundreds of fused multiplies).

The arithmetic (radix-2^8 signed limbs, 38-fold at 2^256, 2/4-pass
vectorized carries) is bit-identical to field.py — same bounds proof, same
results; tests/test_pallas_kernels.py pins equivalence against both
field.py and the RFC 8032 oracle. ``PBFT_PALLAS=1`` switches
ed25519.verify_kernel onto these kernels (interpret mode on CPU backends,
compiled Mosaic on TPU).

Reference analogue: none — the reference left signature verification as
TODOs (src/behavior.rs:127, :185); this is the TPU-native centerpiece the
rebuild adds (SURVEY.md §5, §7).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas.tpu registers TPU lowerings; absent off-TPU installs where
    # only interpret mode runs (memory-space hints are a no-op there).
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised on CPU-only test envs
    pltpu = None

from . import ref
from .field import NLIMBS, RADIX, MASK, P, limbs_const

# Lane-tile width. 128 lanes is the VPU width; the ladder kernel's point
# table is 16 entries x 4 coords x (32, TB) int32 = TB/128 MB, so TB=128
# keeps the whole working set ~2 MB of the ~16 MB VMEM. Overridable for
# interpret-mode tests (narrow tiles make the emulated kernel tractable).
import os as _os

TB = int(_os.environ.get("PBFT_PALLAS_TB", "128"))

_DTYPE = jnp.int32

# Static constants, shaped (32, 1) for limb-major broadcast.
def _cl(v: int) -> np.ndarray:
    return limbs_const(v).reshape(NLIMBS, 1)


_C_2P = _cl(2 * P)
_C_D2 = _cl(2 * ref.D % P)
# [s]B rows of the Shamir table: identity, B, 2B, 3B in extended coords
# (ref.shamir_row0 — the same source ed25519._ROW0 is built from).
_ROW0 = [tuple(_cl(v) for v in coords) for coords in ref.shamir_row0()]


# ---------------------------------------------------------------------------
# In-kernel field arithmetic on limb-major (32, TB) values.
# ---------------------------------------------------------------------------


def _iota():
    return lax.broadcasted_iota(_DTYPE, (NLIMBS, 1), 0)


def _carry(x, passes: int):
    """Vectorized carry, limb-major: the carry leaving each sublane moves
    down one sublane (roll by 1); the one leaving sublane 31 re-enters
    sublane 0 as *38 (2^256 = 38 mod p). Same convergence bounds as
    field.carry."""
    w0 = jnp.where(_iota() == 0, 38, 1)
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX  # arithmetic shift: exact floor for negatives
        x = lo + w0 * jnp.roll(hi, 1, axis=0)
    return x


def _mm(a, b):
    """Field multiply with the 38-fold woven into the accumulation:
    out[n] = sum_i a_i * b_[(n-i) mod 32] * (38 if n < i else 1).
    Inputs carried (|limb| < 2^10.3), output carried; bounds identical to
    field._mul_schoolbook (cols < 2^28.3, inside int32)."""
    io = _iota()
    acc = jnp.zeros_like(b)
    for i in range(NLIMBS):
        w = jnp.where(io < i, 38, 1)
        acc = acc + w * (a[i : i + 1, :] * jnp.roll(b, i, axis=0))
    return _carry(acc, 4)


def _sq(a):
    return _mm(a, a)


def _madd(a, b):
    return _carry(a + b, 2)


def _msub(a, b):
    return _carry(a - b, 2)


def _mneg(a, c2p):
    return _carry(c2p - a, 2)


def _mul_small(a, k: int):
    return _carry(a * k, 4)


def _pow2k(x, k: int):
    if k <= 4:
        for _ in range(k):
            x = _sq(x)
        return x
    return lax.fori_loop(0, k, lambda _, v: _sq(v), x)


def _inv_chain(z):
    """(z^(2^250-1), z^11): field._inv_chain run with the in-kernel ops —
    one chain definition shared across verifier backends."""
    from .field import _inv_chain as chain

    return chain(z, mul=_mm, sqr=_sq, pow2k=_pow2k)


# ---------------------------------------------------------------------------
# In-kernel point arithmetic (a=-1 twisted Edwards, extended coords).
# ---------------------------------------------------------------------------


def _padd(p, q, cd2):
    """add-2008-hwcd-3 — mirrors ed25519.point_add. cd2 = 2d limbs."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mm(_msub(y1, x1), _msub(y2, x2))
    b = _mm(_madd(y1, x1), _madd(y2, x2))
    c = _mm(_mm(t1, cd2), t2)
    d = _mul_small(_mm(z1, z2), 2)
    e = _msub(b, a)
    f = _msub(d, c)
    g = _madd(d, c)
    h = _madd(b, a)
    return (_mm(e, f), _mm(g, h), _mm(f, g), _mm(e, h))


def _pdbl(p, c2p):
    """dbl-2008-hwcd — mirrors ed25519.point_double. c2p = 2p limbs."""
    x1, y1, z1, _ = p
    a = _sq(x1)
    b = _sq(y1)
    c = _mul_small(_sq(z1), 2)
    d = _mneg(a, c2p)
    e = _msub(_msub(_sq(_madd(x1, y1)), a), b)
    g = _madd(d, b)
    f = _msub(g, c)
    h = _msub(d, b)
    return (_mm(e, f), _mm(g, h), _mm(f, g), _mm(e, h))


# ---------------------------------------------------------------------------
# Kernel bodies.
# ---------------------------------------------------------------------------


def _inv_kernel(z_ref, out_ref):
    z = z_ref[:]
    z_250_0, z11 = _inv_chain(z)
    out_ref[:] = _mm(_pow2k(z_250_0, 5), z11)


def _p58_kernel(z_ref, out_ref):
    z = z_ref[:]
    z_250_0, _ = _inv_chain(z)
    out_ref[:] = _mm(_pow2k(z_250_0, 2), z)


# Constant matrix for the ladder kernel, limb-major (32, K): pallas
# kernels may not close over array constants, so every static limb vector
# rides in as one input block. Columns: 0 = 2p, 1 = 2d, 2 = 1, then
# 3 + 4*s + c = coordinate c of [s]B (the h=0 table row).
_NCONST = 3 + 16
_LADDER_CONSTS = np.zeros((NLIMBS, 32), np.int32)  # lane-padded to 32
_LADDER_CONSTS[:, 0:1] = _C_2P
_LADDER_CONSTS[:, 1:2] = _C_D2
_LADDER_CONSTS[:, 2:3] = _cl(1)
for _s, _entry in enumerate(_ROW0):
    for _c, _limbs in enumerate(_entry):
        _LADDER_CONSTS[:, 3 + 4 * _s + _c : 4 + 4 * _s + _c] = _limbs


def _ladder_kernel(consts_ref, digits_ref, ax_ref, ay_ref, az_ref, at_ref, *out_refs):
    """The full Shamir ladder: acc = sum over 128 steps of 4*acc + E[d_k],
    where E[s + 4h] = [s]B + [h](-A) and d_k is the k-th (MSB-first) pair
    of (S, h) bit-digits, precomputed host-side as one int in 0..15.

    The 16-entry table lives in VMEM for the whole kernel; each step is 2
    doublings + 1 unified addition + a 4-level halving mux — identical
    math to ed25519.shamir_ladder."""
    c2p = consts_ref[:, 0:1]
    cd2 = consts_ref[:, 1:2]
    cone = consts_ref[:, 2:3]
    a1 = (ax_ref[:], ay_ref[:], az_ref[:], at_ref[:])
    a2 = _pdbl(a1, c2p)
    a3 = _padd(a2, a1, cd2)
    shape = a1[0].shape
    tb = shape[-1]
    row0 = [
        tuple(
            jnp.broadcast_to(consts_ref[:, 3 + 4 * s + c : 4 + 4 * s + c], shape)
            for c in range(4)
        )
        for s in range(4)
    ]
    # The 12 data-dependent table entries E[4h + s] = [s]B + [h](-A)
    # (h = 1..3) as ONE lane-stacked addition: [s]B rows tiled 3x against
    # [h](-A) repeated 4x — a single _padd on (32, 12*TB) instead of 12
    # unrolled point additions (12x smaller kernel graph, same math).
    r_stack = tuple(
        jnp.concatenate([row0[s][c] for _ in range(3) for s in range(4)], axis=1)
        for c in range(4)
    )
    a_stack = tuple(
        jnp.concatenate(
            [ah[c] for ah in (a1, a2, a3) for _ in range(4)], axis=1
        )
        for c in range(4)
    )
    prods = _padd(r_stack, a_stack, cd2)
    entries = list(row0) + [
        tuple(prods[c][:, j * tb : (j + 1) * tb] for c in range(4))
        for j in range(12)
    ]

    zero = jnp.zeros(shape, _DTYPE)
    one = jnp.broadcast_to(cone, shape)
    ident = (zero, one, one, zero)

    def mux(d):
        cur = entries
        for level in range(4):
            bit = (d >> level) & 1
            cond = bit == 1  # (1, TB)
            cur = [
                tuple(
                    jnp.where(cond, hi_c, lo_c)
                    for lo_c, hi_c in zip(lo, hi)
                )
                for lo, hi in zip(cur[0::2], cur[1::2])
            ]
        return cur[0]

    def body(k, acc):
        d = digits_ref[pl.ds(k, 1), :]  # (1, TB), values 0..15
        acc = _pdbl(_pdbl(acc, c2p), c2p)
        return _padd(acc, mux(d), cd2)

    acc = lax.fori_loop(0, 128, body, ident)
    for o, c in zip(out_refs, acc):
        o[:] = c


# ---------------------------------------------------------------------------
# Host-side wrappers: batch-major (..., 32) <-> limb-major (32, B) plus
# lane padding, one pallas_call per chain.
# ---------------------------------------------------------------------------


def _use_interpret() -> bool:
    if pltpu is None:
        return True
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:
        return True


def _to_lm(x, b_pad: int):
    """(g, 32) -> (32, b_pad) limb-major with lane padding."""
    g = x.shape[0]
    xt = jnp.swapaxes(x, -1, -2)
    if g < b_pad:
        xt = jnp.pad(xt, ((0, 0), (0, b_pad - g)))
    return xt


def _block(n_rows: int):
    if pltpu is None:
        return pl.BlockSpec((n_rows, TB), lambda i: (0, i))
    return pl.BlockSpec((n_rows, TB), lambda i: (0, i), memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnames=("kernel_name",))
def _run_chain(x, kernel_name: str):
    """Shared driver for the single-input chain kernels (inv, p58)."""
    kernel = {"inv": _inv_kernel, "p58": _p58_kernel}[kernel_name]
    shape = x.shape
    g = 1
    for d in shape[:-1]:
        g *= int(d)
    xf = x.reshape(g, NLIMBS)
    b_pad = max(TB, ((g + TB - 1) // TB) * TB)
    xlm = _to_lm(xf, b_pad)
    out = pl.pallas_call(
        kernel,
        grid=(b_pad // TB,),
        in_specs=[_block(NLIMBS)],
        out_specs=_block(NLIMBS),
        out_shape=jax.ShapeDtypeStruct((NLIMBS, b_pad), _DTYPE),
        interpret=_use_interpret(),
    )(xlm)
    return jnp.swapaxes(out, -1, -2)[:g].reshape(shape)


def inv(z):
    """Drop-in for field.inv (z^(p-2), inv(0) = 0) as one fused kernel."""
    return _run_chain(z, kernel_name="inv")


def pow_p58(z):
    """Drop-in for field.pow_p58 (z^((p-5)/8)) as one fused kernel."""
    return _run_chain(z, kernel_name="p58")


@jax.jit
def ladder(s_bits, h_bits, a_neg):
    """Drop-in for ed25519.shamir_ladder: [S]B + [h](-A).

    s_bits, h_bits: (..., 256) int32 LSB-first; a_neg: point tuple with
    (..., 32) coords. Returns the accumulator point, batch-major."""
    shape = s_bits.shape[:-1]
    g = 1
    for d in shape:
        g *= int(d)
    b_pad = max(TB, ((g + TB - 1) // TB) * TB)

    # Digit schedule, MSB-first: step k consumes bit-pair 127-k of each
    # scalar -> d = s0 + 2 s1 + 4 h0 + 8 h1 in 0..15, laid out (128, B).
    sb = s_bits.reshape(g, 256)
    hb = h_bits.reshape(g, 256)
    dig = (
        sb[:, 0::2] + 2 * sb[:, 1::2] + 4 * hb[:, 0::2] + 8 * hb[:, 1::2]
    )  # (g, 128), LSB-first pairs
    dig = dig[:, ::-1]  # MSB-first
    dig_lm = _to_lm(dig, b_pad)  # (128, b_pad)

    coords = [
        _to_lm(c.reshape(g, NLIMBS), b_pad) for c in a_neg
    ]  # 4 x (32, b_pad)

    const_spec = (
        pl.BlockSpec((NLIMBS, 32), lambda i: (0, 0))
        if pltpu is None
        else pl.BlockSpec(
            (NLIMBS, 32), lambda i: (0, 0), memory_space=pltpu.VMEM
        )
    )
    outs = pl.pallas_call(
        _ladder_kernel,
        grid=(b_pad // TB,),
        in_specs=[const_spec, _block(128)] + [_block(NLIMBS)] * 4,
        out_specs=[_block(NLIMBS)] * 4,
        out_shape=[jax.ShapeDtypeStruct((NLIMBS, b_pad), _DTYPE)] * 4,
        interpret=_use_interpret(),
    )(jnp.asarray(_LADDER_CONSTS), dig_lm, *coords)
    return tuple(
        jnp.swapaxes(o, -1, -2)[:g].reshape(shape + (NLIMBS,)) for o in outs
    )
