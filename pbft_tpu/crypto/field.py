"""GF(2^255-19) and mod-L arithmetic in JAX, designed for vmap/XLA on TPU.

Representation: field elements are (..., 32) **int32** arrays of 8-bit limbs,
little-endian (value = sum limb_i * 2^(8*i)) — the radix is chosen for the
TPU's 32-bit vector unit: every op is native int32, no jax x64 mode and no
emulated 64-bit arithmetic anywhere. A pleasant consequence of radix 2^8 is
that the canonical byte encoding and the limb array coincide, so
``bytes_to_limbs``/``limbs_to_bytes`` are casts, not repacks.

Limbs are *signed* and allowed to drift above 8 bits between operations
("loose" form); every multiply renormalizes. The signed-limb choice makes
subtraction carry-free and the arithmetic right shift does borrow
propagation for free.

Bounds that make this sound (see ``mul``): a carried limb is < 2^8 + 38,
and every mul input is a sum/difference of at most 4 carried values (the
point formulas in ed25519.py never nest deeper), so |limb| < 4*(2^8+38)
< 2^10.3. Schoolbook columns are then < 32 * 2^20.6 = 2^25.6 and the
38-fold (2^256 = 38 mod p) keeps every intermediate < 39 * 2^25.6 < 2^30.9
— inside int32. Two carry passes return limbs to carried form. The
``tests/test_field.py`` hostile-bounds test pins this window.

The mod-L half (group order L = 2^252 + delta) implements the 512-bit
challenge-hash reduction with three positivity-preserving folds at the 2^252
boundary: x = hi*2^252 + lo == lo - hi*delta + M_k*L (mod L) where M_k is a
static per-iteration constant chosen so the result stays non-negative while
still shrinking ~127 bits per fold.

This is the arithmetic layer under pbft_tpu.crypto.ed25519; everything here
is batch-agnostic (leading dims broadcast) and contains no data-dependent
control flow, so it jits and vmaps cleanly onto TPU.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
DELTA = L - 2**252
NLIMBS = 32
RADIX = 8
MASK = 0xFF

_DTYPE = jnp.int32


def limbs_const(v: int, n: int = NLIMBS) -> np.ndarray:
    """Static Python int -> (n,) int32 limb array (8-bit, little-endian)."""
    return np.array(
        [(v >> (RADIX * i)) & MASK for i in range(n)], dtype=np.int32
    )


def limbs_to_int(arr) -> int:
    """(…,32) limbs -> Python int (tests/debug only; takes the last axis)."""
    a = np.asarray(arr, dtype=object)
    return int(sum(int(x) << (RADIX * i) for i, x in enumerate(a)))


_P_LIMBS = limbs_const(P)
_2P_LIMBS = limbs_const(2 * P)


def zeros_like_field(x):
    return jnp.zeros(x.shape, _DTYPE)


def carry_seq(x):
    """One exact sequential carry pass; wraps the 2^256 overflow back as
    *38 (mod p). Produces limbs in [0, 2^8) except limb 0, which keeps a
    small fold residue. Used by canon(), whose conditional subtracts need
    exact byte-range limbs; the hot path uses the vectorized ``carry``."""
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        v = x[..., i] + c
        c = v >> RADIX
        out.append(v & MASK)
    r = jnp.stack(out, axis=-1)
    return r.at[..., 0].add(38 * c)


def carry(x, passes: int = 2):
    """Vectorized carry: each pass splits every limb into (low byte, carry)
    simultaneously and shifts the carries up one position — wide (…,32)
    vector ops instead of a 32-step sequential chain, which keeps the XLA
    graph ~5x smaller and maps onto the TPU VPU as a handful of fused
    elementwise ops. The carry leaving limb 31 re-enters limb 0 as *38
    (2^256 = 38 mod p).

    Convergence ("carried" = limbs in (-2^9, 2^9)): 2 passes suffice for
    sums/differences of carried values; 4 passes for mul's folded columns
    (|col| < 2^28.3 -> < 2^25.6 -> ~2^16 -> ~2^13 -> < 2^8 + 38). All
    intermediates stay far inside int32.
    """
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX  # arithmetic shift: exact floor even for negatives
        x = lo + jnp.concatenate(
            [38 * hi[..., NLIMBS - 1 :], hi[..., : NLIMBS - 1]], axis=-1
        )
    return x


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a - b)


def neg(a):
    return carry(jnp.asarray(_2P_LIMBS) - a)


def _mul_schoolbook(a, b):
    """Shifted-accumulate schoolbook: best lowering on XLA:CPU."""
    cols = jnp.zeros(
        jnp.broadcast_shapes(a.shape, b.shape)[:-1] + (2 * NLIMBS - 1,), _DTYPE
    )
    for i in range(NLIMBS):
        cols = cols.at[..., i : i + NLIMBS].add(a[..., i : i + 1] * b)
    lo = cols[..., :NLIMBS]
    lo = lo.at[..., : NLIMBS - 1].add(38 * cols[..., NLIMBS:])
    return carry(lo, passes=4)


def _mul_conv(a, b):
    """Schoolbook + 38-fold as ONE depthwise int32 convolution.

    Polynomial multiplication is a convolution; on TPU, XLA's conv emitter
    runs it ~1.8x faster than the 32-step shifted-accumulate loop and
    compiles ~10x faster (one HLO op instead of 32 dynamic-update-slices).
    The mod-p fold is folded INTO the kernel: correlating b against
    c = [38*a[1:] ‖ a] yields directly
        out[n] = sum_{i+j=n} a_i b_j + 38 * sum_{i+j=n+32} a_i b_j
    i.e. the reduced 32 columns (2^256 = 38 mod p), skipping the separate
    fold pass. Bounds unchanged: |col| < 39 * 32 * 2^18 < 2^28.3.
    """
    from jax import lax

    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    lead = shape[:-1]
    g = 1
    for d in lead:
        g *= int(d)
    af = a.reshape(g, NLIMBS)
    bf = b.reshape(g, NLIMBS)
    kern = jnp.concatenate([38 * af[:, 1:], af], axis=-1)  # (g, 63)
    cols = lax.conv_general_dilated(
        bf[None],  # (1, g, 32)   NCW
        kern[:, None, ::-1],  # (g, 1, 63)   OIW, reversed -> true convolution
        window_strides=(1,),
        padding=[(NLIMBS - 1, NLIMBS - 1)],
        feature_group_count=g,
        dimension_numbers=("NCW", "OIW", "NCW"),
    )[0]  # (g, 32)
    return carry(cols, passes=4).reshape(shape)


def _pick_mul():
    import os

    impl = os.environ.get("PBFT_FIELD_MUL", "auto")
    if impl == "conv":
        return _mul_conv
    if impl == "schoolbook":
        return _mul_schoolbook
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    # conv wins on TPU-class backends; the shifted-accumulate loop wins on
    # XLA:CPU (measured ~2x each way).
    return _mul_schoolbook if backend == "cpu" else _mul_conv


def mul(a, b):
    """Field multiply. Inputs: carried limbs |x| < 2^9. Output: carried.

    Columns |col| < 32 * 2^18 = 2^23; the 38-fold keeps the reduced
    columns < 39 * 2^23 < 2^28.3 — inside int32 with margin. Two
    implementations (picked per backend, override with PBFT_FIELD_MUL)."""
    global _MUL_IMPL
    if _MUL_IMPL is None:
        _MUL_IMPL = _pick_mul()
    return _MUL_IMPL(a, b)


_MUL_IMPL = None


def sqr(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small static scalar (k*limb must stay inside int32)."""
    return carry(a * k, passes=4)


def _sqr_body(_, v):
    return sqr(v)


def pow2k(x, k: int):
    """x^(2^k) by k squarings (static k; fori_loop body is a module-level
    function so jax's trace cache hits across calls)."""
    from jax import lax

    if k <= 4:
        for _ in range(k):
            x = sqr(x)
        return x
    return lax.fori_loop(0, k, _sqr_body, x)


def _inv_chain(z, mul=None, sqr=None, pow2k=None):
    """Shared ladder: returns (z^(2^250-1), z^11).

    The classic curve25519 exponent chain; pieces are reused by both inv()
    (exponent p-2 = 2^255-21) and pow_p58() (exponent (p-5)/8 = 2^252-3).
    The ops are parameters so pallas_kernels runs the IDENTICAL chain with
    its in-kernel primitives — one definition, two backends (divergence
    between verifier backends would split replicas)."""
    mul = mul or globals()["mul"]
    sqr = sqr or globals()["sqr"]
    pow2k = pow2k or globals()["pow2k"]
    z2 = sqr(z)
    z8 = pow2k(z2, 2)
    z9 = mul(z, z8)
    z11 = mul(z2, z9)
    z22 = sqr(z11)
    z_5_0 = mul(z9, z22)  # 2^5 - 1
    z_10_0 = mul(pow2k(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(pow2k(z_10_0, 10), z_10_0)  # 2^20 - 1
    z_40_0 = mul(pow2k(z_20_0, 20), z_20_0)  # 2^40 - 1
    z_50_0 = mul(pow2k(z_40_0, 10), z_10_0)  # 2^50 - 1
    z_100_0 = mul(pow2k(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = mul(pow2k(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = mul(pow2k(z_200_0, 50), z_50_0)  # 2^250 - 1
    return z_250_0, z11


def inv(z):
    """z^(p-2) = z^(2^255-21): the field inverse (inv(0) = 0)."""
    z_250_0, z11 = _inv_chain(z)
    return mul(pow2k(z_250_0, 5), z11)


def pow_p58(z):
    """z^((p-5)/8) = z^(2^252-3), used for the square-root-ratio."""
    z_250_0, _ = _inv_chain(z)
    return mul(pow2k(z_250_0, 2), z)


def canon(x):
    """Canonical form: limbs in [0, 2^8), value in [0, p)."""
    x = carry_seq(carry_seq(x))
    # Force non-negativity: add 2p (== 0 mod p); the value may have been a
    # small negative after signed folds.
    x = carry_seq(x + jnp.asarray(_2P_LIMBS))
    # Fold bit 255+: value < 2^256 -> < 2^255 + 38.
    hi = x[..., NLIMBS - 1] >> (RADIX - 1)
    x = x.at[..., NLIMBS - 1].add(-(hi << (RADIX - 1)))
    x = x.at[..., 0].add(19 * hi)
    x = carry_seq(x)
    # At most two conditional subtracts of p remain.
    for _ in range(2):
        b = jnp.zeros_like(x[..., 0])
        digits = []
        for i in range(NLIMBS):
            v = x[..., i] - jnp.asarray(_P_LIMBS)[i] + b
            digits.append(v & MASK)
            b = v >> RADIX
        y = jnp.stack(digits, axis=-1)
        ge = b == 0  # no final borrow -> x >= p
        x = jnp.where(ge[..., None], y, x)
    return x


def eq(a, b):
    return jnp.all(canon(a) == canon(b), axis=-1)


def is_zero(a):
    return jnp.all(canon(a) == 0, axis=-1)


def bytes_to_limbs(b):
    """(…,n) uint8 little-endian -> (…,n) int32 limbs. At radix 2^8 the
    byte string IS the limb vector (32 bytes -> 32 limbs, 64-byte digests
    -> 64 limbs); only the dtype changes."""
    return jnp.asarray(b).astype(_DTYPE)


def limbs_to_bytes(x):
    """Canonical limbs -> (…,32) uint8 little-endian."""
    return canon(x).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Scalar arithmetic mod L (group order), for the challenge hash and S check.
# ---------------------------------------------------------------------------

_L_LIMBS = limbs_const(L)

# 512-bit inputs are 64 limbs; all fold intermediates live in 65 limbs.
_NL512 = 65


def _plain_carry(x, n: int):
    """Carry pass without any modular fold (plain multi-precision integer)."""
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(n):
        v = x[..., i] + c
        c = v >> RADIX
        out.append(v & MASK)
    out[-1] = out[-1] + (c << RADIX)  # keep any residue in the top limb
    return jnp.stack(out, axis=-1)


def _mul_by_const(x, nx: int, const_limbs: np.ndarray, nout: int):
    """Multi-precision multiply of x (nx limbs) by a static constant."""
    k = len(const_limbs)
    cols = jnp.zeros(x.shape[:-1] + (nout,), _DTYPE)
    for i in range(k):
        ci = int(const_limbs[i])
        if ci == 0:
            continue
        hi = min(nx, nout - i)
        cols = cols.at[..., i : i + hi].add(ci * x[..., :hi])
    return cols


_FOLD_M: list[np.ndarray] = []


def _build_fold_constants():
    """Static M_k*L addends keeping each 2^252-fold non-negative.

    After normalizing to S_k bits, hi < 2^(S_k-252) so hi*delta <
    2^(S_k-252)*2^125. Pick M_k = ceil(2^(S_k-127)/L)+1; then
    lo - hi*delta + M_k*L is in [0, 2^252 + (M_k+1)*L)."""
    sizes = [512, 390, 266]
    for s in sizes:
        m = (1 << max(s - 127, 0)) // L + 2
        _FOLD_M.append(limbs_const(m * L, _NL512))


_build_fold_constants()
_DELTA_LIMBS = limbs_const(DELTA, 16)


def reduce512_mod_l(x):
    """(…,64) limbs (512-bit LE integer) -> (…,32) limbs in [0, L)."""
    x = jnp.concatenate(
        [jnp.asarray(x, _DTYPE), jnp.zeros(x.shape[:-1] + (1,), _DTYPE)],
        axis=-1,
    )
    x = _plain_carry(x, _NL512)
    for m_l in _FOLD_M:
        # hi = x >> 252: bit 252 sits at limb 31 bit 4, so each hi limb
        # stitches the top nibble of x[31+i] to the low nibble of x[32+i].
        hi = (x[..., 31:64] >> 4) | ((x[..., 32:65] & 0xF) << 4)
        hi = jnp.concatenate([hi, x[..., 64:65] >> 4], axis=-1)  # 34 limbs
        lo = x.at[..., 31].set(x[..., 31] & 0xF)
        lo = lo.at[..., 32:].set(0)
        prod = _mul_by_const(hi, 34, _DELTA_LIMBS, 50)
        prod = jnp.concatenate(
            [prod, jnp.zeros(prod.shape[:-1] + (_NL512 - 50,), _DTYPE)],
            axis=-1,
        )
        x = lo - prod + jnp.asarray(m_l)
        x = _plain_carry(x, _NL512)
    # Value now < 2^254-ish: at most 3 conditional subtracts of L.
    x = x[..., : NLIMBS + 1]
    l_ext = np.concatenate([_L_LIMBS, np.zeros(1, np.int32)])
    for _ in range(4):
        b = jnp.zeros_like(x[..., 0])
        digits = []
        for i in range(NLIMBS + 1):
            v = x[..., i] - jnp.asarray(l_ext)[i] + b
            digits.append(v & MASK)
            b = v >> RADIX
        y = jnp.stack(digits, axis=-1)
        x = jnp.where((b == 0)[..., None], y, x)
    return x[..., :NLIMBS]


def scalar_lt_l(s):
    """(…,32) limbs -> bool: is the 256-bit scalar strictly below L?"""
    b = jnp.zeros_like(s[..., 0])
    for i in range(NLIMBS):
        v = s[..., i] - jnp.asarray(_L_LIMBS)[i] + b
        b = v >> RADIX
    return b < 0


def scalar_bits(s, nbits: int = 256):
    """(…,32) limbs -> (…, nbits) int32 bit array, LSB first."""
    shifts = jnp.arange(RADIX, dtype=_DTYPE)
    bits = (s[..., :, None] >> shifts) & 1
    return bits.reshape(s.shape[:-1] + (NLIMBS * RADIX,))[..., :nbits].astype(
        jnp.int32
    )
