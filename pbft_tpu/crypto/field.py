"""GF(2^255-19) and mod-L arithmetic in JAX, designed for vmap/XLA.

Representation: field elements are (..., 16) int64 arrays of 16-bit limbs,
little-endian (value = sum limb_i * 2^(16*i)). Limbs are *signed* and allowed
to drift a few bits above 16 between operations ("loose" form); every multiply
renormalizes. The signed-limb choice makes subtraction carry-free and the
arithmetic right shift does borrow propagation for free.

Bounds that make this sound (see ``mul``): with |limb| < 2^20 on both inputs,
schoolbook columns are < 16 * 2^40 = 2^44 and the 38-fold (2^256 = 38 mod p)
adds < 2^50 — far inside int64. Two carry passes return limbs to < 2^17.

The mod-L half (group order L = 2^252 + delta) implements the 512-bit
challenge-hash reduction with three positivity-preserving folds at the 2^252
boundary: x = hi*2^252 + lo == lo - hi*delta + M_k*L (mod L) where M_k is a
static per-iteration constant chosen so the result stays non-negative while
still shrinking ~127 bits per fold.

This is the arithmetic layer under pbft_tpu.crypto.ed25519; everything here
is batch-agnostic (leading dims broadcast) and contains no data-dependent
control flow, so it jits and vmaps cleanly onto TPU.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
DELTA = L - 2**252
NLIMBS = 16
MASK = 0xFFFF


def limbs_const(v: int, n: int = NLIMBS) -> np.ndarray:
    """Static Python int -> (n,) int64 limb array (16-bit, little-endian)."""
    return np.array([(v >> (16 * i)) & MASK for i in range(n)], dtype=np.int64)


def limbs_to_int(arr) -> int:
    """(…,16) limbs -> Python int (tests/debug only; takes the last axis)."""
    a = np.asarray(arr, dtype=object)
    return int(sum(int(x) << (16 * i) for i, x in enumerate(a)))


_P_LIMBS = limbs_const(P)
_2P_LIMBS = limbs_const(2 * P)


def zeros_like_field(x):
    return jnp.zeros(x.shape, jnp.int64)


def carry(x):
    """One signed carry pass; wraps the 2^256 overflow back as *38 (mod p)."""
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        v = x[..., i] + c
        c = v >> 16
        out.append(v & MASK)
    r = jnp.stack(out, axis=-1)
    return r.at[..., 0].add(38 * c)


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a - b)


def neg(a):
    return carry(jnp.asarray(_2P_LIMBS) - a)


def mul(a, b):
    """Field multiply. Inputs: loose limbs |x| < 2^20. Output: limbs < 2^17."""
    cols = jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape)[:-1] + (31,), jnp.int64)
    for i in range(NLIMBS):
        cols = cols.at[..., i : i + NLIMBS].add(a[..., i : i + 1] * b)
    lo = cols[..., :NLIMBS]
    lo = lo.at[..., : NLIMBS - 1].add(38 * cols[..., NLIMBS:])
    return carry(carry(lo))


def sqr(a):
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small static scalar (k < 2^20)."""
    return carry(a * k)


def _sqr_body(_, v):
    return sqr(v)


def pow2k(x, k: int):
    """x^(2^k) by k squarings (static k; fori_loop body is a module-level
    function so jax's trace cache hits across calls)."""
    from jax import lax

    if k <= 4:
        for _ in range(k):
            x = sqr(x)
        return x
    return lax.fori_loop(0, k, _sqr_body, x)


def _inv_chain(z):
    """Shared ladder: returns (z^(2^250-1), z^11, z^(2^50-1), z^(2^10-1), z2).

    The classic curve25519 exponent chain; pieces are reused by both inv()
    (exponent p-2 = 2^255-21) and pow_p58() (exponent (p-5)/8 = 2^252-3).
    """
    z2 = sqr(z)
    z8 = pow2k(z2, 2)
    z9 = mul(z, z8)
    z11 = mul(z2, z9)
    z22 = sqr(z11)
    z_5_0 = mul(z9, z22)  # 2^5 - 1
    z_10_0 = mul(pow2k(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(pow2k(z_10_0, 10), z_10_0)  # 2^20 - 1
    z_40_0 = mul(pow2k(z_20_0, 20), z_20_0)  # 2^40 - 1
    z_50_0 = mul(pow2k(z_40_0, 10), z_10_0)  # 2^50 - 1
    z_100_0 = mul(pow2k(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = mul(pow2k(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = mul(pow2k(z_200_0, 50), z_50_0)  # 2^250 - 1
    return z_250_0, z11


def inv(z):
    """z^(p-2) = z^(2^255-21): the field inverse (inv(0) = 0)."""
    z_250_0, z11 = _inv_chain(z)
    return mul(pow2k(z_250_0, 5), z11)


def pow_p58(z):
    """z^((p-5)/8) = z^(2^252-3), used for the square-root-ratio."""
    z_250_0, _ = _inv_chain(z)
    return mul(pow2k(z_250_0, 2), z)


def canon(x):
    """Canonical form: limbs in [0, 2^16), value in [0, p)."""
    x = carry(carry(x))
    # Force non-negativity: add 2p (== 0 mod p); the value may have been a
    # small negative after signed folds.
    x = carry(x + jnp.asarray(_2P_LIMBS))
    # Fold bit 255+: value < 2^256 -> < 2^255 + 38.
    hi = x[..., NLIMBS - 1] >> 15
    x = x.at[..., NLIMBS - 1].add(-(hi << 15))
    x = x.at[..., 0].add(19 * hi)
    x = carry(x)
    # At most two conditional subtracts of p remain.
    for _ in range(2):
        b = jnp.zeros_like(x[..., 0])
        digits = []
        for i in range(NLIMBS):
            v = x[..., i] - jnp.asarray(_P_LIMBS)[i] + b
            digits.append(v & MASK)
            b = v >> 16
        y = jnp.stack(digits, axis=-1)
        ge = b == 0  # no final borrow -> x >= p
        x = jnp.where(ge[..., None], y, x)
    return x


def eq(a, b):
    return jnp.all(canon(a) == canon(b), axis=-1)


def is_zero(a):
    return jnp.all(canon(a) == 0, axis=-1)


def bytes_to_limbs(b):
    """(…,2n) uint8 little-endian -> (…,n) int64 limbs (32 bytes -> 16 limbs,
    64-byte digests -> 32 limbs)."""
    b = jnp.asarray(b, jnp.int64)
    pairs = b.reshape(b.shape[:-1] + (b.shape[-1] // 2, 2))
    return pairs[..., 0] + (pairs[..., 1] << 8)


def limbs_to_bytes(x):
    """Canonical limbs -> (…,32) uint8 little-endian."""
    x = canon(x)
    lo = (x & 0xFF).astype(jnp.uint8)
    hi = ((x >> 8) & 0xFF).astype(jnp.uint8)
    return jnp.stack([lo, hi], axis=-1).reshape(x.shape[:-1] + (32,))


# ---------------------------------------------------------------------------
# Scalar arithmetic mod L (group order), for the challenge hash and S check.
# ---------------------------------------------------------------------------

_L_LIMBS = limbs_const(L)


def _plain_carry(x, n: int):
    """Carry pass without any modular fold (plain multi-precision integer)."""
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(n):
        v = x[..., i] + c
        c = v >> 16
        out.append(v & MASK)
    out[-1] = out[-1] + (c << 16)  # keep any residue in the top limb
    return jnp.stack(out, axis=-1)


def _mul_by_const(x, nx: int, const_limbs: np.ndarray, nout: int):
    """Multi-precision multiply of x (nx limbs) by a static constant."""
    k = len(const_limbs)
    cols = jnp.zeros(x.shape[:-1] + (nout,), jnp.int64)
    for i in range(k):
        ci = int(const_limbs[i])
        if ci == 0:
            continue
        hi = min(nx, nout - i)
        cols = cols.at[..., i : i + hi].add(ci * x[..., :hi])
    return cols


_FOLD_M: list[np.ndarray] = []


def _build_fold_constants():
    """Static M_k*L addends keeping each 2^252-fold non-negative.

    After normalizing to S_k bits, hi < 2^(S_k-252) so hi*delta <
    2^(S_k-252)*2^125. Pick M_k = ceil(2^(S_k-127)/L)+1; then
    lo - hi*delta + M_k*L is in [0, 2^252 + (M_k+1)*L)."""
    sizes = [512, 390, 266]
    for s in sizes:
        m = (1 << max(s - 127, 0)) // L + 2
        _FOLD_M.append(limbs_const(m * L, 33))


_build_fold_constants()
_DELTA_LIMBS = limbs_const(DELTA, 8)


def reduce512_mod_l(x):
    """(…,32) limbs (512-bit LE integer) -> (…,16) limbs in [0, L)."""
    x = jnp.concatenate([x, jnp.zeros(x.shape[:-1] + (1,), jnp.int64)], axis=-1)
    x = _plain_carry(x, 33)
    for m_l in _FOLD_M:
        # hi = x >> 252; limb 15 keeps its low 12 bits.
        hi = ((x[..., 15:32] >> 12) | ((x[..., 16:33] & 0xFFF) << 4))
        lo = x.at[..., 15].set(x[..., 15] & 0xFFF)
        lo = lo.at[..., 16:].set(0)
        prod = _mul_by_const(hi, 17, _DELTA_LIMBS, 25)
        prod = jnp.concatenate(
            [prod, jnp.zeros(prod.shape[:-1] + (8,), jnp.int64)], axis=-1
        )
        x = lo - prod + jnp.asarray(m_l)
        x = _plain_carry(x, 33)
    # Value now < 2^254-ish: at most 3 conditional subtracts of L.
    x = x[..., :NLIMBS + 1]
    l_ext = np.concatenate([_L_LIMBS, np.zeros(1, np.int64)])
    for _ in range(4):
        b = jnp.zeros_like(x[..., 0])
        digits = []
        for i in range(NLIMBS + 1):
            v = x[..., i] - jnp.asarray(l_ext)[i] + b
            digits.append(v & MASK)
            b = v >> 16
        y = jnp.stack(digits, axis=-1)
        x = jnp.where((b == 0)[..., None], y, x)
    return x[..., :NLIMBS]


def scalar_lt_l(s):
    """(…,16) limbs -> bool: is the 256-bit scalar strictly below L?"""
    b = jnp.zeros_like(s[..., 0])
    for i in range(NLIMBS):
        v = s[..., i] - jnp.asarray(_L_LIMBS)[i] + b
        b = v >> 16
    return b < 0


def scalar_bits(s, nbits: int = 256):
    """(…,16) limbs -> (…, nbits) int32 bit array, LSB first."""
    shifts = jnp.arange(16, dtype=jnp.int64)
    bits = (s[..., :, None] >> shifts) & 1
    return bits.reshape(s.shape[:-1] + (NLIMBS * 16,))[..., :nbits].astype(jnp.int32)
