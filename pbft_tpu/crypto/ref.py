"""Pure-Python reference Ed25519 (RFC 8032) on big ints.

This is the framework's correctness oracle and host-side signer:

- clients and replicas *sign* here (signing is not the hot path — a replica
  signs one message per phase, while it must *verify* 2f and 2f+1 of them;
  see SURVEY.md §3.4-3.5);
- the JAX/TPU batch verifier (``pbft_tpu.crypto.ed25519``) is
  equivalence-tested against ``verify`` on RFC 8032 vectors and random
  keys/messages.

The reference repo generated an Ed25519 keypair but never signed or verified
anything (signature checks were TODOs: reference src/behavior.rs:127,:185);
this module is the capability those TODOs pointed at.

Implementation notes: textbook twisted-Edwards affine arithmetic over
GF(2^255-19); cofactorless verification equation [S]B == R + [h]A with strict
S < L (RFC 8032 §5.1.7).
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
# Edwards curve constant d = -121665/121666 mod p.
D = (-121665 * pow(121666, P - 2, P)) % P

# Base point B: y = 4/5, x recovered with the even-x convention then negated
# to the canonical odd... (RFC 8032: base point has positive/even x? The
# canonical base point x is the one with x mod 2 == 0.)
_BY = (4 * pow(5, P - 2, P)) % P


def _sqrt_ratio(u: int, v: int) -> Tuple[bool, int]:
    """Return (ok, r) with r^2 * v == u (mod p) when ok.

    Uses the p ≡ 5 (mod 8) trick: candidate r = u * v^3 * (u*v^7)^((p-5)/8),
    correcting by sqrt(-1) when needed.
    """
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    if check == u % P:
        return True, r
    if check == (-u) % P:
        return True, r * pow(2, (P - 1) // 4, P) % P
    return False, 0


def _recover_x(y: int, sign: int) -> int | None:
    """x from y on -x^2 + y^2 = 1 + d x^2 y^2, choosing the given sign bit."""
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    ok, x = _sqrt_ratio(u, v)
    if not ok:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None
BASE = (_BX, _BY)


def point_add(a: Tuple[int, int], b: Tuple[int, int]) -> Tuple[int, int]:
    x1, y1 = a
    x2, y2 = b
    den = D * x1 * x2 % P * y1 % P * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + den, P - 2, P)
    y3 = (y1 * y2 + x1 * x2) * pow(1 - den, P - 2, P)
    return x3 % P, y3 % P


def shamir_row0() -> list:
    """[0]B..[3]B as (x, y, z=1, t=xy) ints: the static h=0 row of the
    verifier's Shamir table. Single source for BOTH verifier backends
    (ed25519.py XLA path and pallas_kernels.py) — two copies that drift
    would split replicas."""
    b2 = point_add(BASE, BASE)
    b3 = point_add(b2, BASE)
    rows = [(0, 1, 1, 0)]
    for p in (BASE, b2, b3):
        rows.append((p[0], p[1], 1, p[0] * p[1] % P))
    return rows


_D2 = 2 * D % P


def _ext_add(p, q):
    """Complete unified addition in extended coordinates (a=-1); avoids the
    per-addition inversions of the affine form — this is the host signer's
    hot loop."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * _D2 % P * t2 % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def scalar_mult(k: int, pt: Tuple[int, int]) -> Tuple[int, int]:
    acc = (0, 1, 1, 0)
    cur = (pt[0], pt[1], 1, pt[0] * pt[1] % P)
    while k:
        if k & 1:
            acc = _ext_add(acc, cur)
        cur = _ext_add(cur, cur)
        k >>= 1
    x, y, z, _ = acc
    zi = pow(z, P - 2, P)
    return x * zi % P, y * zi % P


def point_compress(pt: Tuple[int, int]) -> bytes:
    x, y = pt
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(data: bytes) -> Tuple[int, int] | None:
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return x, y


def _h512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def secret_expand(seed: bytes) -> Tuple[int, bytes]:
    """seed -> (clamped scalar a, hash prefix for nonce derivation)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def keygen(seed: bytes | None = None) -> Tuple[bytes, bytes]:
    """Return (seed a.k.a. private key, 32-byte public key)."""
    if seed is None:
        seed = secrets.token_bytes(32)
    a, _ = secret_expand(seed)
    return seed, point_compress(scalar_mult(a, BASE))


def public_key(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(scalar_mult(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(seed)
    pub = point_compress(scalar_mult(a, BASE))
    r = _h512_int(prefix, msg) % L
    big_r = point_compress(scalar_mult(r, BASE))
    h = _h512_int(big_r, pub, msg) % L
    s = (r + h * a) % L
    return big_r + int.to_bytes(s, 32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Cofactorless RFC 8032 verify: [S]B == R + [h]A, strict S < L."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    a_pt = point_decompress(pub)
    if a_pt is None:
        return False
    r_pt = point_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    h = _h512_int(sig[:32], pub, msg) % L
    lhs = scalar_mult(s, BASE)
    rhs = point_add(r_pt, scalar_mult(h, a_pt))
    return lhs == rhs
