"""Ed25519 verification in JAX, built for one-XLA-launch batch verification.

The consensus hot path (SURVEY.md §3.4-3.5): every PREPARE/COMMIT quorum needs
2f / 2f+1 signatures verified. The reference left signature checks as TODOs
(reference src/behavior.rs:127, :185); here they are the centerpiece, designed
so a whole view-round's quorum certificates verify as one `jax.vmap` batch.

Scalar pipeline per item (pub 32B, msg 32B digest, sig 64B = R||S):
  1. h = SHA-512(R || pub || msg) reduced mod L      (sha512.py + field.py)
  2. decompress pub -> A (reject non-canonical y, off-curve, x=0&sign)
  3. P = [S]B + [h](-A) via a 256-step Shamir (joint double-scalar) ladder
     over the 4-entry table {O, B, -A, B-A}, using complete extended
     twisted-Edwards addition (a=-1, add-2008-hwcd-3) -- completeness means
     no data-dependent branches, which is exactly what XLA wants.
  4. valid = canonical(S) & ok(A) & (compress(P) == R)
     (comparing compressed bytes rejects non-canonical R for free).

Cofactorless equation, strict S < L: bit-for-bit the same accept set as the
pure-Python oracle pbft_tpu.crypto.ref (RFC 8032).

Points are tuples (X, Y, Z, T) of (..., 32)-limb int32 field elements with
T = XY/Z (radix 2^8 — native width for the TPU's 32-bit vector unit; see
field.py). All control flow is static; everything vmaps/jits.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import field as F
from . import ref
from .sha512 import sha512

# Static curve constants, as limb arrays (computed from the oracle's big
# ints; ref.py is the RFC 8032 ground truth).
_D = F.limbs_const(ref.D)
_D2 = F.limbs_const(2 * ref.D % F.P)
_SQRT_M1 = F.limbs_const(pow(2, (F.P - 1) // 4, F.P))
_BX = F.limbs_const(ref.BASE[0])
_BY = F.limbs_const(ref.BASE[1])
_BT = F.limbs_const(ref.BASE[0] * ref.BASE[1] % F.P)
_ONE = F.limbs_const(1)
_ZERO = F.limbs_const(0)

# [0]B, [1]B, [2]B, [3]B in extended coords (X, Y, Z=1, T=XY) — the static
# row of the Shamir table (ref.shamir_row0, shared with pallas_kernels),
# precomputed so the ladder never spends traced point ops on base multiples.
_ROW0 = tuple(
    np.stack([F.limbs_const(v) for v in coords])
    for coords in zip(*ref.shamir_row0())
)  # 4 arrays of shape (4, 32): X-row, Y-row, Z-row, T-row


def identity(shape=()):
    z = jnp.broadcast_to(jnp.asarray(_ZERO), shape + (F.NLIMBS,))
    o = jnp.broadcast_to(jnp.asarray(_ONE), shape + (F.NLIMBS,))
    return (z, o, o, z)


def base_point(shape=()):
    return tuple(
        jnp.broadcast_to(jnp.asarray(c), shape + (F.NLIMBS,))
        for c in (_BX, _BY, _ONE, _BT)
    )


def point_add(p, q):
    """Complete unified addition (a=-1 twisted Edwards, extended coords)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, jnp.asarray(_D2)), t2)
    d = F.mul_small(F.mul(z1, z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4M+4S, vs 9M for the
    unified add — the ladder is doubling-dominated, so this matters."""
    x1, y1, z1, _ = p
    a = F.sqr(x1)
    b = F.sqr(y1)
    c = F.mul_small(F.sqr(z1), 2)
    d = F.neg(a)  # a = -1 twist
    e = F.sub(F.sub(F.sqr(F.add(x1, y1)), a), b)
    g = F.add(d, b)
    f = F.sub(g, c)
    h = F.sub(d, b)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p):
    x, y, z, t = p
    return (F.neg(x), y, z, F.neg(t))


def _use_pallas() -> bool:
    """PBFT_PALLAS=1 routes the three long multiply chains (pow_p58, inv,
    Shamir ladder) through the fused Pallas kernels in pallas_kernels.py.
    Read at trace time — set it before the first verify of a given batch
    shape. Off-TPU the kernels would run under the Pallas INTERPRETER —
    orders of magnitude slower than the XLA path — so a non-TPU backend
    ignores the flag unless PBFT_PALLAS_INTERPRET=1 explicitly opts in
    (equivalence tests do)."""
    import os

    if os.environ.get("PBFT_PALLAS") != "1":
        return False
    if os.environ.get("PBFT_PALLAS_INTERPRET") == "1":
        return True
    import jax

    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _impl_pow_p58(z):
    if _use_pallas():
        from . import pallas_kernels

        return pallas_kernels.pow_p58(z)
    return F.pow_p58(z)


def _impl_inv(z):
    if _use_pallas():
        from . import pallas_kernels

        return pallas_kernels.inv(z)
    return F.inv(z)


def sqrt_ratio(u, v):
    """(ok, r) with v*r^2 == u when ok; the p = 5 (mod 8) method."""
    v2 = F.sqr(v)
    v3 = F.mul(v, v2)
    v7 = F.mul(v3, F.sqr(v2))
    r = F.mul(F.mul(u, v3), _impl_pow_p58(F.mul(u, v7)))
    check = F.mul(v, F.sqr(r))
    ok_plus = F.eq(check, u)
    ok_minus = F.eq(check, F.neg(u))
    r = jnp.where(ok_minus[..., None], F.mul(r, jnp.asarray(_SQRT_M1)), r)
    return ok_plus | ok_minus, r


def decompress(ybytes):
    """(…,32) uint8 -> (ok, point). RFC 8032 §5.1.3 decoding."""
    ybytes = jnp.asarray(ybytes, jnp.uint8)
    sign = (ybytes[..., 31] >> 7).astype(jnp.int32)
    masked = ybytes.at[..., 31].set(ybytes[..., 31] & 0x7F)
    y = F.bytes_to_limbs(masked)
    # Canonical check: y < p.
    b = jnp.zeros_like(y[..., 0])
    for i in range(F.NLIMBS):
        b = (y[..., i] - jnp.asarray(F._P_LIMBS)[i] + b) >> F.RADIX
    ok_canon = b < 0
    y2 = F.sqr(y)
    u = F.sub(y2, jnp.asarray(_ONE))
    v = F.add(F.mul(y2, jnp.asarray(_D)), jnp.asarray(_ONE))
    ok_sqrt, x = sqrt_ratio(u, v)
    x = F.canon(x)
    x_zero = jnp.all(x == 0, axis=-1)
    ok = ok_canon & ok_sqrt & ~(x_zero & (sign == 1))
    flip = (x[..., 0] & 1) != sign
    x = jnp.where(flip[..., None], F.neg(x), x)
    one = jnp.broadcast_to(jnp.asarray(_ONE), y.shape)
    return ok, (x, y, one, F.mul(x, y))


def compress(p):
    """Point -> (…,32) uint8 canonical encoding."""
    x, y, z, _ = p
    zi = _impl_inv(z)
    xa = F.canon(F.mul(x, zi))
    ybytes = F.limbs_to_bytes(F.mul(y, zi))
    sign = (xa[..., 0] & 1).astype(jnp.uint8)
    return ybytes.at[..., 31].add(sign << 7)


def shamir_ladder(s_bits, h_bits, a_neg):
    """[S]B + [h]*(-A) with a joint 2-bit window: one 16-entry table lookup
    per pair of scalar bits. 128 iterations of (2 doublings + 1 addition)
    instead of 256 x (double + add) — ~40% fewer point operations, and the
    whole loop is static control flow (fori_loop) with select-based table
    lookup, exactly what XLA tiles well.

    s_bits, h_bits: (…,256) int32 LSB-first; a_neg: point with (…,32) coords.
    """
    shape = s_bits.shape[:-1]
    # Table E[s + 4h] = [s]B + [h](-A) for s, h in 0..3, held as STACKED
    # arrays (16, …, 32) per coordinate. The B-multiples row is a static
    # constant (_ROW0); the three -A rows cost one doubling, one addition,
    # and ONE batched addition traced over a (3, 4) leading axis — the
    # stacked layout keeps the traced graph a single point_add instead of
    # twelve, and the mux below is 4 selects per coordinate instead of 15.
    row0 = tuple(
        jnp.broadcast_to(
            jnp.asarray(c).reshape((4,) + (1,) * len(shape) + (F.NLIMBS,)),
            (4,) + shape + (F.NLIMBS,),
        )
        for c in _ROW0
    )
    a1 = a_neg
    a2 = point_double(a1)
    a3 = point_add(a2, a1)
    arows = tuple(
        jnp.stack([a1[c], a2[c], a3[c]], axis=0)[:, None]
        for c in range(4)
    )  # (3, 1, …, 32) per coordinate
    prods = point_add(tuple(r[None] for r in row0), arows)  # (3, 4, …, 32)
    entries = tuple(
        jnp.concatenate([row0[c][None], prods[c]], axis=0).reshape(
            (16,) + shape + (F.NLIMBS,)
        )
        for c in range(4)
    )  # index = 4h + s

    def mux(bits, table):
        """table: coordinate arrays with a leading 2^len(bits) axis;
        bits LSB-first halve it with one select per level."""
        cur = table
        for b in bits:
            cond = (b == 1)[..., None]
            cur = tuple(jnp.where(cond, c[1::2], c[0::2]) for c in cur)
        return tuple(c[0] for c in cur)

    def body(k, acc):
        step = 127 - k
        s0 = lax.dynamic_index_in_dim(s_bits, 2 * step, axis=-1, keepdims=False)
        s1 = lax.dynamic_index_in_dim(s_bits, 2 * step + 1, axis=-1, keepdims=False)
        h0 = lax.dynamic_index_in_dim(h_bits, 2 * step, axis=-1, keepdims=False)
        h1 = lax.dynamic_index_in_dim(h_bits, 2 * step + 1, axis=-1, keepdims=False)
        sel = mux([s0, s1, h0, h1], entries)
        acc = point_double(point_double(acc))
        return point_add(acc, sel)

    return lax.fori_loop(0, 128, body, identity(shape))


def verify_kernel(pub, msg, sig):
    """(…,32),(…,32),(…,64) uint8 -> (…,) bool. Batch-agnostic."""
    pub = jnp.asarray(pub, jnp.uint8)
    msg = jnp.asarray(msg, jnp.uint8)
    sig = jnp.asarray(sig, jnp.uint8)
    r_bytes = sig[..., :32]
    s_bytes = sig[..., 32:]
    # Challenge hash: h = SHA512(R || A || M) mod L.
    h_raw = sha512(jnp.concatenate([r_bytes, pub, msg], axis=-1))
    h = F.reduce512_mod_l(F.bytes_to_limbs(h_raw))
    s = F.bytes_to_limbs(s_bytes)
    s_ok = F.scalar_lt_l(s)
    ok_a, a_pt = decompress(pub)
    if _use_pallas():
        from . import pallas_kernels

        p = pallas_kernels.ladder(
            F.scalar_bits(s), F.scalar_bits(h), point_neg(a_pt)
        )
    else:
        p = shamir_ladder(F.scalar_bits(s), F.scalar_bits(h), point_neg(a_pt))
    enc = compress(p)
    match = jnp.all(enc == r_bytes, axis=-1)
    return ok_a & s_ok & match
