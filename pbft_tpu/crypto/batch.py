"""The batched verifier: PBFT's crypto hot path as one XLA launch.

`verify_batch(pubs, msgs, sigs)` verifies B independent Ed25519 signatures in
a single jit-compiled call — the TPU-era replacement for the reference's
(intended) per-message checks. A replica accumulates a view-round's quorum
certificates (up to 2*(2f+1) PREPARE+COMMIT signatures per round, times the
batching window) into fixed-size (pubkey, msg-digest, signature) tensors and
gates phase transitions on the returned bitmap (BASELINE.json north_star).

Shapes are static per batch size; use padded power-of-two batches to bound
the number of XLA compilations (pad slots are filled with a known-good
self-signed triple so padding never fails a batch).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import ref
from .ed25519 import verify_kernel

# One known-valid (pub, msg, sig) triple for padding slots.
_PAD_SEED = bytes(range(32))
_PAD_MSG = b"pbft_tpu batch padding.........."
assert len(_PAD_MSG) == 32
_PAD_PUB = np.frombuffer(ref.public_key(_PAD_SEED), np.uint8)
_PAD_SIG = np.frombuffer(ref.sign(_PAD_SEED, _PAD_MSG), np.uint8)
_PAD_MSG_ARR = np.frombuffer(_PAD_MSG, np.uint8)


@functools.partial(jax.jit, static_argnames=())
def _verify_jit(pubs, msgs, sigs):
    return verify_kernel(pubs, msgs, sigs)


def verify_batch(pubs, msgs, sigs) -> jax.Array:
    """(B,32),(B,32),(B,64) uint8 arrays -> (B,) bool validity bitmap."""
    return _verify_jit(
        jnp.asarray(pubs, jnp.uint8),
        jnp.asarray(msgs, jnp.uint8),
        jnp.asarray(sigs, jnp.uint8),
    )


def pad_batch(items, size: int):
    """items: list of (pub32, msg32, sig64) bytes -> padded uint8 arrays.

    Returns (pubs, msgs, sigs, n) where slots >= n are the known-good pad
    triple (they verify True and are sliced off by the caller).
    """
    n = len(items)
    if n > size:
        raise ValueError(f"batch of {n} exceeds padded size {size}")
    pubs = np.tile(_PAD_PUB, (size, 1))
    msgs = np.tile(_PAD_MSG_ARR, (size, 1))
    sigs = np.tile(_PAD_SIG, (size, 1))
    for i, (pub, msg, sig) in enumerate(items):
        pubs[i] = np.frombuffer(pub, np.uint8)
        msgs[i] = np.frombuffer(msg, np.uint8)
        sigs[i] = np.frombuffer(sig, np.uint8)
    return pubs, msgs, sigs, n


# Padded sizes are drawn from a short ladder so the whole system compiles
# at most len(_PAD_LADDER) kernel shapes (recompiles are minutes on CPU).
_PAD_LADDER = (16, 64, 256, 1024, 4096)


def pad_size(n: int) -> int:
    for size in _PAD_LADDER:
        if n <= size:
            return size
    return ((n + _PAD_LADDER[-1] - 1) // _PAD_LADDER[-1]) * _PAD_LADDER[-1]


def verify_many(
    items,
    pad_to: int | None = None,
    launch=None,
    size_multiple: int = 1,
) -> list[bool]:
    """Convenience host API: list of (pub, msg, sig) byte triples -> bools.

    ``launch`` overrides the XLA call (e.g. a mesh-sharded jit from
    pbft_tpu.parallel); ``size_multiple`` rounds the padded size up to a
    multiple (sharded launches need device-divisible batches). One body
    for every serving path so pad/slice/verdict handling cannot drift.
    """
    if not items:
        return []
    size = pad_to or pad_size(len(items))
    if size % size_multiple:
        size = ((size + size_multiple - 1) // size_multiple) * size_multiple
    pubs, msgs, sigs, n = pad_batch(items, size)
    out = np.asarray((launch or verify_batch)(pubs, msgs, sigs))
    return [bool(v) for v in out[:n]]
