"""Crypto subsystem: Ed25519 + digests.

- ``ref``     — pure-Python (big-int) Ed25519: the correctness oracle, key
  generation, and the signer used by clients/replicas on the host side.
- ``sha512``  — JAX SHA-512 (uint32 pairs), fixed-shape, vmappable.
- ``field``   — JAX GF(2^255-19) and mod-L limb arithmetic.
- ``ed25519`` — JAX Ed25519 verification (decompress, Shamir double-scalar
  ladder, compress) built on ``field`` + ``sha512``.
- ``batch``   — the batched verifier: one XLA launch per (pubkey, msg, sig)
  tensor, returning a per-item validity bitmap.
"""
