"""SHA-512 in JAX as uint32 pairs, fixed-shape and vmappable.

Used for the Ed25519 challenge hash h = SHA-512(R || A || M) inside the
batched TPU verifier. PBFT messages are signed over their 32-byte Blake2b
digests, so the hash input is always exactly 96 bytes — one SHA-512 block
after padding — which keeps every shape static for XLA.

Each 64-bit word lives as a (hi, lo) pair of **uint32** lanes: the TPU's
vector unit is 32-bit, so this runs native-width instead of forcing jax x64
mode and emulated 64-bit ops. Rotations/shifts split across the pair with
static shift counts; 64-bit addition propagates one carry computed by an
unsigned compare.

The round constants and initial state are derived at import time from first
principles (fractional bits of square/cube roots of the first primes,
FIPS 180-4 §4.2.3/§5.3.5) rather than transcribed, and the whole module is
known-answer tested against hashlib.

All functions accept arbitrary leading batch dimensions; the message length
must be static.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp
from jax import lax

_MASK64 = (1 << 64) - 1


def _primes(count: int) -> list[int]:
    out, n = [], 2
    while len(out) < count:
        if all(n % q for q in range(2, int(math.isqrt(n)) + 1)):
            out.append(n)
        n += 1
    return out


def _iroot(n: int, k: int) -> int:
    """Integer floor k-th root by Newton iteration."""
    x = 1 << ((n.bit_length() + k - 1) // k)
    while True:
        y = ((k - 1) * x + n // x ** (k - 1)) // k
        if y >= x:
            return x
        x = y


_PRIMES = _primes(80)
# H0_i = first 64 fractional bits of sqrt(prime_i); K_t likewise for cbrt.
_H0 = [math.isqrt(p << 128) & _MASK64 for p in _PRIMES[:8]]
_K = [_iroot(p << 192, 3) & _MASK64 for p in _PRIMES]
_H0_HI = np.array([h >> 32 for h in _H0], dtype=np.uint32)
_H0_LO = np.array([h & 0xFFFFFFFF for h in _H0], dtype=np.uint32)
_K_HI = np.array([k >> 32 for k in _K], dtype=np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K], dtype=np.uint32)


# A 64-bit lane is the pair (hi, lo); all helpers below take/return pairs.


def _add64(a, b):
    hi, lo = a[0] + b[0], a[1] + b[1]
    return hi + (lo < a[1]).astype(jnp.uint32), lo


def _rotr(x, n: int):
    hi, lo = x
    if n >= 32:
        hi, lo = lo, hi
        n -= 32
    if n == 0:
        return hi, lo
    ns, ms = jnp.uint32(n), jnp.uint32(32 - n)
    return ((hi >> ns) | (lo << ms), (lo >> ns) | (hi << ms))


def _shr(x, n: int):
    """Logical right shift by n < 32."""
    hi, lo = x
    ns, ms = jnp.uint32(n), jnp.uint32(32 - n)
    return (hi >> ns, (lo >> ns) | (hi << ms))


def _xor(*xs):
    hi = xs[0][0]
    lo = xs[0][1]
    for x in xs[1:]:
        hi = hi ^ x[0]
        lo = lo ^ x[1]
    return hi, lo


def _big_sigma0(x):
    return _xor(_rotr(x, 28), _rotr(x, 34), _rotr(x, 39))


def _big_sigma1(x):
    return _xor(_rotr(x, 14), _rotr(x, 18), _rotr(x, 41))


def _small_sigma0(x):
    return _xor(_rotr(x, 1), _rotr(x, 8), _shr(x, 7))


def _small_sigma1(x):
    return _xor(_rotr(x, 19), _rotr(x, 61), _shr(x, 6))


def _compress_block(state, whi, wlo):
    """One SHA-512 compression. state: 8-tuple of (hi, lo) pairs of (...,)
    uint32; whi/wlo: (..., 16) uint32 big-endian message word halves."""
    pad = jnp.zeros(whi.shape[:-1] + (64,), jnp.uint32)
    whi0 = jnp.concatenate([whi, pad], axis=-1)
    wlo0 = jnp.concatenate([wlo, pad], axis=-1)

    def sched(t, w):
        whi, wlo = w

        def at(i):
            return (
                lax.dynamic_index_in_dim(whi, i, axis=-1, keepdims=False),
                lax.dynamic_index_in_dim(wlo, i, axis=-1, keepdims=False),
            )

        v = _add64(
            _add64(_small_sigma1(at(t - 2)), at(t - 7)),
            _add64(_small_sigma0(at(t - 15)), at(t - 16)),
        )
        return (
            lax.dynamic_update_index_in_dim(whi, v[0], t, axis=-1),
            lax.dynamic_update_index_in_dim(wlo, v[1], t, axis=-1),
        )

    whi, wlo = lax.fori_loop(16, 80, sched, (whi0, wlo0))
    khi = jnp.asarray(_K_HI)
    klo = jnp.asarray(_K_LO)

    def rnd(t, st):
        a, b, c, d, e, f, g, h = st
        kt = (
            lax.dynamic_index_in_dim(khi, t, keepdims=False),
            lax.dynamic_index_in_dim(klo, t, keepdims=False),
        )
        wt = (
            lax.dynamic_index_in_dim(whi, t, axis=-1, keepdims=False),
            lax.dynamic_index_in_dim(wlo, t, axis=-1, keepdims=False),
        )
        ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t1 = _add64(
            _add64(_add64(h, _big_sigma1(e)), _add64(ch, kt)), wt
        )
        t2 = _add64(_big_sigma0(a), maj)
        return (_add64(t1, t2), a, b, c, _add64(d, t1), e, f, g)

    out = lax.fori_loop(0, 80, rnd, state)
    return tuple(_add64(s, o) for s, o in zip(state, out))


def sha512(msg) -> jnp.ndarray:
    """SHA-512 of a fixed-length byte array.

    msg: (..., N) uint8 with static N. Returns (..., 64) uint8 digest.
    """
    msg = jnp.asarray(msg, jnp.uint8)
    n = msg.shape[-1]
    nblocks = (n + 17 + 127) // 128
    padlen = nblocks * 128 - n
    pad = np.zeros(padlen, np.uint8)
    pad[0] = 0x80
    pad[-16:] = np.frombuffer((n * 8).to_bytes(16, "big"), np.uint8)
    padded = jnp.concatenate(
        [msg, jnp.broadcast_to(jnp.asarray(pad), msg.shape[:-1] + (padlen,))],
        axis=-1,
    )
    # Big-endian 64-bit words as (hi, lo) uint32 halves: bytes 0-3 / 4-7.
    grouped = padded.reshape(msg.shape[:-1] + (nblocks, 16, 2, 4)).astype(
        jnp.uint32
    )
    shifts = jnp.asarray(np.arange(3, -1, -1, dtype=np.uint32) * 8)
    halves = jnp.sum(grouped << shifts, axis=-1)  # (..., nblocks, 16, 2)
    whi = halves[..., 0]
    wlo = halves[..., 1]

    state = tuple(
        (
            jnp.broadcast_to(jnp.uint32(hi), msg.shape[:-1]),
            jnp.broadcast_to(jnp.uint32(lo), msg.shape[:-1]),
        )
        for hi, lo in zip(_H0_HI, _H0_LO)
    )
    for b in range(nblocks):
        state = _compress_block(state, whi[..., b, :], wlo[..., b, :])

    out_shifts = jnp.asarray(np.arange(3, -1, -1, dtype=np.uint32) * 8)
    digest = jnp.stack(
        [
            ((half[..., None] >> out_shifts) & jnp.uint32(0xFF))
            for s in state
            for half in s
        ],
        axis=-2,
    )
    return digest.reshape(msg.shape[:-1] + (64,)).astype(jnp.uint8)
