"""SHA-512 in JAX (uint64), fixed-shape and vmappable.

Used for the Ed25519 challenge hash h = SHA-512(R || A || M) inside the
batched TPU verifier. PBFT messages are signed over their 32-byte Blake2b
digests, so the hash input is always exactly 96 bytes — one SHA-512 block
after padding — which keeps every shape static for XLA.

The round constants and initial state are derived at import time from first
principles (fractional bits of square/cube roots of the first primes,
FIPS 180-4 §4.2.3/§5.3.5) rather than transcribed, and the whole module is
known-answer tested against hashlib.

All functions accept arbitrary leading batch dimensions; the message length
must be static. uint64 arithmetic relies on jax x64 mode (enabled by
``pbft_tpu.__init__``).
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp
from jax import lax

_MASK64 = (1 << 64) - 1


def _primes(count: int) -> list[int]:
    out, n = [], 2
    while len(out) < count:
        if all(n % q for q in range(2, int(math.isqrt(n)) + 1)):
            out.append(n)
        n += 1
    return out


def _iroot(n: int, k: int) -> int:
    """Integer floor k-th root by Newton iteration."""
    x = 1 << ((n.bit_length() + k - 1) // k)
    while True:
        y = ((k - 1) * x + n // x ** (k - 1)) // k
        if y >= x:
            return x
        x = y


_PRIMES = _primes(80)
# H0_i = first 64 fractional bits of sqrt(prime_i); K_t likewise for cbrt.
_H0 = np.array(
    [math.isqrt(p << 128) & _MASK64 for p in _PRIMES[:8]], dtype=np.uint64
)
_K = np.array([_iroot(p << 192, 3) & _MASK64 for p in _PRIMES], dtype=np.uint64)


def _rotr(x, n: int):
    n = np.uint64(n)
    return (x >> n) | (x << np.uint64(64 - int(n)))


def _big_sigma0(x):
    return _rotr(x, 28) ^ _rotr(x, 34) ^ _rotr(x, 39)


def _big_sigma1(x):
    return _rotr(x, 14) ^ _rotr(x, 18) ^ _rotr(x, 41)


def _small_sigma0(x):
    return _rotr(x, 1) ^ _rotr(x, 8) ^ (x >> np.uint64(7))


def _small_sigma1(x):
    return _rotr(x, 19) ^ _rotr(x, 61) ^ (x >> np.uint64(6))


def _compress_block(state, words):
    """One SHA-512 compression. state: 8-tuple of (...,) uint64;
    words: (..., 16) uint64 big-endian message words."""
    pad = jnp.zeros(words.shape[:-1] + (64,), jnp.uint64)
    w0 = jnp.concatenate([words, pad], axis=-1)

    def sched(t, w):
        def at(i):
            return lax.dynamic_index_in_dim(w, i, axis=-1, keepdims=False)

        v = _small_sigma1(at(t - 2)) + at(t - 7) + _small_sigma0(at(t - 15)) + at(t - 16)
        return lax.dynamic_update_index_in_dim(w, v, t, axis=-1)

    w = lax.fori_loop(16, 80, sched, w0)
    kj = jnp.asarray(_K)

    def rnd(t, st):
        a, b, c, d, e, f, g, h = st
        kt = lax.dynamic_index_in_dim(kj, t, keepdims=False)
        wt = lax.dynamic_index_in_dim(w, t, axis=-1, keepdims=False)
        ch = (e & f) ^ (~e & g)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t1 = h + _big_sigma1(e) + ch + kt + wt
        t2 = _big_sigma0(a) + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = lax.fori_loop(0, 80, rnd, state)
    return tuple(s + o for s, o in zip(state, out))


def sha512(msg) -> jnp.ndarray:
    """SHA-512 of a fixed-length byte array.

    msg: (..., N) uint8 with static N. Returns (..., 64) uint8 digest.
    """
    msg = jnp.asarray(msg, jnp.uint8)
    n = msg.shape[-1]
    nblocks = (n + 17 + 127) // 128
    padlen = nblocks * 128 - n
    pad = np.zeros(padlen, np.uint8)
    pad[0] = 0x80
    pad[-16:] = np.frombuffer((n * 8).to_bytes(16, "big"), np.uint8)
    padded = jnp.concatenate(
        [msg, jnp.broadcast_to(jnp.asarray(pad), msg.shape[:-1] + (padlen,))],
        axis=-1,
    )
    grouped = padded.reshape(msg.shape[:-1] + (nblocks, 16, 8)).astype(jnp.uint64)
    shifts = jnp.asarray(np.arange(7, -1, -1, dtype=np.uint64) * 8)
    words = jnp.sum(grouped << shifts, axis=-1)

    state = tuple(
        jnp.broadcast_to(jnp.uint64(h), msg.shape[:-1]) for h in _H0
    )
    for b in range(nblocks):
        state = _compress_block(state, words[..., b, :])

    out_shifts = jnp.asarray(np.arange(7, -1, -1, dtype=np.uint64) * 8)
    digest = jnp.stack(
        [((s[..., None] >> out_shifts) & jnp.uint64(0xFF)) for s in state], axis=-2
    )
    return digest.reshape(msg.shape[:-1] + (64,)).astype(jnp.uint8)
