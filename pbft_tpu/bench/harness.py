"""Consensus throughput harness: BASELINE.json configs 1-5, both verifier arms.

Measures sustained consensus rounds/sec and signature verifications/sec
through the deterministic replica cores wired by the in-memory transport
(pbft_tpu.consensus.simulation) — the protocol-layer complement to the
repo-root bench.py kernel benchmark.

Verifier arms:
- "cpu":   the native C++ batch verifier (core/ed25519.cc via ctypes) —
           the control arm (falls back to the Python oracle if unbuilt).
- "jax":   the batched XLA kernel (one launch per batching window).

Usage: python -m pbft_tpu.bench.harness [--arm cpu|jax] [--config N] [--out f]
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable, List, Optional, Tuple

from ..consensus.simulation import Cluster


@dataclasses.dataclass
class BenchResult:
    config: str
    replicas: int
    f: int
    clients: int
    requests: int
    seconds: float
    rounds_per_sec: float
    sig_verifies_per_sec: float
    sig_verifications: int
    verifier: str
    byzantine: bool = False
    pipeline: int = 1  # in-flight requests per nominal client (native arms)
    service_inflight: int = 1  # overlapped service launches (native-tpu arm)
    # Request batching (ISSUE 4): with batch_max_items > 1 the unit of
    # agreement is a batch, so requests/sec and rounds/sec diverge —
    # mean_batch (requests executed / rounds executed, from the replicas'
    # own counters) is the measured amplification between them.
    requests_per_sec: float = 0.0
    mean_batch: float = 1.0
    batch_max_items: int = 1
    batch_flush_us: int = 0
    # Client-observed reply latency (ISSUE 9): send -> f+1 quorum, ms,
    # over the timed region's requests. reply_p99_ms is the field
    # scripts/bench_compare.py gates (lower is better).
    reply_p50_ms: float = 0.0
    reply_p95_ms: float = 0.0
    reply_p99_ms: float = 0.0
    # Per-request segment breakdown (utils/waterfall.py join of client
    # stamps with the run's replica traces): segment -> {p50, p95, p99,
    # count} in ms. Empty when the run had no trace dir.
    latency_segments_ms: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def _verifier(arm: str, batch_pad: int) -> Callable:
    if arm == "cpu":
        try:
            from .. import native

            if native.available():
                return native.verify_batch
        except Exception:
            pass
        from ..crypto import ref

        return lambda items: [ref.verify(p, m, s) for p, m, s in items]
    from ..crypto import batch

    def jax_arm(items):
        out = []
        for i in range(0, len(items), batch_pad):
            out.extend(batch.verify_many(items[i : i + batch_pad], pad_to=batch_pad))
        return out

    return jax_arm


CONFIGS = [
    # (name, n, clients, requests, byzantine)
    ("readme-demo f=1", 4, 1, 1, False),
    ("firehose f=1", 4, 1, 200, False),
    ("f=2 multi-client", 7, 4, 100, False),
    ("f=5 large-batch", 16, 8, 50, False),
    ("f=10 byzantine-signer", 31, 8, 12, True),
]

# In-flight requests per nominal client on the NATIVE arms, by config
# index. BASELINE's firehose is "client firehose @ 1k req/s" — an arrival
# rate far above the per-round latency, i.e. deep pipelining: the load
# generator keeps this many requests in flight (each on its own reply
# listener identity), and the replicas batch verification across the
# concurrent sequence numbers (SURVEY.md §7 "batch across pipelined
# rounds"). The lockstep simulation arms keep one request per client.
PIPELINE = {1: 32}


def run_config(
    index: int,
    arm: str = "cpu",
    batch_pad: int = 256,
    requests: Optional[int] = None,
) -> BenchResult:
    name, n, clients, default_requests, byzantine = CONFIGS[index]
    reqs_total = requests or default_requests
    cluster = Cluster(n=n, verifier=_verifier(arm, batch_pad))
    if byzantine:
        import dataclasses as dc

        def corrupt(src, msg):
            if src == n - 1 and getattr(msg, "sig", ""):
                return dc.replace(msg, sig="ff" * 64)
            return msg

        cluster.outbound_mutator = corrupt

    t0 = time.perf_counter()
    pending: List[Tuple[int, int]] = []
    submitted = 0
    # Pipelined submission: keep `clients` requests in flight (a PBFT
    # client has one outstanding request at a time, PBFT §4.1).
    client_ts = {c: 0 for c in range(clients)}
    inflight: dict = {}
    executed = 0
    while executed < reqs_total:
        for c in range(clients):
            if c not in inflight and submitted < reqs_total:
                client_ts[c] += 1
                r = cluster.submit(
                    f"op-{submitted}",
                    client=f"127.0.0.1:{9000 + c}",
                    timestamp=client_ts[c],
                )
                inflight[c] = r.timestamp
                submitted += 1
        if not cluster.step():
            # Quiesced: every in-flight request has either committed or
            # stalled; check replies.
            for c, ts in list(inflight.items()):
                cluster.committed_result(ts)  # raises if not committed
                del inflight[c]
                executed += 1
            if submitted >= reqs_total and not inflight:
                break
    elapsed = time.perf_counter() - t0
    rounds = max(
        (r.counters.get("rounds_executed", 0) for r in cluster.replicas),
        default=0,
    )
    executed = max(
        (r.counters.get("executed", 0) for r in cluster.replicas), default=0
    )
    return BenchResult(
        config=name,
        replicas=n,
        f=cluster.config.f,
        clients=clients,
        requests=reqs_total,
        seconds=round(elapsed, 3),
        rounds_per_sec=round((rounds or reqs_total) / elapsed, 1),
        sig_verifies_per_sec=round(cluster.sig_verifications / elapsed, 1),
        sig_verifications=cluster.sig_verifications,
        verifier=arm,
        byzantine=byzantine,
        requests_per_sec=round(reqs_total / elapsed, 1),
        mean_batch=round(executed / rounds, 2) if rounds else 1.0,
    )


def run_native_config(
    index: int,
    requests: Optional[int] = None,
    verifier: str = "cpu",
    tag: Optional[str] = None,
    trace_dir: Optional[str] = None,
    secure: bool = False,
    pipeline: Optional[int] = None,
    flush_us: int = 0,
    flush_items: int = 0,
    batch_max_items: int = 1,
    batch_flush_us: int = 0,
) -> BenchResult:
    """The same config driven through REAL pbftd processes over loopback
    TCP (framed wire protocol, dial-back replies) instead of the in-memory
    lockstep simulation — the deployment-shaped number. The Byzantine
    config runs replica n-1 with pbftd --byzantine (every outgoing
    signature corrupted); the honest 2f+1 must carry every round.

    ``verifier`` is the daemon's backend selector: "cpu" (in-process C++
    Ed25519) or a "host:port" / unix-path address of a running
    VerifierService — pass a jax-backed service to measure the full
    deployment shape (N daemons -> coalescing service -> one XLA launch
    per window)."""
    import re
    import threading
    from pathlib import Path

    from ..net import LocalCluster, PbftClient

    name, n, clients, default_requests, byzantine = CONFIGS[index]
    if pipeline is None:
        pipeline = PIPELINE.get(index, 1)
    # Pipelined load generators (PbftClient.request_many): each worker
    # streams a WINDOW of requests over one connection — the
    # windowed-async shape that actually fills the primary's request
    # batches (ISSUE 4). The pipeline depth is split across several
    # worker identities (window <= 8 each) because every reply is dialed
    # back per address with per-address serialization — one identity
    # carrying the whole pipeline would measure the reply dialer, not
    # the protocol. (The former drive used clients x pipeline lock-step
    # threads: same concurrency, one request per client per round trip,
    # which can never fill a batch from one client.)
    window = min(pipeline, 8)
    workers = clients * max(1, (pipeline + window - 1) // window)
    reqs_total = requests or max(default_requests, 100, clients * pipeline * 6)
    per_worker = max(1, reqs_total // workers)
    reqs_total = per_worker * workers
    if trace_dir:
        # Fresh trace set per run: pbftd opens trace files in append mode,
        # and stale events from a previous run would corrupt the
        # launch-cost model's occupancy measurement.
        Path(trace_dir).mkdir(parents=True, exist_ok=True)
        for old in Path(trace_dir).glob("replica-*.jsonl"):
            old.unlink()
    with LocalCluster(
        n=n,
        verifier=verifier,
        metrics_every=1,
        byzantine=[n - 1] if byzantine else None,
        trace_dir=trace_dir,
        secure=secure,
        verify_flush_us=flush_us,
        verify_flush_items=flush_items,
        batch_max_items=batch_max_items,
        batch_flush_us=batch_flush_us,
    ) as cluster:
        f_val = cluster.config.f
        handles = [PbftClient(cluster.config) for _ in range(workers)]
        # Generous warmup with retransmission: against a jax-backed
        # verifier service the FIRST window triggers the XLA compile
        # (tens of seconds to minutes on a cold cache), and the paper's
        # client retry keeps the round alive through it.
        handles[0].request_with_retry("warmup", timeout=600, retry_every=5)
        t0 = time.perf_counter()
        t0_mono = time.monotonic()  # client stamps are monotonic-clock

        def drive(ci: int) -> None:
            handles[ci].request_many(
                [f"op-{ci}-{k}" for k in range(per_worker)],
                window=window,
                timeout=60,
            )

        threads = [
            threading.Thread(target=drive, args=(i,)) for i in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        # Client-side latency stamps (ISSUE 9): reply latency percentiles
        # from every worker's send->quorum records, warmup excluded; with
        # a trace dir the client records also join against the replica
        # traces into the per-request segment waterfall.
        client_records = [
            rec
            for c in handles
            for rec in c.latency_records()
            if rec["send"] >= t0_mono
        ]
        reply_ms = sorted(
            (rec["quorum"] - rec["send"]) * 1e3
            for rec in client_records
            if "quorum" in rec
        )

        def _pct(vals, q):
            return vals[min(len(vals) - 1, int(q * len(vals)))] if vals else 0.0

        latency_segments: dict = {}
        if trace_dir:
            from pathlib import Path as _Path

            from ..utils import waterfall as wf_mod

            for ci, c in enumerate(handles):
                c.write_trace(str(_Path(trace_dir) / f"client-{ci}.jsonl"))
            events = wf_mod.load_jsonl(
                sorted(_Path(trace_dir).glob("replica-*.jsonl"))
            )
            latency_segments = wf_mod.build_waterfall(events, client_records)[
                "segments_ms"
            ]
        for c in handles:
            c.close()
        # Cluster-wide counters from each replica's last metrics line
        # (core/net.cc metrics_json / server.py metrics): signature
        # verifications, plus requests vs rounds executed — their ratio
        # is the measured batch occupancy.
        sig_total = 0
        executed_total = 0
        rounds_total = 0
        rounds_max = 0
        time.sleep(1.5)  # one more metrics tick so counters are current
        for i in range(n):
            log = (Path(cluster.tmpdir.name) / f"replica-{i}.log").read_text(
                errors="ignore"
            )
            for pattern, sink in (
                (r'"sig_verified":\s*(\d+)', "sig"),
                (r'"executed":\s*(\d+)', "executed"),
                (r'"rounds_executed":\s*(\d+)', "rounds"),
            ):
                found = re.findall(pattern, log)
                if not found:
                    continue
                val = int(found[-1])
                if sink == "sig":
                    sig_total += val
                elif sink == "executed":
                    executed_total += val
                else:
                    rounds_total += val
                    rounds_max = max(rounds_max, val)
    return BenchResult(
        config=name,
        replicas=n,
        f=f_val,
        clients=clients,
        requests=reqs_total,
        seconds=round(elapsed, 3),
        # rounds/sec = three-phase instances completed (includes the one
        # warmup round); requests/sec = driven requests over the timed
        # region. With batch_max_items=1 the two coincide.
        rounds_per_sec=round(
            (rounds_max or reqs_total) / elapsed, 1
        ),
        sig_verifies_per_sec=round(sig_total / elapsed, 1),
        sig_verifications=sig_total,
        verifier=tag or ("native-secure" if secure else "native"),
        byzantine=byzantine,
        pipeline=pipeline,
        requests_per_sec=round(reqs_total / elapsed, 1),
        mean_batch=(
            round(executed_total / rounds_total, 2) if rounds_total else 1.0
        ),
        batch_max_items=batch_max_items,
        batch_flush_us=batch_flush_us,
        reply_p50_ms=round(_pct(reply_ms, 0.5), 3),
        reply_p95_ms=round(_pct(reply_ms, 0.95), 3),
        reply_p99_ms=round(_pct(reply_ms, 0.99), 3),
        latency_segments_ms=latency_segments,
    )


def run_all(
    arm: str = "cpu",
    out_path: Optional[str] = None,
    trace_dir: Optional[str] = None,
    secure: bool = False,
) -> List[BenchResult]:
    results = []
    for i in range(len(CONFIGS)):
        # Per-config trace subdir: configs differ in n, and pbftd appends
        # to replica-<i>.jsonl — one shared dir would interleave clusters.
        cfg_traces = f"{trace_dir}/cfg{i}" if trace_dir else None
        if arm == "native":
            res = run_native_config(i, trace_dir=cfg_traces, secure=secure)
        elif arm == "native-tpu":
            res = run_native_tpu_config(i, trace_dir=cfg_traces, secure=secure)
        else:
            res = run_config(i, arm=arm)
        print(res.to_json(), flush=True)
        results.append(res)
    if out_path:
        with open(out_path, "w") as fh:
            for r in results:
                fh.write(r.to_json() + "\n")
    return results


def run_native_tpu_config(
    index: int,
    requests: Optional[int] = None,
    trace_dir: Optional[str] = None,
    secure: bool = False,
    pipeline: Optional[int] = None,
    flush_us: int = 0,
    flush_items: int = 0,
    service_backend: str = "jax",
    service_inflight: int = 1,
    batch_max_items: int = 1,
    batch_flush_us: int = 0,
) -> BenchResult:
    """run_native_config against one coalescing VerifierService shared by
    every daemon — the TPU deployment shape (N replicas on one host, one
    XLA launch per batching window). ``service_backend="native"`` swaps
    the chip for the C++ batch verifier: same wire path and coalescing,
    useful for measuring merged-window occupancy on a box without a TPU.

    The service's own per-dispatch trace (the honest items-per-LAUNCH
    measurement — per-replica traces only see each daemon's share of a
    merged window) lands in <trace_dir>-service/service.jsonl."""
    import os

    from ..net import VerifierService

    service_trace = None
    if trace_dir:
        service_trace_dir = f"{trace_dir.rstrip('/')}-service"
        os.makedirs(service_trace_dir, exist_ok=True)
        service_trace = os.path.join(service_trace_dir, "service.jsonl")
        if os.path.exists(service_trace):
            os.unlink(service_trace)  # append mode; stale events corrupt
    service = VerifierService(
        backend=service_backend,
        flush_us=flush_us,
        flush_items=flush_items,
        trace_path=service_trace,
        inflight=service_inflight,
    ).start()
    try:
        res = run_native_config(
            index,
            requests=requests,
            verifier=service.address,
            tag=("native-tpu" if service_backend == "jax" else "native-svc")
            + ("-secure" if secure else ""),
            trace_dir=trace_dir,
            secure=secure,
            pipeline=pipeline,
            batch_max_items=batch_max_items,
            batch_flush_us=batch_flush_us,
        )
        # Recorded in the artifact: rows captured at different overlap
        # settings must never be compared as like-for-like.
        res.service_inflight = service_inflight
        return res
    finally:
        service.stop()


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--arm",
        default="cpu",
        choices=["cpu", "jax", "native", "native-tpu"],
        help="native-tpu = real pbftd daemons -> coalescing jax-backed "
        "VerifierService (the TPU deployment shape)",
    )
    parser.add_argument("--config", type=int, default=None, help="0-4; default all")
    parser.add_argument("--requests", type=int, default=None)
    parser.add_argument("--out", default=None)
    parser.add_argument(
        "--trace-dir",
        default=None,
        help="write per-replica JSONL traces here (native arms only) — "
        "input for scripts/launch_cost_model.py",
    )
    parser.add_argument(
        "--secure",
        action="store_true",
        help="encrypted replica links (native arm only): measures the "
        "handshake + AEAD overhead at protocol level",
    )
    parser.add_argument(
        "--pipeline",
        type=int,
        default=None,
        help="in-flight requests per nominal client (native arms; default "
        "per-config PIPELINE table)",
    )
    parser.add_argument(
        "--flush-us",
        type=int,
        default=0,
        help="bounded verify accumulation window, microseconds (native arm: "
        "per-daemon via network.json; native-tpu arm: at the service)",
    )
    parser.add_argument(
        "--flush-items",
        type=int,
        default=0,
        help="flush early once this many items are pending (0 = pad/window cap)",
    )
    parser.add_argument(
        "--batch-max-items",
        type=int,
        default=1,
        help="requests the primary folds into one three-phase instance "
        "(native arms; ISSUE 4 batching — requests/sec vs rounds/sec)",
    )
    parser.add_argument(
        "--batch-flush-us",
        type=int,
        default=0,
        help="partial-batch flush deadline, microseconds (native arms)",
    )
    parser.add_argument(
        "--service-backend",
        default="jax",
        choices=["jax", "cpu", "native"],
        help="native-tpu arm: the VerifierService backend (native = C++ "
        "batch verifier, for occupancy runs without a chip)",
    )
    parser.add_argument(
        "--service-inflight",
        type=int,
        default=1,
        help="native-tpu arm: overlapped service launches (ship window "
        "N+1 while N executes; 1 = serial)",
    )
    args = parser.parse_args()
    if args.config is not None:
        if args.arm == "native-tpu":
            print(
                run_native_tpu_config(
                    args.config,
                    requests=args.requests,
                    trace_dir=args.trace_dir,
                    secure=args.secure,
                    pipeline=args.pipeline,
                    flush_us=args.flush_us,
                    flush_items=args.flush_items,
                    service_backend=args.service_backend,
                    service_inflight=args.service_inflight,
                    batch_max_items=args.batch_max_items,
                    batch_flush_us=args.batch_flush_us,
                ).to_json()
            )
        elif args.arm == "native":
            print(
                run_native_config(
                    args.config,
                    requests=args.requests,
                    trace_dir=args.trace_dir,
                    secure=args.secure,
                    pipeline=args.pipeline,
                    flush_us=args.flush_us,
                    flush_items=args.flush_items,
                    batch_max_items=args.batch_max_items,
                    batch_flush_us=args.batch_flush_us,
                ).to_json()
            )
        else:
            print(
                run_config(
                    args.config, arm=args.arm, requests=args.requests
                ).to_json()
            )
    else:
        run_all(
            arm=args.arm,
            out_path=args.out,
            trace_dir=args.trace_dir,
            secure=args.secure,
        )


if __name__ == "__main__":
    main()
