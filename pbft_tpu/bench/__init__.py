"""pbft_tpu.bench — the benchmark harness for BASELINE.md's five configs.

The repo-root ``bench.py`` prints the single headline metric (batched
Ed25519 verifies/sec on one chip); this package measures the *consensus*
side: sustained rounds/sec and sig-verifies/sec through the replica state
machines for each BASELINE.json config (4/7/16/31 replicas, firehose
clients, Byzantine signers), on either verifier arm.
"""

from .harness import BenchResult, run_config, run_all

__all__ = ["BenchResult", "run_config", "run_all"]
