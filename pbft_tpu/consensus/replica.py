"""The PBFT replica: a deterministic, I/O-free state machine.

Mirrors the capability surface of the reference's consensus behaviour
(reference src/behavior.rs) with the paper-mandated pieces the reference left
as TODOs filled in:

- real quorums: prepared = pre-prepare + 2f matching PREPAREs; committed-local
  = prepared + 2f+1 COMMITs (reference stubs at src/behavior.rs:181,:208,:222);
- signature verification on every replica message, *batched*: the replica
  never verifies inline — it exposes `pending_items()` as (pubkey, digest,
  sig) triples and resumes in `deliver_verdicts(...)`, so the transport layer
  can gate whole batches through the TPU verifier in one XLA launch;
- watermarks (h, H] + checkpoint protocol for log truncation (TODOs at
  reference src/behavior.rs:154,:192);
- in-order execution with per-client exactly-once timestamps and cached
  replies (reference discards duplicates, src/behavior.rs:391-398; the paper
  resends the cached reply — we do both correctly);
- backup -> primary request forwarding (TODO at reference
  src/client_handler.rs:66-68).

The state machine never touches sockets, clocks, or threads: inputs arrive by
method call, outputs are returned as Action values (SURVEY.md §4 item 1 —
this is what made the reference untestable, its validation was welded to the
libp2p behaviour).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..crypto import ref as crypto
from .config import ClusterConfig
from .wal import (
    WAL_VOTE_COMMIT,
    WAL_VOTE_PRE_PREPARE,
    WAL_VOTE_PREPARE,
    WalState,
)
from .messages import (
    NULL_CLIENT,
    Checkpoint,
    ClientReply,
    ClientRequest,
    Commit,
    Message,
    NewView,
    Prepare,
    PrePrepare,
    StateRequest,
    StateResponse,
    ViewChange,
    _canonical_json,
    batch_digest,
    blake2b_256,
    with_sig,
)


@dataclasses.dataclass(frozen=True)
class Send:
    dest: int
    msg: Message


@dataclasses.dataclass(frozen=True)
class Broadcast:
    msg: Message


@dataclasses.dataclass(frozen=True)
class Reply:
    client: str
    msg: ClientReply


Action = object  # Send | Broadcast | Reply

# Forwarded-request retention bound (ISSUE 12, mirrors core/replica.h
# kMaxForwardedRetained; constants lint): a backup remembers the last
# request it forwarded per client so a view change can RE-AIM it at the
# new primary — without this, a request forwarded to a primary that then
# gets voted out evaporates with the old view, and the only recovery is
# the client's (slow) retransmission timer, during which the request
# timers keep escalating view changes with nothing to order. On overflow
# the map clears: retransmission covers the forgotten entries.
MAX_FORWARDED_RETAINED = 1024


_HOST_SIGNER = None


def _host_sign(seed: bytes, msg: bytes) -> bytes:
    """Host-side message signing: the native C++ signer when built
    (~40-55 us warm; BASELINE.md "Native-runtime arm"),
    else the pure-Python oracle (~4 ms). The two are byte-identical (RFC
    8032 deterministic signatures; parity pinned by
    tests/test_native_crypto.py), so the choice cannot diverge replicas."""
    global _HOST_SIGNER
    if _HOST_SIGNER is None:
        _HOST_SIGNER = crypto.sign
        try:
            from .. import native

            if native.available():
                _HOST_SIGNER = native.sign
        except Exception:  # pragma: no cover - unbuilt native core
            pass
    return _HOST_SIGNER(seed, msg)


_HOST_VERIFIER = None


def _host_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Host-side inline verification (view-change evidence): the native
    C++ verifier when built, else the pure-Python oracle — identical
    accept sets (tests/test_native_crypto.py), so the choice cannot
    diverge replicas. Matters under chaos: a view-change storm verifies
    hundreds of nested certificate signatures inline, and the ~4 ms
    Python oracle turns each storm into seconds."""
    global _HOST_VERIFIER
    if _HOST_VERIFIER is None:
        _HOST_VERIFIER = crypto.verify
        try:
            from .. import native

            if native.available():
                _HOST_VERIFIER = native.verify
        except Exception:  # pragma: no cover - unbuilt native core
            pass
    return _HOST_VERIFIER(pub, msg, sig)


_HOST_BATCH_VERIFIER = None


def host_batch_verify(items):
    """THE local batch-verify arm: the PR-2 native verify pool when the
    C++ core is built (core/verify_pool.cc, fixed RLC windows across
    threads), else the pure-Python oracle — identical accept sets either
    way. This is the safety net every remote-verifier path degrades to:
    a replica that dials a verify service (net/verify_service.py) and
    finds it warming, unreachable, or dead mid-stream verifies the same
    window here instead, so a cold accelerator can never block consensus.
    ``items`` are (pub32, digest32, sig64) triples as produced by
    :meth:`Replica.pending_items`; returns one bool per item."""
    global _HOST_BATCH_VERIFIER
    if _HOST_BATCH_VERIFIER is None:
        _HOST_BATCH_VERIFIER = lambda batch: [  # noqa: E731 - cached lambda
            crypto.verify(p, m, s) for p, m, s in batch
        ]
        try:
            from .. import native

            if native.available():
                _HOST_BATCH_VERIFIER = native.verify_batch
        except Exception:  # pragma: no cover - unbuilt native core
            pass
    return _HOST_BATCH_VERIFIER(items)


def _strip_tentative(d: dict) -> dict:
    d.pop("tentative", None)
    return d


def default_app(operation: str, seq: int) -> str:
    """The reference's execution is a no-op with a hardcoded result
    (reference src/message.rs:70); kept as the default app."""
    return "awesome!"


# Apps may optionally be *stateful*: any callable with ``snapshot() -> str``
# and ``restore(s: str) -> None`` attributes participates in state transfer
# (PBFT §5.3) — its snapshot is embedded in the checkpoint payload that the
# 2f+1-certified checkpoint digest commits to. A bare callable (like
# default_app) is treated as stateless (empty snapshot).


class Replica:
    def __init__(
        self,
        config: ClusterConfig,
        replica_id: int,
        seed: bytes,
        app: Callable[[str, int], str] = default_app,
    ):
        self.config = config
        self.id = replica_id
        self._seed = seed
        self._app = app
        self.view = 0
        self.seq_counter = 0  # primary's PrePrepareSequence (src/message.rs:154-172)
        self.low_mark = 0
        # Logs keyed by (view, seq) for *all three* phases (fixes the
        # reference's view-only commit key, src/state.rs:23).
        self.pre_prepares: Dict[Tuple[int, int], PrePrepare] = {}
        self.prepares: Dict[Tuple[int, int], Dict[int, Prepare]] = {}
        self.commits: Dict[Tuple[int, int], Dict[int, Commit]] = {}
        self.sent_commit: Set[Tuple[int, int]] = set()
        self.executed_upto = 0
        self.pending_execution: Dict[int, Tuple[int, str]] = {}
        self.last_timestamp: Dict[str, int] = {}
        self.last_reply: Dict[str, ClientReply] = {}
        self.checkpoints: Dict[int, Dict[int, Checkpoint]] = {}
        self.state_digest = blake2b_256(b"pbft-genesis")
        # Tentative execution (ISSUE 14, Castro–Liskov §5.3; active when
        # config.tentative). committed_upto <= executed_upto is the
        # highest sequence whose whole prefix is committed-local AND
        # executed — everything above it ran tentatively (at prepared)
        # and can roll back on a view change. Per executed sequence above
        # the floor, _tentative_undo holds what execution changed (prior
        # chain digest, per-request prior timestamp/reply cache entries,
        # app snapshot); _pending_checkpoints holds checkpoint payloads
        # captured at execution time whose EMISSION waits for the commit
        # point (a checkpoint may only cover state that cannot roll
        # back); committed_chain is the chain digest AT the committed
        # floor (what the invariant checker compares across replicas).
        self.committed_upto = 0
        self.committed_chain = self.state_digest
        self._tentative_undo: Dict[int, dict] = {}
        self._committed_seqs: Set[int] = set()
        self._pending_checkpoints: Dict[int, str] = {}
        self.stable_proof: List[dict] = []  # 2f+1 checkpoint dicts @ low_mark
        # Checkpoint payloads we can serve to lagging peers (seq -> canonical
        # JSON, see _checkpoint_payload), and the (seq, digest) we are
        # ourselves waiting to fetch after a watermark jump.
        self.snapshots: Dict[int, str] = {}
        self.awaiting_state: Optional[Tuple[int, str]] = None
        # View change (PBFT §4.4; the reference had no view mutation at all,
        # reference src/view.rs:1-13).
        self.in_view_change = False
        self.pending_view = 0
        self.view_changes: Dict[int, Dict[int, ViewChange]] = {}
        # NEW-VIEW messages this replica (as primary-elect) has already
        # built, keyed by view (ISSUE 12): membership suppresses redundant
        # recomputation when retransmitted VIEW-CHANGEs arrive, and the
        # cached message is RESENT point-to-point to a replica whose
        # VIEW-CHANGE shows it never received the broadcast — lost-frame
        # recovery without a second O computation or a second broadcast.
        self.new_view_sent: Dict[int, NewView] = {}
        # Our own latest VIEW-CHANGE (pending view): the runtime's
        # retransmission timer re-broadcasts it verbatim instead of
        # escalating on every expiry (ISSUE 12, §4.5 liveness under loss).
        self._my_view_change: Optional[ViewChange] = None
        # Write-ahead log (ISSUE 15, consensus/wal.py): when set by the
        # runtime, every vote this replica sends is recorded (and durable
        # before the send — the runtime flushes at its emit boundary),
        # and a vote contradicting a persisted one is REFUSED: the
        # amnesia guard that makes crash-restart safe. None = the
        # pre-durability behavior, one attribute check per vote.
        self.wal = None
        # (message, optional precomputed signable digest) — see receive().
        self._inbox: List[Tuple[Message, Optional[bytes]]] = []
        # Consensus-phase observer (utils.metrics.ConsensusSpans.on_phase):
        # called as hook(phase, view, seq) at each protocol transition. The
        # state machine itself stays clock-free and deterministic — the
        # hook only reports that a transition happened; the runtime stamps
        # it. None (the default) costs one attribute check per transition,
        # never per message (the Tracer discipline, utils/trace.py).
        self.phase_hook: Optional[Callable[[str, int, int], None]] = None
        # Batch-size observer: called with len(pp.requests) at every
        # pre-prepare accept (feeds the pbft_batch_size histogram). Same
        # one-attribute-check-when-unset discipline as phase_hook.
        self.batch_hook: Optional[Callable[[int], None]] = None
        # View-change observer (ISSUE 9, ROADMAP item 4): called with
        # ("view_change_sent", pending_view) when this replica broadcasts
        # VIEW-CHANGE and with ("new_view_installed", view) when it enters
        # the new view. Rare reconfiguration events; the runtime stamps
        # them into the matching trace events and the flight recorder.
        # Same unset discipline as phase_hook.
        self.view_hook: Optional[Callable[[str, int], None]] = None
        # The primary's OPEN batch (ISSUE 4): requests accumulated but not
        # yet sealed under a sequence number. _open_batch_ts tracks the
        # highest pending timestamp per client so duplicate suppression
        # also sees requests that sit in the unsealed batch.
        self._open_batch: List[ClientRequest] = []
        self._open_batch_ts: Dict[str, int] = {}
        # Last request forwarded to the primary, per client (backup role;
        # ISSUE 12): re-aimed at the new primary on view entry, retired
        # at execution. Bounded by MAX_FORWARDED_RETAINED.
        self._forwarded: Dict[str, ClientRequest] = {}
        # Highest timestamp per client this primary has SEALED under a
        # sequence number in the CURRENT view (PBFT §4.2: "the primary
        # checks its log" — without this, a client retransmission arriving
        # after the seal but before execution gets ordered AGAIN, burning
        # a whole three-phase instance on a duplicate the execution-time
        # exactly-once guard then skips). Cleared on view entry: a request
        # sealed in an ABANDONED view may need re-ordering by the new
        # primary, so the memory must not outlive the view.
        self._sealed_ts: Dict[str, int] = {}
        self.counters: Dict[str, int] = {
            "sig_verified": 0,
            "sig_rejected": 0,
            "mac_verified": 0,
            "tentative_executions": 0,
            "tentative_rollbacks": 0,
            "pre_prepares_accepted": 0,
            "prepares_accepted": 0,
            "commits_accepted": 0,
            "executed": 0,
            "rounds_executed": 0,
            "duplicate_requests": 0,
            "checkpoints_stable": 0,
            "view_changes_started": 0,
            "view_changes_completed": 0,
            "state_transfers": 0,
        }

    # -- identity helpers ---------------------------------------------------

    @property
    def is_primary(self) -> bool:
        return self.config.primary_of(self.view) == self.id

    @property
    def primary(self) -> int:
        return self.config.primary_of(self.view)

    @property
    def high_mark(self) -> int:
        return self.low_mark + self.config.watermark_window

    def has_unexecuted(self) -> bool:
        """True when accepted pre-prepares (or committed-but-unexecuted
        slots) sit above executed_upto — the runtime's request-timer
        signal (mirrors core/replica.cc). In tentative mode an executed
        but uncommitted suffix also counts: its commits are still owed,
        and starving them must keep the timer armed."""
        if self.pending_execution:
            return True
        if self.config.tentative and self.executed_upto > self.committed_upto:
            return True
        return any(seq > self.executed_upto for _, seq in self.pre_prepares)

    def progress_marker(self) -> int:
        """What the runtime's view timer treats as progress: COMMITTED
        sequences in tentative mode (tentative executions roll back, so
        they must not placate the timer while commits starve), executed
        sequences otherwise."""
        return (
            self.committed_upto if self.config.tentative else self.executed_upto
        )

    def _sign(self, msg: Message) -> Message:
        return with_sig(msg, _host_sign(self._seed, msg.signable()).hex())

    # -- client request path (reference src/behavior.rs:63-98) --------------

    def on_client_request(self, req: ClientRequest) -> List[Action]:
        # §4.1: EVERY replica re-sends its cached reply on a
        # retransmission of an executed request — backups included,
        # BEFORE the forward-to-primary (mirrors core/replica.cc). The
        # cached reply carries this replica's own signature, so f+1
        # retransmission answers form a distinct-voter quorum.
        cached = self.last_reply.get(req.client)
        if cached is not None and cached.timestamp == req.timestamp:
            self.counters["duplicate_requests"] += 1
            return [Reply(req.client, cached)]
        # A timestamp at or below the client's last EXECUTED one can
        # never execute again (per-client exactly-once) and its reply is
        # no longer cached: drop it on EVERY role (ISSUE 12). Backups
        # used to forward these forever — each forward re-armed the
        # request timer for a request with nothing left to order, and a
        # client stuck retransmitting a superseded timestamp could drive
        # perpetual view changes out of pure duplicate traffic.
        last = self.last_timestamp.get(req.client)
        if last is not None and req.timestamp <= last:
            self.counters["duplicate_requests"] += 1
            return []
        if not self.is_primary:
            # Forward to the primary (reference TODO src/client_handler.rs:66-68),
            # and REMEMBER the request: if this view dies before it
            # executes, _enter_new_view re-aims it at the new primary
            # (ISSUE 12 — see MAX_FORWARDED_RETAINED).
            if len(self._forwarded) >= MAX_FORWARDED_RETAINED:
                self._forwarded.clear()
            self._forwarded[req.client] = req
            return [Send(self.primary, req)]
        # Duplicate suppression must also see the OPEN batch: a
        # retransmission arriving while its first copy waits unsealed
        # would otherwise be ordered (and executed) twice... well, once —
        # the execution-time exactly-once guard catches it — but it would
        # burn batch slots and inflate sequence traffic for nothing.
        pending = self._open_batch_ts.get(req.client)
        if pending is not None and req.timestamp <= pending:
            self.counters["duplicate_requests"] += 1
            return []
        sealed = self._sealed_ts.get(req.client)
        if sealed is not None and req.timestamp <= sealed:
            # Already ordered in this view (sealed, in flight): either it
            # commits here, or a view change clears this memory.
            self.counters["duplicate_requests"] += 1
            return []
        self._open_batch.append(req)
        self._open_batch_ts[req.client] = req.timestamp
        if len(self._open_batch) >= max(1, self.config.batch_max_items):
            return self._seal_batch()
        return []  # the runtime's batch_flush_us timer seals partials

    def open_batch_size(self) -> int:
        """Requests waiting in the unsealed batch — the runtime's flush
        timer (config.batch_flush_us) polls this."""
        return len(self._open_batch)

    def flush_open_batch(self) -> List[Action]:
        """Seal the open batch regardless of occupancy (runtime flush
        timer). No-op while empty or while the watermark window is
        closed (the batch stays open; retried on the next tick)."""
        if not self._open_batch:
            return []
        return self._seal_batch()

    def _seal_batch(self) -> List[Action]:
        if self.seq_counter + 1 > self.high_mark:
            return []  # out of window until a checkpoint advances it
        batch = tuple(self._open_batch)
        if self.wal is not None and not self.wal.note_vote(
            WAL_VOTE_PRE_PREPARE,
            self.view,
            self.seq_counter + 1,
            batch_digest(batch),
        ):
            # A durable pre-prepare for this (view, seq) names a
            # DIFFERENT batch (can only happen if recovery restored a
            # lower seq_counter than the log proves we used): sealing
            # would equivocate. Leave the batch open; the watermark /
            # view machinery resolves the slot.
            return []
        self._open_batch = []
        self._open_batch_ts = {}
        for req in batch:
            self._sealed_ts[req.client] = req.timestamp
        self.seq_counter += 1
        n = self.seq_counter
        hook = self.phase_hook
        if hook is not None:  # primary-only: request -> sequence assignment
            hook("request", self.view, n)
        pp = self._sign(
            PrePrepare(
                view=self.view,
                seq=n,
                digest=batch_digest(batch),
                requests=batch,
                replica=self.id,
            )
        )
        out: List[Action] = [Broadcast(pp)]
        out.extend(self._accept_pre_prepare(pp))
        return out

    # -- signature gating ---------------------------------------------------

    def receive(
        self, msg: Message, signable: Optional[bytes] = None
    ) -> List[Action]:
        """Queue a replica-to-replica message for batched verification.

        ClientRequests skip the queue (clients are unauthenticated, matching
        the reference's client contract). ``signable`` is the 32-byte
        signable digest the net layer derived from the received frame
        bytes (messages.signable_from_payload) — when present,
        pending_items reuses it instead of re-serializing."""
        if isinstance(msg, ClientRequest):
            return self.on_client_request(msg)
        self._inbox.append((msg, signable, False))
        return []

    def pending_count(self) -> int:
        """Queue depth without building the items — the server's bounded
        accumulation window (config.verify_flush_us) polls this."""
        return len(self._inbox)

    def _consume_inbox(self, verdicts: List[bool]):
        """Split the inbox into (entry, ok) pairs covered by ``verdicts``
        and the remainder: pre-authenticated entries pass for free (and
        are consumed greedily at the tail), verification-needing entries
        consume one verdict each, in arrival order."""
        taken: List[Tuple[Message, bool, bool]] = []
        vi = 0
        consumed = 0
        for msg, _signable, preauth in self._inbox:
            if preauth:
                taken.append((msg, True, True))
            else:
                if vi >= len(verdicts):
                    break
                taken.append((msg, verdicts[vi], False))
                vi += 1
            consumed += 1
        self._inbox = self._inbox[consumed:]
        return taken

    def pending_items(self) -> List[Tuple[bytes, bytes, bytes]]:
        """(pubkey32, digest32, sig64) per queued message NEEDING
        verification, for the batch verifier (pre-authenticated entries —
        MAC-accepted frames queued behind the signed types for ordering —
        are skipped; deliver_verdicts treats them as already valid)."""
        items = []
        for msg, signable, preauth in self._inbox:
            if preauth:
                continue
            rid = getattr(msg, "replica", None)
            pub = (
                self.config.identity(rid).pubkey_bytes()
                if rid is not None and 0 <= rid < self.config.n
                else bytes(32)
            )
            try:
                sig = bytes.fromhex(msg.sig)
            except (AttributeError, ValueError):
                sig = b""
            if len(sig) != 64:
                sig = bytes(64)  # guaranteed-invalid placeholder
            # Receive-side canonical reuse: the net layer already hashed
            # the sender's framed bytes — no re-serialization here.
            items.append((pub, signable or msg.signable(), sig))
        return items

    def receive_authenticated(self, msg: Message) -> List[Action]:
        """Dispatch a message the NET layer already authenticated via its
        per-link session MAC (ISSUE 14 authenticator mode): no signature
        check — the MAC lane proved the sender, and the net layer checked
        the claimed replica id against the link's authenticated peer.

        ORDERING: when the verify inbox is non-empty the message queues
        BEHIND it (pre-verified) instead of dispatching immediately — a
        MAC frame overtaking a still-unverified NEW-VIEW from the same
        sender would be dropped as belonging to a view this replica has
        not entered yet, and the primary's per-view duplicate suppression
        then pins the request until the NEXT view change (a liveness
        wedge the chaos soak caught). The inbox only ever holds the rare
        signed types in MAC mode, so the fast path stays fast."""
        self.counters["mac_verified"] += 1
        if isinstance(msg, ClientRequest):
            return self.on_client_request(msg)
        if self._inbox:
            self._inbox.append((msg, None, True))
            return []
        return self._dispatch(msg)

    def deliver_verdicts(self, verdicts: List[bool]) -> List[Action]:
        """Resume processing for the queued messages, in arrival order."""
        out: List[Action] = []
        for msg, ok, preauth in self._consume_inbox(verdicts):
            if not ok:
                self.counters["sig_rejected"] += 1
                continue
            if not preauth:  # MAC-accepted entries counted at receive
                self.counters["sig_verified"] += 1
            out.extend(self._dispatch(msg))
        return out

    # -- protocol dispatch (reference src/behavior.rs:304-414) --------------

    def _dispatch(self, msg: Message) -> List[Action]:
        if isinstance(msg, PrePrepare):
            return self._on_pre_prepare(msg)
        if isinstance(msg, Prepare):
            return self._on_prepare(msg)
        if isinstance(msg, Commit):
            return self._on_commit(msg)
        if isinstance(msg, Checkpoint):
            return self._on_checkpoint(msg)
        if isinstance(msg, ViewChange):
            return self._on_view_change(msg)
        if isinstance(msg, NewView):
            return self._on_new_view(msg)
        if isinstance(msg, StateRequest):
            return self._on_state_request(msg)
        if isinstance(msg, StateResponse):
            return self._on_state_response(msg)
        if isinstance(msg, ClientRequest):
            return self.on_client_request(msg)
        return []

    def _on_pre_prepare(self, pp: PrePrepare) -> List[Action]:
        # validate (reference src/behavior.rs:126-157 + watermark TODO :154)
        if self.in_view_change:
            return []  # §4.4: only checkpoint/view-change/new-view accepted
        if pp.view != self.view or pp.replica != self.primary:
            return []
        if pp.batch_digest() != pp.digest:
            return []
        if not (self.low_mark < pp.seq <= self.high_mark):
            return []
        existing = self.pre_prepares.get((pp.view, pp.seq))
        if existing is not None:
            return []  # already have a pre-prepare for (v, n)
        return self._accept_pre_prepare(pp)

    def _accept_pre_prepare(self, pp: PrePrepare) -> List[Action]:
        key = (pp.view, pp.seq)
        if self.wal is not None:
            # Amnesia guard (ISSUE 15): our durable vote for this slot —
            # the pre-prepare we sealed as primary, or the prepare we
            # broadcast as backup — is the floor a restart must honor. A
            # pre-prepare naming a different digest is refused outright
            # (accepting it could grow a conflicting certificate); one
            # naming the SAME digest re-enters normally, which is how a
            # recovered replica resumes the round without re-voting
            # anything new.
            kind = (
                WAL_VOTE_PRE_PREPARE
                if self.config.primary_of(pp.view) == self.id
                else WAL_VOTE_PREPARE
            )
            if not self.wal.note_vote(kind, pp.view, pp.seq, pp.digest):
                return []
        self.pre_prepares[key] = pp
        self.counters["pre_prepares_accepted"] += 1
        hook = self.phase_hook
        if hook is not None:
            hook("pre_prepare", pp.view, pp.seq)
        bhook = self.batch_hook
        if bhook is not None:
            bhook(len(pp.requests))
        # The primary's pre-prepare stands in for its prepare (PBFT §4.2):
        # only backups multicast PREPARE, and _prepared wants 2f *backup*
        # prepares, giving 2f+1 distinct replicas per certificate.
        if self.config.primary_of(pp.view) == self.id:
            return self._maybe_commit(key)
        prep = self._sign(
            Prepare(view=pp.view, seq=pp.seq, digest=pp.digest, replica=self.id)
        )
        out: List[Action] = [Broadcast(prep)]
        out.extend(self._insert_prepare(prep))
        return out

    def _on_prepare(self, p: Prepare) -> List[Action]:
        if self.in_view_change or p.view != self.view:
            return []
        if not (self.low_mark < p.seq <= self.high_mark):
            return []
        return self._insert_prepare(p)

    def _insert_prepare(self, p: Prepare) -> List[Action]:
        key = (p.view, p.seq)
        slot = self.prepares.setdefault(key, {})
        if p.replica in slot:
            return []
        slot[p.replica] = p
        self.counters["prepares_accepted"] += 1
        return self._maybe_commit(key)

    def _prepared(self, key: Tuple[int, int]) -> bool:
        """pre-prepare + 2f matching *backup* prepares (PBFT §4.2; reference
        stub `>= 1` at src/behavior.rs:177-182). Excluding the primary keeps
        every prepared certificate at 2f+1 distinct replicas — counting a
        primary prepare would shrink it to 2f and break quorum
        intersection across views."""
        pp = self.pre_prepares.get(key)
        if pp is None:
            return False
        primary = self.config.primary_of(key[0])
        matching = sum(
            1
            for rid, p in self.prepares.get(key, {}).items()
            if rid != primary and p.digest == pp.digest
        )
        return matching >= 2 * self.config.f

    def _maybe_commit(self, key: Tuple[int, int]) -> List[Action]:
        if key in self.sent_commit or not self._prepared(key):
            return []
        if self.wal is not None and not self.wal.note_vote(
            WAL_VOTE_COMMIT, key[0], key[1], self.pre_prepares[key].digest
        ):
            return []  # contradicts a durable commit vote: never send
        self.sent_commit.add(key)
        hook = self.phase_hook
        if hook is not None:
            hook("prepared", key[0], key[1])
        pp = self.pre_prepares[key]
        cm = self._sign(
            Commit(view=key[0], seq=key[1], digest=pp.digest, replica=self.id)
        )
        out: List[Action] = [Broadcast(cm)]
        if self.config.tentative:
            # Tentative execution (§5.3): PREPARED is the execute point —
            # the reply goes out one commit round-trip early, flagged
            # tentative; the commit quorum later promotes it (and a view
            # change before that rolls it back).
            view, seq = key
            if seq > self.executed_upto and seq not in self.pending_execution:
                self.pending_execution[seq] = (view, pp.digest)
                out.extend(self._drain_executions())
        out.extend(self._insert_commit(cm))
        return out

    def _on_commit(self, c: Commit) -> List[Action]:
        if self.in_view_change or c.view != self.view:
            return []
        if not (self.low_mark < c.seq <= self.high_mark):
            return []
        return self._insert_commit(c)

    def _insert_commit(self, c: Commit) -> List[Action]:
        key = (c.view, c.seq)
        slot = self.commits.setdefault(key, {})
        if c.replica in slot:
            return []
        slot[c.replica] = c
        self.counters["commits_accepted"] += 1
        return self._maybe_execute(key)

    def _committed_local(self, key: Tuple[int, int]) -> bool:
        """prepared + 2f+1 matching commits (PBFT §4.2; reference stub at
        src/behavior.rs:214-223)."""
        if not self._prepared(key):
            return False
        pp = self.pre_prepares[key]
        matching = sum(
            1 for c in self.commits.get(key, {}).values() if c.digest == pp.digest
        )
        return matching >= 2 * self.config.f + 1

    def _maybe_execute(self, key: Tuple[int, int]) -> List[Action]:
        if not self._committed_local(key):
            return []
        view, seq = key
        if self.config.tentative and seq <= self.executed_upto:
            # Already executed (tentatively) — the commit quorum arrived
            # now: advance the committed floor. No "committed" phase
            # stamp: the span already closed at the tentative execution,
            # and a committed stamp after "executed" would violate the
            # phase-order invariant the timeline checker enforces.
            if seq <= self.committed_upto or seq in self._committed_seqs:
                return []
            return self._note_committed(seq)
        if seq <= self.executed_upto or seq in self.pending_execution:
            return []
        self.pending_execution[seq] = (view, self.pre_prepares[key].digest)
        hook = self.phase_hook
        if hook is not None:
            hook("committed", view, seq)
        return self._drain_executions()

    def _drain_executions(self) -> List[Action]:
        """Execute strictly in sequence order (the reference executed on
        arrival order, src/behavior.rs:383-410; in-order execution is what
        makes replicas' app state deterministic)."""
        out: List[Action] = []
        while self.executed_upto + 1 in self.pending_execution:
            seq = self.executed_upto + 1
            view, digest = self.pending_execution.pop(seq)
            self.executed_upto = seq
            hook = self.phase_hook
            if hook is not None:
                hook("executed", view, seq)
            pp = self.pre_prepares.get((view, seq))
            # Tentative mode: is this execution already backed by a
            # commit quorum (definitive) or only by the prepared
            # certificate (tentative — reply flagged, undo recorded)?
            tentative_mode = self.config.tentative
            committed_now = not tentative_mode or self._committed_local(
                (view, seq)
            )
            undo: Optional[dict] = None
            if tentative_mode:
                # Undo record for EVERY executed sequence above the
                # committed floor (committed-now ones included — the
                # floor may still be below them, and rollback walks the
                # whole suffix): prior chain digest, per-request prior
                # exactly-once entries, app snapshot when stateful.
                snap = getattr(self._app, "snapshot", None)
                undo = {
                    "chain": self.state_digest,
                    "items": [],
                    "app": snap() if callable(snap) else None,
                }
                self._tentative_undo[seq] = undo
            if pp is None:
                # Defensive: can only happen if the pre-prepare log lost an
                # entry for a slot that committed; the watermark-jump path
                # (the old way to get here) now goes through state transfer
                # (_on_state_response) instead of skipping executions.
                if tentative_mode and committed_now:
                    out.extend(self._note_committed(seq))
                continue
            self.counters["rounds_executed"] += 1
            if not pp.requests:
                # Empty batch (view-change gap filler, PBFT §4.4's null
                # request as a batch): a no-op execution that still
                # advances the sequence and the state digest chain — the
                # SAME chain fold a legacy single null request produced,
                # so the two gap-filler encodings cannot fork app state.
                self.state_digest = hashlib.blake2b(
                    self.state_digest + b"<null>" + seq.to_bytes(8, "big"),
                    digest_size=32,
                ).digest()
            for req in pp.requests:
                if req.client == NULL_CLIENT:
                    # Legacy null request (a 1.1.0 peer's gap filler riding
                    # a batch of one): no-op, no reply, chain advances.
                    self.state_digest = hashlib.blake2b(
                        self.state_digest + b"<null>" + seq.to_bytes(8, "big"),
                        digest_size=32,
                    ).digest()
                    continue
                last = self.last_timestamp.get(req.client)
                if last is not None and req.timestamp <= last:
                    # exactly-once (reference src/behavior.rs:391-398),
                    # enforced per batch item in batch order.
                    self.counters["duplicate_requests"] += 1
                    continue
                if undo is not None:
                    undo["items"].append(
                        (req.client, last, self.last_reply.get(req.client))
                    )
                result = self._app(req.operation, seq)
                self.counters["executed"] += 1
                self.state_digest = hashlib.blake2b(
                    self.state_digest
                    + result.encode()
                    + seq.to_bytes(8, "big"),
                    digest_size=32,
                ).digest()
                self.last_timestamp[req.client] = req.timestamp
                self._forwarded.pop(req.client, None)  # executed: retire
                reply = self._sign(
                    ClientReply(
                        view=view,
                        timestamp=req.timestamp,
                        client=req.client,
                        replica=self.id,
                        result=result,
                        tentative=0 if committed_now else 1,
                    )
                )
                self.last_reply[req.client] = reply
                out.append(Reply(req.client, reply))
            if seq % self.config.checkpoint_interval == 0:
                payload = self._checkpoint_payload(seq)
                if tentative_mode:
                    # Deferred emission: the payload is captured NOW (the
                    # state IS the state at seq) but the Checkpoint
                    # message waits for the commit point — a checkpoint
                    # may only ever cover state that cannot roll back.
                    self._pending_checkpoints[seq] = payload
                else:
                    self.snapshots[seq] = payload
                    cp = self._sign(
                        Checkpoint(
                            seq=seq,
                            digest=blake2b_256(payload.encode()).hex(),
                            replica=self.id,
                        )
                    )
                    out.append(Broadcast(cp))
                    out.extend(self._insert_checkpoint(cp))
            if tentative_mode:
                if committed_now:
                    out.extend(self._note_committed(seq))
                else:
                    self.counters["tentative_executions"] += 1
        if not self.config.tentative:
            # Signature mode: every execution is definitive — the floor
            # tracks execution so the progress/metrics surface is uniform.
            self.committed_upto = self.executed_upto
            self.committed_chain = self.state_digest
        return out

    # -- tentative promotion & rollback (ISSUE 14, §5.3) --------------------

    def _note_committed(self, seq: int) -> List[Action]:
        """Sequence ``seq`` is committed-local AND executed: advance the
        committed floor over every contiguously-committed sequence,
        retire their undo records, refresh committed_chain, and emit any
        checkpoint whose (deferred) interval boundary the floor crossed."""
        if seq <= self.committed_upto:
            return []
        self._committed_seqs.add(seq)
        out: List[Action] = []
        while (self.committed_upto + 1) in self._committed_seqs:
            self.committed_upto += 1
            s = self.committed_upto
            self._committed_seqs.discard(s)
            self._tentative_undo.pop(s, None)
            payload = self._pending_checkpoints.pop(s, None)
            if payload is not None:
                self.snapshots[s] = payload
                cp = self._sign(
                    Checkpoint(
                        seq=s,
                        digest=blake2b_256(payload.encode()).hex(),
                        replica=self.id,
                    )
                )
                out.append(Broadcast(cp))
                out.extend(self._insert_checkpoint(cp))
        nxt = self._tentative_undo.get(self.committed_upto + 1)
        self.committed_chain = (
            nxt["chain"] if nxt is not None else self.state_digest
        )
        return out

    def _rollback_tentative(self) -> None:
        """Undo every execution above the committed floor, newest first
        (view-change entry, or a certified checkpoint past the floor):
        chain digest, per-client exactly-once timestamps, cached replies,
        and app state all revert to the committed point; the re-issued
        sequences then re-prepare, re-commit, and re-execute in the new
        view. Clients that accepted a reply are safe regardless: 2f+1
        matching tentative votes imply f+1 HONEST replicas holding the
        full prepared certificate, and any new-view quorum intersects
        them — the same batch is re-issued at the same sequence."""
        if not self.config.tentative or self.executed_upto <= self.committed_upto:
            return
        rolled = 0
        for seq in range(self.executed_upto, self.committed_upto, -1):
            undo = self._tentative_undo.pop(seq, None)
            self._pending_checkpoints.pop(seq, None)
            self._committed_seqs.discard(seq)
            if undo is None:
                continue  # defensive: every executed seq records one
            self.state_digest = undo["chain"]
            for client, prev_ts, prev_reply in reversed(undo["items"]):
                if prev_ts is None:
                    self.last_timestamp.pop(client, None)
                else:
                    self.last_timestamp[client] = prev_ts
                if prev_reply is None:
                    self.last_reply.pop(client, None)
                else:
                    self.last_reply[client] = prev_reply
            if undo["app"] is not None:
                restore = getattr(self._app, "restore", None)
                if callable(restore):
                    restore(undo["app"])
            rolled += 1
        self.executed_upto = self.committed_upto
        self.committed_chain = self.state_digest
        for s in [x for x in self.pending_execution if x > self.committed_upto]:
            del self.pending_execution[s]
        if rolled:
            self.counters["tentative_rollbacks"] += rolled

    # -- checkpoints, watermarks & state transfer (PBFT §4.3, §5.3) ---------

    def _app_snapshot(self) -> str:
        snap = getattr(self._app, "snapshot", None)
        return snap() if callable(snap) else ""

    def _checkpoint_payload(self, seq: int) -> str:
        """Canonical JSON the checkpoint digest commits to: app snapshot,
        the execution chain digest, and the per-client exactly-once caches.
        Byte-identical across the Python and C++ runtimes (sorted keys,
        compact separators) — the digest gates state transfer, so both
        runtimes must serialize the same bytes for the same state."""
        obj = {
            "app": self._app_snapshot(),
            "chain": self.state_digest.hex(),
            # The reply cache is replica-local in its `replica` and `sig`
            # fields; normalize both so all correct replicas digest
            # identical payload bytes (the restorer stamps its own id back
            # in and re-signs). The tentative flag is normalized away too:
            # by the time a checkpoint at this seq is EMITTED the prefix
            # is committed, and capture-time flag skew (one replica
            # executed a seq tentatively, another already held the
            # quorum) must not fork the certified payload bytes.
            "replies": [
                [c, _strip_tentative(
                    {**self.last_reply[c].to_dict(), "replica": -1, "sig": ""}
                )]
                for c in sorted(self.last_reply)
            ],
            "seq": seq,
            "timestamps": [
                [c, self.last_timestamp[c]] for c in sorted(self.last_timestamp)
            ],
        }
        return _canonical_json(obj).decode()

    def retry_state_transfer(self) -> List[Action]:
        """Re-broadcast the pending StateRequest (runtime retry timer)."""
        if self.awaiting_state is None:
            return []
        seq, _ = self.awaiting_state
        return [Broadcast(self._sign(StateRequest(seq=seq, replica=self.id)))]

    def _on_state_request(self, sr: StateRequest) -> List[Action]:
        payload = self.snapshots.get(sr.seq)
        if payload is None or not (0 <= sr.replica < self.config.n):
            return []
        resp = self._sign(
            StateResponse(seq=sr.seq, snapshot=payload, replica=self.id)
        )
        return [Send(sr.replica, resp)]

    def _install_checkpoint_payload(self, seq: int, snapshot: str) -> bool:
        """Install a certified checkpoint payload wholesale: app state,
        chain digest, per-client exactly-once caches, committed floor.
        Shared by §5.3 state transfer and WAL crash-recovery (ISSUE 15).
        False when the payload doesn't parse (nothing was mutated)."""
        try:
            import json as _json

            obj = _json.loads(snapshot)
            replies = {}
            for c, d in obj["replies"]:
                m = Message.from_dict(dict(d))
                if not isinstance(m, ClientReply):
                    return False
                # Stamp our id back in and re-sign: a resent cached reply
                # must carry THIS replica's vote, not a blank one.
                replies[c] = self._sign(
                    dataclasses.replace(m, replica=self.id)
                )
            timestamps = {c: int(t) for c, t in obj["timestamps"]}
            chain = bytes.fromhex(obj["chain"])
        except (KeyError, TypeError, ValueError):
            return False
        restore = getattr(self._app, "restore", None)
        if callable(restore):
            restore(obj.get("app", ""))
        self.state_digest = chain
        self.last_reply = replies
        self.last_timestamp = timestamps
        self.executed_upto = seq
        # The installed state is 2f+1-certified: the committed floor
        # moves with it and any stale tentative bookkeeping dies here.
        self.committed_upto = seq
        self.committed_chain = chain
        self._tentative_undo.clear()
        self._committed_seqs.clear()
        self._pending_checkpoints.clear()
        self.snapshots[seq] = snapshot  # we can serve peers now
        return True

    def _on_state_response(self, resp: StateResponse) -> List[Action]:
        if self.awaiting_state is None:
            return []
        seq, digest = self.awaiting_state
        if resp.seq != seq:
            return []
        if blake2b_256(resp.snapshot.encode()).hex() != digest:
            return []  # content not certified by the 2f+1 checkpoint quorum
        if not self._install_checkpoint_payload(seq, resp.snapshot):
            return []
        self.awaiting_state = None
        self.counters["state_transfers"] += 1
        self._wal_checkpoint(seq)
        return self._drain_executions()

    def restore_from_wal(self, state: WalState) -> bool:
        """Crash-recovery (ISSUE 15): reinstall the durable safety state a
        previous life of this replica persisted — BEFORE the runtime
        starts networking. The replica re-joins the SAME view at its
        stable-checkpoint floor; the vote log (already loaded in
        ``self.wal``) then refuses any send contradicting a pre-crash
        vote, and the suffix past the checkpoint catches up through the
        ordinary protocol (peer checkpoints -> §5.3 state transfer).

        A crash mid-view-change re-joins at the OLD view, not the
        pending one: its VIEW-CHANGE vote (if it got out) already counts
        at the primary-elect, duplicates are ignored, and a completed
        change reaches us as a NEW-VIEW for a higher view. Returns False
        when the persisted checkpoint payload fails to parse (the
        replica then starts fresh — state transfer still covers it)."""
        ok = True
        if state.checkpoint is not None:
            seq, payload, cert_json = state.checkpoint
            if self._install_checkpoint_payload(seq, payload):
                self.low_mark = seq
                try:
                    import json as _json

                    self.stable_proof = list(_json.loads(cert_json))
                except ValueError:
                    self.stable_proof = []
                self.seq_counter = seq
            else:
                ok = False
        self.view = max(self.view, state.view)
        # Never re-assign a sequence a previous life pre-prepared: the
        # durable vote guard would refuse the seal, but starting past the
        # floor avoids even trying.
        self.seq_counter = max(self.seq_counter, state.max_pre_prepare_seq())
        return ok

    def _on_checkpoint(self, cp: Checkpoint) -> List[Action]:
        if cp.seq <= self.low_mark:
            return []
        return self._insert_checkpoint(cp)

    def _insert_checkpoint(self, cp: Checkpoint) -> List[Action]:
        # MAC mode (ISSUE 14): checkpoints were accepted by their link
        # lane, but their embedded signatures are what stable-checkpoint
        # CERTIFICATES (the C component of view changes, and the gate on
        # state transfer) are made of — admit only provable evidence, or
        # one sig-corrupting peer poisons every honest VIEW-CHANGE.
        # Checkpoints are rare (one per interval per replica), so the
        # inline verify costs nothing the fast path can feel; signature
        # mode already verified upstream (fastpath gate keeps it free).
        if self.config.fastpath == "mac" and not self._verify_inline(
            cp.replica, cp.signable(), cp.sig
        ):
            return []
        slot = self.checkpoints.setdefault(cp.seq, {})
        if cp.replica in slot:
            return []
        slot[cp.replica] = cp
        by_digest: Dict[str, int] = {}
        for c in slot.values():
            by_digest[c.digest] = by_digest.get(c.digest, 0) + 1
        out: List[Action] = []
        for digest, count in by_digest.items():
            if count >= 2 * self.config.f + 1:
                # Keep the 2f+1 matching checkpoint messages: they are the
                # C component of our next VIEW-CHANGE (PBFT §4.4).
                proof = [
                    c.to_dict() for c in slot.values() if c.digest == digest
                ]
                out.extend(self._advance_watermark(cp.seq, digest))
                self.stable_proof = proof
                self._wal_checkpoint(cp.seq)
                break
        return out

    def _wal_checkpoint(self, seq: int) -> None:
        """Persist the stable checkpoint (ISSUE 15): payload (app snapshot
        + reply cache) and the adopted 2f+1 certificate. Skipped when we
        don't HOLD the payload yet (a lagging replica mid state transfer
        records it when the StateResponse installs)."""
        if self.wal is None:
            return
        payload = self.snapshots.get(seq)
        if payload is not None:
            self.wal.note_checkpoint(seq, payload, self.stable_proof)

    # -- view change (PBFT §4.4) -------------------------------------------
    #
    # The reference has no view mutation at all (reference src/view.rs:1-13);
    # this is the paper protocol. Design note on verification: the *hot* path
    # (pre-prepare/prepare/commit) is signature-gated through the batched
    # TPU verifier (pending_items/deliver_verdicts); view changes are rare
    # reconfiguration events, so the signatures nested inside their evidence
    # (checkpoint certificates, prepared certificates, the view-change
    # messages embedded in a NEW-VIEW) are verified inline on the host.

    def _verify_inline(self, replica_id: int, signable: bytes, sig_hex: str) -> bool:
        if not (0 <= replica_id < self.config.n):
            return False
        try:
            sig = bytes.fromhex(sig_hex)
        except ValueError:
            return False
        if len(sig) != 64:
            return False
        return _host_verify(
            self.config.identity(replica_id).pubkey_bytes(), signable, sig
        )

    def start_view_change(self, new_view: Optional[int] = None) -> List[Action]:
        """Move to view v+1 (or `new_view`) and broadcast VIEW-CHANGE.

        Called by the runtime when its request timer for the current
        primary expires, or by the f+1 join rule below."""
        floor = self.pending_view if self.in_view_change else self.view
        v = (floor + 1) if new_view is None else new_view
        if v <= floor:
            return []
        self.in_view_change = True
        self.pending_view = v
        if self.wal is not None:
            self.wal.note_view(self.view, True, v)
        self.counters["view_changes_started"] += 1
        vh = self.view_hook
        if vh is not None:
            vh("view_change_sent", v)
        vc = self._sign(
            ViewChange(
                new_view=v,
                last_stable_seq=self.low_mark,
                checkpoint_proof=tuple(self.stable_proof),
                prepared_proofs=tuple(self._prepared_proofs()),
                replica=self.id,
            )
        )
        self._my_view_change = vc
        out: List[Action] = [Broadcast(vc)]
        out.extend(self._on_view_change(vc))  # log our own
        return out

    def retransmit_view_change(self) -> List[Action]:
        """Re-broadcast the VIEW-CHANGE for the pending view, verbatim
        (runtime retransmission timer, ISSUE 12): under link loss the
        original may never have reached the primary-elect — resending the
        SAME signed message converges in the SAME view, where escalating
        would burn a view number per lost frame. No counters move and
        nothing is re-signed; receivers treat it as the duplicate it is
        (and a primary-elect that already sent NEW-VIEW answers it with
        the cached NEW-VIEW, see _on_view_change)."""
        if not self.in_view_change or self._my_view_change is None:
            return []
        return [Broadcast(self._my_view_change)]

    def _prepared_proofs(self) -> List[dict]:
        """P: for each sequence prepared above the low watermark, the
        pre-prepare plus its 2f matching backup prepares (highest view
        wins when a sequence prepared in several views).

        Only evidence with VALID signatures ships (ISSUE 14): in MAC
        mode the hot path accepts frames by their lane without checking
        the embedded signature, so a sig-corrupting Byzantine peer can
        place garbage-signature prepares in honest logs — shipping one
        would make validators reject this replica's whole VIEW-CHANGE
        (the liveness wedge the chaos soak caught). A slot that cannot
        assemble a fully-valid certificate is simply not claimed: the
        client's retransmission re-orders it in the new view. In
        signature mode every logged message was already verified, so the
        filter is a no-op."""
        best: Dict[int, Tuple[int, dict]] = {}
        for (view, seq), pp in self.pre_prepares.items():
            if seq <= self.low_mark or not self._prepared((view, seq)):
                continue
            primary = self.config.primary_of(view)
            if not self._verify_inline(primary, pp.signable(), pp.sig):
                continue  # sig-corrupt primary: slot unprovable
            preps = [
                p.to_dict()
                for rid, p in self.prepares[(view, seq)].items()
                if rid != primary
                and p.digest == pp.digest
                and self._verify_inline(p.replica, p.signable(), p.sig)
            ]
            if len(preps) < 2 * self.config.f:
                continue  # not enough valid-signature evidence
            entry = {"pre_prepare": pp.to_dict(), "prepares": preps}
            if seq not in best or view > best[seq][0]:
                best[seq] = (view, entry)
        return [entry for _, (_, entry) in sorted(best.items())]

    def _validate_view_change(self, vc: ViewChange) -> bool:
        # C: 2f+1 checkpoint messages proving last_stable_seq.
        if vc.last_stable_seq > 0:
            seen: Set[int] = set()
            for d in vc.checkpoint_proof:
                try:
                    cp = Message.from_dict(dict(d))
                except (KeyError, TypeError, ValueError):
                    return False
                if not isinstance(cp, Checkpoint) or cp.seq != vc.last_stable_seq:
                    return False
                if cp.replica in seen:
                    return False
                if not self._verify_inline(cp.replica, cp.signable(), cp.sig):
                    return False
                seen.add(cp.replica)
            if self._majority_digest(vc.checkpoint_proof) is None:
                return False
        # P: each prepared certificate is internally consistent + signed.
        for proof in vc.prepared_proofs:
            try:
                pp = Message.from_dict(dict(proof["pre_prepare"]))
                preps = [Message.from_dict(dict(p)) for p in proof["prepares"]]
            except (KeyError, TypeError, ValueError):
                return False
            if not isinstance(pp, PrePrepare) or pp.seq <= vc.last_stable_seq:
                return False
            primary = self.config.primary_of(pp.view)
            if pp.replica != primary or pp.batch_digest() != pp.digest:
                return False
            if not self._verify_inline(primary, pp.signable(), pp.sig):
                return False
            seen = set()
            for p in preps:
                if not isinstance(p, Prepare):
                    return False
                if (p.view, p.seq, p.digest) != (pp.view, pp.seq, pp.digest):
                    return False
                if p.replica == primary or p.replica in seen:
                    return False
                if not self._verify_inline(p.replica, p.signable(), p.sig):
                    return False
                seen.add(p.replica)
            if len(seen) < 2 * self.config.f:
                return False
        return True

    def _on_view_change(self, vc: ViewChange) -> List[Action]:
        if vc.new_view <= self.view:
            # A VIEW-CHANGE for a view we already lead means the sender
            # never received our NEW-VIEW (it was lost, or the sender is
            # retransmitting on its timer): resend the cached message
            # point-to-point — no recomputation, no re-broadcast
            # (ISSUE 12 NEW-VIEW retransmission/suppression).
            if (
                vc.new_view == self.view
                and self.config.primary_of(vc.new_view) == self.id
                and vc.new_view in self.new_view_sent
                and 0 <= vc.replica < self.config.n
                and vc.replica != self.id
            ):
                return [Send(vc.replica, self.new_view_sent[vc.new_view])]
            return []
        slot = self.view_changes.setdefault(vc.new_view, {})
        if vc.replica in slot:
            return []
        if not self._validate_view_change(vc):
            return []
        slot[vc.replica] = vc
        out: List[Action] = []
        # Join rule (§4.5.2 liveness): f+1 replicas already moved past our
        # view -> join the smallest such view, even if our timer has not
        # fired (prevents a late replica from stalling in an abandoned view).
        floor = self.pending_view if self.in_view_change else self.view
        voters: Set[int] = set()
        candidates: List[int] = []
        for v, reps in self.view_changes.items():
            if v > floor:
                voters.update(reps)
                candidates.append(v)
        if len(voters) >= self.config.f + 1:
            out.extend(self.start_view_change(min(candidates)))
        if self.config.primary_of(vc.new_view) == self.id:
            out.extend(self._maybe_new_view(vc.new_view))
        return out

    def _compute_o(
        self, vcs: List[ViewChange]
    ) -> Tuple[int, List[Tuple[int, str, List[dict]]]]:
        """(min_s, [(seq, digest, request_dicts)]) — the O computation:
        re-issue every sequence some quorum member prepared (the whole
        request BATCH rides along in the prepared proof); gaps are filled
        with EMPTY batches (the batched form of PBFT §4.4's null
        request — execution is a no-op, the sequence still advances)."""
        min_s = max(vc.last_stable_seq for vc in vcs)
        best: Dict[int, Tuple[int, str, List[dict]]] = {}
        for vc in vcs:
            for proof in vc.prepared_proofs:
                ppd = dict(proof["pre_prepare"])
                n = ppd["seq"]
                if n <= min_s:
                    continue
                if n not in best or ppd["view"] > best[n][0]:
                    # Legacy evidence carries the singular `request`;
                    # batched evidence the `requests` list.
                    if "requests" in ppd:
                        reqs = [dict(r) for r in ppd["requests"]]
                    elif ppd.get("request") is not None:
                        reqs = [dict(ppd["request"])]
                    else:
                        reqs = []
                    best[n] = (ppd["view"], ppd["digest"], reqs)
        entries: List[Tuple[int, str, List[dict]]] = []
        max_s = max(best) if best else min_s
        for n in range(min_s + 1, max_s + 1):
            if n in best:
                entries.append((n, best[n][1], best[n][2]))
            else:
                entries.append((n, batch_digest(()), []))
        return min_s, entries

    def _majority_digest(self, proof) -> Optional[str]:
        """The digest backed by >= 2f+1 *distinct replicas* in a checkpoint
        proof, or None. This is THE quorum rule for stable-checkpoint
        evidence: _validate_view_change uses it to accept a proof and
        _stable_digest_for to pick the digest adopted during the watermark
        jump — a proof may also carry correctly-signed checkpoints with a
        minority (Byzantine) digest, so neither entry order nor repeated
        entries from one replica may influence the choice."""
        seen: Set[int] = set()
        by_digest: Dict[str, int] = {}
        for d in proof:
            d = dict(d)
            rid, dig = d.get("replica"), d.get("digest")
            if rid in seen or not isinstance(dig, str):
                continue
            seen.add(rid)
            by_digest[dig] = by_digest.get(dig, 0) + 1
        for dig, count in by_digest.items():
            if count >= 2 * self.config.f + 1:
                return dig
        return None

    def _stable_cert_for(
        self, vcs: List[ViewChange], min_s: int
    ) -> Optional[Tuple[str, List[dict]]]:
        """(digest, 2f+1 matching checkpoint dicts) certifying min_s, from
        the view-change evidence. The PROOF rides along with the digest
        because a replica whose watermark advances through a NEW-VIEW's
        min_s (not its own checkpoint collection) must ADOPT the
        certificate too: its next VIEW-CHANGE claims last_stable_seq =
        min_s, and validators reject a claim whose attached proof still
        certifies the old (pre-jump) checkpoint — a stale proof wedges
        every future view change that needs this replica's vote (found by
        the chaos soak: seed 13's cluster livelocked exactly this way)."""
        for vc in vcs:
            if vc.last_stable_seq == min_s and vc.checkpoint_proof:
                dig = self._majority_digest(vc.checkpoint_proof)
                if dig is not None:
                    proof, seen = [], set()
                    for d in vc.checkpoint_proof:
                        d = dict(d)
                        rid = d.get("replica")
                        if d.get("digest") == dig and rid not in seen:
                            seen.add(rid)
                            proof.append(d)
                    return dig, proof
        return None

    def _maybe_new_view(self, v: int) -> List[Action]:
        if v in self.new_view_sent:
            return []
        slot = self.view_changes.get(v, {})
        if len(slot) < 2 * self.config.f + 1:
            return []
        # Deterministic V: the 2f+1 lowest replica ids.
        vcs = [slot[rid] for rid in sorted(slot)[: 2 * self.config.f + 1]]
        min_s, entries = self._compute_o(vcs)
        pps = [
            self._sign(
                PrePrepare(
                    view=v,
                    seq=n,
                    digest=digest,
                    requests=tuple(
                        ClientRequest(
                            **{k: val for k, val in r.items() if k != "type"}
                        )
                        for r in reqs
                    ),
                    replica=self.id,
                )
            )
            for n, digest, reqs in entries
        ]
        nv = self._sign(
            NewView(
                new_view=v,
                view_changes=tuple(vc.to_dict() for vc in vcs),
                pre_prepares=tuple(pp.to_dict() for pp in pps),
                replica=self.id,
            )
        )
        self.new_view_sent[v] = nv
        out: List[Action] = [Broadcast(nv)]
        out.extend(
            self._enter_new_view(v, min_s, self._stable_cert_for(vcs, min_s), pps)
        )
        return out

    def _on_new_view(self, nv: NewView) -> List[Action]:
        if nv.new_view < self.view or (
            nv.new_view == self.view and not self.in_view_change
        ):
            return []
        if nv.replica != self.config.primary_of(nv.new_view):
            return []
        try:
            vcs = [Message.from_dict(dict(d)) for d in nv.view_changes]
            pps = [Message.from_dict(dict(d)) for d in nv.pre_prepares]
        except (KeyError, TypeError, ValueError):
            return []
        # V: 2f+1 distinct, correctly signed, valid view-changes for this view.
        if len(vcs) < 2 * self.config.f + 1:
            return []
        seen: Set[int] = set()
        for vc in vcs:
            if not isinstance(vc, ViewChange) or vc.new_view != nv.new_view:
                return []
            if vc.replica in seen:
                return []
            if not self._verify_inline(vc.replica, vc.signable(), vc.sig):
                return []
            if not self._validate_view_change(vc):
                return []
            seen.add(vc.replica)
        # O must equal our own recomputation from V (a Byzantine new primary
        # cannot smuggle in requests nobody prepared).
        min_s, entries = self._compute_o(vcs)
        if len(pps) != len(entries):
            return []
        for pp, (n, digest, _reqs) in zip(pps, entries):
            if not isinstance(pp, PrePrepare):
                return []
            if (pp.view, pp.seq, pp.digest) != (nv.new_view, n, digest):
                return []
            if pp.replica != nv.replica or pp.batch_digest() != pp.digest:
                return []
            if not self._verify_inline(pp.replica, pp.signable(), pp.sig):
                return []
        return self._enter_new_view(
            nv.new_view, min_s, self._stable_cert_for(vcs, min_s), pps
        )

    def _enter_new_view(
        self,
        v: int,
        min_s: int,
        stable_cert: Optional[Tuple[str, List[dict]]],
        pps: List[PrePrepare],
    ) -> List[Action]:
        # Tentative executions do not survive a view change (§5.3): roll
        # the uncommitted suffix back BEFORE processing the new view's O
        # — its re-issued pre-prepares re-run the three-phase protocol
        # and re-execute whatever the quorum actually prepared.
        self._rollback_tentative()
        self.view = v
        self.in_view_change = False
        self.pending_view = 0
        if self.wal is not None:
            self.wal.note_view(v, False, 0)
        self._my_view_change = None
        # Keep only the NEW-VIEW for the view we just entered (the one a
        # laggard's retransmitted VIEW-CHANGE may still need); older
        # entries can never be asked for again.
        self.new_view_sent = {
            w: m for w, m in self.new_view_sent.items() if w >= v
        }
        self._sealed_ts = {}  # per-view primary ordering memory
        self.counters["view_changes_completed"] += 1
        vh = self.view_hook
        if vh is not None:
            vh("new_view_installed", v)
        for past in [w for w in self.view_changes if w <= v]:
            del self.view_changes[past]
        out: List[Action] = []
        if min_s > self.low_mark and stable_cert is not None:
            stable_digest, stable_proof = stable_cert
            out.extend(self._advance_watermark(min_s, stable_digest))
            # Adopt the certificate with the watermark: our next
            # VIEW-CHANGE's C component must certify THIS stable seq.
            self.stable_proof = stable_proof
            self._wal_checkpoint(min_s)
        # The new primary continues the sequence after the re-issued slots;
        # harmless for backups (their seq_counter is unused until they lead).
        # low_mark is included: when this replica's stable checkpoint is
        # ahead of min_s (its view-change wasn't among the 2f+1 lowest ids),
        # seqs <= low_mark are already executed everywhere and would never
        # reply if re-assigned.
        self.seq_counter = max(
            self.low_mark, min_s, max((pp.seq for pp in pps), default=min_s)
        )
        # Prune normal-case log entries from abandoned views above min_s that
        # the quorum did not re-issue: they can never prepare in view v, and
        # keeping them makes has_unexecuted() fire the request timer forever.
        reissued = {pp.seq for pp in pps}
        for log in (self.pre_prepares, self.prepares, self.commits):
            for key in [k for k in log if k[0] < v and k[1] not in reissued]:
                del log[key]
        for pp in pps:
            out.extend(self._on_pre_prepare(pp))
        # Re-aim forwarded-but-unexecuted client requests at the NEW
        # primary (ISSUE 12): a request forwarded to a primary that was
        # just voted out evaporated with the old view — without this the
        # only recovery is the client's retransmission timer, and until
        # it fires the request timers keep escalating further view
        # changes with nothing to order (the storm the chaos bench
        # measures). Exactly-once is untouched: duplicates die on the
        # per-client timestamp guards wherever they land.
        for client, req in list(self._forwarded.items()):
            last = self.last_timestamp.get(client)
            if last is not None and req.timestamp <= last:
                self._forwarded.pop(client, None)  # already executed
                continue
            if self.config.primary_of(v) == self.id:
                out.extend(self.on_client_request(req))
            else:
                out.append(Send(self.config.primary_of(v), req))
        return out

    def _advance_watermark(
        self, stable_seq: int, stable_digest: str
    ) -> List[Action]:
        if stable_seq <= self.low_mark:
            return []
        if self.config.tentative and stable_seq > self.committed_upto:
            # A 2f+1 quorum checkpointed past our committed floor: the
            # tentative suffix we hold may not match the certified chain
            # — revert to the committed point and catch up through the
            # certified state (the state-transfer branch below).
            self._rollback_tentative()
        self.low_mark = stable_seq
        self.counters["checkpoints_stable"] += 1
        out: List[Action] = []
        if stable_seq > self.executed_upto:
            # We missed executions that 2f+1 replicas checkpointed, and the
            # pruning below deletes the messages that would replay them:
            # fetch the certified checkpoint state from a peer (PBFT §5.3).
            # Execution stalls (executed_upto stays) until a StateResponse
            # whose payload hashes to stable_digest arrives; the runtime
            # re-broadcasts the request on its retry timer.
            self.awaiting_state = (stable_seq, stable_digest)
            out.append(
                Broadcast(
                    self._sign(StateRequest(seq=stable_seq, replica=self.id))
                )
            )
        for log in (self.pre_prepares, self.prepares, self.commits):
            for key in [k for k in log if k[1] <= stable_seq]:
                del log[key]
        self.sent_commit = {k for k in self.sent_commit if k[1] > stable_seq}
        for seq in [s for s in self.checkpoints if s <= stable_seq]:
            del self.checkpoints[seq]
        for seq in [s for s in self.pending_execution if s <= stable_seq]:
            del self.pending_execution[seq]
        for seq in [s for s in self.snapshots if s < stable_seq]:
            del self.snapshots[seq]
        return out
