"""Declarative fault schedules for the chaos simulation (ISSUE 5).

A ``FaultSchedule`` is an ordered list of (step, action, args) events applied
to a ``simulation.Cluster`` as its scheduler reaches each step — the sim
analogue of a Jepsen nemesis timeline. Schedules serialize to/from JSON so a
failing chaos-soak seed prints a schedule a human can read and
``chaos_soak.py --replay SEED`` can regenerate bit-identically.

``random_schedule`` draws a schedule from one seed while tracking the live
fault budget: at most ``max_faulty`` replicas simultaneously crashed or
Byzantine (the PBFT f bound — the safety invariants only hold under it; the
checker-validity arm of chaos_soak deliberately exceeds it), and every
partition/crash/fault is cleared by ``steps`` so the liveness check has a
healed cluster to converge on.
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import List, Optional, Tuple

from .simulation import FAULT_MODES, Cluster, LinkChaos

# action -> how chaos_soak narrates it on replay.
ACTIONS = (
    "partition",  # args: [[rid, ...], ...]
    "heal",  # args: []
    "crash",  # args: [rid]
    "revive",  # args: [rid]
    "restart",  # args: [rid, from_disk] — process restart (ISSUE 15)
    "set_fault",  # args: [rid, mode]
    "clear_fault",  # args: [rid]
    "chaos",  # args: [drop_pct, dup_pct, delay_min, delay_max]
)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    step: int
    action: str
    args: Tuple = ()

    def to_list(self) -> list:
        return [self.step, self.action, list(self.args)]


class FaultSchedule:
    """Ordered fault events; ``apply_due`` fires everything scheduled at or
    before the cluster's current step exactly once."""

    def __init__(self, events: List[FaultEvent]):
        self.events = sorted(events, key=lambda e: e.step)
        self._next = 0

    def __len__(self) -> int:
        return len(self.events)

    def reset(self) -> None:
        self._next = 0

    def max_step(self) -> int:
        return self.events[-1].step if self.events else 0

    def apply_due(self, cluster: Cluster, step: int) -> List[FaultEvent]:
        """Apply every event with event.step <= step; returns them."""
        fired = []
        while self._next < len(self.events) and self.events[self._next].step <= step:
            ev = self.events[self._next]
            self._next += 1
            self.apply(cluster, ev)
            fired.append(ev)
        return fired

    @staticmethod
    def apply(cluster: Cluster, ev: FaultEvent) -> None:
        a = ev.args
        if ev.action == "partition":
            cluster.partition([set(g) for g in a[0]])
        elif ev.action == "heal":
            cluster.heal()
        elif ev.action == "crash":
            cluster.crash(a[0])
        elif ev.action == "revive":
            cluster.uncrash(a[0])
        elif ev.action == "restart":
            # Process restart (ISSUE 15): the Replica object dies; the
            # replacement replays its write-ahead log (from_disk) or
            # comes back amnesiac — the S5 checker watches either way.
            cluster.restart(a[0], bool(a[1]))
        elif ev.action == "set_fault":
            cluster.set_fault(a[0], a[1])
        elif ev.action == "clear_fault":
            cluster.clear_fault(a[0])
        elif ev.action == "chaos":
            drop, dup, dmin, dmax = a
            chaos = LinkChaos(
                drop_pct=drop, dup_pct=dup, delay_min=int(dmin), delay_max=int(dmax)
            )
            cluster.set_chaos(None if chaos.is_instant() else chaos)
        else:
            raise ValueError(f"unknown fault action {ev.action!r}")

    def to_json(self) -> str:
        return json.dumps([e.to_list() for e in self.events])

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        return cls(
            [FaultEvent(int(s), str(a), tuple(args)) for s, a, args in json.loads(text)]
        )

    def describe(self) -> str:
        return "\n".join(
            f"  step {e.step:>4}: {e.action} {list(e.args)}" for e in self.events
        )


def random_schedule(
    seed: int,
    n: int,
    steps: int,
    max_faulty: Optional[int] = None,
    events_every: int = 20,
    modes: Tuple[str, ...] = FAULT_MODES,
    restart_from_disk: bool = False,
) -> FaultSchedule:
    """A seeded nemesis timeline over ``steps`` scheduler rounds.

    Invariants of the generated schedule (not of the run — that is the
    checker's job): crashed+Byzantine replicas never exceed ``max_faulty``
    (default f = (n-1)//3), and a trailing cleanup block heals partitions,
    revives crashes, clears faults, and turns link chaos off so the
    recovery phase starts from a connected, fault-free cluster.

    ``restart_from_disk`` (ISSUE 15): every recovery from a crash becomes
    a PROCESS RESTART from the write-ahead log ("restart" events,
    from_disk=True) instead of a memory-intact resume — the seeded
    crash-restart fault mode the chaos soak's S5 matrix drives (requires
    a Cluster built with wal=True)."""
    rng = random.Random(seed)
    f = (n - 1) // 3
    budget = f if max_faulty is None else max_faulty
    crashed: set = set()
    faulty: set = set()
    partitioned = False
    events: List[FaultEvent] = []

    def spend() -> int:
        return len(crashed | faulty)

    step = 0
    while True:
        step += rng.randint(max(2, events_every // 2), events_every + events_every // 2)
        if step >= steps:
            break
        roll = rng.random()
        if roll < 0.18 and not partitioned and n >= 4:
            members = list(range(n))
            rng.shuffle(members)
            cut = rng.randint(1, n - 1)
            groups = [sorted(members[:cut]), sorted(members[cut:])]
            events.append(FaultEvent(step, "partition", (groups,)))
            partitioned = True
        elif roll < 0.30 and partitioned:
            events.append(FaultEvent(step, "heal", ()))
            partitioned = False
        elif roll < 0.45 and spend() < budget:
            victim = rng.choice([r for r in range(n) if r not in crashed | faulty])
            crashed.add(victim)
            events.append(FaultEvent(step, "crash", (victim,)))
        elif roll < 0.58 and crashed:
            victim = rng.choice(sorted(crashed))
            crashed.discard(victim)
            if restart_from_disk:
                events.append(FaultEvent(step, "restart", (victim, True)))
            else:
                events.append(FaultEvent(step, "revive", (victim,)))
        elif roll < 0.75 and spend() < budget:
            victim = rng.choice([r for r in range(n) if r not in crashed | faulty])
            mode = rng.choice(list(modes))
            faulty.add(victim)
            events.append(FaultEvent(step, "set_fault", (victim, mode)))
        elif roll < 0.85 and faulty:
            victim = rng.choice(sorted(faulty))
            faulty.discard(victim)
            events.append(FaultEvent(step, "clear_fault", (victim,)))
        else:
            events.append(
                FaultEvent(
                    step,
                    "chaos",
                    (
                        round(rng.uniform(0.0, 0.15), 3),
                        round(rng.uniform(0.0, 0.10), 3),
                        0,
                        rng.randint(1, 4),
                    ),
                )
            )
    # Trailing cleanup: the liveness invariant is only promised once the
    # network heals and the faulty set is within budget (here: empty).
    cleanup = steps
    if partitioned:
        events.append(FaultEvent(cleanup, "heal", ()))
    for rid in sorted(crashed):
        if restart_from_disk:
            events.append(FaultEvent(cleanup, "restart", (rid, True)))
        else:
            events.append(FaultEvent(cleanup, "revive", (rid,)))
    for rid in sorted(faulty):
        events.append(FaultEvent(cleanup, "clear_fault", (rid,)))
    events.append(FaultEvent(cleanup, "chaos", (0.0, 0.0, 0, 0)))
    return FaultSchedule(events)
