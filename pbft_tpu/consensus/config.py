"""Cluster configuration: the rebuild makes network.json real.

The reference shipped a network.json (4 nodes, ports 8000-8003, primary 8000)
that no code ever read (SURVEY.md §2 "Static topology config"); here it is the
actual source of truth for replica identities, keys, f, the batching window,
and the verifier backend selection.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

from ..crypto import ref as crypto_ref
from .messages import blake2b_256


@dataclasses.dataclass(frozen=True)
class ReplicaIdentity:
    replica_id: int
    host: str
    port: int
    pubkey: str  # hex

    def pubkey_bytes(self) -> bytes:
        return bytes.fromhex(self.pubkey)


@dataclasses.dataclass
class ClusterConfig:
    replicas: List[ReplicaIdentity]
    watermark_window: int = 256
    checkpoint_interval: int = 16
    batch_pad: int = 64  # padded batch size fed to the TPU verifier
    # Bounded verify accumulation: when verify_flush_us > 0 a replica
    # holds its verify queue until verify_flush_items are pending
    # (0 = batch_pad) or the oldest item has waited verify_flush_us —
    # trading that much latency for a fatter batching window (more items
    # per verifier launch). 0 = flush every event-loop pass.
    verify_flush_us: int = 0
    verify_flush_items: int = 0
    # Request batching (ISSUE 4): the primary accumulates client requests
    # into an ordered batch and runs ONE three-phase instance per batch.
    # batch_max_items caps the batch (1 = the pre-batching one-instance-
    # per-request protocol, wire-compatible with 1.1.0 peers);
    # batch_flush_us bounds how long a partial batch may wait for more
    # requests before the runtime seals it (0 = seal on the next
    # event-loop pass). Backups ignore both: batch composition is the
    # primary's choice, acceptance is size-agnostic.
    batch_max_items: int = 1
    batch_flush_us: int = 0
    # Admission control (ISSUE 12): explicit overload replies instead of
    # silent queueing into the tail. admission_inflight caps ONE client's
    # estimated in-flight requests (its request timestamp's distance past
    # the last executed one — client timestamps are consecutive, so the
    # distance IS the pipeline depth); admission_backlog watermarks the
    # replica's own backlog (verify inbox + sealed-but-unexecuted
    # sequences). A fresh request past either bound is answered with
    # {"type": "overloaded"} and dropped — clients back off with jitter
    # (net/client.py request_with_retry). Retransmissions always pass
    # (liveness must never be admission-gated). 0 disables either check.
    admission_inflight: int = 0
    admission_backlog: int = 0
    # Multi-core replica core (ISSUE 13): event-loop shard threads (each
    # with a companion crypto pipeline thread) the NATIVE runtime runs;
    # 1 = the classic single-threaded loop. The asyncio runtime accepts
    # the key and stays single-loop (it logs as much at startup) — its
    # parallelism lives in the JAX mesh, not the socket layer. The
    # default is constants-linted against core/replica.h.
    net_threads: int = 1
    # Fast-path modes (ISSUE 14, protocol 1.3.0; defaults constants-linted
    # against core/replica.h). fastpath = "mac" makes this node OFFER the
    # per-link MAC-vector authenticator mode in its hellos — normal-case
    # frames on links where BOTH sides offered it are authenticated by
    # session MACs instead of hot-path signature verification (signatures
    # are still minted: they are the evidence view changes re-verify).
    # tentative = True makes replicas execute and reply once PREPARED
    # (before commit; Castro–Liskov §5.3) with rollback on view change —
    # clients then accept a 2f+1 matching tentative-reply quorum.
    fastpath: str = "sig"
    tentative: bool = False
    # Durable replica recovery (ISSUE 15): when wal_dir is non-empty each
    # replica keeps a write-ahead log at {wal_dir}/replica-{id}.wal —
    # current view, sent votes (digest only), latest stable checkpoint
    # certificate + snapshot — flushed with group-commit fsync batching
    # at the runtime's emit boundary, and replayed on restart so a
    # kill -9'd replica re-joins the SAME view without ever contradicting
    # a persisted vote. wal_fsync=False keeps the writes but skips the
    # fsync (kill -9 of the process stays safe via the page cache; only
    # host power loss can drop the tail) — the A/B lever that makes the
    # durability cost visible in the bench. Defaults constants-linted
    # against core/replica.h.
    wal_dir: str = ""
    wal_fsync: bool = True
    verifier: str = "cpu"  # "cpu" | "tpu"
    # Encrypted replica-replica links (signed-ephemeral DH + AEAD framing,
    # pbft_tpu/net/secure.py) — the reference's development_transport
    # bundles Noise encryption on every link (reference src/main.rs:42).
    secure: bool = False

    @property
    def n(self) -> int:
        return len(self.replicas)

    @property
    def f(self) -> int:
        return (self.n - 1) // 3

    def primary_of(self, view: int) -> int:
        return view % self.n

    def identity(self, replica_id: int) -> ReplicaIdentity:
        return self.replicas[replica_id]

    def to_json(self) -> str:
        return json.dumps(
            {
                "watermark_window": self.watermark_window,
                "checkpoint_interval": self.checkpoint_interval,
                "batch_pad": self.batch_pad,
                "verify_flush_us": self.verify_flush_us,
                "verify_flush_items": self.verify_flush_items,
                "batch_max_items": self.batch_max_items,
                "batch_flush_us": self.batch_flush_us,
                "admission_inflight": self.admission_inflight,
                "admission_backlog": self.admission_backlog,
                "net_threads": self.net_threads,
                "fastpath": self.fastpath,
                "tentative": self.tentative,
                "wal_dir": self.wal_dir,
                "wal_fsync": self.wal_fsync,
                "verifier": self.verifier,
                "secure": self.secure,
                "replicas": [dataclasses.asdict(r) for r in self.replicas],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "ClusterConfig":
        d = json.loads(text)
        return cls(
            replicas=[ReplicaIdentity(**r) for r in d["replicas"]],
            watermark_window=d.get("watermark_window", 256),
            checkpoint_interval=d.get("checkpoint_interval", 16),
            batch_pad=d.get("batch_pad", 64),
            verify_flush_us=d.get("verify_flush_us", 0),
            verify_flush_items=d.get("verify_flush_items", 0),
            batch_max_items=d.get("batch_max_items", 1),
            batch_flush_us=d.get("batch_flush_us", 0),
            admission_inflight=d.get("admission_inflight", 0),
            admission_backlog=d.get("admission_backlog", 0),
            net_threads=d.get("net_threads", 1),
            fastpath=d.get("fastpath", "sig"),
            tentative=bool(d.get("tentative", False)),
            wal_dir=d.get("wal_dir", ""),
            wal_fsync=bool(d.get("wal_fsync", True)),
            verifier=d.get("verifier", "cpu"),
            secure=bool(d.get("secure", False)),
        )


def make_local_cluster(
    n: int, base_port: int = 8000, seed_prefix: bytes = b"pbft-tpu-replica-"
):
    """Deterministic localhost cluster for tests/simulation.

    Returns (config, seeds): seeds[i] is replica i's Ed25519 seed. The
    primary listens for clients on base_port, mirroring the reference's
    fixed client port 8000 (reference src/client_handler.rs:22-28).
    """
    seeds = []
    identities = []
    for i in range(n):
        seed = blake2b_256(seed_prefix + str(i).encode())
        pub = crypto_ref.public_key(seed)
        seeds.append(seed)
        identities.append(
            ReplicaIdentity(
                replica_id=i, host="127.0.0.1", port=base_port + i, pubkey=pub.hex()
            )
        )
    return ClusterConfig(replicas=identities), seeds
