"""Per-replica write-ahead log: durable safety state for crash-restart
(ISSUE 15; PBFT §4.3's stable-storage message log, and the
restart-from-disk recovery of Castro & Liskov's TOCS 2002 paper).

Every recovery story before this assumed a crashed replica came back
with FRESH state and caught up via §5.3 state transfer — which means a
restarted replica has forgotten its PREPARE/COMMIT votes and can, in
principle, vote twice for one (view, seq): the amnesia violation the
stable-storage log exists to prevent. This module persists exactly the
state whose loss breaks safety:

- the current view (and whether a view change was pending at the crash);
- every vote this replica SENT — pre-prepare (primary seal), prepare,
  commit — as (kind, view, seq) -> digest. Digest only: the message
  bodies are recoverable from any peer; what must survive is what WE
  claimed, so the restarted replica can refuse to contradict it;
- the latest stable checkpoint: its canonical payload (which embeds the
  app snapshot AND the per-client exactly-once reply cache) plus the
  2f+1 checkpoint certificate, so recovery reinstalls a proven state
  and the next VIEW-CHANGE can still prove its watermark.

Durability rides the runtimes' existing batching seams (group commit):
``note_*`` appends records to an in-memory buffer and updates the live
mirror; the runtime calls :meth:`WriteAheadLog.flush` once per emit
boundary — BEFORE any of that pass's votes reach a socket — so one
fsync covers a whole verify batch's worth of votes instead of one per
message. ``fsync=False`` (network.json ``wal_fsync``) keeps the write
but skips the fsync: kill -9 of the process stays safe (the page cache
survives), only a whole-host power loss can lose the tail.

The on-disk format is byte-identical to core/wal.{h,cc} (the constants
are linted by pbft_tpu/analysis/constants.py):

    header  WAL_MAGIC (8B) + u32le version
    record  u8 tag + u32le payload length + payload
      view        (0x01)  i64le view + u8 in_view_change + i64le pending
      vote        (0x02)  u8 kind + i64le view + i64le seq + 32B digest
      checkpoint  (0x03)  i64le seq + u32le len + payload
                          + u32le len + certificate JSON

Only the tail record can ever be torn (append-only writes): replay
stops at the first truncated record. On open — and on every stable
checkpoint — the log COMPACTS: a fresh file holding the view record,
the latest checkpoint, and the votes above its sequence is written to
``<path>.tmp``, fsynced, and renamed over the old log, so the file is
bounded by the watermark window instead of growing forever.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import Dict, List, Optional, Tuple

WAL_MAGIC = b"PBFTWAL1"
WAL_VERSION = 1
# Record tags (cross-runtime contract with core/wal.h; constants lint).
WAL_REC_VIEW = 0x01
WAL_REC_VOTE = 0x02
WAL_REC_CHECKPOINT = 0x03
# Vote kinds inside a WAL_REC_VOTE record.
WAL_VOTE_PRE_PREPARE = 1
WAL_VOTE_PREPARE = 2
WAL_VOTE_COMMIT = 3

_HEADER = struct.Struct("<8sI")
_REC_HDR = struct.Struct("<BI")
_VIEW = struct.Struct("<qBq")
_VOTE = struct.Struct("<Bqq32s")
_CP_SEQ = struct.Struct("<q")
_U32 = struct.Struct("<I")


@dataclasses.dataclass
class WalState:
    """What a replay recovered: the state a restarted replica reinstalls."""

    view: int = 0
    in_view_change: bool = False
    pending_view: int = 0
    # (kind, view, seq) -> digest hex — the votes this replica sent.
    votes: Dict[Tuple[int, int, int], str] = dataclasses.field(
        default_factory=dict
    )
    # (seq, canonical payload, certificate JSON) of the stable checkpoint.
    checkpoint: Optional[Tuple[int, str, str]] = None

    def empty(self) -> bool:
        return (
            self.view == 0
            and not self.in_view_change
            and not self.votes
            and self.checkpoint is None
        )

    def max_pre_prepare_seq(self) -> int:
        """Highest sequence this replica (as primary) ever pre-prepared —
        a recovered primary must never re-assign one of these."""
        return max(
            (seq for (kind, _v, seq) in self.votes
             if kind == WAL_VOTE_PRE_PREPARE),
            default=0,
        )


def _encode_view(view: int, ivc: bool, pending: int) -> bytes:
    payload = _VIEW.pack(view, 1 if ivc else 0, pending)
    return _REC_HDR.pack(WAL_REC_VIEW, len(payload)) + payload


def _encode_vote(kind: int, view: int, seq: int, digest_hex: str) -> bytes:
    payload = _VOTE.pack(kind, view, seq, bytes.fromhex(digest_hex))
    return _REC_HDR.pack(WAL_REC_VOTE, len(payload)) + payload


def _encode_checkpoint(seq: int, payload: str, cert_json: str) -> bytes:
    p = payload.encode()
    c = cert_json.encode()
    body = _CP_SEQ.pack(seq) + _U32.pack(len(p)) + p + _U32.pack(len(c)) + c
    return _REC_HDR.pack(WAL_REC_CHECKPOINT, len(body)) + body


def decode_bytes(data: bytes) -> WalState:
    """Replay a log image into a WalState. Tolerates a torn tail record
    (the only kind a kill -9 mid-append can produce); raises ValueError
    on a wrong magic or version (that is corruption, not a torn tail)."""
    state = WalState()
    if len(data) < _HEADER.size:
        return state  # fresh/empty (or torn before the header completed)
    magic, version = _HEADER.unpack_from(data, 0)
    if magic != WAL_MAGIC:
        raise ValueError(f"not a pbft WAL (magic {magic!r})")
    if version != WAL_VERSION:
        raise ValueError(f"unknown WAL version {version}")
    off = _HEADER.size
    while off + _REC_HDR.size <= len(data):
        tag, n = _REC_HDR.unpack_from(data, off)
        off += _REC_HDR.size
        if off + n > len(data):
            break  # torn tail: the record never finished writing
        payload = data[off : off + n]
        off += n
        if tag == WAL_REC_VIEW and n == _VIEW.size:
            view, ivc, pending = _VIEW.unpack(payload)
            state.view = view
            state.in_view_change = bool(ivc)
            state.pending_view = pending
        elif tag == WAL_REC_VOTE and n == _VOTE.size:
            kind, view, seq, digest = _VOTE.unpack(payload)
            state.votes[(kind, view, seq)] = digest.hex()
        elif tag == WAL_REC_CHECKPOINT and n >= _CP_SEQ.size + 2 * _U32.size:
            (seq,) = _CP_SEQ.unpack_from(payload, 0)
            p = _CP_SEQ.size
            (plen,) = _U32.unpack_from(payload, p)
            p += _U32.size
            if p + plen + _U32.size > n:
                continue  # malformed: skip, keep replaying
            cp_payload = payload[p : p + plen]
            p += plen
            (clen,) = _U32.unpack_from(payload, p)
            p += _U32.size
            if p + clen > n:
                continue
            cert = payload[p : p + clen]
            state.checkpoint = (seq, cp_payload.decode(), cert.decode())
            # Votes at or below a stable checkpoint are beneath the
            # watermark: they can never be re-sent, so they no longer
            # constrain anything.
            for key in [k for k in state.votes if k[2] <= seq]:
                del state.votes[key]
        # Unknown tags / wrong-size payloads are skipped: forward compat.
    return state


def replay(path: str) -> WalState:
    """Replay the log at ``path`` (missing file == empty state)."""
    try:
        with open(path, "rb") as fh:
            return decode_bytes(fh.read())
    except FileNotFoundError:
        return WalState()


class WriteAheadLog:
    """The append-side of the log, plus the live mirror the replica's
    no-contradiction guards consult.

    ``path=None`` is the SIMULATOR mode: no file I/O at all — the object
    itself plays the disk (it survives the simulated crash while the
    Replica object is discarded), which is exactly the durability model
    the chaos soak's crash-restart schedules need.
    """

    def __init__(self, path: Optional[str] = None, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self.appends = 0  # records appended (pbft_wal_appends_total)
        self.fsyncs = 0  # fsync syscalls issued (pbft_wal_fsyncs_total)
        self.bytes_written = 0  # file bytes written (pbft_wal_bytes_total)
        self._pending: List[bytes] = []
        self._compact_due = False
        # Live mirror (the guards' source of truth) + the frozen replay
        # snapshot recovery installs.
        self.state = replay(path) if path else WalState()
        self.recovered = dataclasses.replace(
            self.state, votes=dict(self.state.votes)
        )
        if path:
            # Recovery compaction: start the new life from a bounded,
            # cleanly-terminated log (also heals any torn tail record).
            self._compact()

    # -- the replica-facing surface ------------------------------------------

    def vote_digest(self, kind: int, view: int, seq: int) -> Optional[str]:
        return self.state.votes.get((kind, view, seq))

    def note_vote(self, kind: int, view: int, seq: int, digest_hex: str) -> bool:
        """Record a vote about to be sent. Returns False — and records
        NOTHING — when a durable vote for the same (kind, view, seq)
        names a DIFFERENT digest: the caller must not send (sending
        would be the equivocation the log exists to prevent). A repeat
        of an identical vote returns True without growing the log."""
        key = (kind, view, seq)
        held = self.state.votes.get(key)
        if held is not None:
            return held == digest_hex
        self.state.votes[key] = digest_hex
        self._pending.append(_encode_vote(kind, view, seq, digest_hex))
        self.appends += 1
        return True

    def note_view(self, view: int, in_view_change: bool, pending: int) -> None:
        st = self.state
        if (st.view, st.in_view_change, st.pending_view) == (
            view, in_view_change, pending
        ):
            return
        st.view = view
        st.in_view_change = in_view_change
        st.pending_view = pending
        self._pending.append(_encode_view(view, in_view_change, pending))
        self.appends += 1

    def note_checkpoint(self, seq: int, payload: str, cert) -> None:
        """A 2f+1-certified stable checkpoint: the durable restart point.
        ``cert`` is the certificate (a list of checkpoint dicts, or its
        canonical JSON). Prunes votes at or below ``seq`` and schedules a
        compaction for the next flush."""
        cur = self.state.checkpoint
        if cur is not None and cur[0] >= seq:
            return
        cert_json = (
            cert if isinstance(cert, str)
            else json.dumps(cert, sort_keys=True, separators=(",", ":"))
        )
        self.state.checkpoint = (seq, payload, cert_json)
        for key in [k for k in self.state.votes if k[2] <= seq]:
            del self.state.votes[key]
        self._pending.append(_encode_checkpoint(seq, payload, cert_json))
        self.appends += 1
        self._compact_due = True

    def pending(self) -> int:
        return len(self._pending)

    # -- the group-commit point ----------------------------------------------

    def flush(self) -> None:
        """THE durability point (group commit): called by the runtime at
        the emit boundary, before any of this pass's votes reach a
        socket. One write + one fsync per call, however many records
        accumulated; a due compaction replaces the append entirely."""
        if not self._pending and not self._compact_due:
            return
        if self.path is None:  # simulator mode: the object IS the disk
            self._pending.clear()
            self._compact_due = False
            return
        if self._compact_due:
            self._compact()
            return
        data = b"".join(self._pending)
        self._pending.clear()
        fd = os.open(self.path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, data)
            self.bytes_written += len(data)
            if self.fsync:
                os.fsync(fd)
                self.fsyncs += 1
        finally:
            os.close(fd)

    def _compact(self) -> None:
        """Rewrite the log as header + view + checkpoint + live votes
        (tmp, fsync, rename, fsync dir) — bounded by the watermark
        window, and always cleanly terminated."""
        self._pending.clear()
        self._compact_due = False
        if self.path is None:
            return
        st = self.state
        out = [_HEADER.pack(WAL_MAGIC, WAL_VERSION)]
        out.append(_encode_view(st.view, st.in_view_change, st.pending_view))
        if st.checkpoint is not None:
            out.append(_encode_checkpoint(*st.checkpoint))
        for (kind, view, seq) in sorted(st.votes, key=lambda k: (k[1], k[2], k[0])):
            out.append(_encode_vote(kind, view, seq, st.votes[(kind, view, seq)]))
        data = b"".join(out)
        tmp = self.path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, data)
            self.bytes_written += len(data)
            if self.fsync:
                os.fsync(fd)
                self.fsyncs += 1
        finally:
            os.close(fd)
        os.replace(tmp, self.path)
        if self.fsync:
            # The rename must be durable too, or a crash resurrects the
            # pre-compaction file without the records appended since.
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
                self.fsyncs += 1
            finally:
                os.close(dfd)
