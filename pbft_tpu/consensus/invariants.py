"""Machine-checked PBFT safety/liveness invariants (ISSUE 5).

What "correct under faults" MEANS, as executable checks — the piece the
happy-path integration tests structurally cannot provide (Jepsen's lesson;
Twins for the BFT case). Two consumers:

- ``InvariantChecker`` runs against a live ``simulation.Cluster`` after every
  scheduler step (scripts/chaos_soak.py). Safety checks hold under ANY fault
  load as long as at most f replicas are faulty; the liveness check is only
  promised once partitions heal and the faulty set is back within budget.
- ``check_spans`` runs against real-cluster trace data: the per-(view, seq)
  phase-stamp slots that scripts/consensus_timeline.py builds from the PR 1
  ``consensus_span`` events (``--check-invariants``).

The safety invariants:

S1  chain-digest prefix agreement — no two honest replicas ever disagree on
    the execution-chain digest at the same sequence number. The chain digest
    is a fold of every executed (result, seq), so equality at seq s implies
    agreement on the entire prefix [1, s] — batch digests included.
S2  per-(client, timestamp) exactly-once — an honest replica never emits two
    different results for one client timestamp (cached-reply resends carry
    the identical result by construction).
S3  executed => committed-with-quorum — an honest replica only advances
    executed_upto through a sequence for which 2f+1 distinct replicas sent
    COMMIT for one digest (normal case) or a 2f+1 checkpoint certificate at
    or beyond it exists (state-transfer catch-up). Evidence is tallied from
    messages replicas SEND (the cluster's sent_observer feed), so link-level
    drops cannot mask a quorum that never existed.
S5  restart never double-votes (ISSUE 15) — a replica restarted from its
    write-ahead log never sends a pre-prepare/prepare/commit whose digest
    contradicts a vote it had PERSISTED before the crash (same kind, view,
    seq, different digest). The pre-crash vote map is snapshotted by
    ``Cluster.restart``; every post-restart send is checked against it.
    An amnesiac (fresh-state) restart is exactly what can violate this —
    which is why the checker exists.

The liveness invariant:

L1  with partitions healed and <=f faulty, every submitted request
    eventually collects f+1 matching replies from distinct replicas.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from .messages import (
    Checkpoint,
    ClientRequest,
    Commit,
    NewView,
    Prepare,
    PrePrepare,
)
from .simulation import Cluster


class InvariantViolation(AssertionError):
    """A safety invariant failed — carries the machine-readable detail."""

    def __init__(self, name: str, detail: str):
        super().__init__(f"{name}: {detail}")
        self.name = name
        self.detail = detail


class InvariantChecker:
    """Incremental safety checker over a live simulation cluster.

    ``faulty`` names the replicas currently EXEMPT from honesty checks —
    pass a callable (e.g. ``lambda: set(cluster.faults)``) so a schedule
    that flips fault modes mid-run keeps the exemption current. A replica
    that was EVER faulty stays exempt: its state may have been poisoned
    while Byzantine, and PBFT promises nothing about its local logs."""

    def __init__(
        self,
        cluster: Cluster,
        faulty: Optional[Callable[[], Set[int]]] = None,
    ):
        self.cluster = cluster
        self._faulty_now = faulty or (lambda: set(cluster.faults))
        self.ever_faulty: Set[int] = set()
        # Tentative mode (ISSUE 14): executions above the committed
        # floor may legitimately ROLL BACK on a view change, so the
        # checker keys its honesty rules to the floor — S1 compares the
        # chain digest AT committed_upto (the tentative suffix is
        # allowed to diverge transiently), executed_upto may decrease
        # back to the floor, and S3 accepts a 2f+1 PREPARED certificate
        # (pre-prepare + prepares from distinct senders) as the quorum
        # behind a tentative execution.
        self.tentative = bool(getattr(cluster.config, "tentative", False))
        # S1 evidence: rid -> {seq: chain digest hex observed there}.
        self.digest_at: Dict[int, Dict[int, str]] = {
            r.id: {} for r in cluster.replicas
        }
        self._last_executed: Dict[int, int] = {
            r.id: r.executed_upto for r in cluster.replicas
        }
        self._last_committed: Dict[int, int] = {
            r.id: r.committed_upto for r in cluster.replicas
        }
        # S3 evidence from sent messages: (view, seq, digest) -> commit
        # senders; (seq, digest) -> checkpoint senders; (view, seq,
        # digest) -> prepared-certificate senders (prepares + the
        # pre-prepare standing in for the primary's prepare).
        self.commit_senders: Dict[Tuple[int, int, str], Set[int]] = {}
        self.checkpoint_senders: Dict[Tuple[int, str], Set[int]] = {}
        self.prepare_senders: Dict[Tuple[int, int, str], Set[int]] = {}
        # S2 evidence: (rid, client, timestamp) -> result.
        self._reply_results: Dict[Tuple[int, str, int], str] = {}
        self._replies_seen = 0
        # S5 (ISSUE 15): contradictions observed on the wire are queued
        # here (observe() runs inside message delivery, where raising
        # would corrupt the transport) and raised by the next check().
        # _seen_restarts re-baselines the monotonicity tracking when a
        # replica is restarted (its executed_upto legally drops to the
        # recovered checkpoint floor).
        self._s5_pending: List[str] = []
        self._seen_restarts: Dict[int, int] = {}
        self.violations: List[InvariantViolation] = []
        _VOTE_KINDS = {PrePrepare: 1, Prepare: 2, Commit: 3}
        prev = cluster.sent_observer

        def observe(src: int, msg) -> None:
            if prev is not None:
                prev(src, msg)
            kind = _VOTE_KINDS.get(type(msg))
            if kind is not None:
                held = self.cluster.restart_votes.get(src, {}).get(
                    (kind, msg.view, msg.seq)
                ) if hasattr(self.cluster, "restart_votes") else None
                if held is not None and held != msg.digest:
                    self._s5_pending.append(
                        f"replica {src} sent {type(msg).__name__} "
                        f"(v={msg.view}, n={msg.seq}) digest "
                        f"{msg.digest[:16]}.. contradicting its persisted "
                        f"pre-crash vote {held[:16]}.."
                    )
            if isinstance(msg, Commit):
                self.commit_senders.setdefault(
                    (msg.view, msg.seq, msg.digest), set()
                ).add(src)
            elif isinstance(msg, Checkpoint):
                self.checkpoint_senders.setdefault(
                    (msg.seq, msg.digest), set()
                ).add(src)
            elif isinstance(msg, Prepare):
                self.prepare_senders.setdefault(
                    (msg.view, msg.seq, msg.digest), set()
                ).add(src)
            elif isinstance(msg, PrePrepare):
                # The primary's pre-prepare stands in for its prepare
                # (§4.2) — it completes the 2f+1 prepared certificate.
                self.prepare_senders.setdefault(
                    (msg.view, msg.seq, msg.digest), set()
                ).add(src)
            elif isinstance(msg, NewView):
                # A new primary's re-issued pre-prepares ride INSIDE the
                # NEW-VIEW broadcast (never as standalone sends): they
                # are its prepare-equivalent vote for every re-issued
                # slot — without this, every tentative execution right
                # after a view change looks one voter short.
                for ppd in msg.pre_prepares:
                    if not isinstance(ppd, dict):
                        continue
                    view = ppd.get("view")
                    seq = ppd.get("seq")
                    digest = ppd.get("digest")
                    if isinstance(view, int) and isinstance(seq, int) and (
                        isinstance(digest, str)
                    ):
                        self.prepare_senders.setdefault(
                            (view, seq, digest), set()
                        ).add(src)

        cluster.sent_observer = observe

    # -- helpers -------------------------------------------------------------

    def honest(self) -> Set[int]:
        self.ever_faulty |= self._faulty_now()
        return {
            r.id for r in self.cluster.replicas if r.id not in self.ever_faulty
        }

    def _quorum(self) -> int:
        return 2 * self.cluster.config.f + 1

    def _fail(self, name: str, detail: str) -> None:
        v = InvariantViolation(name, detail)
        self.violations.append(v)
        raise v

    # -- the per-step safety pass -------------------------------------------

    def check(self) -> None:
        """Run S1-S3 (+ S5 under crash-restart schedules) against current
        cluster state; raises InvariantViolation on the first failure."""
        honest = self.honest()
        quorum = self._quorum()
        # S5 first: a wire-observed double vote is the gravest finding.
        if self._s5_pending:
            self._fail("restart-vote-contradiction", self._s5_pending[0])
        # A restart legally drops executed_upto to the recovered
        # checkpoint floor: re-baseline the monotonicity tracking for
        # restarted replicas (ISSUE 15). Pre-crash S1 digest evidence
        # stays — those executions happened and re-execution of the same
        # sequences must reproduce the same digests.
        for rid, epoch in getattr(
            self.cluster, "restart_epochs", {}
        ).items():
            if self._seen_restarts.get(rid) != epoch:
                self._seen_restarts[rid] = epoch
                r = self.cluster.replicas[rid]
                self._last_executed[rid] = r.executed_upto
                self._last_committed[rid] = r.committed_upto
        for r in self.cluster.replicas:
            rid = r.id
            prev = self._last_executed[rid]
            cur = r.executed_upto
            if cur < prev:
                # Tentative mode: a rollback to (at or above) the
                # committed floor is the §5.3 view-change contract, not
                # a violation — the rolled-back suffix's S1 evidence
                # dies with it.
                if self.tentative and cur >= r.committed_upto:
                    self._last_executed[rid] = cur
                    da = self.digest_at[rid]
                    for seq in [s for s in da if s > cur]:
                        del da[seq]
                    continue
                if rid in honest:
                    self._fail(
                        "executed-monotonic",
                        f"replica {rid} executed_upto went {prev} -> {cur}",
                    )
                self._last_executed[rid] = cur
                continue
            if cur == prev:
                self._observe_committed(r)
                continue
            self._last_executed[rid] = cur
            # S1 evidence: the chain digest observed at executed_upto=cur.
            # In tentative mode the executed suffix may roll back, so the
            # cross-replica comparison keys on the COMMITTED chain
            # instead (see _observe_committed); the executed-point digest
            # is still recorded for the committed-catches-up case below.
            if not self.tentative:
                self.digest_at[rid][cur] = r.state_digest.hex()
            self._observe_committed(r)
            if rid not in honest:
                continue
            # S3: each newly executed sequence must be quorum-justified.
            for seq in range(prev + 1, cur + 1):
                if self._committed_with_quorum(r, seq, quorum):
                    continue
                if self.tentative and self._prepared_with_quorum(seq, quorum):
                    continue  # tentative execution: prepared certificate
                self._fail(
                    "executed-without-quorum",
                    f"replica {rid} executed seq {seq} with no 2f+1 commit"
                    f"/checkpoint{'/prepared' if self.tentative else ''} "
                    f"evidence",
                )
        # S1: prefix agreement across every honest pair with a common seq.
        self._check_agreement(honest)
        # S2: exactly-once on the reply stream (incremental scan).
        self._check_replies(honest)

    def _observe_committed(self, r) -> None:
        """Tentative mode's S1 feed: record the chain digest AT the
        committed floor whenever it advances — the part of the chain
        that can never roll back is what honest replicas must agree on."""
        if not self.tentative:
            return
        cur = r.committed_upto
        if cur > self._last_committed[r.id] and cur > 0:
            self._last_committed[r.id] = cur
            self.digest_at[r.id][cur] = r.committed_chain.hex()

    def _committed_with_quorum(self, replica, seq: int, quorum: int) -> bool:
        # Normal case: 2f+1 distinct commit senders on one digest at seq.
        for (view, s, digest), senders in self.commit_senders.items():
            if s == seq and len(senders) >= quorum:
                return True
        # State-transfer case: a certified checkpoint at or beyond seq.
        for (s, digest), senders in self.checkpoint_senders.items():
            if s >= seq and len(senders) >= quorum:
                return True
        return False

    def _prepared_with_quorum(self, seq: int, quorum: int) -> bool:
        """Tentative-execution justification: 2f+1 distinct senders of a
        prepared certificate (prepares + the primary's pre-prepare) on
        one digest at seq."""
        for (view, s, digest), senders in self.prepare_senders.items():
            if s == seq and len(senders) >= quorum:
                return True
        return False

    def _check_agreement(self, honest: Set[int]) -> None:
        ids = sorted(honest)
        for i, a in enumerate(ids):
            for b in ids[i + 1 :]:
                da, db = self.digest_at[a], self.digest_at[b]
                for seq in da.keys() & db.keys():
                    if da[seq] != db[seq]:
                        self._fail(
                            "chain-digest-divergence",
                            f"replicas {a} and {b} disagree at seq {seq}: "
                            f"{da[seq][:16]}.. != {db[seq][:16]}..",
                        )

    def _check_replies(self, honest: Set[int]) -> None:
        replies = self.cluster.client_replies
        for rep in replies[self._replies_seen :]:
            if self.tentative and getattr(rep, "tentative", 0):
                # A tentative reply may be superseded by a different
                # result after a rollback (the client's 2f+1 rule is
                # what makes ACCEPTED results durable) — exactly-once is
                # enforced on the committed reply stream.
                continue
            key = (rep.replica, rep.client, rep.timestamp)
            prev = self._reply_results.get(key)
            if prev is None:
                self._reply_results[key] = rep.result
            elif prev != rep.result and rep.replica in honest:
                self._fail(
                    "exactly-once",
                    f"replica {rep.replica} replied both {prev!r} and "
                    f"{rep.result!r} for ({rep.client}, t={rep.timestamp})",
                )
        self._replies_seen = len(replies)

    # -- liveness ------------------------------------------------------------

    def unreplied(
        self, submitted: Iterable[ClientRequest], f: Optional[int] = None
    ) -> List[ClientRequest]:
        """L1 probe: the submitted requests still lacking their reply
        quorum from distinct replicas — f+1 matching COMMITTED replies,
        or (tentative mode, ISSUE 14) 2f+1 matching replies overall.
        Empty list == liveness satisfied."""
        f = self.cluster.config.f if f is None else f
        votes: Dict[Tuple[str, int], Dict[str, Set[int]]] = {}
        committed_votes: Dict[Tuple[str, int], Dict[str, Set[int]]] = {}
        for rep in self.cluster.client_replies:
            votes.setdefault((rep.client, rep.timestamp), {}).setdefault(
                rep.result, set()
            ).add(rep.replica)
            if not getattr(rep, "tentative", 0):
                committed_votes.setdefault(
                    (rep.client, rep.timestamp), {}
                ).setdefault(rep.result, set()).add(rep.replica)
        missing = []
        for req in submitted:
            key = (req.client, req.timestamp)
            done = any(
                len(s) >= f + 1
                for s in committed_votes.get(key, {}).values()
            ) or any(
                len(s) >= 2 * f + 1 for s in votes.get(key, {}).values()
            )
            if not done:
                missing.append(req)
        return missing


# -- trace-data invariants (real clusters, PR 1 span events) -----------------

_PHASE_ORDER = ("request", "pre_prepare", "prepared", "committed", "executed")


def check_spans(slots: Dict) -> List[str]:
    """Invariant scan over consensus_span timeline slots
    ({(view, seq) -> {rid -> {phase -> ts}}}, the structure
    scripts/consensus_timeline.py builds). Trace data carries no digests,
    so this checks the observable protocol-order invariants:

    - phase monotonicity: within one (view, seq, replica), stamps respect
      request <= pre_prepare <= prepared <= committed <= executed;
    - executed-order: a replica's executed stamps are non-decreasing in
      sequence (in-order execution — PBFT's determinism requirement);
    - single-execution: no replica executes one sequence in two views.

    Returns a list of human-readable problem strings (empty = clean)."""
    problems: List[str] = []
    by_replica: Dict[int, List[Tuple[int, int, float]]] = {}
    seq_views: Dict[Tuple[int, int], Set[int]] = {}
    for (view, seq), per in slots.items():
        for rid, stamps in per.items():
            chain = [(p, stamps[p]) for p in _PHASE_ORDER if p in stamps]
            for (pa, ta), (pb, tb) in zip(chain, chain[1:]):
                if tb < ta:
                    problems.append(
                        f"replica {rid} (v={view}, n={seq}): {pb} stamp "
                        f"precedes {pa} ({tb:.6f} < {ta:.6f})"
                    )
            if "executed" in stamps and not stamps.get("estimated"):
                by_replica.setdefault(rid, []).append(
                    (seq, view, stamps["executed"])
                )
                seq_views.setdefault((rid, seq), set()).add(view)
    for (rid, seq), views in seq_views.items():
        if len(views) > 1:
            problems.append(
                f"replica {rid} executed seq {seq} in multiple views "
                f"{sorted(views)}"
            )
    for rid, rows in by_replica.items():
        rows.sort()
        for (s0, v0, t0), (s1, v1, t1) in zip(rows, rows[1:]):
            if t1 < t0:
                problems.append(
                    f"replica {rid}: seq {s1} executed at {t1:.6f}, before "
                    f"seq {s0} at {t0:.6f} (out-of-order execution)"
                )
    return problems


def check_view_events(events) -> List[str]:
    """Protocol-order invariants over the view-change span events
    (view_timer_fired / view_change_sent / new_view_installed, ISSUE 9 —
    the per-replica ordering consensus_timeline.py --check-invariants
    enforces on real-cluster traces):

    - a replica's first view_timer_fired precedes its first
      new_view_installed (the span cannot close before it opened);
    - view_change_sent toward view v precedes new_view_installed of v on
      the same replica (sending is part of joining, when both exist —
      a pure follower may install without ever sending);
    - a replica's view_change_sent pending_view values are non-decreasing
      over time (the floor rule: a replica never campaigns backwards).

    ``events`` are trace-event dicts; returns problem strings (empty =
    clean)."""
    problems: List[str] = []
    per: Dict[int, Dict[str, list]] = {}
    for e in events:
        ev = e.get("ev")
        rid = e.get("replica")
        ts = e.get("ts")
        if not isinstance(rid, int) or not isinstance(ts, (int, float)):
            continue
        if ev == "view_timer_fired":
            per.setdefault(rid, {}).setdefault("fired", []).append(ts)
        elif ev == "view_change_sent":
            per.setdefault(rid, {}).setdefault("sent", []).append(
                (ts, e.get("pending_view"))
            )
        elif ev == "new_view_installed":
            per.setdefault(rid, {}).setdefault("installed", []).append(
                (ts, e.get("view"))
            )
    for rid, evs in per.items():
        fired = sorted(evs.get("fired", []))
        sent = sorted(evs.get("sent", []))
        installed = sorted(evs.get("installed", []))
        if fired and installed and installed[0][0] < fired[0]:
            problems.append(
                f"replica {rid}: new_view_installed at {installed[0][0]:.6f} "
                f"precedes the first view_timer_fired at {fired[0]:.6f}"
            )
        first_sent: Dict[int, float] = {}
        for ts, v in sent:
            if isinstance(v, int) and v not in first_sent:
                first_sent[v] = ts
        for ts, v in installed:
            if isinstance(v, int) and v in first_sent and ts < first_sent[v]:
                problems.append(
                    f"replica {rid}: installed view {v} at {ts:.6f} before "
                    f"sending its view-change at {first_sent[v]:.6f}"
                )
        views = [v for _, v in sent if isinstance(v, int)]
        for a, b in zip(views, views[1:]):
            if b < a:
                problems.append(
                    f"replica {rid}: view_change_sent pending_view went "
                    f"backwards ({a} -> {b})"
                )
    return problems
