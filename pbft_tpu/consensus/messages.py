"""PBFT message types, canonical encoding, digests, and signatures.

Capability parity with the reference's message layer (reference
src/message.rs): ClientRequest / PrePrepare / Prepare / Commit / ClientReply
with a content digest over the client request — plus what the reference left
as TODOs: real signatures on every replica-to-replica message (reference
src/behavior.rs:127,:185) and a Checkpoint message for watermark advancement
(reference src/behavior.rs:154,:192).

Encoding decisions (TPU-first redesign, not a port):
- Canonical bytes = JSON with sorted keys and fixed separators; the digest is
  Blake2b-256 of those bytes (the reference also used Blake2b,
  src/message.rs:3,:209-212).
- Replicas sign the 32-byte Blake2b digest of a message's signable content.
  Fixing the signed payload at 32 bytes makes the Ed25519 challenge hash
  SHA-512(R||A||M) exactly one block — every shape in the TPU batch verifier
  is static (see pbft_tpu.crypto.sha512).
- Wire frame = 4-byte big-endian length + JSON (the reference used
  varint-framed JSON, src/protocol_config.rs:51,:82; a fixed-width prefix is
  friendlier to the C++ runtime and to batch scanning).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, ClassVar, Dict, Optional, Type


def blake2b_256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def _canonical_json(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


class Message:
    """Base: canonical bytes, digest, signable digest, wire (de)serialization."""

    TYPE: ClassVar[str] = ""
    _REGISTRY: ClassVar[Dict[str, Type["Message"]]] = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.TYPE:
            Message._REGISTRY[cls.TYPE] = cls

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["type"] = self.TYPE
        return d

    def canonical(self) -> bytes:
        return _canonical_json(self.to_dict())

    def signable(self) -> bytes:
        """32-byte digest of the content excluding the signature field.

        Hot message types render through a fixed template (byte-identical
        to the generic sorted-keys dump; tests/test_wire_codec.py pins the
        parity) — the generic path pays dataclasses.asdict per call."""
        fast = _signable_bytes_fast(self)
        if fast is not None:
            return blake2b_256(fast)
        d = self.to_dict()
        d.pop("sig", None)
        return blake2b_256(_canonical_json(d))

    @classmethod
    def from_dict(cls, d: dict) -> "Message":
        d = dict(d)
        typ = d.pop("type")
        target = Message._REGISTRY[typ]

        def _req(rd) -> "ClientRequest":
            rd = dict(rd)
            rd.pop("type", None)
            return ClientRequest(**rd)

        if target is PrePrepare:
            # Legacy singular `request` (batch of one) and the batched
            # `requests` list both decode to the requests tuple. A
            # one-element `requests` list is REJECTED, exactly like the
            # C++ parser: each batch has one canonical encoding, and
            # admitting the other form here while the native runtime
            # drops it would be a cross-runtime consensus divergence.
            if "request" in d and isinstance(d["request"], dict):
                d["requests"] = (_req(d.pop("request")),)
            elif isinstance(d.get("requests"), (list, tuple)):
                if len(d["requests"]) == 1:
                    raise ValueError(
                        "one-element `requests` must encode as `request`"
                    )
                d["requests"] = tuple(
                    _req(r) if isinstance(r, dict) else r
                    for r in d["requests"]
                )
        elif "request" in d and isinstance(d["request"], dict):
            d["request"] = _req(d["request"])
        return target(**d)


def to_wire(msg: Message) -> bytes:
    payload = msg.canonical()
    return len(payload).to_bytes(4, "big") + payload


_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def _check_int64(obj) -> None:
    """Reject integers outside int64: the C++ runtime parses int64 and
    *drops* out-of-range messages, so Python must reject the same set or
    the two implementations would digest different canonical bytes for
    the same wire message (a consensus divergence)."""
    if isinstance(obj, bool):
        return
    if isinstance(obj, int):
        if not (_INT64_MIN <= obj <= _INT64_MAX):
            raise ValueError(f"integer out of int64 range: {obj}")
    elif isinstance(obj, dict):
        for v in obj.values():
            _check_int64(v)
    elif isinstance(obj, list):
        for v in obj:
            _check_int64(v)


def from_wire(frame: bytes) -> Message:
    d = json.loads(frame.decode())
    _check_int64(d)
    return Message.from_dict(d)


@dataclasses.dataclass(frozen=True)
class ClientRequest(Message):
    """o=operation, t=timestamp, c=client dial-back address "host:port"
    (reference src/message.rs:34-38)."""

    TYPE: ClassVar[str] = "client-request"
    operation: str
    timestamp: int
    client: str

    def digest(self) -> str:
        return blake2b_256(self.canonical()).hex()


# The tentative-reply flag's JSON member name (ISSUE 14; mirrors
# core/messages.h kTentativeField, constants lint). Omitted when zero so
# committed replies stay byte-identical to pre-1.3.0 peers.
TENTATIVE_FIELD = "tentative"


@dataclasses.dataclass(frozen=True)
class ClientReply(Message):
    """Reply dialed back to the client (reference src/message.rs:55-72),
    signed by the replying replica: PBFT §4.1's f+1 reply quorum only
    means something if a vote proves which replica cast it — unsigned
    replies let one faulty party mint arbitrary votes on the dial-back
    channel.

    ``tentative`` (ISSUE 14): 1 when the replica executed the request at
    *prepared* (before commit, Castro–Liskov §5.3 tentative execution) —
    the client needs 2f+1 matching tentative votes instead of f+1
    committed ones. Part of the SIGNED content (a forgeable flag would
    let a man-in-the-middle upgrade tentative votes to committed ones);
    omitted from the canonical encoding when 0, so committed replies are
    byte-identical to pre-1.3.0 replies."""

    TYPE: ClassVar[str] = "client-reply"
    view: int
    timestamp: int
    client: str
    replica: int
    result: str
    sig: str = ""
    tentative: int = 0

    def to_dict(self) -> dict:
        d = super().to_dict()
        if not d.get(TENTATIVE_FIELD):
            d.pop(TENTATIVE_FIELD, None)
        return d


def batch_digest(requests) -> str:
    """The pre-prepare content digest over an ordered request batch.

    A batch of exactly one request keeps the legacy definition — the
    digest of that request's canonical bytes — so batch=1 pre-prepares
    are byte-identical (wire AND signable) to pre-batching peers. Any
    other size (including the empty batch, the new-view gap filler)
    digests the CONCATENATED per-request digests with Blake2b-256:
    order-sensitive, and collision-free down to the per-request digests."""
    if len(requests) == 1:
        return requests[0].digest()
    return blake2b_256(
        b"".join(bytes.fromhex(r.digest()) for r in requests)
    ).hex()


@dataclasses.dataclass(frozen=True)
class PrePrepare(Message):
    """<<PRE-PREPARE, v, n, d>, M> signed by the primary
    (reference src/message.rs:106-137), where M is an ordered request
    BATCH agreed under one sequence number (Castro & Liskov's batching
    amplifier). ``digest`` is batch_digest(requests). A batch of one
    encodes with the legacy singular ``request`` member (canonical JSON
    and binary alike) for wire compatibility with pre-batching peers;
    any other size uses the ``requests`` list / the 0x06 binary layout."""

    TYPE: ClassVar[str] = "pre-prepare"
    view: int
    seq: int
    digest: str
    requests: tuple  # tuple[ClientRequest, ...]
    replica: int
    sig: str = ""

    def __post_init__(self):
        object.__setattr__(self, "requests", tuple(self.requests))

    def to_dict(self) -> dict:
        d = {
            "view": self.view,
            "seq": self.seq,
            "digest": self.digest,
            "replica": self.replica,
            "sig": self.sig,
            "type": self.TYPE,
        }
        reqs = [dataclasses.asdict(r) for r in self.requests]
        if len(reqs) == 1:
            d["request"] = reqs[0]
        else:
            d["requests"] = reqs
        return d

    def batch_digest(self) -> str:
        return batch_digest(self.requests)


@dataclasses.dataclass(frozen=True)
class Prepare(Message):
    """<PREPARE, v, n, d, i> (reference src/message.rs:175-188)."""

    TYPE: ClassVar[str] = "prepare"
    view: int
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class Commit(Message):
    """<COMMIT, v, n, d, i> (reference src/message.rs:214-239; the rebuild
    keys its log by (v, n), fixing the reference's view-only CommitKey,
    src/state.rs:23)."""

    TYPE: ClassVar[str] = "commit"
    view: int
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class ViewChange(Message):
    """<VIEW-CHANGE, v+1, n, C, P, i> (PBFT §4.4 — absent from the
    reference, whose View was a constant with no mutation API, reference
    src/view.rs:1-13).

    - ``last_stable_seq``/``checkpoint_proof``: n and C — 2f+1 checkpoint
      message dicts proving the replica's last stable checkpoint.
    - ``prepared_proofs``: P — one entry per sequence prepared above n:
      {"pre_prepare": <dict>, "prepares": [<dict>, ...]} with 2f matching
      backup prepares each. Stored as raw dicts: they are *evidence*
      (re-validated structurally + cryptographically by the receiver),
      not live protocol messages."""

    TYPE: ClassVar[str] = "view-change"
    new_view: int
    last_stable_seq: int
    checkpoint_proof: tuple
    prepared_proofs: tuple
    replica: int
    sig: str = ""

    def __post_init__(self):
        # JSON round-trips tuples as lists; normalize for equality.
        object.__setattr__(self, "checkpoint_proof", tuple(self.checkpoint_proof))
        object.__setattr__(self, "prepared_proofs", tuple(self.prepared_proofs))

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["checkpoint_proof"] = list(self.checkpoint_proof)
        d["prepared_proofs"] = list(self.prepared_proofs)
        return d


@dataclasses.dataclass(frozen=True)
class NewView(Message):
    """<NEW-VIEW, v+1, V, O> (PBFT §4.4): V = 2f+1 view-change message
    dicts, O = the new primary's re-issued pre-prepare dicts for every
    in-flight sequence (null requests fill gaps)."""

    TYPE: ClassVar[str] = "new-view"
    new_view: int
    view_changes: tuple
    pre_prepares: tuple
    replica: int
    sig: str = ""

    def __post_init__(self):
        object.__setattr__(self, "view_changes", tuple(self.view_changes))
        object.__setattr__(self, "pre_prepares", tuple(self.pre_prepares))

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["view_changes"] = list(self.view_changes)
        d["pre_prepares"] = list(self.pre_prepares)
        return d


NULL_CLIENT = "<null>"


def null_request() -> "ClientRequest":
    """Filler for sequence gaps in a new view (PBFT §4.4: 'a special null
    request which goes through the protocol like other requests but whose
    execution is a no-op')."""
    return ClientRequest(operation="<null>", timestamp=0, client=NULL_CLIENT)


@dataclasses.dataclass(frozen=True)
class Checkpoint(Message):
    """<CHECKPOINT, n, d, i>: state digest at sequence n; 2f+1 matching
    checkpoints advance the low watermark (PBFT §4.3; a reference TODO,
    src/behavior.rs:154)."""

    TYPE: ClassVar[str] = "checkpoint"
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class StateRequest(Message):
    """<STATE-REQUEST, n, i>: a replica whose watermark jumped past its
    execution asks peers for the checkpoint payload at stable sequence n
    (PBFT §5.3 state-transfer analogue; the reference TODO'd even the
    watermark checks, src/behavior.rs:154,:192)."""

    TYPE: ClassVar[str] = "state-request"
    seq: int
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class StateResponse(Message):
    """<STATE-RESPONSE, n, payload, i>: the canonical checkpoint payload at
    sequence n (app snapshot + chain digest + per-client reply caches,
    see Replica._checkpoint_payload). The receiver trusts it only if its
    Blake2b-256 digest equals the 2f+1-certified stable checkpoint digest —
    the sender's signature gates transport, the digest gates content."""

    TYPE: ClassVar[str] = "state-response"
    seq: int
    snapshot: str
    replica: int
    sig: str = ""


def with_sig(msg: Message, sig_hex: str) -> Message:
    return dataclasses.replace(msg, sig=sig_hex)


# -- fast signable templates -------------------------------------------------
#
# The generic signable path costs a recursive dataclasses.asdict plus a
# sorted-keys dumps per message; the hot types have a fixed key order, so
# their canonical signable bytes render directly. Strings go through
# json.dumps for the exact escaping; int fields are guarded with
# `type(x) is int` because a stray bool would render "True" where the
# generic path emits "true" — mismatched types fall back to the generic
# derivation instead of diverging.

_dumps = json.dumps


def _signable_bytes_fast(msg: "Message") -> Optional[bytes]:
    t = msg.__class__
    if t is Prepare or t is Commit:
        v, s, d, r = msg.view, msg.seq, msg.digest, msg.replica
        if (
            type(v) is int and type(s) is int and type(r) is int
            and type(d) is str
        ):
            return (
                f'{{"digest":{_dumps(d)},"replica":{r},"seq":{s},'
                f'"type":"{t.TYPE}","view":{v}}}'
            ).encode()
        return None
    if t is Checkpoint:
        s, d, r = msg.seq, msg.digest, msg.replica
        if type(s) is int and type(r) is int and type(d) is str:
            return (
                f'{{"digest":{_dumps(d)},"replica":{r},"seq":{s},'
                f'"type":"checkpoint"}}'
            ).encode()
        return None
    if t is PrePrepare:
        reqs = msg.requests
        if not (
            type(msg.view) is int and type(msg.seq) is int
            and type(msg.replica) is int and type(msg.digest) is str
            and type(reqs) is tuple
            and all(
                type(r) is ClientRequest and type(r.timestamp) is int
                and type(r.operation) is str and type(r.client) is str
                for r in reqs
            )
        ):
            return None
        def _req_body(r):
            return (
                f'{{"client":{_dumps(r.client)},'
                f'"operation":{_dumps(r.operation)},'
                f'"timestamp":{r.timestamp}}}'
            )
        if len(reqs) == 1:
            member = f'"request":{_req_body(reqs[0])}'
        else:
            member = '"requests":[' + ",".join(_req_body(r) for r in reqs) + "]"
        return (
            f'{{"digest":{_dumps(msg.digest)},"replica":{msg.replica},'
            f'{member},"seq":{msg.seq},'
            f'"type":"pre-prepare","view":{msg.view}}}'
        ).encode()
    if t is ClientRequest:
        if (
            type(msg.timestamp) is int and type(msg.operation) is str
            and type(msg.client) is str
        ):
            return (
                f'{{"client":{_dumps(msg.client)},'
                f'"operation":{_dumps(msg.operation)},'
                f'"timestamp":{msg.timestamp},"type":"client-request"}}'
            ).encode()
        return None
    return None


# -- receive-side canonical reuse --------------------------------------------

# Types whose "sig" member is uniquely top-level in the canonical JSON —
# view-change/new-view evidence nests signed dicts, so those always take
# the generic derivation (they are rare by construction).
_SPLICE_TYPES = None  # filled below, after the dataclasses exist


def signable_from_payload(payload: bytes, msg: Message) -> bytes:
    """Signable digest straight from a received framed payload.

    For canonical JSON payloads of the hot types, splice out the
    top-level ``"sig"`` member and hash the remaining bytes instead of
    re-serializing the parsed message. Quotes inside JSON string values
    are always escaped, so the first raw ``,"sig":"`` is the real key;
    any ambiguity (duplicate keys, non-canonical input) yields a digest
    matching no honest signable — the signature check fails closed.
    Everything else (binary payloads, nested-sig types) falls back to
    ``msg.signable()``. tests/test_wire_codec.py pins that the two
    derivations agree for every message type."""
    if payload[:1] == b"{" and type(msg) in _SPLICE_TYPES:
        i = payload.find(b',"sig":"')
        if i >= 0:
            j = payload.find(b'"', i + 8)
            if j >= 0:
                return blake2b_256(payload[:i] + payload[j + 1 :])
    return msg.signable()


# -- binary hot-message codec v2 ---------------------------------------------
#
# Negotiated per link via the version-carrying hello (net/secure.py);
# byte-identical to core/messages.cc message_to_binary/from_binary
# (pinned by the cross-runtime fuzz in tests/test_wire_codec.py).
#
#   payload := 0xB2 | type:u8 | fields
#   i64    -> 8 bytes big-endian (two's complement)
#   str    -> u32 big-endian length + UTF-8 bytes
#   digest -> 32 raw bytes (64 hex chars in the JSON codec)
#   sig    -> 64 raw bytes (128 hex chars in the JSON codec)
#
# Signatures still cover the canonical-JSON signable digest, so a signed
# message re-encodes for mixed-codec fan-out without re-signing.

WIRE_BINARY_MAGIC = 0xB2
CODEC_BINARY2 = "bin2"

_BIN_CLIENT_REQUEST = 0x01
_BIN_PRE_PREPARE = 0x02
_BIN_PREPARE = 0x03
_BIN_COMMIT = 0x04
_BIN_CHECKPOINT = 0x05
# Batched pre-prepare (ISSUE 4): same header as 0x02 but the request
# payload is a u32 count followed by that many {operation, timestamp,
# client} groups. Batches of exactly one keep emitting 0x02, so a
# batch=1 cluster's frames are byte-identical to pre-batching peers.
_BIN_PRE_PREPARE_BATCH = 0x06
_BIN_MAX_BATCH = 1 << 16

# MAC-vector authenticated frame variants (ISSUE 14, protocol 1.3.0;
# byte-identical to core/messages.cc — constants lint pins the codes):
#
#   0xB2 | mac_code | <base fields, sig included> |
#       count x (rid:u8 | tag:16B) | count:u8
#
# The base fields are EXACTLY the signature variant's (the Ed25519
# signature rides along — it is the evidence view changes re-verify
# inline; what MAC mode removes is every hot-path signature
# VERIFICATION). The lane vector holds one 16-byte keyed-BLAKE2b tag per
# intended receiver, each under that (sender, receiver) link's session
# key, so ONE encoded payload fans out to every peer (serialize-once)
# and each receiver checks only its own lane. The count byte sits LAST
# so a receiver finds its lane in O(count) from the frame tail without
# re-parsing the variable-length field region.
_BIN_PRE_PREPARE_MAC = 0x12
_BIN_PREPARE_MAC = 0x13
_BIN_COMMIT_MAC = 0x14
_BIN_CHECKPOINT_MAC = 0x15
_BIN_PRE_PREPARE_BATCH_MAC = 0x16
_MAC_VECTOR_MAX = 64

# mac code <-> the base (signature-variant) code it wraps.
_MAC_TO_BASE = {
    _BIN_PRE_PREPARE_MAC: _BIN_PRE_PREPARE,
    _BIN_PREPARE_MAC: _BIN_PREPARE,
    _BIN_COMMIT_MAC: _BIN_COMMIT,
    _BIN_CHECKPOINT_MAC: _BIN_CHECKPOINT,
    _BIN_PRE_PREPARE_BATCH_MAC: _BIN_PRE_PREPARE_BATCH,
}
_BASE_TO_MAC = {base: mac for mac, base in _MAC_TO_BASE.items()}


def _i64(v: int) -> bytes:
    return v.to_bytes(8, "big", signed=True)


def _b_str(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(4, "big") + b


def _b_hex(h: str, n: int) -> Optional[bytes]:
    if type(h) is not str or len(h) != 2 * n:
        return None
    try:
        return bytes.fromhex(h)
    except ValueError:
        return None


def to_binary(msg: Message) -> Optional[bytes]:
    """Binary-v2 encoding of the hot normal-case types; None for any
    other type or a digest/sig field that is not fixed-width hex — the
    caller falls back to the JSON codec."""
    t = msg.__class__
    try:
        if t is ClientRequest:
            return (
                bytes((WIRE_BINARY_MAGIC, _BIN_CLIENT_REQUEST))
                + _b_str(msg.operation) + _i64(msg.timestamp)
                + _b_str(msg.client)
            )
        if t is PrePrepare:
            digest = _b_hex(msg.digest, 32)
            sig = _b_hex(msg.sig, 64)
            if digest is None or sig is None:
                return None
            head = (
                _i64(msg.view) + _i64(msg.seq) + digest
                + _i64(msg.replica) + sig
            )
            if len(msg.requests) == 1:
                req = msg.requests[0]
                return (
                    bytes((WIRE_BINARY_MAGIC, _BIN_PRE_PREPARE)) + head
                    + _b_str(req.operation) + _i64(req.timestamp)
                    + _b_str(req.client)
                )
            if len(msg.requests) > _BIN_MAX_BATCH:
                return None
            body = len(msg.requests).to_bytes(4, "big") + b"".join(
                _b_str(r.operation) + _i64(r.timestamp) + _b_str(r.client)
                for r in msg.requests
            )
            return (
                bytes((WIRE_BINARY_MAGIC, _BIN_PRE_PREPARE_BATCH))
                + head + body
            )
        if t is Prepare or t is Commit:
            digest = _b_hex(msg.digest, 32)
            sig = _b_hex(msg.sig, 64)
            if digest is None or sig is None:
                return None
            code = _BIN_PREPARE if t is Prepare else _BIN_COMMIT
            return (
                bytes((WIRE_BINARY_MAGIC, code))
                + _i64(msg.view) + _i64(msg.seq) + digest
                + _i64(msg.replica) + sig
            )
        if t is Checkpoint:
            digest = _b_hex(msg.digest, 32)
            sig = _b_hex(msg.sig, 64)
            if digest is None or sig is None:
                return None
            return (
                bytes((WIRE_BINARY_MAGIC, _BIN_CHECKPOINT))
                + _i64(msg.seq) + digest + _i64(msg.replica) + sig
            )
    except (OverflowError, AttributeError, UnicodeEncodeError):
        return None
    return None


def to_binary_mac(msg: Message, lanes) -> Optional[bytes]:
    """MAC-vector frame for a hot message: the signature-variant fields
    plus one (receiver id, 16-byte tag) lane per entry in ``lanes``
    (an iterable of ``(rid, tag16)``; the caller computes tags with
    net.secure.mac_tag over the message's signable digest). None when
    the message has no binary form, lanes are empty/over the bound, or
    a lane is malformed — the caller falls back to the signature frame."""
    base = to_binary(msg)
    if base is None:
        return None
    mac_code = _BASE_TO_MAC.get(base[1])
    if mac_code is None:
        return None
    entries = list(lanes)
    if not entries or len(entries) > _MAC_VECTOR_MAX:
        return None
    vec = bytearray()
    for rid, tag in entries:
        if not (isinstance(rid, int) and 0 <= rid <= 0xFF):
            return None
        if not isinstance(tag, (bytes, bytearray)) or len(tag) != 16:
            return None
        vec.append(rid)
        vec += tag
    return (
        bytes((WIRE_BINARY_MAGIC, mac_code))
        + base[2:]
        + bytes(vec)
        + len(entries).to_bytes(1, "big")
    )


def payload_is_mac_frame(payload: bytes) -> bool:
    return (
        len(payload) >= 2
        and payload[0] == WIRE_BINARY_MAGIC
        and payload[1] in _MAC_TO_BASE
    )


def mac_frame_lane(payload: bytes, rid: int) -> Optional[bytes]:
    """This receiver's 16-byte authenticator tag from a MAC frame's lane
    vector, or None (not a MAC frame, malformed vector, or no lane for
    ``rid`` — e.g. a link that joined mid-fan-out; the caller then falls
    back to the signature path, which the embedded sig still serves)."""
    if not payload_is_mac_frame(payload):
        return None
    count = payload[-1]
    if not (1 <= count <= _MAC_VECTOR_MAX):
        return None
    start = len(payload) - 1 - 17 * count
    if start < 2:
        return None
    for k in range(count):
        off = start + 17 * k
        if payload[off] == rid:
            return payload[off + 1 : off + 17]
    return None


class _BinReader:
    __slots__ = ("b", "off")

    def __init__(self, b: bytes, off: int):
        self.b = b
        self.off = off

    def _take(self, n: int) -> bytes:
        end = self.off + n
        if end > len(self.b):
            raise ValueError("truncated binary frame")
        out = self.b[self.off : end]
        self.off = end
        return out

    def i64(self) -> int:
        return int.from_bytes(self._take(8), "big", signed=True)

    def str_(self) -> str:
        n = int.from_bytes(self._take(4), "big")
        if n > (1 << 24):
            raise ValueError("oversized string in binary frame")
        return self._take(n).decode()

    def hex_(self, n: int) -> str:
        return self._take(n).hex()


def from_binary(payload: bytes) -> Message:
    """Decode a binary-v2 payload; raises ValueError on any malformation
    (short reads, trailing bytes, unknown type, invalid UTF-8). MAC
    frame variants decode to the same Message as their signature twins —
    the lane vector is validated structurally here and verified
    cryptographically by the net layer (which holds the link keys)."""
    if len(payload) < 2 or payload[0] != WIRE_BINARY_MAGIC:
        raise ValueError("not a binary-v2 payload")
    code = payload[1]
    if code in _MAC_TO_BASE:
        count = payload[-1]
        if not (1 <= count <= _MAC_VECTOR_MAX):
            raise ValueError("bad MAC-vector count")
        end = len(payload) - 1 - 17 * count
        if end < 2:
            raise ValueError("truncated MAC-vector frame")
        payload = bytes((WIRE_BINARY_MAGIC, _MAC_TO_BASE[code])) + payload[2:end]
        code = payload[1]
    r = _BinReader(payload, 2)
    if code == _BIN_CLIENT_REQUEST:
        msg: Message = ClientRequest(
            operation=r.str_(), timestamp=r.i64(), client=r.str_()
        )
    elif code in (_BIN_PRE_PREPARE, _BIN_PRE_PREPARE_BATCH):
        view, seq = r.i64(), r.i64()
        digest = r.hex_(32)
        replica = r.i64()
        sig = r.hex_(64)
        if code == _BIN_PRE_PREPARE:
            reqs = (
                ClientRequest(
                    operation=r.str_(), timestamp=r.i64(), client=r.str_()
                ),
            )
        else:
            count = int.from_bytes(r._take(4), "big")
            if count > _BIN_MAX_BATCH or count == 1:
                # count==1 must encode as 0x02 (one canonical form per
                # message, or signable digests would fork).
                raise ValueError("invalid batched pre-prepare count")
            reqs = tuple(
                ClientRequest(
                    operation=r.str_(), timestamp=r.i64(), client=r.str_()
                )
                for _ in range(count)
            )
        msg = PrePrepare(
            view=view, seq=seq, digest=digest, requests=reqs,
            replica=replica, sig=sig,
        )
    elif code in (_BIN_PREPARE, _BIN_COMMIT):
        cls = Prepare if code == _BIN_PREPARE else Commit
        msg = cls(
            view=r.i64(), seq=r.i64(), digest=r.hex_(32),
            replica=r.i64(), sig=r.hex_(64),
        )
    elif code == _BIN_CHECKPOINT:
        msg = Checkpoint(
            seq=r.i64(), digest=r.hex_(32), replica=r.i64(), sig=r.hex_(64)
        )
    else:
        raise ValueError(f"unknown binary message type {code:#x}")
    if r.off != len(payload):
        raise ValueError("trailing bytes in binary frame")
    return msg


def decode_payload(payload: bytes) -> Message:
    """Decode a framed payload of either codec (binary-v2 when it opens
    with the magic byte, canonical JSON otherwise)."""
    if payload[:1] == bytes((WIRE_BINARY_MAGIC,)):
        return from_binary(payload)
    return from_wire(payload)


_SPLICE_TYPES = (
    PrePrepare, Prepare, Commit, Checkpoint, StateRequest, StateResponse
)
