"""PBFT message types, canonical encoding, digests, and signatures.

Capability parity with the reference's message layer (reference
src/message.rs): ClientRequest / PrePrepare / Prepare / Commit / ClientReply
with a content digest over the client request — plus what the reference left
as TODOs: real signatures on every replica-to-replica message (reference
src/behavior.rs:127,:185) and a Checkpoint message for watermark advancement
(reference src/behavior.rs:154,:192).

Encoding decisions (TPU-first redesign, not a port):
- Canonical bytes = JSON with sorted keys and fixed separators; the digest is
  Blake2b-256 of those bytes (the reference also used Blake2b,
  src/message.rs:3,:209-212).
- Replicas sign the 32-byte Blake2b digest of a message's signable content.
  Fixing the signed payload at 32 bytes makes the Ed25519 challenge hash
  SHA-512(R||A||M) exactly one block — every shape in the TPU batch verifier
  is static (see pbft_tpu.crypto.sha512).
- Wire frame = 4-byte big-endian length + JSON (the reference used
  varint-framed JSON, src/protocol_config.rs:51,:82; a fixed-width prefix is
  friendlier to the C++ runtime and to batch scanning).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, ClassVar, Dict, Optional, Type


def blake2b_256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def _canonical_json(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


class Message:
    """Base: canonical bytes, digest, signable digest, wire (de)serialization."""

    TYPE: ClassVar[str] = ""
    _REGISTRY: ClassVar[Dict[str, Type["Message"]]] = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.TYPE:
            Message._REGISTRY[cls.TYPE] = cls

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["type"] = self.TYPE
        return d

    def canonical(self) -> bytes:
        return _canonical_json(self.to_dict())

    def signable(self) -> bytes:
        """32-byte digest of the content excluding the signature field."""
        d = self.to_dict()
        d.pop("sig", None)
        return blake2b_256(_canonical_json(d))

    @classmethod
    def from_dict(cls, d: dict) -> "Message":
        d = dict(d)
        typ = d.pop("type")
        target = Message._REGISTRY[typ]
        if "request" in d and isinstance(d["request"], dict):
            req = dict(d["request"])
            req.pop("type", None)
            d["request"] = ClientRequest(**req)
        return target(**d)


def to_wire(msg: Message) -> bytes:
    payload = msg.canonical()
    return len(payload).to_bytes(4, "big") + payload


_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def _check_int64(obj) -> None:
    """Reject integers outside int64: the C++ runtime parses int64 and
    *drops* out-of-range messages, so Python must reject the same set or
    the two implementations would digest different canonical bytes for
    the same wire message (a consensus divergence)."""
    if isinstance(obj, bool):
        return
    if isinstance(obj, int):
        if not (_INT64_MIN <= obj <= _INT64_MAX):
            raise ValueError(f"integer out of int64 range: {obj}")
    elif isinstance(obj, dict):
        for v in obj.values():
            _check_int64(v)
    elif isinstance(obj, list):
        for v in obj:
            _check_int64(v)


def from_wire(frame: bytes) -> Message:
    d = json.loads(frame.decode())
    _check_int64(d)
    return Message.from_dict(d)


@dataclasses.dataclass(frozen=True)
class ClientRequest(Message):
    """o=operation, t=timestamp, c=client dial-back address "host:port"
    (reference src/message.rs:34-38)."""

    TYPE: ClassVar[str] = "client-request"
    operation: str
    timestamp: int
    client: str

    def digest(self) -> str:
        return blake2b_256(self.canonical()).hex()


@dataclasses.dataclass(frozen=True)
class ClientReply(Message):
    """Reply dialed back to the client (reference src/message.rs:55-72),
    signed by the replying replica: PBFT §4.1's f+1 reply quorum only
    means something if a vote proves which replica cast it — unsigned
    replies let one faulty party mint arbitrary votes on the dial-back
    channel."""

    TYPE: ClassVar[str] = "client-reply"
    view: int
    timestamp: int
    client: str
    replica: int
    result: str
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class PrePrepare(Message):
    """<<PRE-PREPARE, v, n, d>, m> signed by the primary
    (reference src/message.rs:106-137)."""

    TYPE: ClassVar[str] = "pre-prepare"
    view: int
    seq: int
    digest: str
    request: ClientRequest
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class Prepare(Message):
    """<PREPARE, v, n, d, i> (reference src/message.rs:175-188)."""

    TYPE: ClassVar[str] = "prepare"
    view: int
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class Commit(Message):
    """<COMMIT, v, n, d, i> (reference src/message.rs:214-239; the rebuild
    keys its log by (v, n), fixing the reference's view-only CommitKey,
    src/state.rs:23)."""

    TYPE: ClassVar[str] = "commit"
    view: int
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class ViewChange(Message):
    """<VIEW-CHANGE, v+1, n, C, P, i> (PBFT §4.4 — absent from the
    reference, whose View was a constant with no mutation API, reference
    src/view.rs:1-13).

    - ``last_stable_seq``/``checkpoint_proof``: n and C — 2f+1 checkpoint
      message dicts proving the replica's last stable checkpoint.
    - ``prepared_proofs``: P — one entry per sequence prepared above n:
      {"pre_prepare": <dict>, "prepares": [<dict>, ...]} with 2f matching
      backup prepares each. Stored as raw dicts: they are *evidence*
      (re-validated structurally + cryptographically by the receiver),
      not live protocol messages."""

    TYPE: ClassVar[str] = "view-change"
    new_view: int
    last_stable_seq: int
    checkpoint_proof: tuple
    prepared_proofs: tuple
    replica: int
    sig: str = ""

    def __post_init__(self):
        # JSON round-trips tuples as lists; normalize for equality.
        object.__setattr__(self, "checkpoint_proof", tuple(self.checkpoint_proof))
        object.__setattr__(self, "prepared_proofs", tuple(self.prepared_proofs))

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["checkpoint_proof"] = list(self.checkpoint_proof)
        d["prepared_proofs"] = list(self.prepared_proofs)
        return d


@dataclasses.dataclass(frozen=True)
class NewView(Message):
    """<NEW-VIEW, v+1, V, O> (PBFT §4.4): V = 2f+1 view-change message
    dicts, O = the new primary's re-issued pre-prepare dicts for every
    in-flight sequence (null requests fill gaps)."""

    TYPE: ClassVar[str] = "new-view"
    new_view: int
    view_changes: tuple
    pre_prepares: tuple
    replica: int
    sig: str = ""

    def __post_init__(self):
        object.__setattr__(self, "view_changes", tuple(self.view_changes))
        object.__setattr__(self, "pre_prepares", tuple(self.pre_prepares))

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["view_changes"] = list(self.view_changes)
        d["pre_prepares"] = list(self.pre_prepares)
        return d


NULL_CLIENT = "<null>"


def null_request() -> "ClientRequest":
    """Filler for sequence gaps in a new view (PBFT §4.4: 'a special null
    request which goes through the protocol like other requests but whose
    execution is a no-op')."""
    return ClientRequest(operation="<null>", timestamp=0, client=NULL_CLIENT)


@dataclasses.dataclass(frozen=True)
class Checkpoint(Message):
    """<CHECKPOINT, n, d, i>: state digest at sequence n; 2f+1 matching
    checkpoints advance the low watermark (PBFT §4.3; a reference TODO,
    src/behavior.rs:154)."""

    TYPE: ClassVar[str] = "checkpoint"
    seq: int
    digest: str
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class StateRequest(Message):
    """<STATE-REQUEST, n, i>: a replica whose watermark jumped past its
    execution asks peers for the checkpoint payload at stable sequence n
    (PBFT §5.3 state-transfer analogue; the reference TODO'd even the
    watermark checks, src/behavior.rs:154,:192)."""

    TYPE: ClassVar[str] = "state-request"
    seq: int
    replica: int
    sig: str = ""


@dataclasses.dataclass(frozen=True)
class StateResponse(Message):
    """<STATE-RESPONSE, n, payload, i>: the canonical checkpoint payload at
    sequence n (app snapshot + chain digest + per-client reply caches,
    see Replica._checkpoint_payload). The receiver trusts it only if its
    Blake2b-256 digest equals the 2f+1-certified stable checkpoint digest —
    the sender's signature gates transport, the digest gates content."""

    TYPE: ClassVar[str] = "state-response"
    seq: int
    snapshot: str
    replica: int
    sig: str = ""


def with_sig(msg: Message, sig_hex: str) -> Message:
    return dataclasses.replace(msg, sig=sig_hex)
