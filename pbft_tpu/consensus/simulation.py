"""In-process cluster simulation: N replica cores over an in-memory transport.

SURVEY.md §4 item 2 — the analogue of the reference's libp2p swarm for
testing: byte-faithful message passing (frames go through to_wire/from_wire so
encoding bugs can't hide), per-replica inboxes, pluggable signature-verifier
backend (cpu oracle or the JAX batch kernel), link-failure and Byzantine
fault injection.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import ref as crypto
from .config import ClusterConfig, make_local_cluster
from .messages import ClientReply, ClientRequest, Message, from_wire, to_wire
from .replica import Broadcast, Replica, Reply, Send


def cpu_verifier(items: List[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """Per-message host verification — the control arm (BASELINE.md config 1)."""
    return [crypto.verify(pub, msg, sig) for pub, msg, sig in items]


def jax_verifier(items: List[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """The batched XLA verifier (lazy import keeps sims jax-free on cpu
    arm); auto-shards over a multi-device host like the serving paths."""
    from ..parallel import verify_many_auto

    return verify_many_auto(items)


class Cluster:
    def __init__(
        self,
        n: int = 4,
        verifier: str | Callable = "cpu",
        seed: int = 0,
        shuffle: bool = False,
        config: Optional[ClusterConfig] = None,
        seeds: Optional[List[bytes]] = None,
        app=None,
        app_factory: Optional[Callable[[], Callable]] = None,
    ):
        if config is None:
            config, seeds = make_local_cluster(n)
        self.config = config

        def _app_kw():
            # app_factory gives each replica its OWN app instance — required
            # for stateful apps (state transfer tests); a bare `app` is
            # shared, fine for stateless callables.
            if app_factory is not None:
                return {"app": app_factory()}
            return {"app": app} if app else {}

        self.replicas = [
            Replica(config, i, seeds[i], **_app_kw()) for i in range(config.n)
        ]
        self.inboxes: Dict[int, List[Message]] = {i: [] for i in range(config.n)}
        self.client_replies: List[ClientReply] = []
        self.rng = random.Random(seed)
        self.shuffle = shuffle
        self.dropped_links: set[Tuple[int, int]] = set()  # (src, dst)
        # outbound_mutator(src, msg) -> Message | None; Byzantine injection.
        self.outbound_mutator: Optional[Callable] = None
        self.sig_verifications = 0
        if callable(verifier):
            self.verify = verifier
        else:
            self.verify = {"cpu": cpu_verifier, "jax": jax_verifier}[verifier]
        self._timestamp = 0

    # -- client side --------------------------------------------------------

    def submit(
        self,
        operation: str,
        client: str = "127.0.0.1:9000",
        timestamp: Optional[int] = None,
        to_replica: Optional[int] = None,
    ) -> ClientRequest:
        if timestamp is None:
            self._timestamp += 1
            timestamp = self._timestamp
        req = ClientRequest(operation=operation, timestamp=timestamp, client=client)
        dest = to_replica if to_replica is not None else self.primary_id
        self._route(dest, dest, req)  # client link: no mutation, no drop
        return req

    @property
    def primary_id(self) -> int:
        view = max(r.view for r in self.replicas)
        return self.config.primary_of(view)

    # -- transport ----------------------------------------------------------

    def _route(self, src: int, dst: int, msg: Message) -> None:
        frame = to_wire(msg)  # byte-faithful round trip on every hop
        self.inboxes[dst].append(from_wire(frame[4:]))

    def _emit(self, src: int, actions) -> None:
        for act in actions:
            if isinstance(act, Broadcast):
                for dst in range(self.config.n):
                    if dst != src:
                        self._deliver(src, dst, act.msg)
            elif isinstance(act, Send):
                self._deliver(src, act.dest, act.msg)
            elif isinstance(act, Reply):
                self.client_replies.append(act.msg)

    def _deliver(self, src: int, dst: int, msg: Message) -> None:
        if (src, dst) in self.dropped_links:
            return
        if self.outbound_mutator is not None:
            msg = self.outbound_mutator(src, msg)
            if msg is None:
                return
        self._route(src, dst, msg)

    # -- scheduler ----------------------------------------------------------

    def step(self) -> bool:
        """One round: every replica ingests its inbox, verifies the batch,
        processes. Returns True if any message moved."""
        moved = False
        for rid, replica in enumerate(self.replicas):
            queue, self.inboxes[rid] = self.inboxes[rid], []
            if not queue:
                continue
            moved = True
            if self.shuffle:
                self.rng.shuffle(queue)
            actions = []
            for msg in queue:
                actions.extend(replica.receive(msg))
            items = replica.pending_items()
            if items:
                verdicts = self.verify(items)
                self.sig_verifications += len(items)
                actions.extend(replica.deliver_verdicts(verdicts))
            self._emit(rid, actions)
        return moved

    def run(self, max_steps: int = 200) -> int:
        steps = 0
        while steps < max_steps and self.step():
            steps += 1
        return steps

    # -- fault / timer injection --------------------------------------------

    def crash(self, replica_id: int) -> None:
        """Crash-stop: sever every link to and from the replica."""
        for other in range(self.config.n):
            self.dropped_links.add((replica_id, other))
            self.dropped_links.add((other, replica_id))

    def uncrash(self, replica_id: int) -> None:
        """Heal every link to and from the replica (recovery after crash)."""
        for other in range(self.config.n):
            self.dropped_links.discard((replica_id, other))
            self.dropped_links.discard((other, replica_id))

    def trigger_view_change(self, replica_ids=None, new_view=None) -> None:
        """Fire the (runtime-owned) request timers: each listed replica
        broadcasts VIEW-CHANGE (PBFT §4.4). In a real deployment the net
        layer calls Replica.start_view_change when a forwarded request
        isn't executed before its timeout."""
        if replica_ids is None:
            replica_ids = [r.id for r in self.replicas]
        for rid in replica_ids:
            self._emit(rid, self.replicas[rid].start_view_change(new_view))

    # -- assertions helpers -------------------------------------------------

    def replies_for(self, timestamp: int) -> List[ClientReply]:
        return [r for r in self.client_replies if r.timestamp == timestamp]

    def committed_result(self, timestamp: int, f: Optional[int] = None) -> str:
        """The client's acceptance rule: f+1 matching replies (PBFT §4.1)."""
        f = self.config.f if f is None else f
        by_result: Dict[str, int] = {}
        for r in self.replies_for(timestamp):
            by_result[r.result] = by_result.get(r.result, 0) + 1
        for result, count in by_result.items():
            if count >= f + 1:
                return result
        raise AssertionError(
            f"no f+1 quorum of matching replies for t={timestamp}: {by_result}"
        )
