"""In-process cluster simulation: N replica cores over an in-memory transport.

SURVEY.md §4 item 2 — the analogue of the reference's libp2p swarm for
testing: byte-faithful message passing (frames go through to_wire/from_wire so
encoding bugs can't hide), per-replica inboxes, pluggable signature-verifier
backend (cpu oracle or the JAX batch kernel), and a seeded chaos transport
(ISSUE 5): per-link delay distributions, probabilistic drop/duplication,
reordering, asymmetric partitions, crash realism, and replica-level Byzantine
behavior modes (sig-corrupt / mute / stutter / equivocate). Everything the
chaos layer does is driven by one ``random.Random`` stream derived from the
cluster seed, so a failing schedule replays deterministically
(scripts/chaos_soak.py --replay SEED).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import ref as crypto
from .config import ClusterConfig, make_local_cluster
from .messages import (
    Checkpoint,
    ClientReply,
    ClientRequest,
    Commit,
    Message,
    Prepare,
    PrePrepare,
    batch_digest,
    from_wire,
    to_wire,
    with_sig,
)
from .replica import Broadcast, Replica, Reply, Send, _host_sign
from .wal import WriteAheadLog

# Replica-level Byzantine behavior modes (the sim arm of the cross-runtime
# --fault flag; core/pbftd.cc and net/server.py accept the same names).
FAULT_MODES = ("sig-corrupt", "mute", "stutter", "equivocate")

# Deterministic equivocation transform: variant B of a batch mutates every
# operation with this suffix (recomputed digest, re-signed). Shared with the
# real daemons so cross-runtime tests recognize equivocated executions.
EQUIV_SUFFIX = "#equiv"


def cpu_verifier(items: List[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """Per-message host verification — the control arm (BASELINE.md config 1)."""
    return [crypto.verify(pub, msg, sig) for pub, msg, sig in items]


def jax_verifier(items: List[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """The batched XLA verifier (lazy import keeps sims jax-free on cpu
    arm); auto-shards over a multi-device host like the serving paths."""
    from ..parallel import verify_many_auto

    return verify_many_auto(items)


@dataclasses.dataclass(frozen=True)
class LinkChaos:
    """Per-link fault distribution, sampled from the cluster's seeded RNG.

    delay_min/delay_max are in *steps* (the sim's time unit): each delivery
    waits a uniform number of extra scheduler rounds, which — combined with
    per-step inbox shuffling — yields reordering. drop_pct / dup_pct are
    per-delivery probabilities in [0, 1]."""

    drop_pct: float = 0.0
    dup_pct: float = 0.0
    delay_min: int = 0
    delay_max: int = 0

    def is_instant(self) -> bool:
        return self.delay_max <= 0 and self.drop_pct <= 0 and self.dup_pct <= 0


class Cluster:
    def __init__(
        self,
        n: int = 4,
        verifier: str | Callable = "cpu",
        seed: int = 0,
        shuffle: bool = False,
        config: Optional[ClusterConfig] = None,
        seeds: Optional[List[bytes]] = None,
        app=None,
        app_factory: Optional[Callable[[], Callable]] = None,
        mode: str = "sig",
        wal: bool = False,
    ):
        if config is None:
            config, seeds = make_local_cluster(n)
        self.config = config
        self.seeds = seeds
        self._app = app
        self._app_factory = app_factory
        # Fast-path authenticator mode (ISSUE 14): "mac" models the real
        # runtimes' per-link session MACs — the transport KNOWS each
        # message's true sender, so a hot-type message whose claimed
        # replica matches the sending link dispatches pre-authenticated
        # (receive_authenticated, no signature verification), an
        # impersonating claim is dropped at the link (exactly what a
        # lane-key mismatch does on the wire), and everything else
        # (view-change/new-view/state evidence) still signature-verifies.
        if mode not in ("sig", "mac"):
            raise ValueError(f"unknown fast-path mode {mode!r}")
        self.mode = mode

        def _app_kw():
            # app_factory gives each replica its OWN app instance — required
            # for stateful apps (state transfer tests); a bare `app` is
            # shared, fine for stateless callables.
            if app_factory is not None:
                return {"app": app_factory()}
            return {"app": app} if app else {}

        self.replicas = [
            Replica(config, i, seeds[i], **_app_kw()) for i in range(config.n)
        ]
        # Durable-recovery model (ISSUE 15): with wal=True each replica
        # gets an in-memory WriteAheadLog — the OBJECT plays the disk
        # (it survives a simulated crash while the Replica object is
        # discarded by restart()). restart_votes snapshots each
        # restarted replica's pre-crash persisted votes for the S5
        # checker; restart_epochs lets the checker re-baseline its
        # executed/committed monotonicity tracking across a restart.
        self.wals: Dict[int, WriteAheadLog] = {}
        self.restart_votes: Dict[int, Dict] = {}
        self.restart_epochs: Dict[int, int] = {}
        if wal:
            for r in self.replicas:
                self.wals[r.id] = WriteAheadLog()
                r.wal = self.wals[r.id]
        # Inbox entries carry the TRUE link-level sender (src, message):
        # the mac mode's authenticity model needs it, and the byte-
        # faithful round trip still runs in _route.
        self.inboxes: Dict[int, List[Tuple[int, Message]]] = {
            i: [] for i in range(config.n)
        }
        self.client_replies: List[ClientReply] = []
        self.rng = random.Random(seed)
        # The chaos layer draws from its OWN stream so enabling/disabling it
        # never perturbs the legacy shuffle stream (seeded reproducibility
        # of pre-chaos tests), while both derive from the one cluster seed.
        self.chaos_rng = random.Random((seed << 1) ^ 0xC4A05)
        self.shuffle = shuffle
        self.dropped_links: set[Tuple[int, int]] = set()  # (src, dst)
        # outbound_mutator(src, msg) -> Message | None; ad-hoc Byzantine
        # injection (the original hook; fault modes below are the
        # declarative layer on top of the same interception point).
        self.outbound_mutator: Optional[Callable] = None
        # sent_observer(src, msg): every concrete protocol message a
        # replica puts on the wire, AFTER fault-mode mutation (what was
        # actually sent, per destination) but before link drops — the
        # invariant checker's quorum-evidence feed. A Byzantine replica
        # that equivocates is observed voting both ways, which is exactly
        # the evidence model the safety checker needs.
        self.sent_observer: Optional[Callable[[int, Message], None]] = None
        self.sig_verifications = 0
        if callable(verifier):
            self.verify = verifier
        else:
            self.verify = {"cpu": cpu_verifier, "jax": jax_verifier}[verifier]
        self._timestamp = 0
        # -- chaos state ----------------------------------------------------
        self.step_count = 0
        self.crashed: set[int] = set()
        self.faults: Dict[int, str] = {}  # replica -> FAULT_MODES entry
        self.partitions: List[set] = []  # symmetric components; [] = whole
        self.default_chaos: Optional[LinkChaos] = None
        self.link_chaos: Dict[Tuple[int, int], LinkChaos] = {}
        # Delayed deliveries: (deliver_at_step, tie_break, src, dst, Message).
        self._in_flight: List[Tuple[int, int, int, int, Message]] = []
        self._flight_seq = 0
        # Per-replica history of sent messages, for the stutter mode.
        self._sent_history: Dict[int, List[Message]] = {}
        # Equivocation engine: (view, seq) -> (digest_a, digest_b,
        # variant-b requests). Shared across colluding equivocators so a
        # faulty backup's prepares/commits track the same two-face split.
        self._equiv: Dict[Tuple[int, int], Tuple[str, str, tuple]] = {}
        self.faults_injected = 0
        self.chaos_dropped = 0

    # -- client side --------------------------------------------------------

    def submit(
        self,
        operation: str,
        client: str = "127.0.0.1:9000",
        timestamp: Optional[int] = None,
        to_replica: Optional[int] = None,
    ) -> ClientRequest:
        if timestamp is None:
            self._timestamp += 1
            timestamp = self._timestamp
        req = ClientRequest(operation=operation, timestamp=timestamp, client=client)
        dest = to_replica if to_replica is not None else self.primary_id
        if dest in self.crashed:
            return req  # a crashed replica accepts no connections
        self._route(dest, dest, req)  # client link: no mutation, no drop
        return req

    @property
    def primary_id(self) -> int:
        view = max(r.view for r in self.replicas)
        return self.config.primary_of(view)

    # -- fault schedule surface ---------------------------------------------

    def set_fault(self, replica_id: int, mode: Optional[str]) -> None:
        """Install (or with ``None`` clear) a Byzantine behavior mode."""
        if mode is None:
            self.faults.pop(replica_id, None)
            return
        if mode not in FAULT_MODES:
            raise ValueError(f"unknown fault mode {mode!r}")
        self.faults[replica_id] = mode

    def clear_fault(self, replica_id: int) -> None:
        self.set_fault(replica_id, None)

    def set_chaos(
        self,
        chaos: Optional[LinkChaos],
        src: Optional[int] = None,
        dst: Optional[int] = None,
    ) -> None:
        """Attach a LinkChaos distribution: cluster-wide by default, or to
        the one directed (src, dst) link when both are given."""
        if src is None and dst is None:
            self.default_chaos = chaos
        elif src is not None and dst is not None:
            if chaos is None:
                self.link_chaos.pop((src, dst), None)
            else:
                self.link_chaos[(src, dst)] = chaos
        else:
            raise ValueError("give both src and dst, or neither")

    def partition(self, groups) -> None:
        """Split the cluster into components: links between groups are
        severed in BOTH directions (use ``dropped_links`` directly for
        asymmetric, single-direction cuts). Replicas named in no group
        form one implicit remainder component together."""
        groups = [set(g) for g in groups]
        named = set().union(*groups) if groups else set()
        rest = set(range(self.config.n)) - named
        if rest:
            groups.append(rest)
        self.partitions = groups

    def heal(self) -> None:
        """Remove every partition (symmetric cuts only — asymmetric
        ``dropped_links`` entries are the caller's to clear)."""
        self.partitions = []

    def _partitioned(self, src: int, dst: int) -> bool:
        for g in self.partitions:
            if src in g:
                return dst not in g
        return False

    # -- transport ----------------------------------------------------------

    def _route(self, src: int, dst: int, msg: Message) -> None:
        frame = to_wire(msg)  # byte-faithful round trip on every hop
        self.inboxes[dst].append((src, from_wire(frame[4:])))

    def _emit(self, src: int, actions) -> None:
        muted = self.faults.get(src) == "mute"
        for act in actions:
            if isinstance(act, Broadcast):
                for dst in range(self.config.n):
                    if dst != src:
                        self._deliver(src, dst, act.msg)
            elif isinstance(act, Send):
                if act.dest == src:
                    self._route(src, src, act.msg)  # self-delivery: no faults
                else:
                    self._deliver(src, act.dest, act.msg)
            elif isinstance(act, Reply):
                if muted:
                    self.faults_injected += 1
                    continue  # a mute replica never dials the client back
                self.client_replies.append(act.msg)

    def _deliver(self, src: int, dst: int, msg: Message) -> None:
        if (src, dst) in self.dropped_links:
            return
        if self.outbound_mutator is not None:
            msg = self.outbound_mutator(src, msg)
            if msg is None:
                return
        for out in self._apply_fault(src, dst, msg):
            if self.sent_observer is not None:
                self.sent_observer(src, out)
            self._enqueue(src, dst, out)

    # -- Byzantine behavior modes -------------------------------------------

    def _resign(self, src: int, msg: Message) -> Message:
        return with_sig(msg, _host_sign(self.seeds[src], msg.signable()).hex())

    def _equiv_variant(self, src: int, pp: PrePrepare):
        """Variant B of a pre-prepare: every operation mutated, digest
        recomputed, re-signed with the sender's own key — both variants
        carry VALID signatures, which is what makes equivocation a real
        attack rather than a corrupt-signature reject."""
        key = (pp.view, pp.seq)
        if key not in self._equiv:
            if not pp.requests:
                return None  # empty (gap-filler) batch: nothing to fork
            reqs_b = tuple(
                dataclasses.replace(r, operation=r.operation + EQUIV_SUFFIX)
                for r in pp.requests
            )
            self._equiv[key] = (pp.digest, batch_digest(reqs_b), reqs_b)
        return self._equiv[key]

    def _apply_fault(self, src: int, dst: int, msg: Message) -> List[Message]:
        """The sender-side fault engine: 0..n concrete messages out."""
        mode = self.faults.get(src)
        if mode is None:
            return [msg]
        if mode == "mute":
            self.faults_injected += 1
            return []
        if mode == "sig-corrupt":
            sig = getattr(msg, "sig", "")
            if sig:
                self.faults_injected += 1
                return [with_sig(msg, "f" * len(sig))]
            return [msg]
        if mode == "stutter":
            history = self._sent_history.setdefault(src, [])
            out = [msg]
            if history and self.chaos_rng.random() < 0.3:
                self.faults_injected += 1
                out.append(self.chaos_rng.choice(history))
            history.append(msg)
            del history[:-32]
            return out
        # equivocate: two-face delivery. The primary's pre-prepare forks
        # into (A, B); a colluding equivocator's prepares/commits for a
        # forked slot track the variant their destination saw. Group split
        # is by destination parity — deterministic, so several equivocating
        # replicas (an over-budget f+1 run) automatically collude, which is
        # exactly the scenario the safety checker must catch.
        if isinstance(msg, PrePrepare) and msg.replica == src:
            var = self._equiv_variant(src, msg)
            if var is None:
                return [msg]
            self.faults_injected += 1
            if dst % 2 == 0:
                return [msg]
            _, digest_b, reqs_b = var
            return [
                self._resign(
                    src,
                    dataclasses.replace(
                        msg, digest=digest_b, requests=reqs_b, sig=""
                    ),
                )
            ]
        if isinstance(msg, (Prepare, Commit)):
            var = self._equiv.get((msg.view, msg.seq))
            if var is not None and msg.digest in var[:2]:
                self.faults_injected += 1
                digest = var[0] if dst % 2 == 0 else var[1]
                if digest == msg.digest:
                    return [msg]
                return [
                    self._resign(
                        src, dataclasses.replace(msg, digest=digest, sig="")
                    )
                ]
        return [msg]

    # -- the chaos link ------------------------------------------------------

    def _enqueue(self, src: int, dst: int, msg: Message) -> None:
        if self._partitioned(src, dst):
            self.chaos_dropped += 1
            return
        chaos = self.link_chaos.get((src, dst), self.default_chaos)
        copies = 1
        delay = 0
        if chaos is not None and not chaos.is_instant():
            if chaos.drop_pct > 0 and self.chaos_rng.random() < chaos.drop_pct:
                self.chaos_dropped += 1
                return
            if chaos.dup_pct > 0 and self.chaos_rng.random() < chaos.dup_pct:
                copies = 2
            if chaos.delay_max > 0:
                delay = self.chaos_rng.randint(
                    min(chaos.delay_min, chaos.delay_max), chaos.delay_max
                )
        for _ in range(copies):
            if delay <= 0:
                if dst not in self.crashed:
                    self._route(src, dst, msg)
            else:
                self._flight_seq += 1
                self._in_flight.append(
                    (self.step_count + delay, self._flight_seq, src, dst, msg)
                )

    def _inject_due(self) -> None:
        if not self._in_flight:
            return
        still, due = [], []
        for entry in self._in_flight:
            (due if entry[0] <= self.step_count else still).append(entry)
        self._in_flight = still
        for _, _, src, dst, msg in sorted(due):
            if dst in self.crashed:
                self.chaos_dropped += 1  # arrived at a dead replica
                continue
            self._route(src, dst, msg)  # already fault/link-processed

    # -- scheduler ----------------------------------------------------------

    def step(self) -> bool:
        """One round: due in-flight messages land, then every live replica
        ingests its inbox, verifies the batch, processes. Returns True if
        any message moved or is still in flight."""
        self.step_count += 1
        self._inject_due()
        moved = False
        for rid, replica in enumerate(self.replicas):
            if rid in self.crashed:
                continue  # a crashed replica does no work at all
            queue, self.inboxes[rid] = self.inboxes[rid], []
            if not queue:
                continue
            moved = True
            if self.shuffle:
                self.rng.shuffle(queue)
            actions = []
            for src, msg in queue:
                if self.mode == "mac" and isinstance(
                    msg, (PrePrepare, Prepare, Commit, Checkpoint)
                ):
                    # Authenticator mode: the link proves the sender. A
                    # claim matching the sending link dispatches
                    # pre-authenticated; an impersonating claim dies at
                    # the link (the wire's lane-key mismatch). src == rid
                    # is self/client delivery — always trusted.
                    if src == rid or msg.replica == src:
                        actions.extend(replica.receive_authenticated(msg))
                    else:
                        continue
                else:
                    actions.extend(replica.receive(msg))
            items = replica.pending_items()
            if items:
                verdicts = self.verify(items)
                self.sig_verifications += len(items)
                actions.extend(replica.deliver_verdicts(verdicts))
            self._emit(rid, actions)
        return moved or bool(self._in_flight)

    def run(self, max_steps: int = 200) -> int:
        steps = 0
        while steps < max_steps and self.step():
            steps += 1
        return steps

    # -- fault / timer injection --------------------------------------------

    def crash(self, replica_id: int) -> None:
        """Crash-stop: the replica stops processing entirely — its inbox is
        discarded (no drain, no signature verification), deliveries to it
        are dropped, and ``submit(to_replica=...)`` can no longer reach it."""
        self.crashed.add(replica_id)
        self.inboxes[replica_id] = []
        self.replicas[replica_id]._inbox = []

    def uncrash(self, replica_id: int) -> None:
        """Recover a crashed replica (state intact, inbox empty — it must
        catch up via checkpoints/state transfer like a real restart)."""
        self.crashed.discard(replica_id)

    def restart(self, replica_id: int, from_disk: bool = True) -> None:
        """Crash-restart realism (ISSUE 15): unlike ``uncrash`` (which
        models a paused process resuming with its memory intact), this
        discards the Replica OBJECT — the process died — and constructs
        a fresh one: ``from_disk=True`` replays its write-ahead log
        (requires wal=True at construction), re-joining the SAME view at
        its stable-checkpoint floor with the no-contradiction guards
        armed; ``from_disk=False`` is the amnesiac restart (fresh state
        AND a blank wal) every pre-ISSUE-15 recovery story assumed.
        Either way the pre-crash persisted votes are snapshotted into
        ``restart_votes`` so the S5 checker can prove (or catch) the
        no-double-vote property on everything sent afterwards."""
        old = self.replicas[replica_id]
        wal = self.wals.get(replica_id)
        if wal is not None:
            self.restart_votes.setdefault(replica_id, {}).update(
                wal.state.votes
            )
        if self._app_factory is not None:
            app_kw = {"app": self._app_factory()}
        elif self._app is not None:
            app_kw = {"app": self._app}
        else:
            app_kw = {}
        fresh = Replica(
            self.config, replica_id, self.seeds[replica_id], **app_kw
        )
        # The observability hooks belong to the "host", not the process:
        # they survive the restart (chaos_soak's flight recorders).
        fresh.phase_hook = old.phase_hook
        fresh.view_hook = old.view_hook
        fresh.batch_hook = old.batch_hook
        if wal is not None:
            if from_disk:
                fresh.wal = wal
                fresh.restore_from_wal(wal.state)
            else:
                self.wals[replica_id] = WriteAheadLog()  # blank disk
                fresh.wal = self.wals[replica_id]
        self.replicas[replica_id] = fresh
        self.inboxes[replica_id] = []
        self.restart_epochs[replica_id] = (
            self.restart_epochs.get(replica_id, 0) + 1
        )
        self.crashed.discard(replica_id)

    def trigger_view_change(self, replica_ids=None, new_view=None) -> None:
        """Fire the (runtime-owned) request timers: each listed replica
        broadcasts VIEW-CHANGE (PBFT §4.4). In a real deployment the net
        layer calls Replica.start_view_change when a forwarded request
        isn't executed before its timeout."""
        if replica_ids is None:
            replica_ids = [r.id for r in self.replicas if r.id not in self.crashed]
        for rid in replica_ids:
            if rid in self.crashed:
                continue
            self._emit(rid, self.replicas[rid].start_view_change(new_view))

    # -- assertions helpers -------------------------------------------------

    def replies_for(self, timestamp: int) -> List[ClientReply]:
        return [r for r in self.client_replies if r.timestamp == timestamp]

    def committed_result(self, timestamp: int, f: Optional[int] = None) -> str:
        """The client's acceptance rule: f+1 matching replies (PBFT §4.1)."""
        f = self.config.f if f is None else f
        by_result: Dict[str, int] = {}
        seen: set[Tuple[int, str]] = set()
        for r in self.replies_for(timestamp):
            if (r.replica, r.result) in seen:
                continue  # one vote per (replica, result): dups don't count
            seen.add((r.replica, r.result))
            by_result[r.result] = by_result.get(r.result, 0) + 1
        for result, count in by_result.items():
            if count >= f + 1:
                return result
        raise AssertionError(
            f"no f+1 quorum of matching replies for t={timestamp}: {by_result}"
        )
