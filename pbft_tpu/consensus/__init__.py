"""The deterministic PBFT consensus core.

Pure message-in/message-out state machine (no sockets, no threads, no clocks)
so it unit-tests as truth tables (SURVEY.md §4 item 1) and drives identically
under the in-memory simulation, the C++ runtime, and multi-process clusters.
"""

from .messages import (
    ClientRequest,
    ClientReply,
    PrePrepare,
    Prepare,
    Commit,
    Checkpoint,
    ViewChange,
    NewView,
    from_wire,
    to_wire,
)
from .config import ClusterConfig, ReplicaIdentity
from .replica import Replica
from .simulation import Cluster
