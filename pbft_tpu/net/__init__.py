"""pbft_tpu.net — the host-side runtime glue around the native daemon.

- ``server``    — the asyncio replica runtime (in-process JAX verifier).
- ``service``   — the JAX/TPU verifier service: the socket server the C++
  ``pbftd`` ships signature batches to (core/verifier.h RemoteVerifier);
  one vmap'd XLA launch per batch, coalesced across daemons.
- ``verify_service`` — the persistent multi-chip daemon around it: owns
  the accelerator, AOT-warms every pad-ladder window shape at startup,
  answers the readiness handshake, and shards each merged window across
  all local devices; plus the replica-side ``ServiceVerifier`` client
  (short connect deadline, native-pool fallback).
- ``secure``    — encrypted replica links + protocol versioning
  (signed-ephemeral-DH handshake, keyed-BLAKE2b AEAD; mirror of
  core/secure.cc — the reference's Noise-secured development_transport,
  reference src/main.rs:42).
- ``discovery`` — UDP-multicast peer discovery (mirror of
  core/discovery.cc; the reference's mDNS layer, src/main.rs:46).
- ``client``    — the PBFT client: sends a raw-JSON request to the primary
  and collects dialed-back replies until f+1 match (PBFT §4.1; the
  reference's manual telnet + ``nc -kl`` walkthrough, README.md:5-43,
  scripted).
- ``launcher``  — spawns a localhost cluster of ``pbftd`` and/or asyncio
  replicas from a ClusterConfig (the reference ran 4 shells by hand).
"""

from .client import PbftClient
from .launcher import LocalCluster, pbftd_path
from .secure import PROTOCOL_VERSION, SecureChannel
from .service import VerifierService
from .verify_service import (
    ServiceVerifier,
    ShardedVerifyEngine,
    VerifyServiceDaemon,
    probe_status,
    probe_status_json,
)

__all__ = [
    "PbftClient",
    "LocalCluster",
    "VerifierService",
    "VerifyServiceDaemon",
    "ShardedVerifyEngine",
    "ServiceVerifier",
    "probe_status",
    "probe_status_json",
    "SecureChannel",
    "PROTOCOL_VERSION",
    "pbftd_path",
]
