"""pbft_tpu.net — the host-side runtime glue around the native daemon.

- ``service``  — the JAX/TPU verifier service: the socket server the C++
  ``pbftd`` ships signature batches to (core/verifier.h RemoteVerifier);
  one vmap'd XLA launch per batch.
- ``client``   — the PBFT client: sends a raw-JSON request to the primary
  and collects dialed-back replies until f+1 match (PBFT §4.1; the
  reference's manual telnet + ``nc -kl`` walkthrough, README.md:5-43,
  scripted).
- ``launcher`` — spawns a localhost cluster of ``pbftd`` processes from a
  ClusterConfig (the reference ran 4 shells by hand).
"""

from .client import PbftClient
from .launcher import LocalCluster, pbftd_path
from .service import VerifierService

__all__ = ["PbftClient", "LocalCluster", "VerifierService", "pbftd_path"]
