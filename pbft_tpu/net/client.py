"""The PBFT client, scripted (the reference's manual walkthrough: telnet a
JSON request to the primary, catch the dialed-back replies with ``nc -kl``,
README.md:5-43).

A client sends a raw-JSON ClientRequest over TCP to a replica and runs a
listener on its advertised dial-back address; it accepts a result once f+1
replicas sent matching replies (PBFT §4.1 — the reply quorum that makes one
faulty replica unable to lie to the client)."""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..consensus.config import ClusterConfig
from ..consensus.messages import ClientReply, ClientRequest


def _dial(host: str, port: int, timeout: float = 5.0) -> socket.socket:
    """Every client dial goes through here: TCP_NODELAY on every stream
    socket (ISSUE 10 satellite; scripts/pbft_lint.py analysis/sockets.py
    statically requires it at each dial site) — a request is one small
    write, and a Nagle stall on it dwarfs the consensus round."""
    s = socket.create_connection((host, port), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _host_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Native C++ verifier when built, else the Python oracle."""
    global _VERIFIER
    if _VERIFIER is None:
        from ..crypto import ref

        _VERIFIER = ref.verify
        try:
            from .. import native

            if native.available():
                _VERIFIER = native.verify
        except Exception:  # pragma: no cover - unbuilt native core
            pass
    return _VERIFIER(pub, msg, sig)


_VERIFIER = None


class PbftClient:
    def __init__(self, config: ClusterConfig, host: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.replies: List[dict] = []
        self._lock = threading.Lock()
        self._new_reply = threading.Condition(self._lock)
        client = self

        class Handler(socketserver.StreamRequestHandler):
            # TCP_NODELAY on accepted reply sockets too (ISSUE 10 socket
            # discipline) — socketserver's built-in spelling of it.
            disable_nagle_algorithm = True

            def handle(self):
                data = self.rfile.read()
                rx = time.monotonic()  # arrival stamp for first-reply latency
                for line in data.splitlines():
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        reply = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(reply, dict):
                        reply["_rx"] = rx
                    with client._new_reply:
                        client.replies.append(reply)
                        client._new_reply.notify_all()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            # Every replica dials back per reply; a burst of pipelined
            # requests means n * pipeline simultaneous connects — far
            # beyond socketserver's default backlog of 5.
            request_queue_size = 128

        self.server = Server((host, port), Handler)
        self.address = "%s:%d" % self.server.server_address
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()
        self._timestamp = 0
        # Per-request latency stamps (ISSUE 9 waterfall, client side):
        # timestamp -> {send, first_reply, quorum} monotonic stamps —
        # comparable with replica trace stamps on one host. Written by the
        # request paths and wait_result; exported by latency_records() /
        # write_trace() for the waterfall join.
        self.latency_log: Dict[int, dict] = {}

    def _stamp_send(self, timestamp: int) -> None:
        # First send only: a retransmission must not erase the queueing
        # delay it is there to measure.
        self.latency_log.setdefault(timestamp, {}).setdefault(
            "send", time.monotonic()
        )

    def close(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # -- request path -------------------------------------------------------

    def request(
        self,
        operation: str,
        to_replica: int = 0,
        timestamp: Optional[int] = None,
    ) -> ClientRequest:
        """Send one raw-JSON request to a replica (primary by default)."""
        if timestamp is None:
            self._timestamp += 1
            timestamp = self._timestamp
        req = ClientRequest(
            operation=operation, timestamp=timestamp, client=self.address
        )
        ident = self.config.identity(to_replica)
        self._stamp_send(timestamp)
        with _dial(ident.host, ident.port) as s:
            s.sendall(req.canonical() + b"\n")
        return req

    def request_many(
        self,
        operations: List[str],
        to_replica: int = 0,
        window: int = 32,
        timeout: float = 30.0,
    ) -> List[str]:
        """Pipelined (windowed-async) submission: stream requests over ONE
        connection, keeping up to ``window`` in flight, completing each in
        submission order. This is what actually FILLS the primary's
        request batches (ISSUE 4) — the lock-step ``request`` +
        ``wait_result`` pair can never put more than one request per
        client into an open batch, so benchmarks driving batching must
        use this (or many client identities).

        Timestamps are consecutive, and TCP preserves their order, so the
        primary sees them monotonically — per-client exactly-once is
        preserved with a whole window in flight. Returns the f+1-quorum
        results in operation order; raises TimeoutError if any request
        misses its quorum (after per-request retransmission to all
        replicas, the paper's client liveness rule)."""
        results: Dict[int, str] = {}
        timestamps: List[int] = []
        inflight: List[Tuple[int, str]] = []  # (timestamp, operation)
        ident = self.config.identity(to_replica)
        sock = _dial(ident.host, ident.port)
        try:
            next_op = 0
            while len(results) < len(operations):
                while next_op < len(operations) and len(inflight) < window:
                    self._timestamp += 1
                    ts = self._timestamp
                    req = ClientRequest(
                        operation=operations[next_op],
                        timestamp=ts,
                        client=self.address,
                    )
                    self._stamp_send(ts)
                    sock.sendall(req.canonical() + b"\n")
                    timestamps.append(ts)
                    inflight.append((ts, operations[next_op]))
                    next_op += 1
                ts, op = inflight.pop(0)
                try:
                    results[ts] = self.wait_result(ts, timeout=timeout)
                    self._drop_replies_upto(ts)
                except TimeoutError:
                    # Retransmission (PBFT §4.1): broadcast to every
                    # replica (forces forwarding, and a view change on a
                    # faulty primary), then wait once more.
                    retry = ClientRequest(
                        operation=op, timestamp=ts, client=self.address
                    )
                    payload = retry.canonical() + b"\n"
                    for rid in range(self.config.n):
                        rident = self.config.identity(rid)
                        try:
                            with _dial(rident.host, rident.port, timeout=2) as s:
                                s.sendall(payload)
                        except OSError:
                            pass
                    results[ts] = self.wait_result(ts, timeout=timeout)
                    self._drop_replies_upto(ts)
        finally:
            sock.close()
        return [results[ts] for ts in timestamps]

    def _drop_replies_upto(self, timestamp: int) -> None:
        """Prune consumed replies. request_many completes requests in
        timestamp order, so everything at or below the completed
        timestamp is dead weight — without pruning, wait_result's scan
        over the reply list is O(total replies) per arrival, and a long
        pipelined run turns quadratic in the client (masking any
        server-side throughput win it was built to measure)."""
        with self._lock:
            self.replies = [
                r for r in self.replies if r.get("timestamp", 0) > timestamp
            ]

    # Overload rejections absorbed across every request_with_retry call
    # (ISSUE 12): explicit {"type": "overloaded"} replies, NOT timeouts.
    overload_rejections = 0

    def _consume_overloaded(self, timestamp: int) -> int:
        """Remove and count explicit overload rejections for
        ``timestamp`` from the reply stream (they never carry a
        signature, so the quorum count can't see them)."""
        with self._lock:
            hits = sum(
                1
                for r in self.replies
                if r.get("type") == "overloaded"
                and r.get("timestamp") == timestamp
            )
            if hits:
                self.replies = [
                    r
                    for r in self.replies
                    if not (
                        r.get("type") == "overloaded"
                        and r.get("timestamp") == timestamp
                    )
                ]
        return hits

    def request_with_retry(
        self,
        operation: str,
        timeout: float = 20.0,
        retry_every: float = 2.0,
    ) -> str:
        """The paper's client liveness rule, hardened for chaos (ISSUE 5):
        send to the primary; on each retransmission timer expiry, ROTATE
        the direct target (a muted/partitioned primary must not consume
        the whole deadline) AND broadcast to all replicas (forcing
        forwards + eventually a view change on a faulty primary), with
        jittered exponential backoff between retries so a thundering herd
        of retrying clients de-synchronizes instead of beating the
        cluster in lockstep.

        An explicit ``overloaded`` rejection (ISSUE 12 admission control)
        is handled DISTINCTLY from a timeout: the cluster is alive and
        told us to slow down, so the client backs off (with jitter)
        WITHOUT rotating targets or broadcasting — a rotating storm of
        rejected retries is exactly the thundering herd admission control
        exists to shed. Rejections are tallied in ``overload_rejections``
        and in the request's latency record (client trace)."""
        import random as _random
        import time as _time

        self._timestamp += 1
        ts = self._timestamp
        req = ClientRequest(operation=operation, timestamp=ts, client=self.address)
        payload = req.canonical() + b"\n"
        self._stamp_send(ts)

        def send_to(rid: int) -> None:
            ident = self.config.identity(rid)
            try:
                with _dial(ident.host, ident.port, timeout=2) as s:
                    s.sendall(payload)
            except OSError:
                pass  # dead replica: that's what the rotation/broadcast is for

        send_to(0)
        deadline = _time.monotonic() + timeout
        attempt = 0
        target = 0
        rng = _random.Random()
        while True:
            # Jittered exponential backoff, capped: base * 1.5^attempt,
            # scaled by a uniform 0.5..1.5 factor, never past the deadline.
            wait = min(retry_every * (1.5 ** attempt), 4 * retry_every)
            wait *= 0.5 + rng.random()
            wait = min(wait, max(0.1, deadline - _time.monotonic()))
            try:
                return self.wait_result(ts, timeout=wait)
            except TimeoutError:
                rejected = self._consume_overloaded(ts)
                if _time.monotonic() >= deadline:
                    raise
                attempt += 1
                if rejected:
                    # Admission-control rejection: back off in place. The
                    # SAME target re-admits us once its backlog drains —
                    # rotating or broadcasting would multiply the load
                    # n-fold exactly when the cluster asked for less.
                    self.overload_rejections += rejected
                    rec = self.latency_log.get(ts)
                    if rec is not None:
                        rec["overloaded"] = (
                            rec.get("overloaded", 0) + rejected
                        )
                    send_to(target)
                    continue
                # Rotate the direct target across replicas, then broadcast
                # (the §4.1 rule) — the rotation guarantees some honest
                # replica hears us even when specific links are dead.
                target = attempt % self.config.n
                send_to(target)
                for rid in range(self.config.n):
                    send_to(rid)

    # -- latency export (ISSUE 9 waterfall, client side) ---------------------

    def latency_records(self) -> List[dict]:
        """Per-request stamp records for the waterfall join:
        {client, req_ts, send[, first_reply, quorum]}, send order."""
        out = []
        for ts in sorted(self.latency_log):
            rec = self.latency_log[ts]
            if "send" not in rec:
                continue
            row = {"client": self.address, "req_ts": ts, "send": rec["send"]}
            for k in ("first_reply", "quorum", "overloaded"):
                if k in rec:
                    row[k] = rec[k]
            out.append(row)
        return out

    def write_trace(self, path: str) -> int:
        """Append one ``client_request`` JSONL event per completed stamp
        record (schema: utils/trace_schema.py) so
        ``scripts/consensus_timeline.py --waterfall`` can join client and
        replica traces from one directory. Returns the event count."""
        from ..utils.trace import Tracer

        n = 0
        with open(path, "a") as fh:
            tracer = Tracer(fh)
            for row in self.latency_records():
                extra = {
                    k: round(row[k], 6)
                    for k in ("first_reply", "quorum")
                    if k in row
                }
                if "overloaded" in row:
                    # Admission-control rejections absorbed (ISSUE 12):
                    # an integer count, not a monotonic stamp.
                    extra["overloaded"] = int(row["overloaded"])
                tracer.event(
                    "client_request",
                    client=row["client"],
                    req_ts=row["req_ts"],
                    send=round(row["send"], 6),
                    **extra,
                )
                n += 1
        return n

    def _reply_signature_valid(self, r: dict, rid: int) -> bool:
        """Check the reply's Ed25519 signature against the configured
        pubkey of the replica it claims to come from."""
        try:
            reply = ClientReply(
                view=int(r["view"]),
                timestamp=int(r["timestamp"]),
                client=str(r["client"]),
                replica=rid,
                result=str(r["result"]),
                sig=str(r["sig"]),
                # Signed content (ISSUE 14): a flipped flag fails the
                # signature check instead of upgrading a tentative vote.
                tentative=int(r.get("tentative", 0)),
            )
            sig = bytes.fromhex(reply.sig)
            pub = bytes.fromhex(self.config.identity(rid).pubkey)
            if len(sig) != 64 or len(pub) != 32:
                return False
            return _host_verify(pub, reply.signable(), sig)
        except (KeyError, TypeError, ValueError):
            return False

    def wait_result(
        self, timestamp: int, f: Optional[int] = None, timeout: float = 10.0
    ) -> str:
        """Block until a reply quorum for `timestamp` arrives: f+1
        matching COMMITTED replies (PBFT §4.1), or — the ISSUE 14 fast
        path — 2f+1 matching replies in one view when some are tentative
        (Castro–Liskov §5.3: 2f+1 tentative votes imply f+1 honest
        replicas holding the full prepared certificate, which every
        new-view quorum intersects, so the outcome cannot roll back)."""
        f = self.config.f if f is None else f
        deadline = time.monotonic() + timeout
        with self._new_reply:
            while True:
                # One vote per replica id (PBFT §4.1: replies from
                # *different* replicas) — retransmitted/duplicated replies
                # from a single replica must not satisfy the quorum.
                votes: Dict[int, Tuple[str, int, int]] = {}
                for r in self.replies:
                    rid = r.get("replica")
                    if not isinstance(rid, int) or not 0 <= rid < self.config.n:
                        continue
                    if r.get("timestamp") != timestamp:
                        continue
                    # §4.1 for real: a reply only votes if it carries a
                    # valid signature from the replica it claims to be —
                    # the dial-back channel is otherwise forgeable.
                    if not self._reply_signature_valid(r, rid):
                        continue
                    votes[rid] = (
                        r.get("result"),
                        r.get("view"),
                        1 if r.get("tentative") else 0,
                    )
                by_result: Dict[Tuple[str, int], int] = {}
                committed_by_result: Dict[str, int] = {}
                for result, view, tentative in votes.values():
                    by_result[(result, view)] = (
                        by_result.get((result, view), 0) + 1
                    )
                    if not tentative:
                        committed_by_result[result] = (
                            committed_by_result.get(result, 0) + 1
                        )
                accepted: Optional[str] = None
                for (result, _view), count in by_result.items():
                    if (
                        count >= 2 * f + 1
                        or committed_by_result.get(result, 0) >= f + 1
                    ):
                        accepted = result
                        break
                if accepted is not None:
                    # getattr: bare test doubles skip __init__.
                    rec = getattr(self, "latency_log", {}).get(timestamp)
                    if rec is not None and "quorum" not in rec:
                        rec["quorum"] = time.monotonic()
                        rxs = [
                            r["_rx"]
                            for r in self.replies
                            if r.get("timestamp") == timestamp
                            and "_rx" in r
                        ]
                        if rxs:
                            rec["first_reply"] = min(rxs)
                    return accepted
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no reply quorum for t={timestamp}; "
                        f"got {by_result}"
                    )
                self._new_reply.wait(remaining)
