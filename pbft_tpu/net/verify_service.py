"""The persistent multi-chip verify service: own the accelerator, pay
compile once, shard every window.

Why a daemon (ROADMAP item 1, BENCH_r02-r05 postmortem): the in-bench TPU
probe paid backend init + an 816 s cold kernel compile *inside* the round
budget, so the CPU fallback won by default. The service flips the
lifecycle: one long-lived process initializes the JAX backend ONCE,
AOT-compiles the sharded verify kernel for every fixed `_PAD_LADDER`
window shape at startup (``jax.jit(...).lower().compile()`` ahead of
first traffic, persistent on-disk cache keyed by host identity + CPU via
``utils/cache.host_keyed_cache_dir``, optional serialized-executable
export so a warm restart skips even tracing), and then serves the
128-byte-triple protocol from ``service.py`` for its whole lifetime —
batches from ALL colocated replicas coalesce into one XLA launch sharded
across every local device (``parallel/verifier.py``).

Readiness handshake: a request with item count 0 returns an 8-byte
status record (state warming|ready|cpu-only + device count + warmed
shape count); count 0xFFFFFFFF returns a length-prefixed JSON status
(compile timings, shapes, uptime) for humans and the bench. Replicas —
``core/verifier.cc`` RemoteVerifier and the asyncio runtime via
:class:`ServiceVerifier` — dial with a SHORT connect deadline, consume
the handshake, and fall back to the PR-2 native pool
(``consensus.replica.host_batch_verify``) while the service is warming
or gone: a cold accelerator can never block consensus.

Host↔device pipeline: every window is staged with an async
``jax.device_put`` against the batch sharding and launched through a
precompiled executable with DONATED input buffers (XLA reuses the device
memory window over window). With the dispatcher's ``inflight=2`` default
the service ships window N+1 from a second launch thread while window N
computes — the double-buffered transfer/compute overlap, with verdict
slicing per connection untouched.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

# The readiness wire format (STATUS_* / STATE_* / pack_status /
# unpack_status) lives in service.py next to the protocol handler;
# re-exported here as the deployment-facing surface.
from .service import (  # noqa: F401 - re-exported API
    Item,
    STATE_CPU_ONLY,
    STATE_NAMES,
    STATE_READY,
    STATE_WARMING,
    STATUS_JSON_PROBE,
    STATUS_LEN,
    STATUS_MAGIC,
    STATUS_PROBE,
    STATUS_VERSION,
    VerifierService,
    _recv_exact,
    pack_status,
    unpack_status,
)


# -- the accelerator-owning engine -------------------------------------------


class ShardedVerifyEngine:
    """Owns the JAX backend: one mesh over the host's local devices and one
    AOT-compiled, input-donating sharded verify executable per window shape.

    ``warm()`` is the once-per-deploy cost the daemon pays at startup,
    outside any request: per shape it first tries the serialized-executable
    export (``deserialize_and_load`` — no tracing at all), else lowers and
    compiles (the persistent compile cache makes a warm restart cheap) and
    writes the export for next time. Export files are keyed by host cache
    key + device count + kernel tag, so a foreign or re-meshed artifact is
    never loaded (same contract as utils/cache).
    """

    def __init__(
        self,
        shapes: Optional[Sequence[int]] = None,
        devices: Optional[int] = None,
        cache_root: Optional[str] = None,
        export_dir: Optional[str] = None,
        kernel=None,
        kernel_tag: str = "ed25519",
    ):
        if shapes is None:
            from ..crypto.batch import _PAD_LADDER

            shapes = _PAD_LADDER
        self._want_shapes = tuple(sorted(set(shapes)))
        self._want_devices = devices
        self._cache_root = cache_root
        self._export_dir = export_dir
        self._kernel = kernel
        self._kernel_tag = kernel_tag
        self._lock = threading.Lock()
        self._mesh = None
        self._spec = None
        self._compiled: dict = {}  # padded size -> jax.stages.Compiled
        self.device_count = 0
        self.stats: dict = {}

    # -- startup -------------------------------------------------------------

    def _export_path(self, size: int) -> Optional[str]:
        if not self._export_dir:
            return None
        from ..utils.cache import host_cache_key

        name = (
            f"verify-{self._kernel_tag}-{host_cache_key()}"
            f"-d{self.device_count}-b{size}.exec"
        )
        return os.path.join(self._export_dir, name)

    def warm(self) -> dict:
        """Initialize the backend and precompile every window shape.

        Returns (and stores in ``self.stats``) the warmup accounting:
        ``aot_loaded``/``compiled`` per-shape counts, ``warm_load_s``
        (seconds spent reloading serialized executables) and
        ``cold_compile_s`` (seconds spent tracing+compiling — cache-hit
        cheap on a warm restart, minutes on a truly cold deploy).
        """
        from ..utils.cache import host_keyed_cache_dir

        if self._cache_root:
            os.environ.setdefault(
                "JAX_COMPILATION_CACHE_DIR",
                host_keyed_cache_dir(self._cache_root),
            )
        import jax

        if "JAX_COMPILATION_CACHE_DIR" in os.environ:
            try:
                jax.config.update(
                    "jax_compilation_cache_dir",
                    os.environ["JAX_COMPILATION_CACHE_DIR"],
                )
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.5
                )
            except Exception:  # pragma: no cover - knob renamed upstream
                pass
        from ..parallel import batch_sharding, compile_sharded, make_mesh

        with self._lock:
            devs = jax.local_devices()
            if self._want_devices:
                devs = devs[: self._want_devices]
            self.device_count = len(devs)
            self._mesh = make_mesh(devices=devs)
            self._spec = batch_sharding(self._mesh)
            if self._export_dir:
                os.makedirs(self._export_dir, exist_ok=True)
            stats = {
                "devices": self.device_count,
                "shapes": [],
                "aot_loaded": 0,
                "compiled": 0,
                "warm_load_s": 0.0,
                "cold_compile_s": 0.0,
            }
            for want in self._want_shapes:
                size = self._round_to_mesh(want)
                if size in self._compiled:
                    continue
                t0 = time.perf_counter()
                compiled = self._load_export(size)
                if compiled is not None:
                    stats["aot_loaded"] += 1
                    stats["warm_load_s"] += time.perf_counter() - t0
                else:
                    import warnings

                    with warnings.catch_warnings():
                        # Donation cannot alias the (B,128B) inputs to the
                        # (B,) bool output, so XLA warns per shape; the
                        # donation still releases the staged input buffers
                        # eagerly, and the warning is pure noise here.
                        warnings.filterwarnings(
                            "ignore", message="Some donated buffers"
                        )
                        compiled = compile_sharded(
                            self._mesh, size, kernel=self._kernel
                        )
                    stats["compiled"] += 1
                    stats["cold_compile_s"] += time.perf_counter() - t0
                    self._write_export(size, compiled)
                self._compiled[size] = compiled
                stats["shapes"].append(size)
            stats["warm_load_s"] = round(stats["warm_load_s"], 3)
            stats["cold_compile_s"] = round(stats["cold_compile_s"], 3)
            self.stats = stats
        return stats

    def _round_to_mesh(self, size: int) -> int:
        d = max(1, self.device_count)
        return ((size + d - 1) // d) * d

    def _load_export(self, size: int):
        path = self._export_path(size)
        if not path or not os.path.exists(path):
            return None
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            with open(path, "rb") as fh:
                serialized, in_tree, out_tree = pickle.load(fh)
            return deserialize_and_load(serialized, in_tree, out_tree)
        except Exception:
            # A stale/foreign export must cost a recompile, never a crash
            # (mirror of the host-keyed cache contract).
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _write_export(self, size: int, compiled) -> None:
        path = self._export_path(size)
        if not path:
            return
        try:
            from jax.experimental.serialize_executable import serialize

            blob = pickle.dumps(serialize(compiled))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        except Exception:  # pragma: no cover - serialization unsupported
            pass  # next startup pays the (cached) compile instead

    @property
    def warmed_sizes(self) -> Tuple[int, ...]:
        return tuple(sorted(self._compiled))

    # -- serving -------------------------------------------------------------

    def verify(self, items: List[Item]) -> List[bool]:
        """Pad to a warmed window shape, stage (async device_put against
        the batch sharding), launch the precompiled executable, read back.
        Oversized batches chunk into top-of-ladder windows — the service
        never compiles a new shape at runtime. Verdicts are bit-identical
        to the single-device and CPU paths (pinned in tests/test_parallel
        and tests/test_service_coalesce)."""
        if not items:
            return []
        if not self._compiled:
            raise RuntimeError("engine not warmed")
        import numpy as np
        import jax

        from ..crypto.batch import pad_batch

        top = max(self._compiled)
        out: List[bool] = []
        for off in range(0, len(items), top):
            chunk = items[off : off + top]
            size = min(
                (s for s in self._compiled if s >= len(chunk)), default=top
            )
            pubs, msgs, sigs, n = pad_batch(chunk, size)
            # Host->device staging is async dispatch; with the service's
            # overlapped launches (inflight=2) window N+1 stages here
            # while window N computes. Donated inputs let XLA reuse the
            # same device memory for every window of this shape.
            dp = jax.device_put(pubs, self._spec)
            dm = jax.device_put(msgs, self._spec)
            ds = jax.device_put(sigs, self._spec)
            verdicts = np.asarray(self._compiled[size](dp, dm, ds))
            out.extend(bool(v) for v in verdicts[:n])
        return out


# -- the daemon --------------------------------------------------------------


class VerifyServiceDaemon:
    """A :class:`~pbft_tpu.net.service.VerifierService` that owns its
    accelerator lifecycle: starts in ``warming`` (all traffic served by the
    native-pool fallback), warms the :class:`ShardedVerifyEngine` on a
    background thread, and flips to ``ready`` — or to ``cpu-only`` when no
    usable JAX backend exists (or ``backend`` pins native/cpu). The
    readiness handshake reports the state + device count so replicas and
    the bench route accordingly without ever blocking on a cold chip."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        unix_path: Optional[str] = None,
        backend: str = "auto",
        devices: Optional[int] = None,
        warm_shapes: Optional[Sequence[int]] = None,
        max_window: Optional[int] = None,
        flush_us: int = 0,
        flush_items: int = 0,
        inflight: int = 2,
        trace_path: Optional[str] = None,
        metrics_port: Optional[int] = None,
        cache_root: Optional[str] = None,
        export_dir: Optional[str] = None,
        engine: Optional[ShardedVerifyEngine] = None,
        fallback: Optional[Callable[[List[Item]], List[bool]]] = None,
    ):
        if backend not in ("auto", "jax", "native", "cpu"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self._t0 = time.monotonic()
        self._state = STATE_WARMING
        self._state_lock = threading.Lock()
        self._warm_error: Optional[str] = None
        self._warm_thread: Optional[threading.Thread] = None
        self.engine = engine
        if engine is None and backend in ("auto", "jax"):
            self.engine = ShardedVerifyEngine(
                shapes=warm_shapes,
                devices=devices,
                cache_root=cache_root,
                export_dir=export_dir,
            )
        if fallback is None:
            if backend == "cpu":
                from .service import cpu_backend as fallback
            else:
                from ..consensus.replica import host_batch_verify as fallback
        self._fallback = fallback
        self.service = VerifierService(
            host=host,
            port=port,
            unix_path=unix_path,
            backend=self._dispatch,
            flush_us=flush_us,
            flush_items=flush_items,
            trace_path=trace_path,
            inflight=inflight,
            metrics_port=metrics_port,
            status_provider=self._status,
            status_json_provider=self.status_json,
        )
        if max_window:
            self.service.MAX_WINDOW = max_window
        if self.service.metrics_registry.enabled:
            # The warm/cold compile gauges exist from the first scrape
            # (service.py's preregister only covers its own emitter set).
            self.service.metrics_registry.preregister("verify_service.py")

    # -- state machine -------------------------------------------------------

    @property
    def state(self) -> int:
        with self._state_lock:
            return self._state

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    @property
    def address(self) -> str:
        return self.service.address

    def _set_state(self, state: int) -> None:
        with self._state_lock:
            self._state = state

    def _status(self) -> Tuple[int, int, int]:
        eng = self.engine
        return (
            self.state,
            eng.device_count if eng else 0,
            len(eng.warmed_sizes) if eng else 0,
        )

    def status_json(self) -> dict:
        eng = self.engine
        out = {
            "state": self.state_name,
            "devices": eng.device_count if eng else 0,
            "warmed_shapes": list(eng.warmed_sizes) if eng else [],
            "backend": self.backend,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "requests": self.service.requests,
            "launches": self.service.batches,
            "items": self.service.items,
        }
        if eng and eng.stats:
            out["warm_stats"] = eng.stats
        if self._warm_error:
            out["warm_error"] = self._warm_error
        return out

    # -- serving -------------------------------------------------------------

    def _dispatch(self, items: List[Item]) -> List[bool]:
        """The service backend: the warmed sharded engine when ready, the
        native-pool fallback otherwise — a request never waits on warmup."""
        if self.state == STATE_READY:
            return self.engine.verify(items)
        return self._fallback(items)

    def _warm(self) -> None:
        try:
            stats = self.engine.warm()
        except Exception as e:  # noqa: BLE001 - any backend failure
            self._warm_error = f"{type(e).__name__}: {e}"
            self._set_state(STATE_CPU_ONLY)
            return
        reg = self.service.metrics_registry
        if reg.enabled:
            reg.gauge("pbft_verify_service_cold_compile_seconds").set(
                stats["cold_compile_s"]
            )
            reg.gauge("pbft_verify_service_warm_compile_seconds").set(
                stats["warm_load_s"]
            )
        self._set_state(STATE_READY)

    def start(self, wait_ready: bool = False, timeout: float = 900.0):
        self.service.start()
        if self.engine is None:
            self._set_state(STATE_CPU_ONLY)
            return self
        self._warm_thread = threading.Thread(target=self._warm, daemon=True)
        self._warm_thread.start()
        if wait_ready:
            self._warm_thread.join(timeout)
        return self

    def stop(self) -> None:
        self.service.stop()


# -- the replica-side client -------------------------------------------------


def _dial(target: str, timeout: float) -> socket.socket:
    if target.startswith("/"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(target)
        return sock
    host, port = target.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    # Socket-option discipline (ISSUE 10): every TCP dial sets
    # TCP_NODELAY — a 4-byte verify header must not sit in a Nagle stall.
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def probe_status(
    target: str, timeout: float = 0.5
) -> Optional[Tuple[int, int, int]]:
    """One short-deadline status probe: (state, devices, warmed) or None
    when the service is unreachable (or pre-handshake legacy: state READY
    with devices/warmed unknown is NOT inferred here — callers decide)."""
    try:
        with _dial(target, timeout) as sock:
            sock.sendall(STATUS_PROBE.to_bytes(4, "big"))
            return unpack_status(_recv_exact(sock, STATUS_LEN))
    except (OSError, ConnectionError, ValueError):
        return None


def probe_status_json(target: str, timeout: float = 2.0) -> Optional[dict]:
    """The JSON status (state, devices, warm stats …), or None."""
    try:
        with _dial(target, timeout) as sock:
            sock.sendall(STATUS_JSON_PROBE.to_bytes(4, "big"))
            n = int.from_bytes(_recv_exact(sock, 4), "big")
            if n > 1 << 20:
                return None
            return json.loads(_recv_exact(sock, n).decode())
    except (OSError, ConnectionError, ValueError):
        return None


class ServiceVerifier:
    """The asyncio runtime's remote-verifier client (Python mirror of
    ``core/verifier.cc`` RemoteVerifier): dial the colocated verify
    service with a SHORT connect deadline, consume the readiness
    handshake, and ship (pub, digest, sig) batches over the 128-byte
    protocol. Any failure — connect refused, service warming, killed
    mid-stream, wrong-length reply — degrades to the PR-2 native pool
    (``consensus.replica.host_batch_verify``) for that batch and backs
    off reconnecting, so the replica's verify loop NEVER stalls on the
    service's lifecycle. ``verify_batch`` never raises."""

    def __init__(
        self,
        target: str,
        fallback: Optional[Callable[[List[Item]], List[bool]]] = None,
        connect_timeout: float = 0.25,
        io_timeout: float = 30.0,
        retry_s: float = 1.0,
    ):
        self.target = target
        if fallback is None:
            from ..consensus.replica import host_batch_verify as fallback
        self._fallback = fallback
        self._connect_timeout = connect_timeout
        self._io_timeout = io_timeout
        self._retry_s = retry_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._retry_after = 0.0
        self.state: Optional[int] = None
        self.devices = 0
        self.used_fallback = 0  # batches the local pool absorbed

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self.state = None
        self._retry_after = time.monotonic() + self._retry_s

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            # Re-probe a warming service at the retry cadence; ready and
            # cpu-only connections are settled.
            if self.state != STATE_WARMING:
                return self.state in (STATE_READY, STATE_CPU_ONLY)
            if time.monotonic() < self._retry_after:
                return False
            self._retry_after = time.monotonic() + self._retry_s
            try:
                self._sock.sendall(STATUS_PROBE.to_bytes(4, "big"))
                st = unpack_status(_recv_exact(self._sock, STATUS_LEN))
            except (OSError, ConnectionError):
                st = None
            if st is None:
                self._drop()
                return False
            self.state, self.devices, _ = st
            return self.state in (STATE_READY, STATE_CPU_ONLY)
        if time.monotonic() < self._retry_after:
            return False
        try:
            sock = _dial(self.target, self._connect_timeout)
            sock.settimeout(self._io_timeout)
            sock.sendall(STATUS_PROBE.to_bytes(4, "big"))
            st = unpack_status(_recv_exact(sock, STATUS_LEN))
        except (OSError, ConnectionError):
            self._retry_after = time.monotonic() + self._retry_s
            return False
        if st is None:
            sock.close()
            self._retry_after = time.monotonic() + self._retry_s
            return False
        self._sock = sock
        self.state, self.devices, _ = st
        # Warming: keep the connection (the handshake was answered) but
        # serve from the fallback until a later probe reports ready.
        return self.state in (STATE_READY, STATE_CPU_ONLY)

    def verify_batch(self, items: List[Item]) -> List[bool]:
        if not items:
            return []
        with self._lock:
            if not self._ensure_connected():
                self.used_fallback += 1
                return self._fallback(items)
            try:
                payload = b"".join(p + m + s for p, m, s in items)
                self._sock.sendall(
                    len(items).to_bytes(4, "big") + payload
                )
                out = _recv_exact(self._sock, len(items))
                return [bool(b) for b in out]
            except (OSError, ConnectionError):
                # Killed mid-stream: drop the link (partial verdict bytes
                # must never pair with the next batch) and verify THIS
                # batch locally — the liveness contract.
                self._drop()
                self.used_fallback += 1
                return self._fallback(items)

    # API parity with the verdict-list contract used by the server's
    # verify loop (callable style).
    __call__ = verify_batch

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


def main(argv: Optional[List[str]] = None) -> None:
    """The verifyd CLI (scripts/verifyd.py is a thin path-setup wrapper)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="persistent multi-chip verify service daemon",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7600)
    parser.add_argument("--unix", default=None, help="unix socket path instead of TCP")
    parser.add_argument(
        "--backend",
        default="auto",
        choices=["auto", "jax", "native", "cpu"],
        help="auto/jax warm the sharded JAX engine (native-pool fallback "
        "while warming); native/cpu skip JAX entirely (state cpu-only)",
    )
    parser.add_argument(
        "--devices",
        type=int,
        default=None,
        help="shard windows over this many local devices (default: all)",
    )
    parser.add_argument(
        "--warm-shapes",
        default=os.environ.get("PBFT_SERVICE_WARM_SHAPES"),
        help="comma-separated window sizes to precompile (default: "
        "$PBFT_SERVICE_WARM_SHAPES, else the crypto pad ladder "
        "16,64,256,1024,4096)",
    )
    parser.add_argument(
        "--window",
        type=int,
        default=None,
        help="largest merged window in items (default: top of the ladder)",
    )
    parser.add_argument("--flush-us", type=int, default=0)
    parser.add_argument("--flush-items", type=int, default=0)
    parser.add_argument(
        "--inflight",
        type=int,
        default=2,
        help="overlapped launches; 2 = double-buffer window N+1's "
        "host->device transfer behind window N's compute",
    )
    parser.add_argument("--trace", default=None)
    parser.add_argument("--metrics-port", type=int, default=None)
    parser.add_argument(
        "--cache-dir",
        default=None,
        help="persistent compile cache ROOT (host-keyed subdir is "
        "appended); default: <repo>/.jax_cache",
    )
    parser.add_argument(
        "--export-dir",
        default=None,
        help="serialized-executable exports (warm restarts skip tracing); "
        "default: <cache-dir>/executables",
    )
    parser.add_argument(
        "--wait-ready",
        action="store_true",
        help="block until warmup finishes before announcing readiness "
        "on stdout (the socket still answers status probes meanwhile)",
    )
    args = parser.parse_args(argv)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    cache_root = args.cache_dir or os.path.join(repo_root, ".jax_cache")
    export_dir = args.export_dir or os.path.join(cache_root, "executables")
    shapes = (
        [int(s) for s in args.warm_shapes.split(",") if s]
        if args.warm_shapes
        else None
    )
    daemon = VerifyServiceDaemon(
        host=args.host,
        port=args.port,
        unix_path=args.unix,
        backend=args.backend,
        devices=args.devices,
        warm_shapes=shapes,
        max_window=args.window,
        flush_us=args.flush_us,
        flush_items=args.flush_items,
        inflight=args.inflight,
        trace_path=args.trace,
        metrics_port=args.metrics_port,
        cache_root=cache_root,
        export_dir=export_dir,
    )
    daemon.start(wait_ready=args.wait_ready)
    print(
        json.dumps(
            {
                "ev": "verify_service_listening",
                "addr": daemon.address,
                **daemon.status_json(),
            }
        ),
        flush=True,
    )
    try:
        while True:
            state = daemon.state
            time.sleep(0.25)
            if daemon.state != state:
                print(json.dumps(daemon.status_json()), flush=True)
    except KeyboardInterrupt:  # pragma: no cover - operator stop
        daemon.stop()


if __name__ == "__main__":  # pragma: no cover - CLI entry
    main()
