"""The TPU-native replica runtime: asyncio event loop + in-process JAX verifier.

Two deployment shapes ship with this framework (SURVEY.md §5):

1. ``pbftd`` (C++, core/net.cc) — the native daemon; its ``tpu`` verifier
   ships batches over a socket to the colocated VerifierService.
2. This module — replicas ARE the JAX process, so signature batches never
   cross a process boundary: the event loop drains every socket, then runs
   ONE batched XLA launch over everything that arrived (the batching
   window), then emits the resulting protocol messages.

Wire-compatible with pbftd: framed canonical JSON between replicas, raw
JSON with dial-back replies for clients (the reference's client contract,
reference src/client_handler.rs:75-84). A pbftd cluster and an
AsyncReplicaServer cluster interoperate — the encodings are byte-identical
(tests/test_native_messages.py).

Run one replica:  python -m pbft_tpu.net.server --config network.json \
                      --id 0 --seed <64-hex> [--verifier cpu|jax]
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..consensus.config import ClusterConfig
import hmac

from ..consensus.messages import (
    ClientReply,
    ClientRequest,
    Message,
    PrePrepare,
    batch_digest,
    decode_payload,
    from_wire,
    mac_frame_lane,
    payload_is_mac_frame,
    signable_from_payload,
    to_binary,
    to_binary_mac,
    with_sig,
)
from ..consensus.replica import (
    Broadcast,
    Replica,
    Reply,
    Send,
    _host_sign,
    host_batch_verify,
)
from ..utils import (
    ConsensusSpans,
    MetricsRegistry,
    count_open_fds,
    file_size_bytes,
    get_tracer,
    read_rss_bytes,
    start_metrics_server,
)
from ..utils.trace_schema import HEALTH_DOC_VERSION
from . import secure
from .gateway import GATEWAY_CLIENT_PREFIX


def _frame_bytes(payload: bytes) -> bytes:
    return len(payload).to_bytes(4, "big") + payload


class _PeerLink:
    """One dialed peer link: the stream writer, the secure channel (None
    on plaintext links), and the negotiated payload codec. ``binary``
    flips when the peer's hello (plaintext hello-ack or secure hello_r)
    offers the binary-v2 codec; frames sent before that go as JSON —
    receivers detect the codec per frame. ``mac`` (ISSUE 14): both sides
    offered the authenticator mode on this link, so hot messages go out
    as MAC-vector frames (the link's send lane key lives in the server's
    _mac_send_keys table, feeding the shared per-broadcast vector)."""

    __slots__ = ("writer", "chan", "binary", "mac")

    def __init__(self, writer, chan=None, binary=False, mac=False):
        self.writer = writer
        self.chan = chan
        self.binary = binary
        self.mac = mac


class _EncodedOut:
    """A message mid-fan-out: canonical JSON and binary-v2 encodings are
    computed lazily, AT MOST ONCE each, however many peers the message
    goes to (the serialize-once invariant). Encoding is synchronous, so
    concurrent _send_to tasks sharing one instance cannot race. When the
    owning server is set, each actual encode bumps its
    ``broadcast_encodes`` counter — the invariant test compares that
    against the broadcast count (encodes == broadcasts, never
    broadcasts x peers)."""

    __slots__ = (
        "msg", "_json", "_binary", "_binary_tried", "_mac", "_mac_tried",
        "_server",
    )

    def __init__(self, msg: Message, server=None):
        self.msg = msg
        self._json: Optional[bytes] = None
        self._binary: Optional[bytes] = None
        self._binary_tried = False
        self._mac: Optional[bytes] = None
        self._mac_tried = False
        self._server = server

    def _count(self) -> None:
        if self._server is not None:
            self._server.broadcast_encodes += 1
            if self._server.metrics_registry.enabled:
                self._server.metrics_registry.counter(
                    "pbft_broadcast_encodes_total"
                ).inc()

    def json_payload(self) -> bytes:
        if self._json is None:
            self._json = self.msg.canonical()
            self._count()
        return self._json

    def binary_payload(self) -> Optional[bytes]:
        if not self._binary_tried:
            self._binary_tried = True
            self._binary = to_binary(self.msg)
            if self._binary is not None:
                self._count()
        return self._binary

    def mac_payload(self, keys: Dict[int, bytes]) -> Optional[bytes]:
        """The MAC-vector frame (ISSUE 14), computed AT MOST ONCE per
        broadcast: one lane per peer in ``keys`` (the sender-side session
        keys of every mac-negotiated link), all over the message's
        signable digest — the serialize-once invariant extended to the
        authenticator mode. A peer whose link joins mid-fan-out misses
        its lane and falls back to signature verification (the sig rides
        in the frame), so staleness costs a signature check, never a
        drop. None when the type has no MAC form (or no mac links yet)."""
        if not self._mac_tried:
            self._mac_tried = True
            if keys:
                digest = self.msg.signable()
                self._mac = to_binary_mac(
                    self.msg,
                    [
                        (rid, secure.mac_tag(key, digest))
                        for rid, key in sorted(keys.items())
                    ],
                )
                if self._mac is not None:
                    self._count()
        return self._mac


def _frame_obj(obj: dict) -> bytes:
    return _frame_bytes(json.dumps(obj, separators=(",", ":")).encode())


# Replica-level Byzantine behavior modes (--fault, ISSUE 5). Same names as
# core/pbftd.cc --fault and the simulation's FAULT_MODES, so one chaos
# scenario scripts identically against either daemon. "" = honest.
FAULT_MODES = ("sig-corrupt", "mute", "stutter", "equivocate")

# Deterministic equivocation transform (matches core/net.cc and
# consensus/simulation.py): variant B mutates every operation with this
# suffix, recomputes the batch digest, and RE-SIGNS — both variants carry
# valid signatures, which is what makes equivocation a real attack.
EQUIV_SUFFIX = "#equiv"

# Bounded per-connection outbound (ISSUE 10, mirrors core/net.cc
# kMaxConnOutbound; constants lint): a frame that would grow a slow
# reader's write buffer past this is dropped and counted — PBFT
# retransmission absorbs the loss like any link drop.
MAX_CONN_OUTBOUND = 8 << 20
# Gateway route-cache bound (mirrors kMaxGatewayRoutes): on overflow the
# cache clears and un-routed "gw/" replies fan out over all gateway links.
MAX_GATEWAY_ROUTES = 1 << 17


class ViewTimerBackoff:
    """Pure §4.5.2 view-timer policy (ISSUE 12), shared semantics with
    core/net.cc check_progress_timer and unit-tested in
    tests/test_view_change.py. The runtime polls it with the current
    clock and progress markers; the policy answers what to do:

      "armed"      a fresh deadline was set (timeout_s x level)
      "idle"       deadline not reached yet
      "progress"   work advanced since arming — level resets to 1
      "retransmit" deadline expired mid-view-change, first expiry at this
                   level: re-broadcast the pending VIEW-CHANGE verbatim
                   (lost-frame recovery converges in the SAME view)
      "escalate"   deadline expired with no progress (again): start the
                   next view change; the level doubles (T, 2T, 4T, ...,
                   capped) so cascading view changes decelerate instead
                   of storming.
    """

    MAX_LEVEL = 64  # cap: 64 x T between escalations at the extreme

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self.level = 1
        self.deadline: Optional[float] = None
        self._snapshot = (0, 0)  # (executed_upto, view) at arm time
        self._retransmitted = False

    def clear(self) -> None:
        """No pending work: disarm and reset the backoff."""
        self.deadline = None
        self.level = 1
        self._retransmitted = False

    def poll(
        self, now: float, executed: int, view: int, in_view_change: bool
    ) -> str:
        if self.deadline is None:
            self._snapshot = (executed, view)
            self.deadline = now + self.timeout_s * self.level
            return "armed"
        if now < self.deadline:
            return "idle"
        self.deadline = None  # rearmed by the next poll while work pends
        exec_snap, view_snap = self._snapshot
        if executed > exec_snap or view > view_snap:
            self.level = 1
            self._retransmitted = False
            return "progress"
        if in_view_change and not self._retransmitted:
            self._retransmitted = True
            return "retransmit"
        self.level = min(self.level * 2, self.MAX_LEVEL)
        self._retransmitted = False
        return "escalate"


async def _read_frame(reader, timeout: float = 10.0) -> bytes:
    hdr = await asyncio.wait_for(reader.readexactly(4), timeout)
    n = int.from_bytes(hdr, "big")
    if n > (1 << 24):
        raise ConnectionError("oversized frame")
    return await asyncio.wait_for(reader.readexactly(n), timeout)


class AsyncReplicaServer:
    def __init__(
        self,
        config: ClusterConfig,
        replica_id: int,
        seed: bytes,
        verifier: Callable | str = "cpu",
        vc_timeout: float = 0.0,
        discovery: str = "",
        byzantine: bool = False,
        fault: str = "",
        chaos_drop_pct: float = 0.0,
        chaos_delay_ms: int = 0,
        chaos_seed: Optional[int] = None,
        metrics_port: Optional[int] = None,
        flight=None,
        wal=None,
    ):
        self.config = config
        self.id = replica_id
        self.replica = Replica(config, replica_id, seed)
        # Durable recovery (ISSUE 15, consensus/wal.py): attach the
        # write-ahead log (opened/replayed by main() BEFORE the event
        # loop — file I/O stays off the loop) and reinstall any
        # persisted pre-crash state. The recovery span is stamped into
        # the flight ring + the pbft_recovery_seconds gauge once the
        # metrics registry exists (below).
        self.wal = wal
        self.recovered_from_wal = False
        self._recovery_seconds = 0.0
        self._seen_wal = (0, 0, 0)  # (appends, fsyncs, bytes) snapshots
        if wal is not None:
            self.replica.wal = wal
            if not wal.recovered.empty():
                if flight is not None:
                    rec = wal.recovered
                    flight.record(
                        "recovery_started",
                        view=rec.view,
                        seq=rec.checkpoint[0] if rec.checkpoint else 0,
                    )
                t0 = time.monotonic()
                self.replica.restore_from_wal(wal.recovered)
                self._recovery_seconds = time.monotonic() - t0
                self.recovered_from_wal = True
                if flight is not None:
                    flight.record(
                        "recovery_complete",
                        view=self.replica.view,
                        seq=self.replica.executed_upto,
                    )
        # Metrics + consensus-phase spans (utils/metrics.py; names are the
        # cross-runtime contract in utils/trace_schema.py). The registry is
        # live whenever a scrape surface was asked for; spans additionally
        # feed consensus_span trace events when tracing is on. With neither,
        # phase_hook stays None — zero per-transition cost.
        self.metrics_registry = MetricsRegistry(
            labels={"replica": str(replica_id)}, enabled=metrics_port is not None
        )
        if self.metrics_registry.enabled:
            self.metrics_registry.preregister()  # full replica series set
        self.metrics_port = metrics_port
        self._metrics_server = None
        self.metrics_listen_port = 0
        # Black-box flight recorder (ISSUE 9, utils/flight.py): the last N
        # protocol events in a bounded ring, dumped on SIGTERM/fatal (the
        # runner installs the handler — see main()). None = one attribute
        # check per event site, like the tracer.
        self.flight = flight
        if self.metrics_registry.enabled or get_tracer().enabled:
            self.spans = ConsensusSpans(
                self.metrics_registry, tracer=get_tracer(), replica=replica_id
            )
            if flight is not None:
                _spans_hook = self.spans.on_phase
                _flight_hook = flight.record_phase

                def _phase(phase, view, seq):
                    _flight_hook(phase, view, seq)
                    _spans_hook(phase, view, seq)

                self.replica.phase_hook = _phase
            else:
                self.replica.phase_hook = self.spans.on_phase
        else:
            self.spans = None
            if flight is not None:
                self.replica.phase_hook = flight.record_phase
        # View-change spans (ROADMAP item 4): view_change_sent /
        # new_view_installed are rare reconfiguration events — the hook is
        # always wired; the tracer/flight checks inside gate the cost.
        self.replica.view_hook = self._on_view_event
        # When the primary's open batch first became non-empty (monotonic)
        # — the "batch wait" waterfall segment measured at seal time.
        self._batch_open_since: Optional[float] = None
        if self.metrics_registry.enabled:
            # Batch occupancy at every pre-prepare accept (ISSUE 4).
            _batch_hist = self.metrics_registry.histogram("pbft_batch_size")
            self.replica.batch_hook = _batch_hist.observe
        # Last-seen replica execution counters, for the
        # pbft_requests_executed_total / pbft_consensus_rounds_total deltas.
        self._seen_executed = 0
        self._seen_rounds = 0
        self.service_verifier = None
        if callable(verifier):
            self.verify = verifier
        elif verifier == "jax":
            # The service-layer backend auto-shards over a multi-device
            # mesh and reduces to the single-chip path otherwise.
            from .service import jax_backend

            self.verify = jax_backend
        elif verifier not in ("", "cpu") and (
            ":" in verifier or verifier.startswith("/")
        ):
            # A "host:port" / unix-path spec dials the colocated verify
            # service (mirror of pbftd's RemoteVerifier): short connect
            # deadline, readiness handshake, and the PR-2 native pool as
            # the per-batch fallback whenever the service is warming,
            # unreachable, or dies mid-stream — consensus never blocks
            # on a cold accelerator.
            from .verify_service import ServiceVerifier

            self.service_verifier = ServiceVerifier(verifier)
            self.verify = self.service_verifier.verify_batch
        else:
            # Host CPU arm (consensus.replica.host_batch_verify): the
            # native C++ batch verifier when built (114 us/item), else
            # the pure-Python oracle (~8 ms/item). Byte-identical accept
            # sets (tests/test_native_crypto.py), so the choice cannot
            # diverge replicas.
            self.verify = host_batch_verify
        self.vc_timeout = vc_timeout
        self.secure = config.secure
        self._seed = seed
        # Fast-path modes (ISSUE 14): whether this node OFFERS the MAC
        # authenticator mode in its hellos (config.fastpath == "mac",
        # unless an env lever capped the advertised protocol), the
        # per-dest sender-side lane keys of every mac-negotiated link
        # (feeding the shared per-broadcast MAC vector), and the frame
        # tallies. Tentative execution is config-driven inside Replica;
        # the runtime only stamps its flight/metrics surface.
        self.fastpath_mac = secure.wire_offer_mac(config.fastpath == "mac")
        self._mac_send_keys: Dict[int, bytes] = {}
        self.mac_frames = 0
        self.mac_rejected = 0
        self._seen_tentative = 0
        self._seen_rollbacks = 0
        self.discovery_target = discovery
        self._discovery = None
        self._warned_no_discovery = False
        # Fault injection (ISSUE 5, parity with pbftd --fault): one of
        # FAULT_MODES, or "" for honest. ``byzantine`` is the legacy
        # spelling of sig-corrupt. Self-delivery stays honest in every
        # mode (a Byzantine replica trusts its own messages).
        if fault and fault not in FAULT_MODES:
            raise ValueError(f"unknown fault mode {fault!r}")
        self.fault = fault or ("sig-corrupt" if byzantine else "")
        # Seeded link-level chaos (--chaos-drop-pct / --chaos-delay-ms):
        # outbound peer frames drop with probability drop_pct; delay
        # holds each send for a uniform 0..delay_ms. Per-destination
        # ordering is preserved (the per-dest link lock serializes the
        # seal+write), so secure-channel AEAD nonces stay in sequence.
        self.chaos_drop_pct = chaos_drop_pct
        self.chaos_delay_ms = chaos_delay_ms
        self._chaos_rng = random.Random(
            chaos_seed if chaos_seed is not None else replica_id
        )
        self.faults_injected = 0
        self.chaos_dropped = 0
        # Recently broadcast messages, for the stutter mode's replays.
        self._stutter_history: List[Message] = []
        self._server: Optional[asyncio.Server] = None
        # Gateway tier (ISSUE 10): inbound links whose hello carried
        # role=gateway. Framed client requests arrive on them; replies for
        # the clients they forwarded fan BACK over the same link instead
        # of per-reply dial-backs. link id -> writer, plus the bounded
        # client-token route cache (on overflow it clears and un-routed
        # "gw/" replies fan out over every gateway link).
        self._gateway_links: Dict[int, asyncio.StreamWriter] = {}
        self._gateway_routes: Dict[str, int] = {}
        self._gateway_link_seq = 0
        self.gateway_forwarded = 0
        # Event-loop + backpressure accounting (ISSUE 10): stream-read
        # completions (the asyncio analogue of poller wakeups), and
        # bounded-outbound drops against slow readers.
        self.event_wakeups = 0
        self.backpressure_events = 0
        self._conns_open = 0
        # dest -> _PeerLink; guarded by a per-dest lock so one handshake
        # runs per destination and sealed-frame counters never interleave.
        self._peer_links: Dict[int, _PeerLink] = {}
        self._peer_locks: Dict[int, asyncio.Lock] = {}
        self._batch_wakeup = asyncio.Event()
        # Pending seal of the primary's partial request batch (ISSUE 4):
        # armed when the open batch first becomes non-empty, fires after
        # config.batch_flush_us (0 = the next loop turn, which still
        # coalesces everything already queued on the event loop).
        self._batch_flush_handle: Optional[asyncio.TimerHandle] = None
        self._stopping = False
        self.listen_port = 0
        self.batches_run = 0
        self.frames_in = 0
        # Serialize-once accounting (metrics() + the counter-based
        # invariant test): encodes track broadcasts, never
        # broadcasts x peers. Frame counters split by negotiated codec.
        self.broadcasts = 0
        self.broadcast_encodes = 0
        self.codec_binary_frames = 0
        self.codec_json_frames = 0
        # Reply-dial pacing (mirrors core/net.cc start_reply_dial): the
        # reply address is UNTRUSTED client input, so dials are
        # deadline-bounded, capped in flight, and serialized per address
        # (an asyncio.Lock wakes waiters FIFO, so replies to one client
        # go out in order with zero polling) — a burst of black-holed
        # addresses must not accumulate tasks/FDs for the OS connect
        # timeout. A dropped reply is re-fetched from the reply cache on
        # client retransmission (PBFT §4.1).
        self._reply_dial_sem = asyncio.Semaphore(32)
        self._reply_addr_locks: Dict[str, asyncio.Lock] = {}
        self._reply_addr_refs: Dict[str, int] = {}
        # Progress timer state (mirrors core/net.cc check_progress_timer):
        # the ViewTimerBackoff policy decides retransmit-vs-escalate and
        # the exponential level (ISSUE 12, §4.5.2).
        self._waiting_requests: Dict[Tuple[str, int], float] = {}
        self._state_retry_deadline: Optional[float] = None
        self._vc_policy = ViewTimerBackoff(vc_timeout)
        self._gauged_backoff = 1  # last backoff level pushed to the gauge
        # Admission control (ISSUE 12): explicit overload rejections
        # instead of silent queueing — config.admission_inflight caps a
        # client's estimated in-flight requests (timestamp distance past
        # its last executed one), config.admission_backlog watermarks the
        # replica's own backlog (verify inbox + sealed-but-unexecuted
        # sequences). 0 disables either check.
        self.overload_rejections = 0
        # Gateway-fabric accounting (ISSUE 12): live gateway links that
        # died (clients behind them must fail over to another gateway).
        self.gateway_failovers = 0
        # Health-document progress tracker (ISSUE 16; mirrors
        # core/net.cc refresh_health): the executed_upto we last saw
        # move and when we saw it — last_progress_seconds is quantized
        # to the refresh cadence (every metrics()/status render).
        self._start_time = time.monotonic()
        self._progress_seen_executed = -1
        self._progress_seen_at = time.monotonic()

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "AsyncReplicaServer":
        ident = self.config.identity(self.id)
        self._server = await asyncio.start_server(
            self._on_connection, host="0.0.0.0", port=ident.port
        )
        self.listen_port = self._server.sockets[0].getsockname()[1]
        # Multi-core key (ISSUE 13): pbftd shards its event loop across
        # net_threads; this runtime is one asyncio loop by design — accept
        # the network.json key, say so, and expose the gauge as 1 so a
        # mixed-runtime scrape attributes per-replica loop counts
        # honestly. The offload-depth gauge and cross-thread-wake counter
        # exist for series-set parity (no crypto pipelines here: both
        # stay 0).
        if self.config.net_threads > 1:
            print(
                f"async replica {self.id}: net_threads="
                f"{self.config.net_threads} requested; asyncio runtime is "
                "single-loop (key accepted, sharding is pbftd-only)",
                flush=True,
            )
        if self.metrics_registry.enabled:
            self.metrics_registry.gauge("pbft_net_loop_threads").set(1)
            self.metrics_registry.gauge(
                "pbft_crypto_offload_queue_depth"
            ).set(0)
            self.metrics_registry.counter(
                "pbft_cross_thread_wakes_total"
            ).inc(0)
            # Durable-recovery surface (ISSUE 15): how long the WAL
            # replay + reinstall took (0 = this life started fresh).
            self.metrics_registry.gauge("pbft_recovery_seconds").set(
                round(self._recovery_seconds, 6)
            )
        if self.discovery_target:
            from .discovery import Discovery

            self._discovery = await Discovery(
                self.discovery_target, self.id, self.listen_port, self.config.n
            ).start()
        if self.metrics_port is not None:
            # /status serves the health document (ISSUE 16). metrics()
            # runs on the scrape thread there: it only reads GIL-atomic
            # runtime state (ints, preset-key dicts) — same contract as
            # the registry reads the Prometheus path does.
            self._metrics_server = start_metrics_server(
                self.metrics_registry, self.metrics_port,
                status_fn=self.metrics,
            )
            self.metrics_listen_port = self._metrics_server.server_address[1]
        asyncio.get_running_loop().create_task(self._batch_pump())
        if self.vc_timeout > 0:
            asyncio.get_running_loop().create_task(self._timer_loop())
        return self

    async def stop(self) -> None:
        self._stopping = True
        if self._batch_flush_handle is not None:
            self._batch_flush_handle.cancel()
            self._batch_flush_handle = None
        self._batch_wakeup.set()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()
        if self._discovery:
            self._discovery.stop()
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for link in self._peer_links.values():
            link.writer.close()

    # -- inbound ------------------------------------------------------------

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conn_delta(+1)
        try:
            first = await reader.read(1)
            if not first:
                return
            if first == b"{":
                await self._client_connection(first, reader)
            else:
                await self._peer_connection(first, reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError, asyncio.TimeoutError):
            pass
        finally:
            self._conn_delta(-1)
            writer.close()

    # -- scale-out accounting (ISSUE 10) -------------------------------------

    def _conn_delta(self, d: int) -> None:
        """Track open sockets (accepted + dialed peer links) and refresh
        the pbft_connections_open gauge — parity with core/net.cc's
        end-of-iteration sweep."""
        self._conns_open += d
        if self.metrics_registry.enabled:
            self.metrics_registry.gauge("pbft_connections_open").set(
                max(0, self._conns_open) + len(self._peer_links)
            )

    def _count_wakeup(self) -> None:
        """One event-loop readiness wakeup serviced (a stream read
        completed) — the asyncio analogue of a poller wait() return."""
        self.event_wakeups += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter("pbft_epoll_wakeups_total").inc()

    def _count_backpressure(self) -> None:
        self.backpressure_events += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter(
                "pbft_write_backpressure_events_total"
            ).inc()

    def _writer_has_room(self, writer: asyncio.StreamWriter) -> bool:
        """Bounded-outbound admission (ISSUE 10 satellite, mirrors
        core/net.cc): a frame that would grow a slow reader's transport
        buffer past MAX_CONN_OUTBOUND is dropped and counted instead of
        buffering without limit — retransmission absorbs the loss."""
        try:
            size = writer.transport.get_write_buffer_size()
        except (AttributeError, RuntimeError):
            return True
        if size > MAX_CONN_OUTBOUND:
            self._count_backpressure()
            return False
        return True

    # A raw-JSON client line may not exceed this; longer input is a
    # protocol violation (or an attack) and drops the connection instead
    # of buffering without bound.
    MAX_CLIENT_LINE = 1 << 20

    def _ingest_client_line(self, line: bytes) -> None:
        line = line.strip()
        if not line:
            return
        try:
            msg = from_wire(line)
        except (ValueError, KeyError, json.JSONDecodeError):
            return
        self._ingest(msg)

    async def _client_connection(self, first: bytes, reader) -> None:
        # Raw JSON, one message per line (telnet-able, like the reference's
        # gateway). Proper line buffering: requests larger than one read()
        # are reassembled, and a line above MAX_CLIENT_LINE drops the
        # connection (bounded buffering on an unauthenticated socket).
        buf = first
        while True:
            nl = buf.find(b"\n")
            if nl >= 0:
                line, buf = buf[:nl], buf[nl + 1 :]
                self._ingest_client_line(line)
                continue
            if len(buf) > self.MAX_CLIENT_LINE:
                return  # oversized line: drop the connection
            chunk = await reader.read(65536)
            if not chunk:
                break
            self._count_wakeup()
            buf += chunk
        self._ingest_client_line(buf)  # trailing JSON without newline

    def _pubkey_of(self, node: int) -> Optional[bytes]:
        if 0 <= node < self.config.n:
            return self.config.identity(node).pubkey_bytes()
        return None

    async def _peer_connection(self, first: bytes, reader, writer) -> None:
        """Framed replica link. The first frame must be a ``hello`` carrying
        the protocol version (rejected cleanly on mismatch); in secure
        clusters the responder side of the handshake runs here and every
        subsequent frame is AEAD-opened before parsing."""
        buf = first
        chan: Optional[secure.SecureChannel] = None
        hello_seen = False
        # Gateway link state (ISSUE 10): set when the hello carried
        # role=gateway; cleaned up on disconnect so replies stop fanning
        # to a dead link (stale routes fall back to the all-links fan-out,
        # which skips the removed id).
        gw_link_id: Optional[int] = None
        try:
            while True:
                while len(buf) < 4:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    self._count_wakeup()
                    buf += chunk
                n = int.from_bytes(buf[:4], "big")
                if n > (1 << 24):
                    return  # corrupt frame
                while len(buf) < 4 + n:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    self._count_wakeup()
                    buf += chunk
                payload, buf = buf[4 : 4 + n], buf[4 + n :]
                if not hello_seen or (
                    chan is not None and not chan.established
                ):
                    try:
                        obj = json.loads(payload)
                    except (ValueError, UnicodeDecodeError):
                        obj = None
                    try:
                        if not hello_seen:
                            if (
                                not isinstance(obj, dict)
                                or obj.get("type") != "hello"
                            ):
                                if self.secure:
                                    raise secure.HandshakeError(
                                        "plaintext peer rejected: first "
                                        "frame must be an encrypted-link "
                                        "hello"
                                    )
                                # Plaintext cluster: tolerate a missing
                                # hello (raw protocol frame) for tooling
                                # compat.
                                hello_seen = True
                            else:
                                secure.SecureChannel.check_version(obj)
                                hello_seen = True
                                peer_mac = (
                                    self.fastpath_mac
                                    and secure.hello_offers_mac(obj)
                                )
                                if obj.get("role") == "gateway":
                                    # Gateway trust (ISSUE 10, parity with
                                    # core/net.cc): framed client requests
                                    # arrive on this link; replies for the
                                    # clients it forwarded fan BACK over
                                    # it. A gateway has no replica
                                    # identity, so the signed-DH handshake
                                    # cannot admit one: plaintext only.
                                    if self.secure:
                                        raise secure.HandshakeError(
                                            "gateway links require a "
                                            "plaintext cluster (a gateway "
                                            "has no replica identity to "
                                            "authenticate)"
                                        )
                                    self._gateway_link_seq += 1
                                    gw_link_id = self._gateway_link_seq
                                    self._gateway_links[gw_link_id] = writer
                                if self.secure:
                                    chan = secure.SecureChannel(
                                        self.id,
                                        self._seed,
                                        self._pubkey_of,
                                        initiator=False,
                                        offer_mac=self.fastpath_mac,
                                    )
                                    reply = chan.on_hello(obj)
                                    writer.write(_frame_obj(reply))
                                    await writer.drain()
                                elif peer_mac and isinstance(
                                    obj.get("eph"), str
                                ):
                                    # Authenticator mode on a plaintext
                                    # cluster (ISSUE 14): run the SAME
                                    # signed station-to-station handshake
                                    # purely for lane-key agreement +
                                    # peer identity — frames after it
                                    # stay plaintext (auth-only channel,
                                    # never sealed/opened).
                                    chan = secure.SecureChannel(
                                        self.id,
                                        self._seed,
                                        self._pubkey_of,
                                        initiator=False,
                                        offer_mac=self.fastpath_mac,
                                        auth_only=True,
                                    )
                                    reply = chan.on_hello(obj)
                                    writer.write(_frame_obj(reply))
                                    await writer.drain()
                                else:
                                    # Plaintext hello-ack: advertise this
                                    # node's version + codec offer so the
                                    # dialing peer can negotiate binary-v2
                                    # (a 1.0.0 initiator parses and
                                    # ignores any non-reject frame).
                                    writer.write(
                                        _frame_obj(
                                            secure.plain_hello(
                                                self.id,
                                                offer_mac=self.fastpath_mac,
                                            )
                                        )
                                    )
                                    await writer.drain()
                                continue
                        elif chan is not None:
                            if (
                                not isinstance(obj, dict)
                                or obj.get("type") != "auth"
                            ):
                                raise secure.HandshakeError(
                                    "expected auth frame"
                                )
                            chan.on_auth(obj)
                            continue
                    except secure.HandshakeError as e:
                        try:
                            writer.write(
                                _frame_obj(secure.reject_payload(str(e)))
                            )
                            await writer.drain()
                        except (ConnectionError, OSError):
                            pass
                        return
                if chan is not None and not chan.auth_only:
                    try:
                        payload = chan.open_frame(payload)
                    except secure.HandshakeError:
                        return  # tampered/desynced stream: drop the conn
                try:
                    msg = decode_payload(payload)
                except (ValueError, KeyError, json.JSONDecodeError):
                    continue
                if gw_link_id is not None and isinstance(msg, ClientRequest):
                    # Remember the forwarding link so this client's reply
                    # fans back over it (exact route; the "gw/" fan-out
                    # fallback covers replicas that only saw the request
                    # via pre-prepare).
                    self._note_gateway_route(msg.client, gw_link_id)
                    self.gateway_forwarded += 1
                    if self.metrics_registry.enabled:
                        self.metrics_registry.counter(
                            "pbft_gateway_forwarded_total"
                        ).inc()
                if (
                    chan is not None
                    and chan.established
                    and chan.mac_negotiated
                    and payload_is_mac_frame(payload)
                ):
                    self._ingest_mac(msg, payload, chan)
                else:
                    self._ingest(msg, payload)
        finally:
            if gw_link_id is not None:
                self._gateway_links.pop(gw_link_id, None)
                if not self._stopping:
                    # A live gateway link died (ISSUE 12): clients behind
                    # it must fail over to another gateway — count it so
                    # the chaos bench can attribute the blip.
                    self.gateway_failovers += 1
                    if self.metrics_registry.enabled:
                        self.metrics_registry.counter(
                            "pbft_gateway_failovers_total"
                        ).inc()
                    if self.flight is not None:
                        self.flight.record(
                            "gateway_failover",
                            view=self.replica.view,
                            peer=gw_link_id & 0x7FFF,
                        )

    def _note_gateway_route(self, client: str, link_id: int) -> None:
        """Bounded route cache (mirrors core/net.cc note_gateway_route):
        on overflow it CLEARS — un-routed replies degrade to the all-links
        fan-out, extra frames but never lost quorums."""
        if len(self._gateway_routes) >= MAX_GATEWAY_ROUTES:
            self._gateway_routes.clear()
        self._gateway_routes[client] = link_id

    def _on_view_event(self, ev: str, v: int) -> None:
        """Replica.view_hook target: stamp view-change span events."""
        if self.flight is not None:
            self.flight.record(ev, view=v)
        tracer = get_tracer()
        if not tracer.enabled:
            return
        if ev == "view_change_sent":
            tracer.event("view_change_sent", replica=self.id, pending_view=v)
        else:
            tracer.event("new_view_installed", replica=self.id, view=v)

    def _admission_reject(self, req: ClientRequest) -> bool:
        """Admission control at request ingest (ISSUE 12): a FRESH request
        past the per-client in-flight cap or the global backlog watermark
        is answered with an explicit {"type": "overloaded"} line (over the
        gateway link or the dial-back channel) and dropped — the client
        backs off with jitter instead of silently queueing into the p99.
        Retransmissions (timestamp at or below the client's last executed
        one) always pass: the reply cache answers them, and liveness must
        never be admission-gated. Mirrors core/net.cc."""
        cfg = self.config
        if cfg.admission_inflight <= 0 and cfg.admission_backlog <= 0:
            return False
        last = self.replica.last_timestamp.get(req.client, 0)
        if req.timestamp <= last:
            return False
        reject = (
            cfg.admission_inflight > 0
            and req.timestamp - last > cfg.admission_inflight
        )
        if not reject and cfg.admission_backlog > 0:
            backlog = self.replica.pending_count() + max(
                0, self.replica.seq_counter - self.replica.executed_upto
            )
            reject = backlog > cfg.admission_backlog
        if not reject:
            return False
        self.overload_rejections += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter(
                "pbft_overload_rejections_total"
            ).inc()
        if self.flight is not None:
            self.flight.record(
                "overload_rejected",
                view=self.replica.view,
                seq=req.timestamp,
            )
        payload = json.dumps(
            {
                "type": "overloaded",
                "client": req.client,
                "timestamp": req.timestamp,
                "replica": self.id,
            },
            separators=(",", ":"),
        ).encode()
        if req.client.startswith(GATEWAY_CLIENT_PREFIX):
            self._gateway_line(req.client, payload)
        else:
            asyncio.get_running_loop().create_task(
                self._dial_line(req.client, payload + b"\n")
            )
        return True

    def _ingest_mac(self, msg: Message, payload: bytes, chan) -> None:
        """One MAC-vector frame off an authenticator-mode link: verify
        this replica's lane against the link's session key and the
        message's claimed sender against the link's authenticated peer,
        then dispatch WITHOUT the verify queue (the whole point — zero
        hot-path signature verification). A frame with no lane for us
        (link joined mid-fan-out) falls back to the signature path the
        embedded sig still serves; a lane MISMATCH is dropped and
        counted (a tampered or replayed-across-links frame)."""
        lane = mac_frame_lane(payload, self.id)
        if lane is None:
            self._ingest(msg, payload)
            return
        expected = secure.mac_tag(
            chan.auth_recv_key, signable_from_payload(payload, msg)
        )
        if not hmac.compare_digest(lane, expected) or (
            getattr(msg, "replica", None) != chan.peer_id
        ):
            self.mac_rejected += 1
            return
        self.frames_in += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter("pbft_frames_in_total").inc()
        actions = self.replica.receive_authenticated(msg)
        if actions:
            self._emit(actions)
        self._batch_wakeup.set()

    def _ingest(self, msg: Message, payload: Optional[bytes] = None) -> None:
        self.frames_in += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter("pbft_frames_in_total").inc()
        if isinstance(msg, ClientRequest):
            if self._admission_reject(msg):
                return
            # Request-level waterfall anchor (ISSUE 9): when this replica
            # first saw the request — on the primary, the start of the
            # client-queue -> batch-wait handoff.
            if self.flight is not None:
                self.flight.record(
                    "request_rx", view=self.replica.view, seq=msg.timestamp
                )
            tracer = get_tracer()
            if tracer.enabled:
                tracer.event(
                    "request_rx",
                    replica=self.id,
                    client=msg.client,
                    req_ts=msg.timestamp,
                )
        if payload is not None and not isinstance(msg, ClientRequest):
            # Receive-side canonical reuse: derive the signable digest
            # from the framed bytes (sig-splice for JSON; the binary path
            # falls through to the fixed signable template) so the verify
            # queue never re-serializes. The raw client gateway passes no
            # payload — its input is not guaranteed canonical.
            actions = self.replica.receive(
                msg, signable_from_payload(payload, msg)
            )
        else:
            actions = self.replica.receive(msg)
        if actions:
            self._emit(actions)
        if self.replica.open_batch_size() > 0:
            if self._batch_open_since is None:
                self._batch_open_since = time.monotonic()
            if self._batch_flush_handle is None:
                self._batch_flush_handle = (
                    asyncio.get_running_loop().call_later(
                        self.config.batch_flush_us / 1e6,
                        self._flush_open_batch,
                    )
                )
        self._batch_wakeup.set()

    def _flush_open_batch(self) -> None:
        """batch_flush_us expired: seal the partial batch. A seal refused
        by a closed watermark window keeps the batch open — re-arm so the
        next tick retries instead of dropping the requests."""
        self._batch_flush_handle = None
        self._emit(self.replica.flush_open_batch())
        if self.replica.open_batch_size() > 0 and not self._stopping:
            self._batch_flush_handle = asyncio.get_running_loop().call_later(
                max(self.config.batch_flush_us / 1e6, 0.001),
                self._flush_open_batch,
            )
        self._batch_wakeup.set()

    # -- the batching window -------------------------------------------------

    async def _batch_pump(self) -> None:
        """Drain -> one batched verify (one XLA launch) -> emit, forever."""
        loop = asyncio.get_running_loop()
        flush_s = self.config.verify_flush_us / 1e6
        flush_target = self.config.verify_flush_items or self.config.batch_pad
        while not self._stopping:
            await self._batch_wakeup.wait()
            self._batch_wakeup.clear()
            if flush_s > 0 and self.replica.pending_count():
                # Bounded accumulation (config.verify_flush_us/_items):
                # hold the queue until the item target or the deadline so
                # one launch carries a whole window, not one wakeup's
                # trickle. Socket readers keep appending meanwhile.
                deadline = loop.time() + flush_s
                while (
                    not self._stopping
                    and self.replica.pending_count() < flush_target
                ):
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    await asyncio.sleep(min(remaining, flush_s / 8))
            items = self.replica.pending_items()
            if not items:
                continue
            self.batches_run += 1
            if self.metrics_registry.enabled:  # batch boundaries only, like tracing
                self.metrics_registry.gauge("pbft_verify_queue_depth").set(len(items))
            # The JAX call blocks; run it off the event loop so sockets
            # keep draining into the next batch meanwhile.
            t0 = time.monotonic()
            verdicts = await loop.run_in_executor(None, self.verify, items)
            secs = time.monotonic() - t0
            if self.metrics_registry.enabled:
                self.metrics_registry.counter("pbft_verify_batches_total").inc()
                self.metrics_registry.counter("pbft_verify_items_total").inc(len(items))
                self.metrics_registry.counter("pbft_verify_rejected_total").inc(
                    verdicts.count(False)
                )
                self.metrics_registry.histogram("pbft_verify_batch_size").observe(len(items))
                self.metrics_registry.histogram("pbft_verify_seconds").observe(secs)
                # In-process verifier: the "inflight age" IS the last
                # launch's round trip (mirrors the C++ async gauge).
                self.metrics_registry.gauge("pbft_verify_inflight_age_seconds").set(
                    round(secs, 6)
                )
            if self.flight is not None:
                self.flight.record(
                    "verify_batch",
                    view=self.replica.view,
                    seq=len(items),
                    peer=verdicts.count(False),
                )
            tracer = get_tracer()
            if tracer.enabled:  # batch boundaries only — never per message
                tracer.event(
                    "verify_batch",
                    replica=self.id,
                    size=len(items),
                    rejected=verdicts.count(False),
                    secs=round(secs, 6),
                    view=self.replica.view,
                    executed=self.replica.executed_upto,
                )
            self._emit(self.replica.deliver_verdicts(verdicts))

    # -- outbound ------------------------------------------------------------

    def _count_fault(self) -> None:
        self.faults_injected += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter("pbft_faults_injected_total").inc()

    def _equivocate_variant(self, pp: PrePrepare) -> Message:
        """Variant B of this primary's own pre-prepare: operations
        mutated, digest recomputed, re-signed (mirrors core/net.cc)."""
        reqs_b = tuple(
            dataclasses.replace(r, operation=r.operation + EQUIV_SUFFIX)
            for r in pp.requests
        )
        variant = dataclasses.replace(
            pp, requests=reqs_b, digest=batch_digest(reqs_b), sig=""
        )
        return with_sig(
            variant, _host_sign(self._seed, variant.signable()).hex()
        )

    def _broadcast(self, loop, msg: Message) -> None:
        """One serialize-once fan-out of ``msg`` to every peer."""
        self.broadcasts += 1
        enc = _EncodedOut(self._corrupt_sig(msg), server=self)
        for dest in range(self.config.n):
            if dest != self.id:
                loop.create_task(self._send_to(dest, enc))

    def _trace_batch_sealed(self, pp: PrePrepare) -> None:
        """The primary sealed a batch (its own pre-prepare broadcast):
        emit the waterfall join record — (view, seq) plus the ordered
        [client, req_ts] keys and how long the batch waited open."""
        wait = 0.0
        if self._batch_open_since is not None:
            wait = max(0.0, time.monotonic() - self._batch_open_since)
            self._batch_open_since = None
        tracer = get_tracer()
        if tracer.enabled:
            tracer.event(
                "batch_sealed",
                replica=self.id,
                view=pp.view,
                seq=pp.seq,
                batch=len(pp.requests),
                wait_s=round(wait, 6),
                reqs=[[r.client, r.timestamp] for r in pp.requests],
            )

    def _flush_wal(self) -> None:
        """Group commit (ISSUE 15): one write + one fsync for every WAL
        record noted since the last emit boundary — durability BEFORE
        visibility, off the per-message path. Sync on purpose: the send
        tasks _emit creates only run after this method returns, so no
        vote can reach a socket before it is durable."""
        wal = self.wal
        if wal is None or not wal.pending():
            return
        wal.flush()
        if self.metrics_registry.enabled:
            a0, f0, b0 = self._seen_wal
            reg = self.metrics_registry
            reg.counter("pbft_wal_appends_total").inc(wal.appends - a0)
            reg.counter("pbft_wal_fsyncs_total").inc(wal.fsyncs - f0)
            reg.counter("pbft_wal_bytes_total").inc(wal.bytes_written - b0)
        self._seen_wal = (wal.appends, wal.fsyncs, wal.bytes_written)

    def _emit(self, actions: List) -> None:
        if self.wal is not None:
            self._flush_wal()
        loop = asyncio.get_running_loop()
        mute = self.fault == "mute"
        for act in actions:
            if isinstance(act, Broadcast):
                if (
                    isinstance(act.msg, PrePrepare)
                    and act.msg.replica == self.id
                ):
                    # Seal observed BEFORE the fault modes: even a muted
                    # or equivocating primary sealed locally. (The flight
                    # record comes from the "request" phase transition.)
                    self._trace_batch_sealed(act.msg)
                if mute:  # receives but never sends (--fault mute)
                    self._count_fault()
                    continue
                if (
                    self.fault == "equivocate"
                    and isinstance(act.msg, PrePrepare)
                    and act.msg.replica == self.id
                    and act.msg.requests
                ):
                    # The equivocating primary forks its own pre-prepare:
                    # even peers get the genuine batch, odd peers a
                    # conflicting validly-signed one — same (view, seq),
                    # different digest. At <= f faulty neither side can
                    # reach a commit quorum; the honest replicas' timers
                    # must vote this primary out.
                    self._count_fault()
                    self.broadcasts += 1
                    enc_a = _EncodedOut(act.msg, server=self)
                    enc_b = _EncodedOut(
                        self._equivocate_variant(act.msg), server=self
                    )
                    for dest in range(self.config.n):
                        if dest != self.id:
                            loop.create_task(
                                self._send_to(
                                    dest, enc_a if dest % 2 == 0 else enc_b
                                )
                            )
                    continue
                # Serialize-once fan-out: ONE canonical encode (and at
                # most one binary-v2 encode, when any link negotiated it)
                # per broadcast, shared by every destination task. The
                # Byzantine corruption is applied once too.
                self._broadcast(loop, act.msg)
                if self.fault == "stutter":
                    # Seeded stale replays alongside the fresh broadcast:
                    # honest replicas must treat the replay as the
                    # duplicate it is.
                    if self._stutter_history and self._chaos_rng.random() < 0.3:
                        self._count_fault()
                        self._broadcast(
                            loop, self._chaos_rng.choice(self._stutter_history)
                        )
                    self._stutter_history.append(act.msg)
                    del self._stutter_history[:-32]
            elif isinstance(act, Send):
                if isinstance(act.msg, ClientRequest) and self.vc_timeout > 0:
                    self._waiting_requests[
                        (act.msg.client, act.msg.timestamp)
                    ] = time.monotonic() + self.vc_timeout
                if act.dest == self.id:
                    self._ingest(act.msg)
                elif mute:
                    self._count_fault()
                else:
                    loop.create_task(
                        self._send_to(
                            act.dest, _EncodedOut(self._corrupt_sig(act.msg))
                        )
                    )
            elif isinstance(act, Reply):
                self._waiting_requests.pop(
                    (act.msg.client, act.msg.timestamp), None
                )
                if mute:  # a mute replica never dials the client back
                    self._count_fault()
                    continue
                if self.flight is not None:
                    self.flight.record(
                        "reply_tx", view=act.msg.view, seq=act.msg.timestamp
                    )
                    if act.msg.tentative:
                        # Fast-path coverage (ISSUE 14): the reply left
                        # at PREPARED, one commit round-trip early.
                        self.flight.record(
                            "tentative_reply",
                            view=act.msg.view,
                            seq=act.msg.timestamp,
                        )
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.event(
                        "reply_tx",
                        replica=self.id,
                        client=act.msg.client,
                        req_ts=act.msg.timestamp,
                        view=act.msg.view,
                    )
                if act.client.startswith(GATEWAY_CLIENT_PREFIX):
                    # Gateway-routed client (ISSUE 10): the "address" is a
                    # routing token, never dialable — one framed write on
                    # the persistent gateway link instead of a dial-back.
                    self._gateway_reply(act.client, act.msg)
                else:
                    loop.create_task(self._dial_reply(act.client, act.msg))
        # Tentative-execution surface (ISSUE 14): counter deltas + the
        # rollback flight record (a rollback is a rare, load-bearing
        # event — exactly what the black box exists to capture).
        t_roll = self.replica.counters["tentative_rollbacks"]
        if t_roll > self._seen_rollbacks:
            if self.flight is not None:
                self.flight.record(
                    "tentative_rollback",
                    view=self.replica.view,
                    seq=t_roll - self._seen_rollbacks,
                )
            if self.metrics_registry.enabled:
                self.metrics_registry.counter(
                    "pbft_tentative_rollbacks_total"
                ).inc(t_roll - self._seen_rollbacks)
            self._seen_rollbacks = t_roll
        if self.metrics_registry.enabled:
            t_exec = self.replica.counters["tentative_executions"]
            if t_exec > self._seen_tentative:
                self.metrics_registry.counter(
                    "pbft_tentative_executions_total"
                ).inc(t_exec - self._seen_tentative)
                self._seen_tentative = t_exec
            # Deltas of the replica's own counters: "executed" counts per
            # REQUEST, "rounds_executed" per sequence number — together
            # the batch amplification (requests per three-phase instance).
            executed = self.replica.counters["executed"]
            rounds = self.replica.counters["rounds_executed"]
            if executed > self._seen_executed:
                self.metrics_registry.counter(
                    "pbft_requests_executed_total"
                ).inc(executed - self._seen_executed)
                self._seen_executed = executed
            if rounds > self._seen_rounds:
                self.metrics_registry.counter(
                    "pbft_consensus_rounds_total"
                ).inc(rounds - self._seen_rounds)
                self._seen_rounds = rounds

    async def _open_peer_link(self, dest: int) -> Optional[_PeerLink]:
        """Dial a peer and run the link prologue: always a hello first
        frame (protocol version); in secure clusters the full initiator
        handshake (hello -> hello_r -> auth) before any protocol frame."""
        ident = self.config.identity(dest)
        host, port = ident.host, ident.port
        if port == 0:  # discovery-addressed peer (the mDNS equivalent)
            if self._discovery is None:
                if not self._warned_no_discovery:
                    self._warned_no_discovery = True
                    print(
                        f"replica {self.id}: config lists port-0 peers but "
                        "discovery is disabled (--discovery); those peers "
                        "are unreachable",
                        flush=True,
                    )
                return None
            addr = self._discovery.peers.get(dest)
            if addr is None:
                return None  # no beacon yet: retransmission covers the loss
            host, _, p = addr.rpartition(":")
            port = int(p)
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError:
            return None  # peer down: PBFT tolerates f of these
        if not self.secure and not self.fastpath_mac:
            writer.write(_frame_obj(secure.plain_hello(self.id)))
            # A version-mismatched responder answers with a reject frame,
            # and a 1.1.0 responder answers with its own hello (the codec
            # offer); watch for both so rejects are loud and the link
            # upgrades to binary-v2 the moment the offer arrives.
            link = _PeerLink(writer)
            asyncio.get_running_loop().create_task(
                self._watch_link(dest, reader, link)
            )
            return link
        # Secure link handshake — or, in authenticator mode on a
        # plaintext cluster, the SAME signed handshake run auth-only
        # (lane-key agreement + peer identity; frames stay plaintext).
        chan = secure.SecureChannel(
            self.id,
            self._seed,
            self._pubkey_of,
            initiator=True,
            expected_peer=dest,
            offer_mac=self.fastpath_mac,
            auth_only=not self.secure,
        )
        try:
            writer.write(_frame_obj(chan.initiator_hello()))
            await writer.drain()
            reply = json.loads(await _read_frame(reader))
            if not self.secure and not (
                isinstance(reply, dict) and isinstance(reply.get("eph"), str)
            ):
                # A plaintext responder that answered the mac-offering
                # hello with a classic hello-ack (pre-1.3.0, or
                # signature-mode config): downgrade this link to the
                # plain flavor — its ack still carried the codec offer.
                if (
                    isinstance(reply, dict)
                    and reply.get("type") == "reject"
                ):
                    raise secure.HandshakeError(
                        f"peer rejected handshake: {reply.get('reason')}"
                    )
                link = _PeerLink(
                    writer, binary=secure.hello_offers_binary(reply)
                )
                asyncio.get_running_loop().create_task(
                    self._watch_link(dest, reader, link)
                )
                return link
            auth = chan.on_hello_reply(reply)
            writer.write(_frame_obj(auth))
            await writer.drain()
        except (
            secure.HandshakeError,
            ConnectionError,
            OSError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            ValueError,
        ) as e:
            print(
                f"replica {self.id}: handshake with {dest} failed: {e}",
                flush=True,
            )
            writer.close()
            return None
        # Secure links need the watcher too: a responder-side reject or
        # close after the handshake must drop the cached link immediately,
        # not linger until the next write fails (silently losing one send).
        # hello_r carried the responder's codec offer: binary-v2 from here
        # on when both sides speak it — and the mac offer (ISSUE 14): a
        # mutually-offered link registers its sender-side lane key so
        # broadcasts grow a lane for this peer.
        mac = chan.mac_negotiated
        if mac:
            self._mac_send_keys[dest] = chan.auth_send_key
        else:
            self._mac_send_keys.pop(dest, None)
        link = _PeerLink(
            writer,
            chan if self.secure else None,
            binary=secure.hello_offers_binary(reply),
            mac=mac,
        )
        asyncio.get_running_loop().create_task(
            self._watch_link(dest, reader, link)
        )
        return link

    async def _watch_link(self, dest: int, reader, link: _PeerLink) -> None:
        """Watch a dialed link (plain or secure) for reject frames, the
        plaintext hello-ack (the responder's codec offer), and EOF.
        Dropping the cached link the moment the responder closes or
        rejects means the next _send_to re-dials instead of writing into
        a dead socket's kernel buffer (which would silently lose the
        first post-failure send)."""
        writer = link.writer
        try:
            while True:
                raw = await _read_frame(reader, timeout=3600.0)
                try:
                    obj = json.loads(raw)
                except ValueError:
                    continue  # sealed frame on a secure link — not a reject
                if isinstance(obj, dict) and obj.get("type") == "reject":
                    print(
                        f"replica {self.id}: peer {dest} rejected link: "
                        f"{obj.get('reason')}",
                        flush=True,
                    )
                    break
                if isinstance(obj, dict) and obj.get("type") == "hello":
                    link.binary = secure.hello_offers_binary(obj)
        except (
            ConnectionError,
            OSError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            ValueError,
        ):
            pass  # EOF / dead or hour-idle link: drop and re-dial on demand
        writer.close()
        if (cached := self._peer_links.get(dest)) and cached.writer is writer:
            self._peer_links.pop(dest, None)

    def _corrupt_sig(self, msg: Message) -> Message:
        """The Byzantine signer's outgoing message: same content, garbage
        signature (mirrors core/net.cc corrupt_sig — 'f' * len)."""
        if self.fault != "sig-corrupt":
            return msg
        sig = getattr(msg, "sig", "")
        if not sig:
            return msg
        self._count_fault()
        return with_sig(msg, "f" * len(sig))

    async def _send_to(self, dest: int, enc: _EncodedOut) -> None:
        if self.chaos_drop_pct > 0 and (
            self._chaos_rng.random() < self.chaos_drop_pct
        ):
            # Seeded link loss (--chaos-drop-pct): the frame never leaves
            # this replica — PBFT's retransmission paths must absorb it.
            self.chaos_dropped += 1
            if self.metrics_registry.enabled:
                self.metrics_registry.counter("pbft_chaos_dropped_total").inc()
            return
        if self.chaos_delay_ms > 0:
            # Held BEFORE the per-dest link lock: concurrent sends wake in
            # jittered order, so frames reorder across broadcasts, while
            # the lock still serializes the seal+write per link (secure
            # channels keep their AEAD nonce sequence).
            await asyncio.sleep(
                self._chaos_rng.random() * self.chaos_delay_ms / 1000.0
            )
        lock = self._peer_locks.setdefault(dest, asyncio.Lock())
        async with lock:
            link = self._peer_links.get(dest)
            if link is None or link.writer.is_closing():
                link = await self._open_peer_link(dest)
                if link is None:
                    return
                self._peer_links[dest] = link
            payload = None
            mac_frame = False
            if link.mac:
                # Authenticator mode: the shared MAC-vector frame — one
                # encode + one lane set per broadcast, every mac link
                # ships the same bytes (its receiver verifies its lane
                # instead of the hot-path signature).
                payload = enc.mac_payload(self._mac_send_keys)
                mac_frame = payload is not None
            if payload is None and link.binary:
                payload = enc.binary_payload()
            if payload is not None:
                self.codec_binary_frames += 1
                if self.metrics_registry.enabled:
                    self.metrics_registry.counter(
                        "pbft_codec_binary_frames_total"
                    ).inc()
                if mac_frame:
                    self.mac_frames += 1
                    if self.metrics_registry.enabled:
                        self.metrics_registry.counter(
                            "pbft_mac_frames_total"
                        ).inc()
            else:
                payload = enc.json_payload()
                self.codec_json_frames += 1
                if self.metrics_registry.enabled:
                    self.metrics_registry.counter(
                        "pbft_codec_json_frames_total"
                    ).inc()
            # Bounded-outbound admission BEFORE the seal (ISSUE 10): a
            # black-holed peer whose drain() never completes must not
            # grow the transport buffer (or the task queue behind the
            # link lock) without limit — and on secure links the drop
            # must happen before the AEAD nonce is consumed.
            if not self._writer_has_room(link.writer):
                return  # drop-and-count: retransmission absorbs the loss
            if link.chan is not None:
                # Per-peer sealing over the SHARED plaintext: the AEAD
                # counter is per-link state, so only the seal (not the
                # encode) runs per peer.
                payload = link.chan.seal_frame(payload)
            try:
                link.writer.write(_frame_bytes(payload))
                await link.writer.drain()
            except (ConnectionError, OSError):
                self._peer_links.pop(dest, None)

    def _gateway_reply(self, client: str, reply: ClientReply) -> None:
        self._gateway_line(client, reply.canonical())

    def _gateway_line(self, client: str, line: bytes) -> None:
        """Fan a raw-JSON line (reply or overloaded notice) back over the
        gateway link that forwarded for ``client`` (exact route), or over
        EVERY live gateway link when the route is unknown/stale —
        gateways drop tokens they don't own, so degradation is extra
        frames, never a lost reply quorum. Writes are admission-checked
        (bounded outbound) and never awaited: a slow gateway costs
        dropped replies, not a stalled replica."""
        payload = _frame_bytes(line)
        wid = self._gateway_routes.get(client)
        if wid is not None and wid in self._gateway_links:
            writers = [self._gateway_links[wid]]
        else:
            if wid is not None:
                self._gateway_routes.pop(client, None)  # stale route
            writers = list(self._gateway_links.values())
        for w in writers:
            if w.is_closing() or not self._writer_has_room(w):
                continue
            try:
                w.write(payload)
            except (ConnectionError, OSError, RuntimeError):
                pass

    async def _dial_reply(self, client_addr: str, reply: ClientReply) -> None:
        reply = self._corrupt_sig(reply)
        await self._dial_line(client_addr, reply.canonical() + b"\n")

    async def _dial_line(self, client_addr: str, line: bytes) -> None:
        # One dial per address at a time — a LATER reply to the same
        # address is a distinct message (the client may already be on its
        # next request), so queue on the address lock (FIFO) rather than
        # drop, bounded by the same ~6 s TTL the C++ reply backlog uses
        # (core/net.cc). Lock entries are refcounted away when idle.
        deadline = time.monotonic() + 6.0
        lock = self._reply_addr_locks.setdefault(client_addr, asyncio.Lock())
        self._reply_addr_refs[client_addr] = (
            self._reply_addr_refs.get(client_addr, 0) + 1
        )
        try:
            async with lock:
                if time.monotonic() >= deadline:
                    return  # expired in the queue: retransmission (§4.1)
                async with self._reply_dial_sem:
                    if time.monotonic() >= deadline:
                        # Expired waiting for a dial slot (e.g. behind a
                        # burst of black-holed addresses): a reply this
                        # stale is the retransmission path's job now.
                        return
                    host, _, port = client_addr.rpartition(":")
                    try:
                        _, writer = await asyncio.wait_for(
                            asyncio.open_connection(host, int(port)),
                            timeout=3.0,
                        )
                        writer.write(line)
                        await asyncio.wait_for(writer.drain(), timeout=3.0)
                        writer.close()
                    except (OSError, ValueError, asyncio.TimeoutError):
                        pass  # client gone / black-holed address
        finally:
            refs = self._reply_addr_refs[client_addr] - 1
            if refs:
                self._reply_addr_refs[client_addr] = refs
            else:
                del self._reply_addr_refs[client_addr]
                self._reply_addr_locks.pop(client_addr, None)

    # -- request/progress timer (PBFT §4.4 liveness) -------------------------

    async def _timer_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.vc_timeout / 4)
            now = time.monotonic()
            for key in [
                k
                for k, t in self._waiting_requests.items()
                if now - t > 10 * self.vc_timeout
            ]:
                del self._waiting_requests[key]
            if self.replica.awaiting_state is not None:
                # A lagging replica waiting on state transfer retries the
                # fetch once per vc_timeout (mirrors core/net.cc) — a view
                # change would not help it catch up.
                self._timer_deadline = None
                if self._state_retry_deadline is None:
                    self._state_retry_deadline = now + self.vc_timeout
                elif now >= self._state_retry_deadline:
                    self._emit(self.replica.retry_state_transfer())
                    self._state_retry_deadline = None
                continue
            self._state_retry_deadline = None
            pending = bool(self._waiting_requests) or self.replica.has_unexecuted()
            if not pending:
                self._vc_policy.clear()
                self._observe_backoff_level()
                continue
            state = self._vc_policy.poll(
                now,
                # Tentative mode: progress = COMMITTED sequences, so a
                # commit-starved cluster still escalates (tentative
                # executions roll back — they must not placate the timer).
                self.replica.progress_marker(),
                self.replica.view,
                self.replica.in_view_change,
            )
            if state == "retransmit":
                # First no-progress expiry while a view change pends:
                # re-broadcast the pending VIEW-CHANGE verbatim instead
                # of escalating — a lost VIEW-CHANGE/NEW-VIEW recovers in
                # the SAME view (ISSUE 12). The primary-elect answers a
                # retransmitted VIEW-CHANGE with its cached NEW-VIEW.
                if self.flight is not None:
                    self.flight.record(
                        "view_timer_fired",
                        view=self.replica.view,
                        seq=self._vc_policy.level,
                    )
                get_tracer().event(
                    "view_timer_fired",
                    replica=self.id,
                    view=self.replica.view,
                    backoff=self._vc_policy.level,
                )
                self._emit(self.replica.retransmit_view_change())
            elif state == "escalate":
                if self.metrics_registry.enabled:
                    self.metrics_registry.counter("pbft_view_changes_total").inc()
                # The view-change span opens here (ROADMAP item 4):
                # timer fired -> view_change_sent -> new_view_installed.
                if self.flight is not None:
                    self.flight.record(
                        "view_timer_fired",
                        view=self.replica.view,
                        seq=self._vc_policy.level,
                    )
                get_tracer().event(
                    "view_timer_fired",
                    replica=self.id,
                    view=self.replica.view,
                    backoff=self._vc_policy.level,
                )
                get_tracer().event(
                    "view_change_start",
                    replica=self.id,
                    pending_view=self.replica.view + 1,
                    backoff=self._vc_policy.level,
                )
                self._emit(self.replica.start_view_change())
            self._observe_backoff_level()

    def _observe_backoff_level(self) -> None:
        """Push the view-timer backoff level to the gauge + flight
        recorder when it changed (ISSUE 12): a sustained high level IS
        the storm signal the chaos bench reads."""
        level = self._vc_policy.level
        if level == self._gauged_backoff:
            return
        self._gauged_backoff = level
        if self.metrics_registry.enabled:
            self.metrics_registry.gauge("pbft_view_timer_backoff_level").set(
                level
            )
        if self.flight is not None:
            self.flight.record(
                "backoff_level", view=self.replica.view, seq=level
            )

    def _refresh_health(self) -> dict:
        """Advance the last-progress tracker and push the health gauges
        (ISSUE 16; mirrors core/net.cc refresh_health). Returns the
        health-document fields metrics() folds into the status dict.
        Lazy: runs only when the status surface renders, so an
        unscraped replica pays nothing."""
        now = time.monotonic()
        executed = self.replica.executed_upto
        if executed != self._progress_seen_executed:
            self._progress_seen_executed = executed
            self._progress_seen_at = now
        rss = read_rss_bytes()
        fds = count_open_fds()
        wal_bytes = file_size_bytes(self.wal.path if self.wal else None)
        inbox = self.replica.pending_count()
        since = round(now - self._progress_seen_at, 6)
        if self.metrics_registry.enabled:
            reg = self.metrics_registry
            reg.gauge("pbft_process_rss_bytes").set(rss)
            reg.gauge("pbft_open_fds").set(fds)
            reg.gauge("pbft_wal_disk_bytes").set(wal_bytes)
            reg.gauge("pbft_last_progress_seconds").set(since)
            reg.gauge("pbft_inbox_depth").set(inbox)
        return {
            "health_version": HEALTH_DOC_VERSION,
            "uptime_seconds": round(now - self._start_time, 6),
            "rss_bytes": rss,
            "open_fds": fds,
            "wal_disk_bytes": wal_bytes,
            "inbox_depth": inbox,
            "sealed_unexecuted": max(
                0, self.replica.seq_counter - self.replica.executed_upto
            ),
            "waiting_requests": len(self._waiting_requests),
            "last_progress_seconds": since,
            "chain_digest": self.replica.committed_chain.hex(),
            "state_digest": self.replica.state_digest.hex(),
        }

    def metrics(self) -> dict:
        return {
            "replica": self.id,
            "port": self.listen_port,
            "frames_in": self.frames_in,
            "verify_batches": self.batches_run,
            # Remote-verifier health (service spec only): batches the
            # local native pool absorbed because the service was warming,
            # unreachable, or died mid-stream.
            "verify_service_fallbacks": (
                self.service_verifier.used_fallback
                if self.service_verifier is not None
                else 0
            ),
            "broadcasts": self.broadcasts,
            "broadcast_encodes": self.broadcast_encodes,
            "codec_binary_frames": self.codec_binary_frames,
            "codec_json_frames": self.codec_json_frames,
            # Scale-out surface (ISSUE 10; parity with core/net.cc
            # metrics_json). net_threads reports 1: the asyncio runtime
            # is single-loop whatever the config asked for (ISSUE 13).
            "net_backend": "asyncio",
            "net_threads": 1,
            "cross_thread_wakes": 0,
            "crypto_offload_queue_depth": 0,
            "connections_open": max(0, self._conns_open)
            + len(self._peer_links),
            "event_wakeups": self.event_wakeups,
            "backpressure_events": self.backpressure_events,
            "gateway_links": len(self._gateway_links),
            "gateway_forwarded": self.gateway_forwarded,
            # Perf-under-faults surface (ISSUE 12).
            "overload_rejections": self.overload_rejections,
            "gateway_failovers": self.gateway_failovers,
            "view_timer_backoff": self._vc_policy.level,
            "faults_injected": self.faults_injected,
            "chaos_dropped": self.chaos_dropped,
            # Fast-path surface (ISSUE 14): the negotiated-offer mode,
            # tentative execution, MAC frame tallies, committed floor.
            "mode": "mac" if self.fastpath_mac else "sig",
            "tentative": self.config.tentative,
            "mac_frames": self.mac_frames,
            "mac_rejected": self.mac_rejected,
            # Durable-recovery surface (ISSUE 15).
            "wal_enabled": self.wal is not None,
            "recovered_from_wal": self.recovered_from_wal,
            "wal_appends": self.wal.appends if self.wal else 0,
            "wal_fsyncs": self.wal.fsyncs if self.wal else 0,
            "wal_bytes": self.wal.bytes_written if self.wal else 0,
            "committed_upto": self.replica.committed_upto,
            "executed_upto": self.replica.executed_upto,
            "low_mark": self.replica.low_mark,
            "view": self.replica.view,
            "in_view_change": self.replica.in_view_change,
            # Health document (ISSUE 16; shape contracted with
            # core/net.cc metrics_json by HEALTH_DOC_VERSION).
            **self._refresh_health(),
            **self.replica.counters,
        }


async def _amain(args, config_text: str, flight=None, wal=None) -> None:
    # config_text is read by main() BEFORE the event loop starts: file
    # I/O inside a coroutine is a blocking call on the loop (flagged by
    # pbft_tpu/analysis/async_blocking.py, scripts/pbft_lint.py). The
    # WAL is opened/replayed there too (ISSUE 15) for the same reason.
    config = ClusterConfig.from_json(config_text)
    # --batch-* override network.json (ISSUE 4), mirroring pbftd.
    import dataclasses as _dc

    if args.batch_max_items is not None and args.batch_max_items >= 1:
        config = _dc.replace(config, batch_max_items=args.batch_max_items)
    if args.batch_flush_us is not None and args.batch_flush_us >= 0:
        config = _dc.replace(config, batch_flush_us=args.batch_flush_us)
    # Fast-path overrides (ISSUE 14), mirroring pbftd --fastpath /
    # --tentative: network.json stays the default source of truth.
    if args.fastpath:
        config = _dc.replace(config, fastpath=args.fastpath)
    if args.tentative:
        config = _dc.replace(config, tentative=True)
    server = AsyncReplicaServer(
        config,
        args.id,
        bytes.fromhex(args.seed),
        verifier=args.verifier,
        vc_timeout=args.vc_timeout_ms / 1000.0,
        discovery=args.discovery,
        byzantine=args.byzantine,
        fault=args.fault,
        chaos_drop_pct=args.chaos_drop_pct,
        chaos_delay_ms=args.chaos_delay_ms,
        chaos_seed=args.chaos_seed,
        metrics_port=args.metrics_port,
        flight=flight,
        wal=wal,
    )
    await server.start()
    print(
        f"async replica {args.id} listening on {server.listen_port} "
        f"(verifier={args.verifier})",
        flush=True,
    )
    while True:
        await asyncio.sleep(args.metrics_every or 3600)
        if args.metrics_every:
            print(json.dumps(server.metrics()), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", required=True)
    parser.add_argument("--id", type=int, required=True)
    parser.add_argument("--seed", required=True, help="64-hex Ed25519 seed")
    # "jax" = in-process XLA batch verifier; anything else = host oracle
    # (a "host:port" passed by a shared launcher config falls back to cpu —
    # this runtime needs no remote service, the TPU path is in-process).
    parser.add_argument("--verifier", default="cpu")
    parser.add_argument("--vc-timeout-ms", type=int, default=0)
    parser.add_argument("--metrics-every", type=int, default=0)
    parser.add_argument(
        "--batch-max-items",
        type=int,
        default=None,
        help="requests the primary folds into ONE three-phase instance "
        "(overrides network.json batch_max_items; 1 = pre-batching "
        "one-instance-per-request)",
    )
    parser.add_argument(
        "--batch-flush-us",
        type=int,
        default=None,
        help="how long a partial batch may wait for more requests before "
        "the runtime seals it (overrides network.json batch_flush_us)",
    )
    parser.add_argument(
        "--fastpath",
        default="",
        choices=("", "sig", "mac"),
        help="fast-path authenticator mode (ISSUE 14): 'mac' offers "
        "per-link session-MAC authentication of normal-case frames in "
        "this node's hellos (overrides network.json fastpath); links "
        "whose peer did not offer it fall back to signature mode",
    )
    parser.add_argument(
        "--tentative",
        action="store_true",
        help="execute + reply at PREPARED (tentative, ISSUE 14) with "
        "rollback on view change; clients need 2f+1 matching tentative "
        "votes (overrides network.json tentative=false)",
    )
    parser.add_argument(
        "--wal-dir",
        default="",
        help="durable recovery (ISSUE 15): keep a write-ahead log at "
        "{dir}/replica-{id}.wal (view, sent votes, stable checkpoint) "
        "with group-commit fsync, and on restart replay it so this "
        "replica re-joins the SAME view without contradicting a "
        "persisted vote (overrides network.json wal_dir)",
    )
    parser.add_argument(
        "--wal-fsync",
        type=int,
        default=-1,
        choices=(-1, 0, 1),
        help="1/0 overrides network.json wal_fsync: 0 keeps the WAL "
        "writes but skips fsync (kill -9 of the process stays safe via "
        "the page cache; only host power loss can drop the tail)",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve Prometheus text format on this port (0 = ephemeral); "
        "metric names match pbftd --metrics-port so a mixed-runtime "
        "cluster scrapes uniformly",
    )
    parser.add_argument(
        "--discovery",
        default="",
        help="multicast group:port for peer discovery (mDNS equivalent)",
    )
    parser.add_argument(
        "--byzantine",
        action="store_true",
        help="fault injection: corrupt every outgoing signature "
        "(legacy spelling of --fault sig-corrupt)",
    )
    parser.add_argument(
        "--fault",
        default="",
        choices=("",) + FAULT_MODES,
        help="Byzantine behavior mode (parity with pbftd --fault): "
        "sig-corrupt | mute | stutter | equivocate",
    )
    parser.add_argument(
        "--chaos-drop-pct",
        type=float,
        default=0.0,
        help="seeded link chaos: drop this fraction of outbound peer "
        "frames (0..1)",
    )
    parser.add_argument(
        "--chaos-delay-ms",
        type=int,
        default=0,
        help="seeded link chaos: hold each outbound peer frame for a "
        "uniform 0..N ms",
    )
    parser.add_argument(
        "--chaos-seed",
        type=int,
        default=None,
        help="chaos RNG seed (default: the replica id) — same seed, same "
        "drop/delay pattern",
    )
    parser.add_argument("--trace", default=None, help="JSONL trace file")
    parser.add_argument(
        "--flight-file",
        default=None,
        help="black-box flight recorder dump target: the last N protocol "
        "events, written on SIGTERM/SIGINT/fatal (decode with "
        "scripts/flight_dump.py); mirrors pbftd --flight-file",
    )
    args = parser.parse_args()
    if args.trace:
        from ..utils import set_trace_file

        set_trace_file(args.trace)
    flight = None
    if args.flight_file:
        from ..utils.flight import FlightRecorder, install_signal_dump

        flight = FlightRecorder(capacity=8192)
        install_signal_dump(flight, args.flight_file)
    with open(args.config) as fh:
        config_text = fh.read()
    # Durable recovery (ISSUE 15): open + replay the WAL here, before
    # the event loop exists — replay is file I/O, and the no-blocking-
    # calls-on-the-loop lint applies to it like any other read.
    wal = None
    cfg_for_wal = ClusterConfig.from_json(config_text)
    wal_dir = args.wal_dir or cfg_for_wal.wal_dir
    if wal_dir:
        import os as _os

        from ..consensus.wal import WriteAheadLog

        _os.makedirs(wal_dir, exist_ok=True)
        do_fsync = (
            cfg_for_wal.wal_fsync if args.wal_fsync < 0 else bool(args.wal_fsync)
        )
        wal = WriteAheadLog(
            _os.path.join(wal_dir, f"replica-{args.id}.wal"), fsync=do_fsync
        )
    try:
        asyncio.run(_amain(args, config_text, flight=flight, wal=wal))
    except BaseException:
        # Fatal path (unhandled exception, loop torn down): the black box
        # must still ship — same contract as pbftd's on_fatal handler.
        if flight is not None:
            flight.dump(args.flight_file)
        raise


if __name__ == "__main__":
    main()
