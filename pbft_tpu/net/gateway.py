"""Client-gateway tier (ISSUE 10): multiplex thousands of client
connections onto a few persistent replica links.

The reference's client contract (raw JSON request in, reply *dialed back*
to the client's advertised host:port) costs the cluster ~n sockets per
concurrent client — at the ROADMAP's "millions of users" scale that is FD
exhaustion long before it is a throughput problem. The gateway keeps the
telnet-able downstream contract (raw JSON lines in, raw JSON reply lines
out, all on ONE connection) and swaps the upstream shape: one framed,
persistent link per replica, announced by a ``role=gateway`` hello, over
which client requests flow up and replies fan BACK (both runtimes trust
the link instead of dialing the client; core/net.cc + net/server.py).
10k concurrent clients then cost the cluster ~n·gateways sockets.

Identity: a gateway-routed client addresses itself with a ROUTING TOKEN,
never a dialable address — the ``gw/``-prefixed ``client`` field
(GATEWAY_CLIENT_PREFIX, mirrored by core/net.h kGatewayClientPrefix;
constants lint). Tokens are client-chosen and stable across reconnects,
so per-(client, ts) exactly-once and the cached-reply retransmission path
(PBFT §4.1) survive a gateway restart exactly as they survive a client
redial. The gateway forwards request bytes UNCHANGED (canonicality is
end-to-end); replies are routed downstream by the token each reply
carries, and every replica's copy is forwarded — the f+1 reply-quorum
count stays where the paper puts it, in the client.

Forwarding policy: a fresh (token, ts) goes to the current primary
(tracked from the view field of routed replies); a retransmission (ts
not above the token's high-water mark) broadcasts to ALL replicas —
the paper's client liveness rule, which forces forwarding and
eventually a view change on a faulty primary.

Run one gateway:  python -m pbft_tpu.net.gateway --config network.json \
                      [--port P] [--metrics-port M]
Secure clusters are refused upstream: a gateway holds no replica
identity, so the signed-DH handshake cannot admit it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import socket
import threading
import time
from typing import Dict, List, Optional

from ..consensus.config import ClusterConfig
from ..consensus.messages import ClientRequest
from ..utils import (
    MetricsRegistry,
    count_open_fds,
    read_rss_bytes,
    start_metrics_server,
)
from ..utils.trace_schema import HEALTH_DOC_VERSION
from . import secure
from .client import PbftClient

# Gateway-routed client identities carry this prefix (mirrored by
# core/net.h kGatewayClientPrefix; constants lint): such a "client
# address" is a routing token, never a dialable host:port.
GATEWAY_CLIENT_PREFIX = "gw/"

# Bounded outbound per downstream/upstream connection (mirrors
# server.py MAX_CONN_OUTBOUND / core/net.cc kMaxConnOutbound).
_MAX_WRITE_BUFFER = 8 << 20
# Token bookkeeping bound: on overflow the maps clear — a cleared route
# re-registers on the client's next request, a cleared high-water mark
# turns one fresh request into a broadcast (extra frames, never loss).
_MAX_TOKENS = 1 << 17

# A raw-JSON client line may not exceed this (same bound as the replica
# gateways): longer input is a protocol violation on an unauthenticated
# socket and drops the connection instead of buffering without bound.
MAX_CLIENT_LINE = 1 << 20


def _frame_bytes(payload: bytes) -> bytes:
    return len(payload).to_bytes(4, "big") + payload


def gateway_hello() -> dict:
    """The version-carrying hello that opens every upstream link. The
    ``role`` field is the trust switch: both runtimes mark the link as a
    gateway link (requests arrive on it, replies fan back over it)."""
    return {
        "type": "hello",
        "ver": secure.wire_hello_version(),
        "node": -1,
        "role": "gateway",
    }


class _UpstreamLink:
    """One persistent framed link to a replica."""

    __slots__ = ("writer", "task")

    def __init__(self, writer: asyncio.StreamWriter, task: asyncio.Task):
        self.writer = writer
        self.task = task


class ClientGateway:
    """One gateway process: a raw-JSON line server for clients in front
    of n persistent framed replica links."""

    def __init__(
        self,
        config: ClusterConfig,
        host: str = "0.0.0.0",
        port: int = 0,
        metrics_port: Optional[int] = None,
        max_inflight: int = 0,
        max_queue_depth: int = 0,
        flight=None,
    ):
        if config.secure:
            raise ValueError(
                "gateway tier requires a plaintext cluster: a gateway has "
                "no replica identity for the signed-DH handshake"
            )
        self.config = config
        self.host = host
        self.port = port
        self.listen_port = 0
        self.metrics_registry = MetricsRegistry(
            labels={"gateway": "0"}, enabled=metrics_port is not None
        )
        if self.metrics_registry.enabled:
            self.metrics_registry.preregister(emitter="gateway.py")
        self.metrics_port = metrics_port
        self._metrics_server = None
        self.metrics_listen_port = 0
        self._server: Optional[asyncio.Server] = None
        # token -> downstream writer (the reply route), and the per-token
        # forwarded-timestamp high-water mark (retransmission detection).
        self._routes: Dict[str, asyncio.StreamWriter] = {}
        self._last_ts: Dict[str, int] = {}
        # rid -> _UpstreamLink, each guarded by a per-rid lock so one
        # dial+hello runs per replica.
        self._links: Dict[int, _UpstreamLink] = {}
        self._link_locks: Dict[int, asyncio.Lock] = {}
        # Current view, tracked from routed replies: fresh requests go to
        # view % n, so a view change re-aims the firehose without any
        # client knowing.
        self._view = 0
        self._stopping = False
        self._keeper_task: Optional[asyncio.Task] = None
        self.clients_open = 0
        self.forwarded = 0
        self.replies_routed = 0
        self.backpressure_events = 0
        # Admission control (ISSUE 12): per-token in-flight cap +
        # a global queue-depth watermark. A FRESH request past either
        # bound is answered with an explicit {"type": "overloaded"} line
        # downstream and NOT forwarded; retransmissions of an already
        # in-flight (token, ts) always pass — liveness is never
        # admission-gated. In-flight entries prune when a reply routes
        # (per-client execution is timestamp-ordered, so a reply for ts
        # retires every entry at or below it). 0 disables either bound.
        self.max_inflight = max_inflight
        self.max_queue_depth = max_queue_depth
        self._inflight: Dict[str, set] = {}
        self._inflight_total = 0
        self.overload_rejections = 0
        # Gateway-fabric failovers (ISSUE 12): upstream replica links this
        # gateway had to re-dial after they died mid-run.
        self.upstream_failovers = 0
        # Black-box flight recorder (utils/flight.py, --flight-file):
        # failover/overload events ship with the chaos bench's black
        # boxes the same way replica recorders do. None = one attribute
        # check per event site.
        self.flight = flight
        # Health-document uptime anchor (ISSUE 16).
        self._start_time = time.monotonic()

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "ClientGateway":
        self._server = await asyncio.start_server(
            self._on_client, host=self.host, port=self.port
        )
        self.listen_port = self._server.sockets[0].getsockname()[1]
        if self.metrics_port is not None:
            # /status serves the gateway's health document (ISSUE 16) so
            # pbft_top can watch the tier alongside the replicas.
            self._metrics_server = start_metrics_server(
                self.metrics_registry, self.metrics_port,
                status_fn=self.metrics,
            )
            self.metrics_listen_port = self._metrics_server.server_address[1]
        # EVERY replica needs a live gateway link, not just the ones
        # requests flow to: a backup only ever SENDS on its link (the
        # reply fan-back for requests it saw via pre-prepare), so lazy
        # dial-on-send would leave backup replies with nowhere to go and
        # the client short of its f+1 quorum.
        self._keeper_task = asyncio.get_running_loop().create_task(
            self._link_keeper()
        )
        return self

    async def _link_keeper(self) -> None:
        while not self._stopping:
            for rid in range(self.config.n):
                try:
                    await self._ensure_link(rid)
                except OSError:
                    pass  # replica down: PBFT tolerates f of these
            await asyncio.sleep(1.0)

    async def stop(self) -> None:
        self._stopping = True
        if self._keeper_task is not None:
            self._keeper_task.cancel()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for link in self._links.values():
            link.writer.close()
            link.task.cancel()
        self._links.clear()

    def metrics(self) -> dict:
        return {
            # Health document (ISSUE 16): the gateway is a replica-less
            # process, so its document is the resource subset — no
            # progress watermarks or chain digests to report.
            "health_version": HEALTH_DOC_VERSION,
            "uptime_seconds": round(time.monotonic() - self._start_time, 6),
            "rss_bytes": read_rss_bytes(),
            "open_fds": count_open_fds(),
            "gateway_clients_open": self.clients_open,
            "gateway_forwarded": self.forwarded,
            "replies_routed": self.replies_routed,
            "backpressure_events": self.backpressure_events,
            "upstream_links": len(self._links),
            "overload_rejections": self.overload_rejections,
            "gateway_failovers": self.upstream_failovers,
            "inflight": self._inflight_total,
            "view": self._view,
        }

    # -- downstream (clients) ------------------------------------------------

    def _set_clients_gauge(self) -> None:
        if self.metrics_registry.enabled:
            self.metrics_registry.gauge("pbft_gateway_clients_open").set(
                self.clients_open
            )

    def _writer_has_room(self, writer: asyncio.StreamWriter) -> bool:
        """Bounded outbound against a slow reader (drop-and-count): the
        dropped reply is re-fetched from the replicas' reply caches on
        retransmission, a dropped request is retransmission-covered."""
        try:
            size = writer.transport.get_write_buffer_size()
        except (AttributeError, RuntimeError):
            return True
        if size > _MAX_WRITE_BUFFER:
            self.backpressure_events += 1
            if self.metrics_registry.enabled:
                self.metrics_registry.counter(
                    "pbft_write_backpressure_events_total"
                ).inc()
            return False
        return True

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.clients_open += 1
        self._set_clients_gauge()
        owned_tokens: List[str] = []
        try:
            buf = b""
            while True:
                nl = buf.find(b"\n")
                if nl >= 0:
                    line, buf = buf[:nl], buf[nl + 1 :]
                    await self._handle_line(line.strip(), writer, owned_tokens)
                    continue
                if len(buf) > MAX_CLIENT_LINE:
                    return  # oversized line: drop the connection
                chunk = await reader.read(65536)
                if not chunk:
                    return
                buf += chunk
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        finally:
            self.clients_open -= 1
            self._set_clients_gauge()
            for token in owned_tokens:
                if self._routes.get(token) is writer:
                    self._routes.pop(token, None)
            writer.close()

    async def _handle_line(
        self, line: bytes, writer: asyncio.StreamWriter, owned_tokens: List[str]
    ) -> None:
        if not line:
            return
        try:
            obj = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(obj, dict):
            return
        token = obj.get("client")
        if not isinstance(token, str) or not token.startswith(
            GATEWAY_CLIENT_PREFIX
        ):
            # A dialable address through the gateway would re-open the
            # per-client-socket cost the tier exists to remove — and an
            # unauthenticated redirect channel. Drop it.
            return
        if token not in self._routes:
            owned_tokens.append(token)
        if len(self._routes) >= _MAX_TOKENS:
            self._routes.clear()
        self._routes[token] = writer
        ts = obj.get("timestamp")
        retransmission = (
            isinstance(ts, int) and self._last_ts.get(token, -1) >= ts
        )
        if not retransmission and isinstance(ts, int):
            # Admission control (ISSUE 12): a fresh request past the
            # per-token in-flight cap or the global watermark is rejected
            # with an explicit overloaded line instead of queueing into
            # the cluster's tail. Retransmissions always pass.
            pend = self._inflight.setdefault(token, set())
            if ts not in pend and (
                (self.max_inflight > 0 and len(pend) >= self.max_inflight)
                or (
                    self.max_queue_depth > 0
                    and self._inflight_total >= self.max_queue_depth
                )
            ):
                self._reject_overloaded(token, ts, writer)
                return
            if ts not in pend:
                pend.add(ts)
                self._inflight_total += 1
        framed = _frame_bytes(bytes(line))
        self.forwarded += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter("pbft_gateway_forwarded_total").inc()
        if isinstance(ts, int) and not retransmission:
            if len(self._last_ts) >= _MAX_TOKENS:
                self._last_ts.clear()
            self._last_ts[token] = ts
        if retransmission:
            # The paper's client liveness rule by proxy: a retransmitted
            # request broadcasts to every replica, forcing forwards and
            # eventually a view change on a faulty primary.
            for rid in range(self.config.n):
                await self._send_upstream(rid, framed)
        else:
            await self._send_upstream(self._view % self.config.n, framed)

    def _reject_overloaded(self, token: str, ts: int, writer) -> None:
        """Answer a rejected request with an explicit overloaded line —
        the client backs off with jitter (request_with_retry) instead of
        interpreting silence as a faulty primary."""
        self.overload_rejections += 1
        if self.metrics_registry.enabled:
            self.metrics_registry.counter(
                "pbft_overload_rejections_total"
            ).inc()
        if self.flight is not None:
            self.flight.record("overload_rejected", view=self._view, seq=ts)
        if writer.is_closing() or not self._writer_has_room(writer):
            return
        try:
            writer.write(
                json.dumps(
                    {
                        "type": "overloaded",
                        "client": token,
                        "timestamp": ts,
                        "replica": -1,
                    },
                    separators=(",", ":"),
                ).encode()
                + b"\n"
            )
        except (ConnectionError, OSError, RuntimeError):
            pass

    def _retire_inflight(self, token: str, ts: int) -> None:
        """A reply for (token, ts) routed downstream: per-client execution
        is timestamp-ordered, so every in-flight entry at or below ts is
        complete (or superseded) — prune them all."""
        pend = self._inflight.get(token)
        if not pend:
            return
        done = {t for t in pend if t <= ts}
        if done:
            pend.difference_update(done)
            self._inflight_total -= len(done)
        if not pend:
            del self._inflight[token]

    # -- upstream (replicas) -------------------------------------------------

    async def _send_upstream(self, rid: int, framed: bytes) -> None:
        link = await self._ensure_link(rid)
        if link is None:
            return  # replica down: PBFT tolerates f of these
        if link.writer.is_closing() or not self._writer_has_room(link.writer):
            return  # drop-and-count: retransmission absorbs the loss
        try:
            link.writer.write(framed)
        except (ConnectionError, OSError, RuntimeError):
            self._drop_link(rid, link)

    async def _ensure_link(self, rid: int) -> Optional[_UpstreamLink]:
        link = self._links.get(rid)
        if link is not None and not link.writer.is_closing():
            return link
        lock = self._link_locks.setdefault(rid, asyncio.Lock())
        async with lock:
            link = self._links.get(rid)
            if link is not None and not link.writer.is_closing():
                return link
            ident = self.config.identity(rid)
            try:
                reader, writer = await asyncio.open_connection(
                    ident.host, ident.port
                )
            except OSError:
                return None
            writer.write(
                _frame_bytes(
                    json.dumps(
                        gateway_hello(), separators=(",", ":")
                    ).encode()
                )
            )
            task = asyncio.get_running_loop().create_task(
                self._link_reader(rid, reader)
            )
            link = _UpstreamLink(writer, task)
            self._links[rid] = link
            return link

    def _drop_link(self, rid: int, link: _UpstreamLink) -> None:
        if self._links.get(rid) is link:
            self._links.pop(rid, None)
        link.writer.close()

    async def _link_reader(self, rid: int, reader: asyncio.StreamReader) -> None:
        """Drain one upstream link: hello-acks are consumed, rejects are
        loud, and every reply frame routes downstream by its token."""
        buf = b""
        try:
            while True:
                while len(buf) < 4:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    buf += chunk
                n = int.from_bytes(buf[:4], "big")
                if n > (1 << 24):
                    return  # corrupt frame: drop the link
                while len(buf) < 4 + n:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    buf += chunk
                payload, buf = buf[4 : 4 + n], buf[4 + n :]
                try:
                    obj = json.loads(payload)
                except (ValueError, UnicodeDecodeError):
                    continue
                if not isinstance(obj, dict):
                    continue
                kind = obj.get("type")
                if kind == "hello":
                    continue  # the responder's version/codec ack
                if kind == "reject":
                    print(
                        f"gateway: replica {rid} rejected link: "
                        f"{obj.get('reason')}",
                        flush=True,
                    )
                    return
                self._route_reply(obj, payload)
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        finally:
            link = self._links.get(rid)
            if link is not None and link.task is asyncio.current_task():
                self._links.pop(rid, None)
            if not self._stopping:
                # Upstream replica link died mid-run (ISSUE 12): the
                # keeper re-dials within a second — count the failover so
                # a chaos arm can attribute the blip.
                self.upstream_failovers += 1
                if self.metrics_registry.enabled:
                    self.metrics_registry.counter(
                        "pbft_gateway_failovers_total"
                    ).inc()
                if self.flight is not None:
                    self.flight.record(
                        "gateway_failover", view=self._view, peer=rid
                    )

    def _route_reply(self, obj: dict, payload: bytes) -> None:
        token = obj.get("client")
        if not isinstance(token, str):
            return
        view = obj.get("view")
        if isinstance(view, int) and view > self._view:
            self._view = view  # a view change re-aims fresh requests
        ts = obj.get("timestamp")
        if isinstance(ts, int):
            # Completion retires admission bookkeeping whether or not the
            # downstream client is still connected to hear about it.
            self._retire_inflight(token, ts)
        w = self._routes.get(token)
        if w is None or w.is_closing():
            return  # token not ours (fan-out copy) or client gone
        if not self._writer_has_room(w):
            return  # slow client: drop; retransmission re-fetches
        try:
            w.write(payload + b"\n")
            self.replies_routed += 1
        except (ConnectionError, OSError, RuntimeError):
            pass


# -- the client side of the tier ---------------------------------------------

_token_seq_lock = threading.Lock()
_token_seq = 0


def next_token(prefix: str = "c") -> str:
    """A process-unique gateway routing token. Stable identity is the
    CALLER's job across reconnects (pass the same token back in); this
    only guarantees two clients in one process never collide."""
    global _token_seq
    with _token_seq_lock:
        _token_seq += 1
        return (
            f"{GATEWAY_CLIENT_PREFIX}{prefix}-"
            f"{threading.get_native_id():x}-{_token_seq:x}"
        )


class GatewayClient(PbftClient):
    """PbftClient surface over a gateway connection: same f+1
    signature-verified reply quorum (wait_result is inherited), but no
    dial-back listener — requests and replies share ONE socket, and the
    identity is a routing token instead of host:port.

    HA (ISSUE 12): pass SEVERAL gateway addresses and the client fails
    over on a dead socket — reconnect to the next gateway, same stable
    ``gw/`` token, and replay of the in-flight request lines. Because the
    token and timestamps are unchanged, the replicas' per-(client, ts)
    exactly-once guard + reply caches make the replay safe: a request the
    dead gateway already forwarded executes once and the replay is
    answered from the cache, one it never forwarded gets ordered now —
    completion stays 100% through a gateway death mid-request."""

    def __init__(
        self,
        config: ClusterConfig,
        gateway_addr,
        token: Optional[str] = None,
    ):
        # Deliberately no super().__init__: the base class would start a
        # dial-back listener, which is exactly what the gateway removes.
        self.config = config
        self.replies = []
        self._lock = threading.Lock()
        self._new_reply = threading.Condition(self._lock)
        self._send_lock = threading.Lock()
        self._timestamp = 0
        self.latency_log = {}
        self.address = token or next_token()
        self._addrs: List[str] = (
            [gateway_addr]
            if isinstance(gateway_addr, str)
            else list(gateway_addr)
        )
        self._addr_idx = 0
        # ts -> raw request line, for the failover replay. Entries retire
        # on the first reply seen for their timestamp (a partially-voted
        # request is re-covered by the normal retransmission path).
        self._inflight_lines: Dict[int, bytes] = {}
        self.failovers = 0
        self._closed = False
        self.sock = self._dial_gateway(first=True)
        self._rx_thread = threading.Thread(
            target=self._read_loop, args=(self.sock,), daemon=True
        )
        self._rx_thread.start()

    def _dial_gateway(self, first: bool = False) -> socket.socket:
        """Dial gateways round-robin starting at the current index;
        raises the last OSError when none answers."""
        last_err: Optional[OSError] = None
        for i in range(len(self._addrs)):
            idx = (self._addr_idx + (0 if first else 1) + i) % len(
                self._addrs
            )
            host, _, port = self._addrs[idx].rpartition(":")
            try:
                s = socket.create_connection((host, int(port)), timeout=10)
            except OSError as e:
                last_err = e
                continue
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._addr_idx = idx
            return s
        raise last_err or OSError("no gateway reachable")

    def _failover_locked(self, dead: socket.socket) -> None:
        """Replace a dead gateway socket (caller holds _send_lock): dial
        the next gateway, replay every in-flight request line under the
        SAME token, restart the reader. Raises OSError when no gateway
        answers (callers surface it or retry on their own timer)."""
        if self._closed or self.sock is not dead:
            return  # another thread already failed over
        try:
            dead.close()
        except OSError:
            pass
        s = self._dial_gateway()
        self.sock = s
        self.failovers += 1
        for ts in sorted(self._inflight_lines):
            try:
                s.sendall(self._inflight_lines[ts])
            except OSError:
                break  # the next _send_line attempt fails over again
        self._rx_thread = threading.Thread(
            target=self._read_loop, args=(s,), daemon=True
        )
        self._rx_thread.start()

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            fh = sock.makefile("rb")
            for line in fh:
                rx = time.monotonic()
                line = line.strip()
                if not line:
                    continue
                try:
                    reply = json.loads(line)
                except (ValueError, UnicodeDecodeError):
                    continue
                if isinstance(reply, dict):
                    reply["_rx"] = rx
                    ts = reply.get("timestamp")
                    if (
                        isinstance(ts, int)
                        and reply.get("type") != "overloaded"
                    ):
                        self._inflight_lines.pop(ts, None)
                    with self._new_reply:
                        self.replies.append(reply)
                        self._new_reply.notify_all()
        except (OSError, ValueError):
            pass  # socket closed
        # EOF/error on the CURRENT socket = the gateway died under us:
        # fail over proactively so queued replies keep flowing even
        # before the next send notices.
        if not self._closed:
            with self._send_lock:
                try:
                    self._failover_locked(sock)
                except OSError:
                    pass  # no gateway up right now; sends will retry

    def close(self) -> None:
        self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def _send_line(self, payload: bytes) -> None:
        with self._send_lock:  # not _lock: sendall must never block the
            for _ in range(1 + len(self._addrs)):  # reply reader's notify
                sock = self.sock
                try:
                    sock.sendall(payload)
                    return
                except OSError:
                    self._failover_locked(sock)  # raises when none answer

    def request(self, operation, to_replica=0, timestamp=None):
        """One raw-JSON request through the gateway (the gateway picks
        the replica; ``to_replica`` is accepted for drop-in compat and
        ignored)."""
        del to_replica
        if timestamp is None:
            self._timestamp += 1
            timestamp = self._timestamp
        req = ClientRequest(
            operation=operation, timestamp=timestamp, client=self.address
        )
        self._stamp_send(timestamp)
        line = req.canonical() + b"\n"
        self._inflight_lines[timestamp] = line
        self._send_line(line)
        return req

    def request_many(self, operations, to_replica=0, window=32, timeout=30.0):
        """Pipelined submission over the single gateway connection —
        mirrors PbftClient.request_many, with retransmission resending
        the SAME line (the gateway broadcasts a retransmitted (token, ts)
        to all replicas, the paper's liveness rule by proxy)."""
        del to_replica
        results: Dict[int, str] = {}
        timestamps: List[int] = []
        inflight: List[tuple] = []  # (timestamp, operation)
        next_op = 0
        while len(results) < len(operations):
            while next_op < len(operations) and len(inflight) < window:
                self._timestamp += 1
                ts = self._timestamp
                req = ClientRequest(
                    operation=operations[next_op],
                    timestamp=ts,
                    client=self.address,
                )
                self._stamp_send(ts)
                line = req.canonical() + b"\n"
                self._inflight_lines[ts] = line
                self._send_line(line)
                timestamps.append(ts)
                inflight.append((ts, operations[next_op]))
                next_op += 1
            ts, op = inflight.pop(0)
            try:
                results[ts] = self.wait_result(ts, timeout=timeout)
                self._drop_replies_upto(ts)
            except TimeoutError:
                retry = ClientRequest(
                    operation=op, timestamp=ts, client=self.address
                )
                line = retry.canonical() + b"\n"
                self._inflight_lines[ts] = line
                self._send_line(line)
                results[ts] = self.wait_result(ts, timeout=timeout)
                self._drop_replies_upto(ts)
            self._inflight_lines.pop(ts, None)
        return [results[ts] for ts in timestamps]


# -- daemon entry -------------------------------------------------------------


async def _amain(args, config_text: str, flight=None) -> None:
    config = ClusterConfig.from_json(config_text)
    gw = ClientGateway(
        config,
        host=args.host,
        port=args.port,
        metrics_port=args.metrics_port,
        max_inflight=args.max_inflight,
        max_queue_depth=args.max_queue_depth,
        flight=flight,
    )
    await gw.start()
    print(f"gateway listening on {gw.listen_port}", flush=True)
    if gw.metrics_listen_port:
        # pbft_top / endurance_soak parse this to find /status (ISSUE 16).
        print(f"gateway metrics on {gw.metrics_listen_port}", flush=True)
    while True:
        await asyncio.sleep(args.metrics_every or 3600)
        if args.metrics_every:
            print(json.dumps(gw.metrics()), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", required=True)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--metrics-every", type=int, default=0)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve Prometheus text format on this port (0 = ephemeral)",
    )
    parser.add_argument(
        "--max-inflight",
        type=int,
        default=0,
        help="admission control (ISSUE 12): per-client-token in-flight "
        "request cap — a fresh request past it is answered with an "
        "explicit overloaded line instead of forwarded (0 = off)",
    )
    parser.add_argument(
        "--max-queue-depth",
        type=int,
        default=0,
        help="admission control: global in-flight watermark across every "
        "token this gateway forwards for (0 = off)",
    )
    parser.add_argument(
        "--flight-file",
        default=None,
        help="black-box flight recorder dump target (failover/overload "
        "events), written on SIGTERM/SIGINT — decode with "
        "scripts/flight_dump.py",
    )
    args = parser.parse_args()
    flight = None
    if args.flight_file:
        from ..utils.flight import FlightRecorder, install_signal_dump

        flight = FlightRecorder(capacity=8192)
        install_signal_dump(flight, args.flight_file)
    with open(args.config) as fh:
        config_text = fh.read()
    try:
        asyncio.run(_amain(args, config_text, flight=flight))
    except BaseException:
        if flight is not None:
            flight.dump(args.flight_file)
        raise


if __name__ == "__main__":
    main()
