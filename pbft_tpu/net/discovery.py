"""UDP-multicast peer discovery for the asyncio runtime.

Protocol mirror of core/discovery.cc (one beacon format, two runtimes, so
a mixed pbftd/asyncio cluster discovers itself): replicas beacon
``{"id": N, "port": P}`` to a multicast group ~1/s and learn each other's
addresses from received beacons, letting network.json list identities
(pubkeys) without pinning ports (``"port": 0``). The reference applies
mDNS to every node (reference src/main.rs:46,
src/network_behaviour_composer.rs:24-42); round 3 had wired the rebuilt
equivalent only into pbftd — this closes the gap for the asyncio runtime.

Like mDNS, discovery is unauthenticated *addressing* only: consensus
safety rests on the Ed25519 signatures checked at the protocol layer (and
on the secure-link handshake when enabled), so a spoofed beacon can at
worst misroute traffic that then fails verification.
"""

from __future__ import annotations

import asyncio
import json
import socket
import struct
import time
from typing import Dict, Optional

DEFAULT_PORT = 17700


class Discovery(asyncio.DatagramProtocol):
    """Join ``target`` ("group:port", e.g. "239.255.77.77:17700"), beacon
    this replica's TCP port, and collect peer addresses into ``peers``.

    ``cluster_n`` bounds accepted beacon ids to [0, cluster_n) — the
    channel is unauthenticated, so out-of-cluster ids must not grow the
    map. ``expiry_s`` ages out peers whose beacons stop (the reference's
    mDNS-expiry TODO, reference src/network_behaviour_composer.rs:34-40).
    """

    def __init__(
        self,
        target: str,
        replica_id: int,
        tcp_port: int,
        cluster_n: int = 0,
        expiry_s: float = 10.0,
    ):
        group, _, port = target.rpartition(":")
        if not group:
            group, port = target, str(DEFAULT_PORT)
        self.group = group
        self.port = int(port)
        self.id = replica_id
        self.tcp_port = tcp_port
        self.cluster_n = cluster_n
        self.expiry_s = expiry_s
        self.peers: Dict[int, str] = {}  # id -> "host:port"
        self._last_seen: Dict[int, float] = {}
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._send_sock: Optional[socket.socket] = None
        self._beacon_task: Optional[asyncio.Task] = None
        self._stopping = False

    async def start(self) -> "Discovery":
        loop = asyncio.get_running_loop()
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if hasattr(socket, "SO_REUSEPORT"):
            recv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        recv.bind(("", self.port))
        group = socket.inet_aton(self.group)
        on_loopback = True
        try:  # loopback interface first (the dev/test topology) ...
            mreq = group + socket.inet_aton("127.0.0.1")
            recv.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
        except OSError:  # ... else the default interface (multi-host LAN)
            mreq = group + struct.pack("!I", socket.INADDR_ANY)
            recv.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
            on_loopback = False
        recv.setblocking(False)
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, sock=recv
        )
        send = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        if on_loopback:
            # Pin the send interface to match the joined one; when the
            # join fell back to the default interface, leave the kernel's
            # default route so beacons actually leave the host.
            send.setsockopt(
                socket.IPPROTO_IP,
                socket.IP_MULTICAST_IF,
                socket.inet_aton("127.0.0.1"),
            )
        send.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
        self._send_sock = send
        self._beacon_task = loop.create_task(self._beacon_loop())
        return self

    def stop(self) -> None:
        self._stopping = True
        if self._beacon_task:
            self._beacon_task.cancel()
        if self._transport:
            self._transport.close()
        if self._send_sock:
            self._send_sock.close()

    def announce(self) -> None:
        if self._send_sock is None:
            return
        beacon = json.dumps({"id": self.id, "port": self.tcp_port}).encode()
        try:
            self._send_sock.sendto(beacon, (self.group, self.port))
        except OSError:
            pass

    async def _beacon_loop(self) -> None:
        while not self._stopping:
            self.announce()
            self._expire()
            await asyncio.sleep(1.0)

    def _expire(self) -> None:
        now = time.monotonic()
        for rid in [
            r for r, t in self._last_seen.items() if now - t > self.expiry_s
        ]:
            del self._last_seen[rid]
            self.peers.pop(rid, None)

    # -- DatagramProtocol ----------------------------------------------------

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            obj = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(obj, dict):
            return
        rid, port = obj.get("id"), obj.get("port")
        # The channel is unauthenticated: strict field validation so a
        # spoofed beacon can at worst misroute traffic, never poison the
        # peer map with unusable addresses (bool is an int subclass and
        # must not pass; ports must be dialable).
        if isinstance(rid, bool) or not isinstance(rid, int):
            return
        if isinstance(port, bool) or not isinstance(port, int):
            return
        if not 0 < port <= 65535:
            return
        if rid == self.id:
            return
        if rid < 0 or (self.cluster_n > 0 and rid >= self.cluster_n):
            return
        self.peers[rid] = f"{addr[0]}:{port}"
        self._last_seen[rid] = time.monotonic()
