"""Cluster launcher: spawn a localhost pbftd cluster from a ClusterConfig.

The reference's 'launcher' was four shell windows plus netcat
(README.md:5-43); here the same scenario is a context manager used by the
integration tests and the benchmark harness. Builds the native core on
demand (cmake+ninja, pbft_tpu.native.build)."""

from __future__ import annotations

import dataclasses
import os
import socket
import subprocess
import tempfile
import time
from pathlib import Path
from typing import List, Optional

from .. import native
from ..consensus.config import ClusterConfig, make_local_cluster


def pbftd_path() -> Path:
    native.build()
    return native._BUILD_DIR / "pbftd"


def free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class LocalCluster:
    """N replica processes on loopback ephemeral ports.

    ``impl`` selects the runtime per replica: "cxx" spawns the native
    pbftd daemon, "py" spawns the asyncio runtime
    (python -m pbft_tpu.net.server). The two are wire-compatible (framed
    canonical JSON), so mixed clusters interoperate — the strongest form
    of the cross-implementation determinism requirement (SURVEY.md §7)."""

    def __init__(
        self,
        n: int = 4,
        verifier: str = "cpu",
        metrics_every: int = 0,
        vc_timeout_ms: int = 0,
        impl: "str | List[str]" = "cxx",
        discovery: bool = False,
        config: Optional[ClusterConfig] = None,
        seeds: Optional[List[bytes]] = None,
        trace_dir: Optional[str] = None,
        flight_dir: Optional[str] = None,
        byzantine: Optional[List[int]] = None,
        secure: bool = False,
        verify_flush_us: int = 0,
        verify_flush_items: int = 0,
        batch_max_items: "int | List[int]" = 1,
        batch_flush_us: "int | List[int]" = 0,
        extra_env: Optional[List[Optional[dict]]] = None,
        faults: Optional[dict] = None,
        chaos_drop_pct: float = 0.0,
        chaos_delay_ms: int = 0,
        chaos_seed: Optional[int] = None,
        admission_inflight: int = 0,
        admission_backlog: int = 0,
        net_threads: int = 1,
        fastpath: str = "sig",
        tentative: bool = False,
        wal: bool = False,
        wal_fsync: bool = True,
        metrics_ports: bool = False,
    ):
        self.trace_dir = trace_dir
        # Black-box flight recorders (ISSUE 9): each daemon dumps its last
        # N protocol events to {flight_dir}/replica-{i}.flight on
        # SIGTERM/fatal — kill() therefore ships the dead replica's black
        # box (decode with scripts/flight_dump.py).
        self.flight_dir = flight_dir
        # Request batching (ISSUE 4): scalars land in network.json; lists
        # become per-replica --batch-* CLI overrides (e.g. a batching
        # primary among batch=1 peers for the mixed-mode interop test).
        n_for_lists = (config.n if config is not None else n)
        self.batch_max_items = (
            batch_max_items
            if isinstance(batch_max_items, list)
            else [batch_max_items] * n_for_lists
        )
        self.batch_flush_us = (
            batch_flush_us
            if isinstance(batch_flush_us, list)
            else [batch_flush_us] * n_for_lists
        )
        self._batch_scalar = not (
            isinstance(batch_max_items, list) or isinstance(batch_flush_us, list)
        )
        # Replica ids whose daemons corrupt every outgoing signature
        # (--byzantine, both runtimes; the real-daemon analogue of the
        # simulation's outbound mutator).
        self.byzantine = set(byzantine or [])
        # Generalized fault injection (ISSUE 5): {replica_id: mode} maps
        # to --fault on the daemon (sig-corrupt|mute|stutter|equivocate),
        # and the chaos_* scalars become seeded --chaos-* link knobs on
        # EVERY replica (per-replica seeds derive from chaos_seed + id so
        # one scalar still gives each daemon its own stream).
        self.faults = dict(faults or {})
        # Durable recovery (ISSUE 15): wal=True gives every replica a
        # write-ahead log under {tmpdir}/wal (--wal-dir on both
        # runtimes); kill(hard=True) + revive(from_disk=True) then
        # exercises the kill -9 -> replay-from-disk path. wal_fsync=False
        # keeps the writes but skips the fsync (the A/B durability-cost
        # lever).
        self.wal = wal
        self.wal_fsync = wal_fsync
        # Health introspection (ISSUE 16): metrics_ports=True gives every
        # replica a loopback scrape listener (--metrics-port, both
        # runtimes) serving Prometheus + the /status health document;
        # self.metrics_ports maps replica id -> bound port after
        # __enter__ (pre-allocated — pbftd logs its ephemeral port to
        # stderr, but pre-allocation keeps revive() on the same port).
        self.want_metrics_ports = metrics_ports
        self.metrics_ports: List[int] = []
        self.chaos_drop_pct = chaos_drop_pct
        self.chaos_delay_ms = chaos_delay_ms
        self.chaos_seed = chaos_seed
        self.discovery = discovery
        if config is None:
            config, seeds = make_local_cluster(n, base_port=0)
            # Discovery mode: every replica binds an ephemeral port and
            # finds peers via multicast beacons (the mDNS-equivalent);
            # otherwise pre-allocate loopback ports in the config.
            ports = [0] * n if discovery else free_ports(n)
            config = dataclasses.replace(
                config,
                replicas=[
                    dataclasses.replace(r, port=ports[i])
                    for i, r in enumerate(config.replicas)
                ],
                verifier=verifier,
                secure=secure,
                verify_flush_us=verify_flush_us,
                verify_flush_items=verify_flush_items,
                batch_max_items=(
                    batch_max_items if self._batch_scalar else 1
                ),
                batch_flush_us=(
                    batch_flush_us if self._batch_scalar else 0
                ),
                # Admission control (ISSUE 12): network.json knobs, read
                # identically by both runtimes.
                admission_inflight=admission_inflight,
                admission_backlog=admission_backlog,
                # Multi-core replica core (ISSUE 13): pbftd shards its
                # event loop; the asyncio runtime accepts the key and
                # stays single-loop.
                net_threads=net_threads,
                # Fast-path modes (ISSUE 14): the MAC authenticator
                # offer and tentative execution, read identically by
                # both runtimes from network.json.
                fastpath=fastpath,
                tentative=tentative,
                # Durable recovery (ISSUE 15): wal_fsync rides in
                # network.json; the directory itself is a per-launch
                # --wal-dir flag (set in __enter__, where tmpdir exists).
                wal_fsync=wal_fsync,
            )
        self.config = config
        self.seeds = seeds
        self.verifier = verifier
        self.metrics_every = metrics_every
        self.vc_timeout_ms = vc_timeout_ms
        self.impl = [impl] * self.config.n if isinstance(impl, str) else list(impl)
        # Per-replica environment overrides (e.g. PBFT_WIRE_CODEC=json to
        # force a JSON-only 1.0.0 peer in a mixed-codec interop test).
        self.extra_env = extra_env or [None] * self.config.n
        self.procs: List[subprocess.Popen] = []
        self.tmpdir: Optional[tempfile.TemporaryDirectory] = None
        self._cmds: List[tuple] = []  # (cmd, env) per replica, for revive()

    def __enter__(self) -> "LocalCluster":
        import random
        import sys

        if self.discovery:
            # Unique group:port per cluster so parallel tests don't hear
            # each other's beacons.
            self._discovery_target = "239.255.%d.%d:%d" % (
                random.randint(1, 254),
                random.randint(1, 254),
                free_ports(1)[0],
            )
        daemon = pbftd_path() if "cxx" in self.impl else None
        self.tmpdir = tempfile.TemporaryDirectory(prefix="pbftd-")
        cfg_path = Path(self.tmpdir.name) / "network.json"
        cfg_path.write_text(self.config.to_json())
        repo_root = str(Path(__file__).resolve().parent.parent.parent)
        for i in range(self.config.n):
            log = open(Path(self.tmpdir.name) / f"replica-{i}.log", "wb")
            if self.impl[i] == "cxx":
                cmd = [str(daemon)]
                env = None
            else:
                cmd = [sys.executable, "-m", "pbft_tpu.net.server"]
                env = dict(os.environ, PYTHONPATH=repo_root)
                if self.verifier != "jax":
                    # Keep a cpu-verifier replica from initializing any
                    # accelerator backend at import time.
                    env["JAX_PLATFORMS"] = "cpu"
            if self.extra_env[i]:
                env = dict(env if env is not None else os.environ)
                env.update(self.extra_env[i])
            cmd += [
                "--config",
                str(cfg_path),
                "--id",
                str(i),
                "--seed",
                self.seeds[i].hex(),
                "--verifier",
                self.verifier,
            ]
            if self.metrics_every:
                cmd += ["--metrics-every", str(self.metrics_every)]
            if self.want_metrics_ports:
                if not self.metrics_ports:
                    self.metrics_ports = free_ports(self.config.n)
                cmd += ["--metrics-port", str(self.metrics_ports[i])]
            if not self._batch_scalar:
                cmd += [
                    "--batch-max-items", str(self.batch_max_items[i]),
                    "--batch-flush-us", str(self.batch_flush_us[i]),
                ]
            if self.vc_timeout_ms:
                cmd += ["--vc-timeout-ms", str(self.vc_timeout_ms)]
            if self.discovery:
                cmd += ["--discovery", self._discovery_target]
            if self.trace_dir:
                cmd += ["--trace", str(Path(self.trace_dir) / f"replica-{i}.jsonl")]
            if self.flight_dir:
                Path(self.flight_dir).mkdir(parents=True, exist_ok=True)
                cmd += [
                    "--flight-file",
                    str(Path(self.flight_dir) / f"replica-{i}.flight"),
                ]
            if self.wal:
                wal_dir = Path(self.tmpdir.name) / "wal"
                wal_dir.mkdir(parents=True, exist_ok=True)
                cmd += ["--wal-dir", str(wal_dir)]
            if i in self.byzantine:
                cmd += ["--byzantine"]
            if self.faults.get(i):
                cmd += ["--fault", str(self.faults[i])]
            if self.chaos_drop_pct > 0:
                cmd += ["--chaos-drop-pct", str(self.chaos_drop_pct)]
            if self.chaos_delay_ms > 0:
                cmd += ["--chaos-delay-ms", str(self.chaos_delay_ms)]
            if (self.chaos_drop_pct > 0 or self.chaos_delay_ms > 0) and (
                self.chaos_seed is not None
            ):
                cmd += ["--chaos-seed", str(self.chaos_seed + i)]
            self._cmds.append((cmd, env))
            self.procs.append(
                subprocess.Popen(
                    cmd, stdout=log, stderr=log, close_fds=True, env=env
                )
            )
        if self.discovery:
            self._learn_discovered_ports()
        self._wait_listening()
        return self

    _discovery_target = ""

    def _learn_discovered_ports(self, timeout: float = 20.0) -> None:
        """Parse each replica's 'listening on N' log line so the *client*
        knows where to dial; the replicas themselves learn each other
        from beacons."""
        import re

        deadline = time.monotonic() + timeout
        ports: dict = {}
        while len(ports) < self.config.n:
            for i in range(self.config.n):
                if i in ports:
                    continue
                log = Path(self.tmpdir.name) / f"replica-{i}.log"
                if log.exists():
                    m = re.search(r"listening on (\d+)", log.read_text(errors="replace"))
                    if m:
                        ports[i] = int(m.group(1))
            if time.monotonic() > deadline:
                raise TimeoutError(f"discovery ports not learned\n{self.logs()}")
            time.sleep(0.05)
        self.config = dataclasses.replace(
            self.config,
            replicas=[
                dataclasses.replace(r, port=ports[i])
                for i, r in enumerate(self.config.replicas)
            ],
        )

    def _wait_listening(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        for ident in self.config.replicas:
            while True:
                try:
                    with socket.create_connection(
                        (ident.host, ident.port), timeout=0.2
                    ) as probe:
                        probe.setsockopt(
                            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                        )
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"replica {ident.replica_id} never listened on "
                            f"{ident.host}:{ident.port}\n{self.logs()}"
                        )
                    time.sleep(0.05)

    def logs(self) -> str:
        out = []
        if self.tmpdir:
            for p in sorted(Path(self.tmpdir.name).glob("replica-*.log")):
                out.append(f"=== {p.name} ===\n{p.read_text(errors='replace')}")
        return "\n".join(out)

    def kill(self, replica_id: int, hard: bool = False) -> None:
        """Crash-stop one replica (fault injection: PBFT tolerates f).
        ``hard=True`` sends SIGKILL (the kill -9 realism arm, ISSUE 15):
        no signal handler runs — no flight dump, no final fsync beyond
        what group commit already made durable."""
        if hard:
            self.procs[replica_id].kill()
        else:
            self.procs[replica_id].terminate()
        self.procs[replica_id].wait(timeout=5)

    _KEEP = object()  # revive() sentinel: carry the original launch flag

    def revive(
        self,
        replica_id: int,
        fault=_KEEP,
        chaos_drop_pct=_KEEP,
        chaos_delay_ms=_KEEP,
        from_disk: bool = False,
    ) -> None:
        """Restart a killed replica.

        The default is the historic FRESH-STATE restart: the daemon
        forgets everything and catches up via checkpoints + state
        transfer (PBFT §5.3). CAVEAT this default silently relies on —
        and tests composing faults must respect — the <= f window: an
        amnesiac restart has forgotten its PREPARE/COMMIT votes, so for
        the duration of its catch-up it can (under adversarial message
        timing) vote differently than its previous life and must be
        budgeted as one of the f tolerable faults. It is safe in every
        scenario that keeps total concurrent faults within f, which is
        why it was acceptable so far — but it is NOT a durability story.

        ``from_disk=True`` (ISSUE 15) is the durability story: the
        daemon relaunches with its original ``--wal-dir`` (requires the
        cluster to have been built with ``wal=True``), replays the
        write-ahead log, re-joins the SAME view at its stable-checkpoint
        floor, and refuses to emit any vote contradicting a persisted
        one — a from-disk restart never spends fault budget.

        Either way the revived daemon CARRIES the fault/chaos flags of
        the original launch, so kill -> revive composes with fault
        schedules instead of silently swapping in a clean replica. Pass
        ``fault=None`` / ``chaos_*=0`` to revive clean(er), or a new
        mode/value to change the behavior across the restart."""
        cmd, env = self._cmds[replica_id]
        if from_disk:
            if "--wal-dir" not in cmd:
                raise ValueError(
                    "revive(from_disk=True) needs a cluster launched with "
                    "wal=True (no --wal-dir on the original command)"
                )
        elif "--wal-dir" in cmd:
            # Fresh-state semantics must stay the default even on a
            # wal-enabled cluster: wipe this replica's log so the replay
            # finds nothing (the amnesia scenario, deliberately).
            ix = cmd.index("--wal-dir")
            wal_path = Path(cmd[ix + 1]) / f"replica-{replica_id}.wal"
            try:
                wal_path.unlink()
            except FileNotFoundError:
                pass
        if fault is not self._KEEP or chaos_drop_pct is not self._KEEP or (
            chaos_delay_ms is not self._KEEP
        ):
            cmd = self._strip_fault_flags(
                list(cmd),
                strip_fault=fault is not self._KEEP,
                strip_drop=chaos_drop_pct is not self._KEEP,
                strip_delay=chaos_delay_ms is not self._KEEP,
            )
            if fault is not self._KEEP and fault:
                cmd += ["--fault", str(fault)]
            if chaos_drop_pct is not self._KEEP and chaos_drop_pct > 0:
                cmd += ["--chaos-drop-pct", str(chaos_drop_pct)]
            if chaos_delay_ms is not self._KEEP and chaos_delay_ms > 0:
                cmd += ["--chaos-delay-ms", str(chaos_delay_ms)]
            self._cmds[replica_id] = (cmd, env)
        log = open(
            Path(self.tmpdir.name) / f"replica-{replica_id}.log", "ab"
        )
        self.procs[replica_id] = subprocess.Popen(
            cmd, stdout=log, stderr=log, close_fds=True, env=env
        )

    @staticmethod
    def _strip_fault_flags(cmd, strip_fault, strip_drop, strip_delay):
        out, skip = [], 0
        for arg in cmd:
            if skip:
                skip -= 1
                continue
            if strip_fault and arg == "--byzantine":
                continue
            if strip_fault and arg == "--fault":
                skip = 1
                continue
            if strip_drop and arg == "--chaos-drop-pct":
                skip = 1
                continue
            if strip_delay and arg == "--chaos-delay-ms":
                skip = 1
                continue
            out.append(arg)
        return out

    def __exit__(self, *exc) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if self.tmpdir:
            self.tmpdir.cleanup()
