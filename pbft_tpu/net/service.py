"""The JAX/TPU verifier service: the FFI boundary between the native replica
runtime and the XLA crypto hot path (SURVEY.md §5 "Distributed communication
backend": consensus-critical small messages stay on the host network; only
signature *batches* cross into the JAX process).

Protocol (mirrors core/verifier.h RemoteVerifier):
    request:  u32be count N, then N * 128 bytes (pub 32 | msg 32 | sig 64)
    response: N bytes, each 0/1

Batches are padded to the next power of two (bounded set of compiled
shapes); pad slots carry a known-good triple so padding cost is pure
compute, never a false reject.

Cross-connection coalescing: when several colocated daemons (one per
replica on a TPU host) submit batches concurrently, a dispatcher merges
everything queued into ONE backend call — one XLA launch for the whole
host's quorum traffic instead of one per daemon. The launch cost is paid
once per *window*, which is the framework's batching-window thesis applied
at the FFI boundary. No artificial delay: the window is exactly "whatever
queued while the previous launch ran".
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, List, Optional, Tuple

Item = Tuple[bytes, bytes, bytes]

# -- readiness handshake wire format (ISSUE 7) -------------------------------
#
# Request header (u32be item count) values that are NOT batches:
#   STATUS_PROBE (0)               -> 8-byte binary status reply
#   STATUS_JSON_PROBE (0xFFFFFFFF) -> u32be length + JSON status reply
# Real batches are capped far below (MAX_WINDOW / the C++ async write
# budget), so neither value can collide with traffic; pre-handshake
# clients never sent count 0 (an empty batch was short-circuited before
# the socket on both runtimes).

STATUS_PROBE = 0
STATUS_JSON_PROBE = 0xFFFFFFFF
STATUS_MAGIC = b"VS"
STATUS_VERSION = 1
STATUS_LEN = 8

STATE_WARMING = 0
STATE_READY = 1
STATE_CPU_ONLY = 2
STATE_NAMES = {
    STATE_WARMING: "warming",
    STATE_READY: "ready",
    STATE_CPU_ONLY: "cpu-only",
}


def pack_status(state: int, devices: int, warmed: int) -> bytes:
    """8 bytes: 'V' 'S' version state u16be devices u16be warmed-shapes."""
    return STATUS_MAGIC + struct.pack(
        ">BBHH", STATUS_VERSION, state, min(devices, 0xFFFF), min(warmed, 0xFFFF)
    )


def unpack_status(blob: bytes) -> Optional[Tuple[int, int, int]]:
    """(state, devices, warmed_shapes), or None if not a status record."""
    if len(blob) != STATUS_LEN or blob[:2] != STATUS_MAGIC:
        return None
    version, state, devices, warmed = struct.unpack(">BBHH", blob[2:])
    if version != STATUS_VERSION or state not in STATE_NAMES:
        return None
    return state, devices, warmed


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # Preallocated buffer + recv_into: the n*128-byte blob read is on the
    # coalesced-window hot path, and the old `bytes += chunk` accumulation
    # re-copied the whole prefix per chunk (quadratic across a large
    # window split into MTU-sized reads).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed mid-message")
        got += r
    return bytes(buf)


def jax_backend(items: List[Item]) -> List[bool]:
    # Single- or multi-chip is decided in one place (sharded over LOCAL
    # devices when several; tests/test_parallel.py pins equivalence).
    from ..parallel import verify_many_auto

    return verify_many_auto(items)


def cpu_backend(items: List[Item]) -> List[bool]:
    from ..crypto import ref

    return [ref.verify(p, m, s) for p, m, s in items]


def native_backend(items: List[Item]) -> List[bool]:
    """The C++ batch verifier (core/ed25519.cc via ctypes): one fast host
    verifier process serving every colocated daemon — the CPU-deployment
    analogue of the jax backend, and the realistic control arm for
    measuring coalesced window occupancy on a box without a chip."""
    from .. import native

    return [bool(v) for v in native.verify_batch(items)]


class _Pending:
    __slots__ = ("items", "event", "verdicts", "error")

    def __init__(self, items: List[Item]):
        self.items = items
        self.event = threading.Event()
        self.verdicts: Optional[List[bool]] = None
        self.error: Optional[Exception] = None


class VerifierService:
    """Threaded TCP (or unix-domain) batch-verification server."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        unix_path: Optional[str] = None,
        backend: Callable[[List[Item]], List[bool]] | str = "jax",
        coalesce: bool = True,
        flush_us: int = 0,
        flush_items: int = 0,
        trace_path: Optional[str] = None,
        inflight: int = 1,
        metrics_port: Optional[int] = None,
        status_provider: Optional[Callable[[], Tuple[int, int, int]]] = None,
        status_json_provider: Optional[Callable[[], dict]] = None,
    ):
        backend_name = backend if isinstance(backend, str) else None
        if isinstance(backend, str):
            backend = {
                "jax": jax_backend,
                "cpu": cpu_backend,
                "native": native_backend,
            }[backend]
        self.backend = backend
        # Readiness handshake (verify_service.py): a bare VerifierService
        # has no warmup lifecycle, so the default status is settled at
        # construction — "ready" for the jax backend (it warms lazily on
        # first traffic, the pre-daemon behavior), "cpu-only" for
        # everything else (incl. test callables). The daemon overrides
        # both providers with its live state machine.
        self._status_provider = status_provider or (
            lambda: (
                STATE_READY if backend_name == "jax" else STATE_CPU_ONLY,
                0,
                0,
            )
        )
        self._status_json_provider = status_json_provider or (
            lambda: {
                "state": STATE_NAMES[self._status_provider()[0]],
                "devices": self._status_provider()[1],
                "backend": backend_name or "custom",
                "requests": self.requests,
                "launches": self.batches,
                "items": self.items,
            }
        )
        # Bounded accumulation (the service-side analogue of the replicas'
        # verify_flush_us): after the first request queues, the dispatcher
        # waits until flush_items are pending (0 = MAX_WINDOW) or flush_us
        # have passed, trading that much latency for a fatter merged
        # window. 0 = dispatch as soon as the previous launch returns.
        self._flush_s = flush_us / 1e6
        self._flush_target = flush_items or self.MAX_WINDOW
        # Overlapped launches: with inflight > 1 the dispatcher ships
        # window N+1 while N is still executing, hiding host-side launch
        # overhead behind device compute (XLA serializes execution per
        # device; the dispatch/transfer cost is what overlaps). Default 1
        # preserves the "window = what queued during the previous launch"
        # dynamic; raising it trades window size for launch concurrency.
        self._inflight = max(1, inflight)
        self._inflight_sem = threading.Semaphore(self._inflight)
        self._launch_threads: List[threading.Thread] = []
        # Per-dispatch JSONL trace ({"ev":"verify_batch","size":merged,..}):
        # the honest occupancy measurement for the launch-cost model — the
        # merged window IS the launch, where per-replica traces only see
        # each daemon's share.
        from ..utils.trace import Tracer

        self._tracer = Tracer(open(trace_path, "a") if trace_path else None)
        # Metrics (utils/metrics.py; the verify subset of the cross-runtime
        # contract in utils/trace_schema.py). Disabled unless a scrape
        # surface was asked for — the dispatcher is the single writer.
        from ..utils import MetricsRegistry, start_metrics_server

        self.metrics_registry = MetricsRegistry(
            labels={"replica": "service"}, enabled=metrics_port is not None
        )
        if self.metrics_registry.enabled:
            self.metrics_registry.preregister("service.py")
        self._metrics_server = None
        self.metrics_listen_port = 0
        if metrics_port is not None:
            self._metrics_server = start_metrics_server(self.metrics_registry, metrics_port)
            self.metrics_listen_port = self._metrics_server.server_address[1]
        self.batches = 0  # backend calls (XLA launches)
        self.requests = 0  # wire requests (>= batches when coalescing)
        self.items = 0
        self._coalesce = coalesce
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        self._running = True
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                # TCP_NODELAY on accepted verify streams (ISSUE 10 socket
                # discipline): the 1-byte-per-item verdict reply must not
                # sit in a Nagle stall. Unix sockets have no Nagle.
                if self.request.family == socket.AF_INET:
                    self.request.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )

            def handle(self):  # one connection, many batches
                sock = self.request
                try:
                    while True:
                        header = _recv_exact(sock, 4)
                        n = int.from_bytes(header, "big")
                        if n == STATUS_PROBE:
                            # Readiness handshake: replicas/bench decide
                            # whether to route here before shipping work.
                            sock.sendall(pack_status(*service._status_provider()))
                            continue
                        if n == STATUS_JSON_PROBE:
                            blob = json.dumps(
                                service._status_json_provider()
                            ).encode()
                            sock.sendall(len(blob).to_bytes(4, "big") + blob)
                            continue
                        blob = _recv_exact(sock, n * 128)
                        items = [
                            (
                                blob[i * 128 : i * 128 + 32],
                                blob[i * 128 + 32 : i * 128 + 64],
                                blob[i * 128 + 64 : i * 128 + 128],
                            )
                            for i in range(n)
                        ]
                        verdicts = service._submit(items)
                        sock.sendall(bytes(1 if v else 0 for v in verdicts))
                except (ConnectionError, OSError):
                    return

        if unix_path is not None:

            class UnixServer(socketserver.ThreadingUnixStreamServer):
                daemon_threads = True

            self.server = UnixServer(unix_path, Handler)
            self.address = unix_path
        else:

            class TcpServer(socketserver.ThreadingTCPServer):
                daemon_threads = True
                allow_reuse_address = True

            self.server = TcpServer((host, port), Handler)
            self.address = "%s:%d" % self.server.server_address
        self._thread: Optional[threading.Thread] = None
        self._dispatcher: Optional[threading.Thread] = None
        if self._coalesce:
            # Started here (not in start()) so the CLI's bare
            # serve_forever() path coalesces too.
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True
            )
            self._dispatcher.start()

    # Largest merged window, in items: the top of the pad ladder
    # (crypto/batch.py _PAD_LADDER) — bigger merges would compile new XLA
    # shapes at runtime. Overflow stays queued for the next window.
    MAX_WINDOW = 4096

    def _submit(self, items: List[Item]) -> List[bool]:
        """Handler-thread entry: verify `items`, possibly merged with other
        connections' concurrent submissions into one backend call."""
        if not self._coalesce:
            with self._cond:
                self.requests += 1
                self.batches += 1
                self.items += len(items)
            t0 = time.monotonic()
            verdicts = self._checked(self.backend, items)
            if self.metrics_registry.enabled:
                self.metrics_registry.counter("pbft_verify_batches_total").inc()
                self.metrics_registry.counter("pbft_verify_items_total").inc(len(items))
                self.metrics_registry.counter("pbft_verify_rejected_total").inc(
                    verdicts.count(False)
                )
                self.metrics_registry.histogram("pbft_verify_batch_size").observe(len(items))
                self.metrics_registry.histogram("pbft_verify_seconds").observe(
                    time.monotonic() - t0
                )
                # Service-surface mirror (ISSUE 7): uncoalesced, every
                # request is its own single-client launch window.
                self.metrics_registry.counter(
                    "pbft_verify_service_launches_total"
                ).inc()
                self.metrics_registry.histogram(
                    "pbft_verify_service_window_size"
                ).observe(len(items))
                self.metrics_registry.histogram(
                    "pbft_verify_service_coalesced_clients"
                ).observe(1)
            return verdicts
        p = _Pending(items)
        with self._cond:
            self.requests += 1
            if not self._running:  # dispatcher gone: fail this connection
                raise ConnectionError("verifier service stopping")
            self._pending.append(p)
            self._cond.notify()
        # No fixed deadline (a first XLA compile can legitimately take
        # minutes), but a dead dispatcher must not strand the connection.
        while not p.event.wait(timeout=1.0):
            if self._dispatcher is not None and not self._dispatcher.is_alive():
                raise ConnectionError("verifier dispatcher died")
        if p.error is not None:
            raise ConnectionError(f"verification failed: {p.error!r}")
        assert p.verdicts is not None
        return p.verdicts

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._pending:
                    self._cond.wait(0.5)
                if not self._running and not self._pending:
                    return
                if self._flush_s > 0:
                    # Bounded accumulation: hold the window open until the
                    # item target or the deadline. _cond.wait releases the
                    # lock, so handler threads keep enqueueing meanwhile.
                    deadline = time.monotonic() + self._flush_s
                    while (
                        self._running
                        and sum(len(p.items) for p in self._pending)
                        < self._flush_target
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                # Take whole requests up to MAX_WINDOW items (a single
                # oversized request still goes through, alone).
                window: List[_Pending] = []
                size = 0
                while self._pending:
                    nxt = len(self._pending[0].items)
                    if window and size + nxt > self.MAX_WINDOW:
                        break
                    size += nxt
                    window.append(self._pending.pop(0))
                if self.metrics_registry.enabled:  # items left queued past MAX_WINDOW
                    self.metrics_registry.gauge("pbft_verify_queue_depth").set(
                        sum(len(p.items) for p in self._pending)
                    )
            self._inflight_sem.acquire()
            if self._inflight == 1:
                self._dispatch_guarded(window)
            else:
                # Overlapped mode: the launch runs on its own thread while
                # the dispatcher loops back to accumulate the next window.
                t = threading.Thread(
                    target=self._dispatch_guarded, args=(window,), daemon=True
                )
                with self._cond:  # stop() reads this list concurrently
                    self._launch_threads = [
                        x for x in self._launch_threads if x.is_alive()
                    ]
                    self._launch_threads.append(t)
                t.start()

    def _dispatch_guarded(self, window: List[_Pending]) -> None:
        try:
            self._dispatch_window(window)
        except Exception as e:  # noqa: BLE001 - never strand a handler
            # Any dispatcher bug outside the backend guard must still
            # wake every waiting connection with an error rather than
            # leaving clients hung mid-read.
            for p in window:
                if not p.event.is_set():
                    p.error = e
                    p.event.set()
        finally:
            self._inflight_sem.release()

    @staticmethod
    def _checked(backend, items: List[Item]) -> List[bool]:
        """Run the backend and validate the verdict count — a wrong-length
        result would otherwise mis-slice silently across connections."""
        verdicts = backend(items)
        if verdicts is None or len(verdicts) != len(items):
            got = "None" if verdicts is None else str(len(verdicts))
            raise ValueError(
                f"backend returned {got} verdicts for {len(items)} items"
            )
        return verdicts

    def _dispatch_window(self, window: List[_Pending]) -> None:
        merged: List[Item] = []
        for p in window:
            merged.extend(p.items)
        t0 = time.monotonic()
        try:
            verdicts = self._checked(self.backend, merged)
        except Exception:
            # One launch failing must not reject every client's honest
            # signatures ("never a false reject"): retry each request
            # alone so only the actually-poisoned one errors out.
            verdicts = None
        if self._tracer.enabled:
            # A failed merged launch is NOT a verify_batch event: the
            # launch-cost model reads verify_batch sizes as items-per-
            # launch, and counting the failed merge (plus not counting
            # its per-request retries below) would overstate occupancy.
            self._tracer.event(
                "verify_batch" if verdicts is not None else "verify_window_failed",
                replica="service",
                size=len(merged),
                requests=len(window),
                rejected=(
                    verdicts.count(False) if verdicts is not None else -1
                ),
                secs=round(time.monotonic() - t0, 6),
            )
        with self._cond:
            self.batches += 1
            self.items += len(merged)
            if self.metrics_registry.enabled:
                # Under the lock: with --inflight > 1 several launch
                # threads finish concurrently (the replica runtimes'
                # single-writer discipline doesn't hold here).
                secs = time.monotonic() - t0
                self.metrics_registry.counter("pbft_verify_batches_total").inc()
                self.metrics_registry.counter("pbft_verify_items_total").inc(len(merged))
                self.metrics_registry.histogram("pbft_verify_batch_size").observe(
                    len(merged)
                )
                self.metrics_registry.histogram("pbft_verify_seconds").observe(secs)
                self.metrics_registry.gauge("pbft_verify_inflight_age_seconds").set(
                    round(secs, 6)
                )
                # Service launch surface (ISSUE 7): items per XLA launch
                # and how many connections each merged window carried —
                # the coalescing win the launch-cost model prices.
                self.metrics_registry.counter(
                    "pbft_verify_service_launches_total"
                ).inc()
                self.metrics_registry.histogram(
                    "pbft_verify_service_window_size"
                ).observe(len(merged))
                self.metrics_registry.histogram(
                    "pbft_verify_service_coalesced_clients"
                ).observe(len(window))
                if verdicts is not None:
                    self.metrics_registry.counter("pbft_verify_rejected_total").inc(
                        verdicts.count(False)
                    )
        if verdicts is None:
            for p in window:
                t1 = time.monotonic()
                try:
                    p.verdicts = self._checked(self.backend, p.items)
                except Exception as e:  # noqa: BLE001 - handed to submitter
                    p.error = e
                if self._tracer.enabled:
                    if p.verdicts is not None:
                        self._tracer.event(
                            "verify_batch",
                            replica="service",
                            size=len(p.items),
                            requests=1,
                            rejected=p.verdicts.count(False),
                            secs=round(time.monotonic() - t1, 6),
                        )
                    else:
                        # NOT a verify_batch event: trace_report sums the
                        # rejected field over verify_batch events, and an
                        # errored retry has no verdicts to count.
                        self._tracer.event(
                            "verify_batch_error",
                            replica="service",
                            size=len(p.items),
                            secs=round(time.monotonic() - t1, 6),
                        )
                p.event.set()
            return
        off = 0
        for p in window:
            p.verdicts = verdicts[off : off + len(p.items)]
            off += len(p.items)
            p.event.set()

    def start(self) -> "VerifierService":
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # Flip _running BEFORE joining anything: handlers enqueueing after
        # this point get a ConnectionError instead of waiting on an event
        # nobody will set; the dispatcher drains what's already queued.
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._dispatcher:
            self._dispatcher.join(timeout=5)
        with self._cond:
            launch_threads = list(self._launch_threads)
        for t in launch_threads:
            t.join(timeout=5)
        if self._tracer.sink is not None and (
            (self._dispatcher is None or not self._dispatcher.is_alive())
            and not any(t.is_alive() for t in launch_threads)
        ):
            # Only close once the dispatcher is provably done with it: a
            # join timeout (e.g. a minutes-long first XLA compile still in
            # flight) must leak the fd rather than turn that window's
            # successful verifications into I/O errors mid-write.
            self._tracer.sink.close()
            self._tracer = type(self._tracer)()  # disabled from here on


def main() -> None:
    """CLI: run the service for a pbftd cluster (TPU by default)."""
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7600)
    parser.add_argument("--unix", default=None)
    parser.add_argument(
        "--backend", default="jax", choices=["jax", "cpu", "native"]
    )
    parser.add_argument(
        "--flush-us",
        type=int,
        default=0,
        help="bounded accumulation: hold each window up to this many "
        "microseconds (0 = dispatch immediately)",
    )
    parser.add_argument(
        "--flush-items",
        type=int,
        default=0,
        help="...or until this many items are pending (0 = MAX_WINDOW)",
    )
    parser.add_argument(
        "--trace", default=None, help="JSONL per-dispatch trace file"
    )
    parser.add_argument(
        "--inflight",
        type=int,
        default=1,
        help="overlapped launches: ship window N+1 while N executes "
        "(hides host-side launch overhead; 1 = serial)",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve Prometheus text format on this port (0 = ephemeral)",
    )
    args = parser.parse_args()
    svc = VerifierService(
        host=args.host,
        port=args.port,
        unix_path=args.unix,
        backend=args.backend,
        flush_us=args.flush_us,
        flush_items=args.flush_items,
        trace_path=args.trace,
        inflight=args.inflight,
        metrics_port=args.metrics_port,
    )
    print(f"verifier service on {svc.address} backend={args.backend}", flush=True)
    svc.server.serve_forever()


if __name__ == "__main__":
    main()
