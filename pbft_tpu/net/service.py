"""The JAX/TPU verifier service: the FFI boundary between the native replica
runtime and the XLA crypto hot path (SURVEY.md §5 "Distributed communication
backend": consensus-critical small messages stay on the host network; only
signature *batches* cross into the JAX process).

Protocol (mirrors core/verifier.h RemoteVerifier):
    request:  u32be count N, then N * 128 bytes (pub 32 | msg 32 | sig 64)
    response: N bytes, each 0/1

One request = one padded-batch XLA launch. Batches are padded to the next
power of two (bounded set of compiled shapes); pad slots carry a known-good
triple so padding cost is pure compute, never a false reject.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Callable, List, Optional, Tuple

Item = Tuple[bytes, bytes, bytes]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def jax_backend(items: List[Item]) -> List[bool]:
    from ..crypto import batch

    return batch.verify_many(items)


def cpu_backend(items: List[Item]) -> List[bool]:
    from ..crypto import ref

    return [ref.verify(p, m, s) for p, m, s in items]


class VerifierService:
    """Threaded TCP (or unix-domain) batch-verification server."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        unix_path: Optional[str] = None,
        backend: Callable[[List[Item]], List[bool]] | str = "jax",
    ):
        if isinstance(backend, str):
            backend = {"jax": jax_backend, "cpu": cpu_backend}[backend]
        self.backend = backend
        self.batches = 0
        self.items = 0
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):  # one connection, many batches
                sock = self.request
                try:
                    while True:
                        header = _recv_exact(sock, 4)
                        n = int.from_bytes(header, "big")
                        blob = _recv_exact(sock, n * 128)
                        items = [
                            (
                                blob[i * 128 : i * 128 + 32],
                                blob[i * 128 + 32 : i * 128 + 64],
                                blob[i * 128 + 64 : i * 128 + 128],
                            )
                            for i in range(n)
                        ]
                        verdicts = service.backend(items)
                        service.batches += 1
                        service.items += n
                        sock.sendall(bytes(1 if v else 0 for v in verdicts))
                except (ConnectionError, OSError):
                    return

        if unix_path is not None:

            class UnixServer(socketserver.ThreadingUnixStreamServer):
                daemon_threads = True

            self.server = UnixServer(unix_path, Handler)
            self.address = unix_path
        else:

            class TcpServer(socketserver.ThreadingTCPServer):
                daemon_threads = True
                allow_reuse_address = True

            self.server = TcpServer((host, port), Handler)
            self.address = "%s:%d" % self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "VerifierService":
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main() -> None:
    """CLI: run the service for a pbftd cluster (TPU by default)."""
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7600)
    parser.add_argument("--unix", default=None)
    parser.add_argument("--backend", default="jax", choices=["jax", "cpu"])
    args = parser.parse_args()
    svc = VerifierService(
        host=args.host, port=args.port, unix_path=args.unix, backend=args.backend
    )
    print(f"verifier service on {svc.address} backend={args.backend}", flush=True)
    svc.server.serve_forever()


if __name__ == "__main__":
    main()
