"""Authenticated, encrypted replica-replica links.

The reference secures every libp2p link with ``development_transport``
(Noise encryption + yamux muxing, reference src/main.rs:42) and names its
protocol ``/ackintosh/pbft/1.0.0`` (reference src/protocol_config.rs:24).
This module is the rebuild's equivalent, designed around the primitives
both runtimes already ship (Ed25519 point arithmetic + BLAKE2b) instead
of pulling in a Noise stack:

- **Handshake**: signed ephemeral Diffie-Hellman on edwards25519 (the
  station-to-station pattern). Each side sends a fresh ephemeral public
  key; both sign the transcript hash with their *identity* key (the one
  registered in network.json), giving mutual authentication + forward
  secrecy. ECDH reuses the existing curve code — clamped scalars clear
  the cofactor exactly as in X25519.
- **Versioning**: the first frame on every peer connection is a plaintext
  ``hello`` carrying ``ver``; a mismatch is answered with a ``reject``
  frame naming both versions, then the connection closes — a mixed-version
  cluster fails loudly instead of with undiagnosable JSON errors.
- **AEAD**: encrypt-then-MAC with keyed BLAKE2b (RFC 7693 keyed mode is a
  PRF): per-direction keys, implicit frame counters (TCP preserves
  order), 64-byte keystream blocks, 16-byte tag. hashlib.blake2b on this
  side; core/blake2b.cc's keyed mode on the C++ side — byte-identical
  (tests/test_secure.py pins interop).

Handshake frames (canonical JSON payloads inside the normal 4-byte
length framing; initiator = the dialing replica):

    hello_i: {"type":"hello","ver":V,"node":i,"eph":<64hex>}
    hello_r: {"type":"hello","ver":V,"node":r,"eph":<64hex>,"sig":<128hex>}
    auth_i:  {"type":"auth","node":i,"sig":<128hex>}
    reject:  {"type":"reject","reason":...,"ver":V}

with sig_r = Ed25519(identity_r, transcript || "|resp") and
sig_i = Ed25519(identity_i, transcript || "|init"), where
transcript = BLAKE2b-256("pbft-tpu-hs1|" + V + "|" + eph_i + "|" + eph_r).
In plaintext clusters (``secure: false``) only ``hello_i`` is sent — the
version check still runs on every link, but no keys are negotiated.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets
from typing import Optional, Tuple

from ..consensus.messages import CODEC_BINARY2
from ..crypto import ref

# 1.1.0 adds the negotiated binary-v2 payload codec
# (consensus/messages.py); 1.2.0 adds the batched pre-prepare (binary
# 0x06 / JSON `requests`, ISSUE 4) whose batch=1 frames stay
# byte-identical to 1.1.0; 1.3.0 adds the fast-path modes (ISSUE 14):
# per-link session-MAC authenticators on normal-case frames (the
# MAC-vector binary variants, consensus/messages.py 0x12-0x16) and the
# tentative client-reply flag. Older peers stay interoperable — the
# hello's ver gates what a sender may offer (a link only runs MAC mode
# when BOTH hellos offered "mac1"), the handshake transcript binds to
# the initiator's advertised version so mixed-version secure handshakes
# still agree on the signed bytes, and a batching primary simply must
# not be pointed at pre-1.2.0 peers with batch_max_items > 1.
PROTOCOL_VERSION = "pbft-tpu/1.3.0"
PROTOCOL_VERSION_BATCH = "pbft-tpu/1.2.0"
PROTOCOL_VERSION_BIN2 = "pbft-tpu/1.1.0"
PROTOCOL_VERSION_LEGACY = "pbft-tpu/1.0.0"
_COMPATIBLE_VERSIONS = (
    PROTOCOL_VERSION,
    PROTOCOL_VERSION_BATCH,
    PROTOCOL_VERSION_BIN2,
    PROTOCOL_VERSION_LEGACY,
)

# The authenticator-mode offer carried in the 1.3.0 hello's "auth" list
# (mirrors core/secure.h kAuthModeMac; constants lint): per-link session
# MACs over the signable digest, keys derived from the handshake
# transcript. MAC_TAG_LEN and MAC_CONTEXT are the tag width and the
# domain-separation label (core/secure.h kMacTagLen / kMacContext).
AUTH_MODE_MAC = "mac1"
MAC_TAG_LEN = 16
MAC_CONTEXT = "pbft-tpu-auth1|"


def _wire_json_forced() -> bool:
    return os.environ.get("PBFT_WIRE_CODEC") == "json"


def _proto_capped_12() -> bool:
    """PBFT_PROTO_CAP=1.2.0 advertises the 1.2.0 hello with no fast-path
    offer — the interop-test lever simulating a pre-1.3.0 peer (the same
    role PBFT_WIRE_CODEC=json plays for 1.0.0)."""
    return os.environ.get("PBFT_PROTO_CAP") == "1.2.0"


def wire_hello_version() -> str:
    """The version this node advertises: 1.3.0 with the codec + fast-path
    offers, 1.2.0 under PBFT_PROTO_CAP=1.2.0, or the legacy 1.0.0
    JSON-only hello when PBFT_WIRE_CODEC=json (the mixed-cluster escape
    hatches and the interop-test levers)."""
    if _wire_json_forced():
        return PROTOCOL_VERSION_LEGACY
    if _proto_capped_12():
        return PROTOCOL_VERSION_BATCH
    return PROTOCOL_VERSION


def wire_offer_binary() -> bool:
    return not _wire_json_forced()


def wire_offer_mac(fastpath_mac: bool) -> bool:
    """Whether this node's hellos offer the MAC authenticator mode: the
    cluster config asked for it (fastpath == "mac") AND nothing capped
    the advertised protocol below 1.3.0."""
    return fastpath_mac and not _wire_json_forced() and not _proto_capped_12()


def hello_offers_binary(obj: dict) -> bool:
    """True when a peer's hello offers the binary-v2 codec (and this node
    offers it too): the sender may then encode hot messages as binary."""
    if not wire_offer_binary():
        return False
    codecs = obj.get("codecs")
    return isinstance(codecs, list) and CODEC_BINARY2 in codecs


def hello_offers_mac(obj: dict) -> bool:
    """True when a peer's hello offers the MAC authenticator mode. The
    caller still ANDs this with its own offer — a link runs MAC frames
    only when both sides advertised mac1."""
    auth = obj.get("auth")
    return isinstance(auth, list) and AUTH_MODE_MAC in auth


def _attach_codecs(o: dict, offer_mac: bool = False) -> dict:
    if wire_offer_binary():
        o["codecs"] = [CODEC_BINARY2]
    if wire_offer_mac(offer_mac):
        o["auth"] = [AUTH_MODE_MAC]
    return o


def mac_tag(key: bytes, signable_digest: bytes) -> bytes:
    """One authenticator lane: keyed BLAKE2b over the domain label + the
    32-byte signable digest (the same bytes a signature would cover).
    Byte-identical to core/secure.cc mac_tag."""
    return hashlib.blake2b(
        MAC_CONTEXT.encode() + signable_digest, key=key,
        digest_size=MAC_TAG_LEN,
    ).digest()
_HS_CONTEXT = b"pbft-tpu-hs1|"
_KDF_CONTEXT = b"pbft-tpu-k1|"
TAG_LEN = 16
# Point of small order (the identity) in compressed encoding: y = 1.
_IDENTITY_ENC = (1).to_bytes(32, "little")


def _clamp(k: bytes) -> int:
    a = int.from_bytes(k, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def dh_keypair(seed: Optional[bytes] = None) -> Tuple[bytes, bytes]:
    """Ephemeral keypair: (secret 32B, compressed public 32B)."""
    if seed is None:
        seed = secrets.token_bytes(32)
    pub = ref.point_compress(ref.scalar_mult(_clamp(seed), ref.BASE))
    return seed, pub


def dh_shared(secret: bytes, peer_pub: bytes) -> Optional[bytes]:
    """Shared secret = compress(clamp(secret) * decompress(peer_pub)).

    None on an invalid peer point or a small-order result (the clamped
    scalar is a multiple of 8, so a small-order peer point collapses to
    the identity — rejecting it prevents a key-contribution bypass).
    """
    pt = ref.point_decompress(peer_pub)
    if pt is None:
        return None
    out = ref.point_compress(ref.scalar_mult(_clamp(secret), pt))
    if out == _IDENTITY_ENC:
        return None
    return out


def transcript(ver: str, eph_i: bytes, eph_r: bytes) -> bytes:
    return hashlib.blake2b(
        _HS_CONTEXT + ver.encode() + b"|" + eph_i + b"|" + eph_r,
        digest_size=32,
    ).digest()


def derive_keys(shared: bytes, eph_i: bytes, eph_r: bytes) -> Tuple[bytes, bytes]:
    """(key_i2r, key_r2i): 64 bytes each = enc key 32 || mac key 32."""
    def kdf(label: bytes) -> bytes:
        return hashlib.blake2b(
            _KDF_CONTEXT + label + b"|" + eph_i + b"|" + eph_r,
            key=shared,
            digest_size=64,
        ).digest()

    return kdf(b"i2r"), kdf(b"r2i")


def derive_auth_keys(
    shared: bytes, eph_i: bytes, eph_r: bytes
) -> Tuple[bytes, bytes]:
    """(auth_i2r, auth_r2i): 32 bytes each — the per-direction session
    keys behind the ISSUE 14 MAC-vector authenticators. Derived from the
    SAME handshake transcript material as the AEAD keys but under
    distinct labels, so authenticator lanes and frame sealing never share
    key bytes. Byte-identical to core/secure.cc derive_key("a-i2r"...)."""
    def kdf(label: bytes) -> bytes:
        return hashlib.blake2b(
            _KDF_CONTEXT + label + b"|" + eph_i + b"|" + eph_r,
            key=shared,
            digest_size=32,
        ).digest()

    return kdf(b"a-i2r"), kdf(b"a-r2i")


def seal(key: bytes, ctr: int, plaintext: bytes) -> bytes:
    """ciphertext || 16-byte tag (encrypt-then-MAC, keyed BLAKE2b)."""
    enc, mac = key[:32], key[32:]
    nonce = ctr.to_bytes(8, "little")
    ks = b"".join(
        hashlib.blake2b(
            nonce + j.to_bytes(4, "little"), key=enc, digest_size=64
        ).digest()
        for j in range((len(plaintext) + 63) // 64)
    )
    n = len(plaintext)
    ct = (
        int.from_bytes(plaintext, "little") ^ int.from_bytes(ks[:n], "little")
    ).to_bytes(n, "little")
    tag = hashlib.blake2b(nonce + ct, key=mac, digest_size=TAG_LEN).digest()
    return ct + tag


def open_sealed(key: bytes, ctr: int, sealed: bytes) -> Optional[bytes]:
    """Inverse of seal(); None on a bad tag (constant-time compare)."""
    if len(sealed) < TAG_LEN:
        return None
    ct, tag = sealed[:-TAG_LEN], sealed[-TAG_LEN:]
    nonce = ctr.to_bytes(8, "little")
    expect = hashlib.blake2b(nonce + ct, key=key[32:], digest_size=TAG_LEN).digest()
    if not hmac.compare_digest(expect, tag):
        return None
    ks = b"".join(
        hashlib.blake2b(
            nonce + j.to_bytes(4, "little"), key=key[:32], digest_size=64
        ).digest()
        for j in range((len(ct) + 63) // 64)
    )
    n = len(ct)
    return (
        int.from_bytes(ct, "little") ^ int.from_bytes(ks[:n], "little")
    ).to_bytes(n, "little")


class HandshakeError(Exception):
    """Terminal handshake failure; the connection must close."""


def _hex_field(obj: dict, key: str, nbytes: int) -> bytes:
    """Decode a hex handshake field; malformed input is a protocol error
    (HandshakeError), never a stray ValueError escaping the handler."""
    val = obj.get(key)
    if not isinstance(val, str) or len(val) != 2 * nbytes:
        raise HandshakeError(f"handshake frame without valid {key!r} field")
    try:
        return bytes.fromhex(val)
    except ValueError:
        raise HandshakeError(f"non-hex {key!r} field in handshake frame")


class SecureChannel:
    """One connection's handshake state machine + sealed-frame codec.

    Drive with ``initiator_hello()`` / ``on_hello()`` / ``on_hello_reply()``
    / ``on_auth()`` until ``established``; then ``seal_frame()`` /
    ``open_frame()``. Byte-compatible with core/secure.cc.
    """

    def __init__(
        self,
        my_id: int,
        identity_seed: bytes,
        pubkey_of,  # Callable[[int], Optional[bytes]] — network.json table
        initiator: bool,
        expected_peer: Optional[int] = None,
        eph_secret: Optional[bytes] = None,
        offer_mac: bool = False,
        auth_only: bool = False,
    ):
        self.my_id = my_id
        self._seed = identity_seed
        self._pubkey_of = pubkey_of
        self.initiator = initiator
        self.expected_peer = expected_peer
        self.peer_id: Optional[int] = None
        self._eph_secret, self.eph_pub = dh_keypair(eph_secret)
        self._peer_eph: Optional[bytes] = None
        self._send_key: Optional[bytes] = None
        self._recv_key: Optional[bytes] = None
        self._send_ctr = 0
        self._recv_ctr = 0
        self.established = False
        # Fast-path negotiation (ISSUE 14): whether THIS node offers the
        # MAC authenticator mode, whether the peer's hello offered it,
        # and the per-direction session keys once established.
        # ``auth_only`` marks a channel that runs the SAME signed
        # handshake purely for key agreement + identity — frames on the
        # link stay plaintext (the fastpath=mac, secure=false flavor);
        # callers must not seal/open through an auth-only channel.
        self.offer_mac = offer_mac
        self.auth_only = auth_only
        self.peer_offers_mac = False
        self.auth_send_key: Optional[bytes] = None
        self.auth_recv_key: Optional[bytes] = None
        # The transcript binds to the INITIATOR's advertised version
        # (both sides know it after hello_i): initiator = the version it
        # sends; responder = set from hello_i in on_hello.
        self._hs_version = wire_hello_version()

    # -- handshake ----------------------------------------------------------

    def initiator_hello(self) -> dict:
        return _attach_codecs(
            {
                "type": "hello",
                "ver": wire_hello_version(),
                "node": self.my_id,
                "eph": self.eph_pub.hex(),
            },
            offer_mac=self.offer_mac,
        )

    @staticmethod
    def check_version(obj: dict) -> None:
        # Compatible set, not exact match: 1.1.0 only ADDS the negotiated
        # binary codec, so 1.0.0 peers interoperate (JSON frames both ways).
        ver = obj.get("ver")
        if ver not in _COMPATIBLE_VERSIONS:
            raise HandshakeError(
                f"protocol version mismatch: peer speaks {ver!r}, "
                f"this node speaks {PROTOCOL_VERSION!r}"
            )

    def _transcript(self) -> bytes:
        eph_i = self.eph_pub if self.initiator else self._peer_eph
        eph_r = self._peer_eph if self.initiator else self.eph_pub
        return transcript(self._hs_version, eph_i, eph_r)

    def _finish(self) -> None:
        shared = dh_shared(self._eph_secret, self._peer_eph)
        if shared is None:
            raise HandshakeError("invalid ephemeral key from peer")
        eph_i = self.eph_pub if self.initiator else self._peer_eph
        eph_r = self._peer_eph if self.initiator else self.eph_pub
        k_i2r, k_r2i = derive_keys(shared, eph_i, eph_r)
        self._send_key = k_i2r if self.initiator else k_r2i
        self._recv_key = k_r2i if self.initiator else k_i2r
        a_i2r, a_r2i = derive_auth_keys(shared, eph_i, eph_r)
        self.auth_send_key = a_i2r if self.initiator else a_r2i
        self.auth_recv_key = a_r2i if self.initiator else a_i2r
        self.established = True

    def _verify_peer_sig(self, obj: dict, label: bytes) -> None:
        node = obj.get("node")
        if not isinstance(node, int):
            raise HandshakeError("handshake frame without node id")
        if self.expected_peer is not None and node != self.expected_peer:
            raise HandshakeError(
                f"peer claims node {node}, expected {self.expected_peer}"
            )
        pub = self._pubkey_of(node)
        if pub is None:
            raise HandshakeError(f"unknown node id {node}")
        sig = _hex_field(obj, "sig", 64)
        if not ref.verify(pub, self._transcript() + label, sig):
            raise HandshakeError(f"bad handshake signature from node {node}")
        self.peer_id = node

    def on_hello(self, obj: dict) -> dict:
        """Responder: process hello_i, return hello_r."""
        self.check_version(obj)
        if not isinstance(obj.get("eph"), str):
            raise HandshakeError(
                "plaintext peer rejected: this cluster requires encrypted "
                "links (hello carried no ephemeral key)"
            )
        # check_version admitted the initiator's version into the
        # compatible set; the transcript binds to it.
        self._hs_version = obj["ver"]
        self.peer_offers_mac = hello_offers_mac(obj)
        self._peer_eph = _hex_field(obj, "eph", 32)
        sig = ref.sign(self._seed, self._transcript() + b"|resp")
        return _attach_codecs(
            {
                "type": "hello",
                "ver": wire_hello_version(),
                "node": self.my_id,
                "eph": self.eph_pub.hex(),
                "sig": sig.hex(),
            },
            offer_mac=self.offer_mac,
        )

    def on_hello_reply(self, obj: dict) -> dict:
        """Initiator: process hello_r, return auth_i; channel established."""
        if obj.get("type") == "reject":
            raise HandshakeError(f"peer rejected handshake: {obj.get('reason')}")
        self.check_version(obj)
        if not isinstance(obj.get("eph"), str):
            raise HandshakeError("responder hello carried no ephemeral key")
        self.peer_offers_mac = hello_offers_mac(obj)
        self._peer_eph = _hex_field(obj, "eph", 32)
        self._verify_peer_sig(obj, b"|resp")
        sig = ref.sign(self._seed, self._transcript() + b"|init")
        self._finish()
        return {"type": "auth", "node": self.my_id, "sig": sig.hex()}

    def on_auth(self, obj: dict) -> None:
        """Responder: process auth_i; channel established."""
        if self._peer_eph is None:
            raise HandshakeError("auth before hello")
        self._verify_peer_sig(obj, b"|init")
        self._finish()

    @property
    def mac_negotiated(self) -> bool:
        """Both sides offered the MAC authenticator mode on this link."""
        return wire_offer_mac(self.offer_mac) and self.peer_offers_mac

    # -- sealed frames ------------------------------------------------------

    def seal_frame(self, payload: bytes) -> bytes:
        sealed = seal(self._send_key, self._send_ctr, payload)
        self._send_ctr += 1
        return sealed

    def open_frame(self, sealed: bytes) -> bytes:
        payload = open_sealed(self._recv_key, self._recv_ctr, sealed)
        if payload is None:
            raise HandshakeError(
                f"AEAD tag mismatch on frame {self._recv_ctr} "
                f"from node {self.peer_id}"
            )
        self._recv_ctr += 1
        return payload


def reject_payload(reason: str) -> dict:
    return {"type": "reject", "reason": reason, "ver": wire_hello_version()}


def plain_hello(my_id: int, offer_mac: bool = False) -> dict:
    """The version-carrying (and codec-offering) hello sent on plaintext
    peer links — both as the dialing side's first frame and as the
    responder's hello-ack that lets the dialer negotiate binary-v2."""
    return _attach_codecs(
        {"type": "hello", "ver": wire_hello_version(), "node": my_id},
        offer_mac=offer_mac,
    )
