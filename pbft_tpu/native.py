"""ctypes bindings to the C++ core (core/ -> libpbftcore.so).

The native library provides the CPU verifier backend (the control arm of the
CPU-vs-TPU A/B) plus Blake2b/SHA-512/Ed25519 primitives, all equivalence-
tested against the Python oracle and the JAX kernels. pybind11 is not in this
environment; the C ABI in core/capi.cc is the binding surface.
"""

from __future__ import annotations

import ctypes
import json
import os
import shutil
import subprocess
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

_REPO_ROOT = Path(__file__).resolve().parent.parent
_BUILD_DIR = _REPO_ROOT / "build-core"
_LIB_PATH = _BUILD_DIR / "libpbftcore.so"

_lib: Optional[ctypes.CDLL] = None

# Library sources in core/CMakeLists.txt order; pbftd.cc / core_test.cc
# link against the shared library.
_LIB_SOURCES = [
    "blake2b.cc", "sha512.cc", "ed25519.cc", "json.cc", "messages.cc",
    "metrics.cc", "flight.cc", "wal.cc", "replica.cc", "verifier.cc",
    "verify_pool.cc",
    "secure.cc", "net.cc", "net_shard.cc", "discovery.cc", "capi.cc",
]


def _build_direct() -> Path:
    """Fallback build without cmake/ninja: drive g++ directly (same flags
    as the CMake Release config). Keeps the native arm usable on stripped
    containers where only a compiler is present."""
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found for the native core")
    _BUILD_DIR.mkdir(exist_ok=True)
    core = _REPO_ROOT / "core"
    # Strict by default, like the CMake STRICT option: warnings fail the
    # build. PBFT_CORE_NO_WERROR=1 is the escape hatch for toolchains
    # whose headers trip -Wextra (mirrors cmake -DSTRICT=OFF).
    common = ["-O2", "-std=c++17", "-Wall", "-Wextra", "-pthread"]
    if not os.environ.get("PBFT_CORE_NO_WERROR"):
        common.append("-Werror")
    subprocess.run(
        [cxx, *common, "-fPIC", "-shared", "-o", str(_LIB_PATH)]
        + [str(core / s) for s in _LIB_SOURCES],
        check=True,
        capture_output=True,
    )
    for exe, src in (("pbftd", "pbftd.cc"), ("core_test", "core_test.cc")):
        subprocess.run(
            [cxx, *common, "-o", str(_BUILD_DIR / exe), str(core / src),
             "-L", str(_BUILD_DIR), "-lpbftcore", "-Wl,-rpath,$ORIGIN"],
            check=True,
            capture_output=True,
        )
    return _LIB_PATH


def build(force: bool = False) -> Path:
    """Build the native core with cmake+ninja (idempotent); falls back to
    a direct g++ build when cmake or ninja is unavailable."""
    if _LIB_PATH.exists() and not force:
        return _LIB_PATH
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        return _build_direct()
    subprocess.run(
        ["cmake", "-S", str(_REPO_ROOT / "core"), "-B", str(_BUILD_DIR), "-G", "Ninja"],
        check=True,
        capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", str(_BUILD_DIR)], check=True, capture_output=True
    )
    return _LIB_PATH


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        path = build()
        _lib = ctypes.CDLL(str(path))
        _lib.pbft_ed25519_verify.restype = ctypes.c_int
    return _lib


def available() -> bool:
    try:
        lib()
        return True
    except Exception:
        return False


def blake2b(data: bytes, digest_size: int = 32) -> bytes:
    out = ctypes.create_string_buffer(digest_size)
    lib().pbft_blake2b(out, digest_size, data, len(data))
    return out.raw


def sha512(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(64)
    lib().pbft_sha512(out, data, len(data))
    return out.raw


def public_key(seed: bytes) -> bytes:
    out = ctypes.create_string_buffer(32)
    lib().pbft_ed25519_public_key(out, seed)
    return out.raw


def sign(seed: bytes, msg: bytes) -> bytes:
    out = ctypes.create_string_buffer(64)
    lib().pbft_ed25519_sign(out, seed, msg, len(msg))
    return out.raw


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(pub) != 32 or len(sig) != 64:
        return False
    return bool(lib().pbft_ed25519_verify(pub, msg, len(msg), sig))


def blake2b_keyed(key: bytes, data: bytes, digest_size: int = 32) -> bytes:
    out = ctypes.create_string_buffer(digest_size)
    lib().pbft_blake2b_keyed(out, digest_size, key, len(key), data, len(data))
    return out.raw


def dh_public(secret: bytes) -> bytes:
    out = ctypes.create_string_buffer(32)
    lib().pbft_dh_public(out, secret)
    return out.raw


def dh_shared(secret: bytes, peer_pub: bytes) -> Optional[bytes]:
    out = ctypes.create_string_buffer(32)
    ok = lib().pbft_dh_shared(out, secret, peer_pub)
    return out.raw if ok else None


def aead_seal(key: bytes, ctr: int, plaintext: bytes) -> bytes:
    out = ctypes.create_string_buffer(len(plaintext) + 16)
    lib().pbft_aead_seal(key, ctypes.c_uint64(ctr), plaintext, len(plaintext), out)
    return out.raw


def aead_open(key: bytes, ctr: int, sealed: bytes) -> Optional[bytes]:
    out = ctypes.create_string_buffer(max(len(sealed), 1))
    fn = lib().pbft_aead_open
    fn.restype = ctypes.c_long
    n = fn(key, ctypes.c_uint64(ctr), sealed, len(sealed), out)
    return out.raw[:n] if n >= 0 else None


def verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """Native batch verify over (pub32, msg32, sig64) triples — the CPU
    control arm with the same call shape as crypto.batch.verify_many.
    Dispatched through the native verify pool (core/verify_pool.cc); width
    is set_verify_threads (default: hardware concurrency)."""
    n = len(items)
    if n == 0:
        return []
    pubs = b"".join(i[0] for i in items)
    msgs = b"".join(i[1] for i in items)
    sigs = b"".join(i[2] for i in items)
    out = ctypes.create_string_buffer(n)
    lib().pbft_ed25519_verify_batch(pubs, msgs, sigs, out, n)
    return [b == 1 for b in out.raw]


def set_verify_threads(threads: int) -> None:
    """Reconfigure the native verify pool width (0 = hardware
    concurrency). Tears down the existing pool; call between batches."""
    lib().pbft_set_verify_threads(ctypes.c_int(threads))


def verify_threads() -> int:
    """The native verify pool's actual width (creates the pool)."""
    fn = lib().pbft_verify_threads
    fn.restype = ctypes.c_int
    return fn()


def verify_pool_stats() -> dict:
    """Lifetime pool counters: threads, batches, windows, items, busy/wall
    seconds, utilization, last queue depth / window items."""
    fn = lib().pbft_verify_pool_stats_json
    fn.restype = ctypes.c_size_t
    buf = ctypes.create_string_buffer(512)
    n = fn(buf, len(buf))
    return json.loads(buf.raw[:n].decode())


def force_entropy_exhaustion(on: bool) -> None:
    """TEST hook: simulate entropy exhaustion so the RLC fast path
    disables and windows verify per-item (ADVICE round-5 regression)."""
    lib().pbft_test_force_entropy_exhaustion(ctypes.c_int(1 if on else 0))


def pubkey_cache_clear() -> None:
    """Drop every entry in the native per-key decompressed-point cache."""
    lib().pbft_pubkey_cache_clear()


def pubkey_cache_disable(on: bool) -> None:
    """TEST hook: force the cold (uncached) pubkey-decompression path so
    parity tests can compare warm vs cold verdicts."""
    lib().pbft_test_pubkey_cache_disable(ctypes.c_int(1 if on else 0))


def flight_configure(capacity: int) -> None:
    """(Re)size + enable the native black-box flight recorder ring
    (core/flight.cc); capacity 0 disables it."""
    lib().pbft_flight_configure(ctypes.c_size_t(capacity))


def flight_record(ev: int, view: int = 0, seq: int = 0, peer: int = -1) -> None:
    """Record one event into the native ring (trace_schema.FLIGHT_EVENTS
    ids) — a no-op (one branch) while the recorder is disabled."""
    lib().pbft_flight_record(
        ctypes.c_int(ev),
        ctypes.c_longlong(view),
        ctypes.c_longlong(seq),
        ctypes.c_int(peer),
    )


def flight_total() -> int:
    """Total records the native ring ever accepted (not capacity-clamped)."""
    fn = lib().pbft_flight_total
    fn.restype = ctypes.c_ulonglong
    return int(fn())


def flight_dump(path: str) -> int:
    """Write the native ring's binary dump; returns the record count
    (-1 on failure). Decode with pbft_tpu.utils.flight.decode_file."""
    fn = lib().pbft_flight_dump
    fn.restype = ctypes.c_long
    return int(fn(str(path).encode()))


def flight_reset() -> None:
    lib().pbft_flight_reset()


def message_to_binary(payload: bytes) -> Optional[bytes]:
    """Parse a JSON message payload in the C++ core and encode it with the
    native binary-v2 codec (None when the type has no binary form) — the
    cross-runtime byte-parity surface for tests/test_wire_codec.py."""
    fn = lib().pbft_message_to_binary
    fn.restype = ctypes.c_size_t
    out = ctypes.create_string_buffer(len(payload) + 256)
    n = fn(payload, len(payload), out, len(out))
    if n == 0 or n > len(out):
        return None
    return out.raw[:n]


def message_from_binary(payload: bytes) -> Optional[Tuple[bytes, bytes]]:
    """Decode a binary-v2 payload in the C++ core: returns (canonical
    JSON bytes, signable digest) or None on decode failure."""
    fn = lib().pbft_message_from_binary
    fn.restype = ctypes.c_size_t
    out = ctypes.create_string_buffer(4 * len(payload) + 1024)
    digest = ctypes.create_string_buffer(32)
    n = fn(payload, len(payload), out, len(out), digest)
    if n == 0 or n > len(out):
        return None
    return out.raw[:n], digest.raw


def signable_from_payload(payload: bytes) -> Optional[bytes]:
    """The C++ receive-side signable derivation (JSON sig-splice / binary
    template, with the generic fallback) for a framed payload."""
    fn = lib().pbft_signable_from_payload
    fn.restype = ctypes.c_int
    digest = ctypes.create_string_buffer(32)
    if not fn(payload, len(payload), digest):
        return None
    return digest.raw


def message_to_binary_mac(payload: bytes, lanes) -> Optional[bytes]:
    """Encode a JSON message payload as a native MAC-vector frame
    (ISSUE 14): ``lanes`` is a sequence of (rid, 16-byte tag). None when
    the type has no MAC form — the cross-runtime byte-parity surface."""
    blob = b"".join(
        rid.to_bytes(1, "big") + bytes(tag) for rid, tag in lanes
    )
    fn = lib().pbft_message_to_binary_mac
    fn.restype = ctypes.c_size_t
    out = ctypes.create_string_buffer(len(payload) + len(blob) + 256)
    n = fn(payload, len(payload), blob, len(lanes), out, len(out))
    if n == 0 or n > len(out):
        return None
    return out.raw[:n]


def mac_frame_lane(payload: bytes, rid: int) -> Optional[bytes]:
    """The C++ lane extraction for a MAC frame; None when absent."""
    fn = lib().pbft_mac_frame_lane
    fn.restype = ctypes.c_int
    tag = ctypes.create_string_buffer(16)
    if not fn(payload, len(payload), ctypes.c_longlong(rid), tag):
        return None
    return tag.raw


def mac_tag(key: bytes, signable: bytes) -> bytes:
    """The C++ authenticator-lane tag (net/secure.py mac_tag parity)."""
    assert len(key) == 32 and len(signable) == 32
    tag = ctypes.create_string_buffer(16)
    lib().pbft_mac_tag(key, signable, tag)
    return tag.raw
