"""Durable replica recovery (ISSUE 15): the write-ahead log, the
no-contradiction (amnesia) guards, crash-restart in the simulator with
the S5 invariant, and kill -9 -> restart-from-disk on real daemons.

The on-disk format is the cross-runtime contract: the golden-bytes test
pins the Python encoder, core_test.cc pins the same goldens for the C++
encoder, and the real-cluster tests replay pbftd-written logs with the
Python decoder — byte identity by construction, checked three ways.
"""

import json
import re
import time
from pathlib import Path

import pytest

from pbft_tpu.consensus import wal as W
from pbft_tpu.consensus.config import make_local_cluster
from pbft_tpu.consensus.invariants import InvariantChecker, InvariantViolation
from pbft_tpu.consensus.simulation import Cluster


# -- the on-disk format -------------------------------------------------------


def test_record_golden_bytes(tmp_path):
    """Pin the exact file image (header + view + checkpoint + vote): the
    same goldens are asserted by core_test.cc test_wal_roundtrip, so the
    two encoders cannot drift without one of the pins going red."""
    p = tmp_path / "replica-0.wal"
    w = W.WriteAheadLog(str(p))
    w.note_view(3, True, 4)
    w.note_vote(W.WAL_VOTE_PREPARE, 3, 17, "ab" * 32)
    w.note_checkpoint(16, "PAYLOAD", "[]")
    w.flush()  # checkpoint -> compaction: canonical ordering on disk
    data = p.read_bytes()
    assert data[:8] == b"PBFTWAL1"
    assert data[8:12] == (1).to_bytes(4, "little")
    # view record: tag 0x01, len 17, i64 view, u8 ivc, i64 pending
    off = 12
    assert data[off] == W.WAL_REC_VIEW
    assert data[off + 1 : off + 5] == (17).to_bytes(4, "little")
    assert data[off + 5 : off + 13] == (3).to_bytes(8, "little")
    assert data[off + 13] == 1
    assert data[off + 14 : off + 22] == (4).to_bytes(8, "little")
    off += 5 + 17
    # checkpoint record: tag 0x03, seq 16, "PAYLOAD", "[]"
    assert data[off] == W.WAL_REC_CHECKPOINT
    body = data[off + 5 :]
    assert body[:8] == (16).to_bytes(8, "little")
    assert body[8:12] == (7).to_bytes(4, "little")
    assert body[12:19] == b"PAYLOAD"
    assert body[19:23] == (2).to_bytes(4, "little")
    assert body[23:25] == b"[]"
    off += 5 + 8 + 4 + 7 + 4 + 2
    # vote record: tag 0x02, kind prepare, view 3, seq 17, raw digest
    assert data[off] == W.WAL_REC_VOTE
    assert data[off + 5] == W.WAL_VOTE_PREPARE
    assert data[off + 6 : off + 14] == (3).to_bytes(8, "little")
    assert data[off + 14 : off + 22] == (17).to_bytes(8, "little")
    assert data[off + 22 : off + 54] == bytes.fromhex("ab" * 32)
    assert off + 54 == len(data)


def test_replay_contradiction_and_compaction(tmp_path):
    p = tmp_path / "replica-1.wal"
    w = W.WriteAheadLog(str(p))
    assert w.note_vote(W.WAL_VOTE_PREPARE, 0, 1, "11" * 32)
    assert w.note_vote(W.WAL_VOTE_PREPARE, 0, 1, "11" * 32)  # idempotent
    assert not w.note_vote(W.WAL_VOTE_PREPARE, 0, 1, "22" * 32)  # refused
    assert w.note_vote(W.WAL_VOTE_COMMIT, 0, 20, "33" * 32)
    w.note_checkpoint(16, '{"seq":16}', '[{"replica":0}]')
    w.flush()
    st = W.replay(str(p))
    assert st.checkpoint == (16, '{"seq":16}', '[{"replica":0}]')
    # the seq-1 vote fell beneath the checkpoint; seq-20 survives
    assert st.votes == {(W.WAL_VOTE_COMMIT, 0, 20): "33" * 32}
    # reopening replays + compacts; the guards stay armed
    w2 = W.WriteAheadLog(str(p))
    assert not w2.note_vote(W.WAL_VOTE_COMMIT, 0, 20, "44" * 32)
    assert w2.recovered.checkpoint == st.checkpoint
    assert st.max_pre_prepare_seq() == 0


def test_torn_tail_tolerated(tmp_path):
    p = tmp_path / "replica-2.wal"
    w = W.WriteAheadLog(str(p))
    w.note_vote(W.WAL_VOTE_PREPARE, 0, 5, "aa" * 32)
    w.flush()
    whole = W.replay(str(p))
    with open(p, "ab") as fh:  # a kill -9 mid-append: partial record
        fh.write(bytes([W.WAL_REC_VOTE]) + (49).to_bytes(4, "little") + b"xx")
    torn = W.replay(str(p))
    assert torn.votes == whole.votes
    # ...and reopening heals the tear (recovery compaction)
    W.WriteAheadLog(str(p))
    healed = W.replay(str(p))
    assert healed.votes == whole.votes
    with pytest.raises(ValueError):
        W.decode_bytes(b"NOTAWAL0" + bytes(8))


# -- simulator crash-restart + S5 --------------------------------------------


def _wal_cluster(n=4, checkpoint_interval=4):
    config, seeds = make_local_cluster(n)
    config.checkpoint_interval = checkpoint_interval
    return Cluster(config=config, seeds=seeds, wal=True)


def test_sim_restart_from_disk_rejoins_without_revoting():
    c = _wal_cluster()
    checker = InvariantChecker(c)
    for i in range(6):
        c.submit(f"op-{i + 1}")
        c.run()
        checker.check()
    assert c.replicas[3].low_mark == 4  # a stable checkpoint exists
    votes_before = dict(c.wals[3].state.votes)
    assert votes_before  # votes above the checkpoint floor persist
    c.crash(3)
    c.submit("op-7")
    c.run()
    checker.check()
    c.restart(3, from_disk=True)
    r3 = c.replicas[3]
    # Re-joined the SAME view at the stable-checkpoint floor.
    assert r3.view == 0
    assert r3.executed_upto == r3.low_mark == 4
    assert r3.wal is c.wals[3]
    # Catch up through the ordinary protocol; S5 holds throughout.
    for i in range(7, 12):
        c.submit(f"op-{i + 1}")
        c.run()
        checker.check()
    assert r3.executed_upto == c.replicas[0].executed_upto
    assert r3.state_digest == c.replicas[0].state_digest
    # "Without re-voting": every pre-crash persisted vote kept its digest.
    for key, digest in votes_before.items():
        after = c.wals[3].state.votes.get(key)
        assert after is None or after == digest  # None = checkpoint-pruned


def test_sim_fresh_restart_absorbed_by_quorum():
    """Satellite 1's other half: an AMNESIAC restart mid-round is
    absorbed by the quorum (it spends fault budget — the <= f window the
    old revive() silently relied on, now documented)."""
    c = _wal_cluster()
    checker = InvariantChecker(c)
    for i in range(4):
        c.submit(f"op-{i + 1}")
        c.run()
        checker.check()
    c.crash(3)
    c.restart(3, from_disk=False)  # blank disk, blank state
    r3 = c.replicas[3]
    assert r3.executed_upto == 0 and r3.view == 0
    for i in range(4, 10):
        c.submit(f"op-{i + 1}")
        c.run()
        checker.check()  # S1-S3 hold: 3 honest survivors carry it
    assert c.replicas[0].executed_upto == 10
    # the amnesiac caught up via state transfer like any fresh replica
    assert r3.executed_upto == 10


def test_s5_checker_validity():
    """A checker that can't fail is not a checker: fabricate a persisted
    pre-crash vote that contradicts what replica 1 is about to send —
    the S5 pass must trip on the very next prepare."""
    c = _wal_cluster()
    checker = InvariantChecker(c)
    c.restart_votes[1] = {(W.WAL_VOTE_PREPARE, 0, 1): "00" * 32}
    c.submit("op-1")
    with pytest.raises(InvariantViolation, match="restart-vote"):
        for _ in range(40):
            c.step()
            checker.check()
    assert checker.violations


def test_chaos_soak_crash_restart_smoke():
    import pathlib
    import sys

    sys.path.insert(
        0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts")
    )
    import chaos_soak

    res = chaos_soak.run_one(3, 4, 120, crash_restart=True)
    assert res["ok"], res


@pytest.mark.slow
def test_chaos_soak_crash_restart_matrix():
    """The acceptance matrix (ISSUE 15): >= 10 seeds x {n=4, n=7} x
    {sig, mac} crash-restart schedules with zero S1-S3/L1/S5
    violations."""
    import pathlib
    import sys

    sys.path.insert(
        0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts")
    )
    import chaos_soak

    for seed in range(10):
        for n in (4, 7):
            for mode in ("sig", "mac"):
                res = chaos_soak.run_one(
                    seed, n, 300, mode=mode, crash_restart=True
                )
                assert res["ok"], res


# -- real daemons: kill -9 and restart from disk ------------------------------


def _metrics_lines(cluster, rid):
    log = Path(cluster.tmpdir.name) / f"replica-{rid}.log"
    return [
        json.loads(x)
        for x in re.findall(
            r"^\{.*\}$", log.read_text(errors="replace"), re.M
        )
        if '"replica"' in x
    ]


def _drive(client, lo, hi):
    for i in range(lo, hi):
        client.request(f"op-{i}")


def _wait_metric(cluster, rid, pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        lines = _metrics_lines(cluster, rid)
        if lines and pred(lines[-1]):
            return lines[-1]
        time.sleep(0.3)
    raise AssertionError(
        f"replica {rid} never satisfied predicate; last: "
        f"{_metrics_lines(cluster, rid)[-1:]}\n{cluster.logs()[-4000:]}"
    )


@pytest.mark.parametrize("impl", ["cxx", "py"])
def test_kill9_restart_from_disk(impl):
    """kill -9 a backup mid-run, restart with its WAL: it re-joins the
    SAME view, reports recovered_from_wal, never contradicts a persisted
    vote (checked by replaying the C++/Python-written log with the
    PYTHON decoder — the cross-runtime byte-identity proof), and catches
    the suffix up via state transfer."""
    from pbft_tpu.net.client import PbftClient
    from pbft_tpu.net.launcher import LocalCluster

    with LocalCluster(
        n=4, metrics_every=1, wal=True, vc_timeout_ms=2000, impl=impl
    ) as cluster:
        client = PbftClient(cluster.config)
        _drive(client, 1, 41)  # checkpoints at 16 and 32
        wal_path = Path(cluster.tmpdir.name) / "wal" / "replica-3.wal"
        time.sleep(0.6)
        cluster.kill(3, hard=True)
        st = W.replay(str(wal_path))
        assert st.checkpoint is not None and st.checkpoint[0] >= 16
        votes_before = dict(st.votes)
        pre_lines = len(_metrics_lines(cluster, 3))
        cluster.revive(3, from_disk=True)
        last = _wait_metric(
            cluster,
            3,
            lambda m: m.get("recovered_from_wal") is True
            and len(_metrics_lines(cluster, 3)) > pre_lines,
        )
        assert last["wal_enabled"] is True
        assert last["view"] == 0  # the SAME view
        assert last["executed_upto"] >= st.checkpoint[0]
        _drive(client, 41, 61)
        last = _wait_metric(
            cluster, 3, lambda m: m.get("executed_upto", 0) >= 60
        )
        # No re-voting: the post-restart log still holds the pre-crash
        # digests for every surviving (kind, view, seq).
        st_after = W.replay(str(wal_path))
        for key, digest in votes_before.items():
            after = st_after.votes.get(key)
            assert after is None or after == digest


def test_revive_fresh_default_and_from_disk_guard():
    """Satellite 1 regression: the DEFAULT revive stays fresh-state even
    on a wal-enabled cluster (the log is wiped so replay finds nothing),
    the quorum absorbs the amnesiac while it catches up, and
    from_disk=True on a wal-less cluster refuses loudly."""
    from pbft_tpu.net.client import PbftClient
    from pbft_tpu.net.launcher import LocalCluster

    with LocalCluster(
        n=4, metrics_every=1, wal=True, vc_timeout_ms=2000
    ) as cluster:
        client = PbftClient(cluster.config)
        _drive(client, 1, 25)
        time.sleep(0.6)
        cluster.kill(3, hard=True)
        pre_lines = len(_metrics_lines(cluster, 3))
        cluster.revive(3)  # DEFAULT: fresh state, wal wiped
        last = _wait_metric(
            cluster,
            3,
            lambda m: len(_metrics_lines(cluster, 3)) > pre_lines,
        )
        assert last["recovered_from_wal"] is False
        # The amnesiac rejoined; the cluster (quorum of 3) kept serving
        # and the fresh replica catches up via checkpoint/state transfer.
        _drive(client, 25, 45)
        _wait_metric(cluster, 3, lambda m: m.get("executed_upto", 0) >= 32)

    with LocalCluster(n=4) as cluster2:
        cluster2.kill(1)
        with pytest.raises(ValueError, match="wal=True"):
            cluster2.revive(1, from_disk=True)
        cluster2.revive(1)  # fresh revive still fine
