"""Pool/serial verdict parity for the native verify pool (ISSUE 2).

The C++ batch path splits every batch into fixed RLC windows
(core/ed25519.cc kEd25519RlcWindowItems = 256) whose boundaries depend
only on item order — never on thread count — so the accept set must be
identical across pool widths, including the documented torsion-pair
caveat. Also the ADVICE round-5 regression: entropy exhaustion must
disable the RLC fast path (per-item verification), not fall back to
predictable coefficients a crafted cancelling pair could satisfy.
"""

import os
import random

import pytest

from pbft_tpu import native
from pbft_tpu.crypto import ref

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not buildable"
)

WINDOW = 256  # mirrors core/ed25519.h kEd25519RlcWindowItems

THREAD_COUNTS = sorted({1, 2, os.cpu_count() or 1})


@pytest.fixture(autouse=True)
def _restore_pool():
    yield
    native.force_entropy_exhaustion(False)
    native.set_verify_threads(0)


# Torsion-defect crafting (same construction as tests/test_native_crypto.py,
# duplicated here because that module's hypothesis importorskip would skip
# this whole file on import).


def _torsion_point():
    """A nonzero small-order point: [L]P for a curve point P outside the
    prime subgroup."""
    for y in range(2, 60):
        pt = ref.point_decompress(y.to_bytes(32, "little"))
        if pt is None:
            continue
        t = ref.scalar_mult(ref.L, pt)
        if t != (0, 1):
            return t
    raise AssertionError("no torsion point found in scan range")


def _craft_torsion_sig(seed: bytes, msg: bytes, defect):
    """A signature whose verification defect is exactly -defect (a
    Byzantine signer using its own secret key)."""
    a, _prefix = ref.secret_expand(seed)
    pub = ref.point_compress(ref.scalar_mult(a, ref.BASE))
    r = 0x1234567
    big_r = ref.point_compress(
        ref.point_add(ref.scalar_mult(r, ref.BASE), defect)
    )
    h = ref._h512_int(big_r, pub, msg) % ref.L
    s = (r + h * a) % ref.L
    return pub, big_r + s.to_bytes(32, "little")


def _signed(i: int, msg: bytes | None = None):
    seed = bytes([i % 249 + 1, 0x5C]) * 16
    m = msg if msg is not None else bytes([i % 256, 0x77]) * 16
    return (native.public_key(seed), m, native.sign(seed, m))


def _corrupt(item, off: int = 40):
    pub, msg, sig = item
    return (pub, msg, sig[:off] + bytes([sig[off] ^ 0x5A]) + sig[off + 1 :])


def test_pool_parity_invalids_at_window_boundaries():
    """Invalid signatures pinned to every window edge (first/last item of
    each 256-wide window) plus random interior corruption: identical
    accept sets at thread counts {1, 2, hardware}, all equal to per-item
    verify."""
    n = 2 * WINDOW + 37  # three windows, last one ragged
    items = [_signed(i) for i in range(n)]
    rng = random.Random(0x5EED)
    bad = {0, WINDOW - 1, WINDOW, 2 * WINDOW - 1, 2 * WINDOW, n - 1}
    bad |= {rng.randrange(n) for _ in range(5)}
    for i in bad:
        items[i] = _corrupt(items[i])
    want = [i not in bad for i in range(n)]
    verdicts = {}
    for t in THREAD_COUNTS:
        native.set_verify_threads(t)
        assert native.verify_threads() == t
        verdicts[t] = native.verify_batch(items)
        assert verdicts[t] == want, f"threads={t}"
    assert len({tuple(v) for v in verdicts.values()}) == 1


def test_pool_parity_randomized_batches():
    """Randomized sizes (straddling the window width and the RLC
    crossover) and corruption patterns: every thread count agrees with
    per-item verify."""
    rng = random.Random(7)
    for trial, n in enumerate([1, 7, 8, 255, 256, 257, 300]):
        items = [_signed(1000 * trial + i) for i in range(n)]
        bad = {rng.randrange(n) for _ in range(rng.randrange(0, 4))}
        for i in bad:
            items[i] = _corrupt(items[i], off=rng.randrange(64))
        per_item = [native.verify(p, m, s) for p, m, s in items]
        for t in THREAD_COUNTS:
            native.set_verify_threads(t)
            assert native.verify_batch(items) == per_item, (n, t)


def test_torsion_pair_same_window_consistent_across_thread_counts():
    """The documented accept-set caveat is thread-count independent: a
    cancelling torsion-defect pair INSIDE one window is batch-accepted
    identically at every pool width (window composition is fixed by item
    order, so replicas with different --verify-threads cannot disagree)."""

    t = _torsion_point()
    neg_t = (ref.P - t[0], t[1])
    crafted = []
    for i, defect in ((0, t), (1, neg_t)):
        seed = bytes([i + 1]) * 32
        msg = bytes([0xE0 + i]) * 32
        pub, bad = _craft_torsion_sig(seed, msg, defect)
        assert not native.verify(pub, msg, bad)
        crafted.append((pub, msg, bad))
    items = [_signed(i) for i in range(10)] + crafted  # one window
    for threads in THREAD_COUNTS:
        native.set_verify_threads(threads)
        assert native.verify_batch(items) == [True] * 12, threads


def test_torsion_pair_split_across_windows_rejected_at_every_width():
    """The same pair split across the fixed window boundary (item indices
    WINDOW-1 and WINDOW): each window's RLC sees a lone defect, the
    bisect runs, and per-item authority rejects both — at every thread
    count, i.e. also when the two windows run on different workers."""

    t = _torsion_point()
    neg_t = (ref.P - t[0], t[1])
    pair = []
    for i, defect in ((0, t), (1, neg_t)):
        msg = bytes([0xE0 + i]) * 32
        pub, bad = _craft_torsion_sig(bytes([i + 1]) * 32, msg, defect)
        pair.append((pub, msg, bad))
    n = WINDOW + 8
    items = [_signed(i) for i in range(n)]
    items[WINDOW - 1] = pair[0]
    items[WINDOW] = pair[1]
    want = [True] * n
    want[WINDOW - 1] = want[WINDOW] = False
    for threads in THREAD_COUNTS:
        native.set_verify_threads(threads)
        assert native.verify_batch(items) == want, threads


def test_entropy_exhaustion_disables_rlc_and_rejects_cancelling_pair():
    """ADVICE round-5 medium regression: with entropy exhausted the RLC
    fast path must be disabled entirely — windows verify per-item, so the
    crafted cancelling-defect pair that the (randomized) RLC accepts is
    now rejected, and honest items still pass. The old behavior derived
    coefficients from a predictable counter, which a forger could satisfy."""

    t = _torsion_point()
    neg_t = (ref.P - t[0], t[1])
    crafted = []
    for i, defect in ((0, t), (1, neg_t)):
        pub, bad = _craft_torsion_sig(
            bytes([i + 1]) * 32, bytes([0xE0 + i]) * 32, defect
        )
        crafted.append((pub, bytes([0xE0 + i]) * 32, bad))
    items = [_signed(i) for i in range(10)] + crafted
    # Sanity: with entropy, the pair is the documented in-window accept.
    native.set_verify_threads(1)
    assert native.verify_batch(items) == [True] * 12
    native.force_entropy_exhaustion(True)
    try:
        for threads in THREAD_COUNTS:
            native.set_verify_threads(threads)
            verdicts = native.verify_batch(items)
            assert verdicts == [True] * 10 + [False, False], threads
    finally:
        native.force_entropy_exhaustion(False)
    # Entropy restored: the fast path (and its documented caveat) return.
    assert native.verify_batch(items) == [True] * 12


def test_pool_lifecycle_stress_fast():
    """Tier-1 pool stress, no sleeps: repeated reconfigure + verify +
    implicit teardown across widths, interleaving batch sizes above and
    below the window width; verdicts stay exact throughout and the stats
    counters add up."""
    base = [_signed(i) for i in range(70)]
    bad_idx = 33
    batch = list(base)
    batch[bad_idx] = _corrupt(batch[bad_idx])
    want = [i != bad_idx for i in range(len(batch))]
    for threads in (1, 2, 3, 1, 2):
        native.set_verify_threads(threads)
        for size in (1, 8, 70):
            sub = batch[:size]
            assert native.verify_batch(sub) == want[:size], (threads, size)
    stats = native.verify_pool_stats()
    assert stats["threads"] == 2  # last configured width
    assert stats["batches"] == 3 and stats["windows"] == 3
    assert stats["items"] == 79
    assert stats["wall_seconds"] > 0
    assert 0.0 <= stats["utilization"] <= 1.0 + 1e-9


def test_pubkey_cache_warm_cold_parity():
    """The per-key decompressed-point cache (ISSUE 3 satellite) is pure
    memoization: a replica-shaped batch (a tiny stable key set, repeated)
    must produce identical verdicts cold (empty cache), warm (every key
    cached), and with the cache disabled outright — including corrupted
    signatures, a non-canonical pubkey, and at every pool width."""
    n = WINDOW + 40  # two windows, second ragged
    # 4 signer identities repeated across the batch — the replica shape
    # the cache exists for.
    items = [_signed(i % 4, msg=bytes([i % 256, 0x31]) * 16) for i in range(n)]
    bad = {3, WINDOW - 1, WINDOW + 5}
    for i in bad:
        items[i] = _corrupt(items[i])
    # A non-canonical pubkey encoding (y >= p): decompression fails, the
    # failure itself must cache without flipping any verdict.
    items[7] = (b"\xff" * 32, items[7][1], items[7][2])
    bad.add(7)
    want = [i not in bad for i in range(n)]
    try:
        for t in THREAD_COUNTS:
            native.set_verify_threads(t)
            native.pubkey_cache_clear()
            cold = native.verify_batch(items)
            warm = native.verify_batch(items)  # every key now cached
            native.pubkey_cache_disable(True)
            nocache = native.verify_batch(items)
            native.pubkey_cache_disable(False)
            assert cold == want, f"threads={t}"
            assert warm == cold, f"threads={t}"
            assert nocache == cold, f"threads={t}"
    finally:
        native.pubkey_cache_disable(False)


def test_bench_native_arm_reports_threads(tmp_path):
    """The bench's native arm must emit threads + single-thread vs pooled
    rates (acceptance criterion surface) — run it in-process-shaped via a
    subprocess with a tiny budget."""
    import json
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(
        PBFT_BENCH_NATIVE="1",
        PBFT_BENCH_SECS="0.2",
        PBFT_BENCH_BATCH="64",
        PBFT_VERIFY_THREADS="2",
    )
    out = subprocess.run(
        [sys.executable, str(native._REPO_ROOT / "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["backend"] == "cpu-native"
    assert result["threads"] == 2
    assert result["single_thread_per_sec"] > 0
    assert result["pooled_per_sec"] == result["value"]
    assert result["pool_speedup"] > 0
