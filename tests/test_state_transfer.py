"""State transfer (PBFT §5.3): a lagging replica fetches the certified
checkpoint state — app snapshot, chain digest, per-client reply caches —
from a peer and verifies it against the 2f+1 stable checkpoint digest,
instead of silently skipping missed executions (the round-2 gap: the old
watermark jump adopted the digest only, which was correct solely for
stateless apps)."""

from pbft_tpu.consensus.config import make_local_cluster
from pbft_tpu.consensus.messages import StateResponse, blake2b_256
from pbft_tpu.consensus.replica import Replica
from pbft_tpu.consensus.simulation import Cluster


class CounterApp:
    """Stateful app: every result depends on all prior operations, so a
    replica that skipped executions would produce diverging replies."""

    def __init__(self):
        self.total = 0

    def __call__(self, operation: str, seq: int) -> str:
        self.total += int(operation)
        return f"total={self.total}"

    def snapshot(self) -> str:
        return str(self.total)

    def restore(self, s: str) -> None:
        self.total = int(s) if s else 0


def make_cluster() -> Cluster:
    config, seeds = make_local_cluster(4)
    config.checkpoint_interval = 4
    return Cluster(config=config, seeds=seeds, app_factory=CounterApp)


def test_lagging_replica_catches_up_with_stateful_app():
    c = make_cluster()
    c.crash(3)  # replica 3 misses a stretch spanning a checkpoint
    for i in range(6):
        c.submit(str(i + 1))
        c.run()
    for rid in (0, 1, 2):
        assert c.replicas[rid].executed_upto == 6
        assert c.replicas[rid].low_mark == 4
    assert c.replicas[3].executed_upto == 0

    # Heal; new traffic produces the next stable checkpoint, which replica 3
    # learns about, triggering the fetch.
    c.uncrash(3)
    for i in range(6, 10):
        c.submit(str(i + 1))
        c.run()
    r3 = c.replicas[3]
    assert r3.counters["state_transfers"] >= 1
    assert r3.awaiting_state is None
    assert r3.executed_upto == c.replicas[0].executed_upto == 10
    assert r3.state_digest == c.replicas[0].state_digest
    assert r3._app.total == c.replicas[0]._app.total == sum(range(1, 11))

    # The recovered replica now serves replies that MATCH the quorum —
    # the whole point of transferring app state.
    t = c.submit("100")
    c.run()
    result = c.committed_result(t.timestamp)
    replies3 = [
        r
        for r in c.client_replies
        if r.replica == 3 and r.timestamp == t.timestamp
    ]
    assert replies3 and all(r.result == result for r in replies3)


def test_exactly_once_cache_transfers():
    """A duplicate of a request executed while the replica was down must be
    answered from the TRANSFERRED reply cache, not re-executed."""
    c = make_cluster()
    c.crash(3)
    for i in range(6):
        c.submit(str(i + 1))
        c.run()
    c.uncrash(3)
    for i in range(6, 10):
        c.submit(str(i + 1))
        c.run()
    r3 = c.replicas[3]
    assert r3.counters["state_transfers"] >= 1
    # Replay timestamp 2 (executed during the outage) directly at replica 3.
    dup = c.submit("2", timestamp=2, to_replica=3)
    c.run()
    assert r3.last_timestamp[dup.client] >= 2
    assert r3._app.total == c.replicas[0]._app.total  # no double-execution


def test_tampered_state_response_rejected():
    """A response whose payload does not hash to the certified digest is
    ignored — a Byzantine peer cannot inject bogus state."""
    config, seeds = make_local_cluster(4)
    config.checkpoint_interval = 4
    r = Replica(config, 3, seeds[3], app=CounterApp())
    good = '{"app":"7","chain":"%s","replies":[],"seq":4,"timestamps":[]}' % (
        "00" * 32
    )
    digest = blake2b_256(good.encode()).hex()
    r.awaiting_state = (4, digest)
    evil = good.replace('"7"', '"9"')
    r._on_state_response(StateResponse(seq=4, snapshot=evil, replica=1))
    assert r.awaiting_state == (4, digest)  # still waiting, nothing adopted
    assert r._app.total == 0
    r._on_state_response(StateResponse(seq=4, snapshot=good, replica=1))
    assert r.awaiting_state is None
    assert r._app.total == 7
    assert r.executed_upto == 4
