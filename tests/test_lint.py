"""Tests for the static-analysis layer itself (ISSUE 8).

Two halves:

1. The clean tree passes every pass (this is the tier-1 wiring for
   scripts/pbft_lint.py — runtime drift fails the build here).
2. Each pass actually TRIPS on its violation class, proven against a
   shadow tree: a copy of exactly the files the passes scan, with one
   deliberate violation injected — a divergent cross-runtime constant, a
   blocking call inside ``async def``, an unregistered metric. The entry
   point must exit nonzero on each.

Plus the @slow sanitizer-matrix arm: scripts/sanitize.py builds the
strict/TSan/ASan+UBSan flavors of core_test + core/race_stress.cc and
must report zero unsuppressed findings.
"""

from __future__ import annotations

import json
import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from pbft_tpu import analysis  # noqa: E402
from pbft_tpu.analysis import (  # noqa: E402
    async_blocking,
    constants,
    metrics_lint,
    sockets,
)

LINT = REPO / "scripts" / "pbft_lint.py"


def _shadow_tree(tmp_path: pathlib.Path) -> pathlib.Path:
    """Copy exactly the files the passes scan into a fresh tree."""
    root = tmp_path / "tree"
    for src in analysis.scanned_files(REPO):
        rel = src.relative_to(REPO)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(src, dst)
    return root


def _run_lint(root: pathlib.Path, passes: str = None):
    cmd = [sys.executable, str(LINT), "--root", str(root)]
    if passes:
        cmd += ["--passes", passes]
    return subprocess.run(cmd, capture_output=True, text=True)


# -- 1. the clean tree -------------------------------------------------------

def test_clean_tree_all_passes():
    results = analysis.run_all(REPO)
    flat = [e for errs in results.values() for e in errs]
    assert flat == [], "\n".join(flat)


def test_entry_point_clean_tree_exit_zero():
    proc = _run_lint(REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all passes clean" in proc.stdout


def test_entry_point_usage():
    proc = _run_lint(REPO, passes="no-such-pass")
    assert proc.returncode == 2


# -- 2. each violation class trips its pass ----------------------------------

def test_divergent_constant_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    msgs = root / "pbft_tpu" / "consensus" / "messages.py"
    text = msgs.read_text()
    assert "WIRE_BINARY_MAGIC = 0xB2" in text
    msgs.write_text(text.replace(
        "WIRE_BINARY_MAGIC = 0xB2", "WIRE_BINARY_MAGIC = 0xB3"))
    errors = constants.check(root)
    assert any("wire binary magic" in e for e in errors), errors
    proc = _run_lint(root, passes="constants")
    assert proc.returncode == 1
    assert "wire binary magic" in proc.stdout


def test_divergent_protocol_version_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    sec = root / "pbft_tpu" / "net" / "secure.py"
    text = sec.read_text()
    assert 'PROTOCOL_VERSION = "pbft-tpu/1.3.0"' in text
    sec.write_text(text.replace(
        'PROTOCOL_VERSION = "pbft-tpu/1.3.0"',
        'PROTOCOL_VERSION = "pbft-tpu/1.4.0"'))
    errors = constants.check(root)
    assert any("protocol version (current)" in e for e in errors), errors


def test_divergent_mac_constants_trip(tmp_path):
    """ISSUE 14 pairs: a drifted MAC tag length, domain label, or frame
    code each fails the build — one byte of drift and a mixed-runtime
    mac link rejects every frame."""
    root = _shadow_tree(tmp_path)
    sec = root / "pbft_tpu" / "net" / "secure.py"
    text = sec.read_text()
    assert "MAC_TAG_LEN = 16" in text
    sec.write_text(text.replace("MAC_TAG_LEN = 16", "MAC_TAG_LEN = 12"))
    errors = constants.check(root)
    assert any("MAC tag length" in e for e in errors), errors

    root2 = _shadow_tree(tmp_path / "b")
    sec2 = root2 / "pbft_tpu" / "net" / "secure.py"
    sec2.write_text(sec2.read_text().replace(
        'MAC_CONTEXT = "pbft-tpu-auth1|"', 'MAC_CONTEXT = "pbft-tpu-auth2|"'))
    errors = constants.check(root2)
    assert any("MAC domain-separation label" in e for e in errors), errors

    root3 = _shadow_tree(tmp_path / "c")
    msgs = root3 / "pbft_tpu" / "consensus" / "messages.py"
    msgs.write_text(msgs.read_text().replace(
        "_BIN_PREPARE_MAC = 0x13", "_BIN_PREPARE_MAC = 0x17"))
    errors = constants.check(root3)
    assert any("binary tag: prepare (MAC)" in e for e in errors), errors


def test_divergent_tentative_field_trips(tmp_path):
    """The tentative-reply member name is SIGNED content: a renamed
    field forks every tentative reply's signable bytes across runtimes."""
    root = _shadow_tree(tmp_path)
    msgs = root / "pbft_tpu" / "consensus" / "messages.py"
    text = msgs.read_text()
    assert 'TENTATIVE_FIELD = "tentative"' in text
    msgs.write_text(text.replace(
        'TENTATIVE_FIELD = "tentative"', 'TENTATIVE_FIELD = "tent"'))
    errors = constants.check(root)
    assert any("tentative-reply field tag" in e for e in errors), errors


def test_divergent_fastpath_default_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    cfg = root / "pbft_tpu" / "consensus" / "config.py"
    text = cfg.read_text()
    assert 'fastpath: str = "sig"' in text
    cfg.write_text(text.replace(
        'fastpath: str = "sig"', 'fastpath: str = "mac"'))
    errors = constants.check(root)
    assert any("ClusterConfig default: fastpath" in e for e in errors), errors


def test_divergent_config_default_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    cfg = root / "pbft_tpu" / "consensus" / "config.py"
    cfg.write_text(cfg.read_text().replace(
        "watermark_window: int = 256", "watermark_window: int = 128"))
    errors = constants.check(root)
    assert any("watermark_window" in e for e in errors), errors


def test_divergent_wal_constants_trip(tmp_path):
    """ISSUE 15 pairs: a drifted WAL magic, record tag, or wal_fsync
    config default each fails the build — the on-disk format is the
    cross-runtime recovery contract (a pbftd-written log must replay in
    the Python tooling byte-for-byte, and a sparse network.json must
    mean fsync-on in both runtimes)."""
    root = _shadow_tree(tmp_path)
    w = root / "pbft_tpu" / "consensus" / "wal.py"
    text = w.read_text()
    assert 'WAL_MAGIC = b"PBFTWAL1"' in text
    w.write_text(text.replace(
        'WAL_MAGIC = b"PBFTWAL1"', 'WAL_MAGIC = b"PBFTWAL2"'))
    errors = constants.check(root)
    assert any("WAL file magic" in e for e in errors), errors

    root2 = _shadow_tree(tmp_path / "b")
    hdr = root2 / "core" / "wal.h"
    hdr.write_text(hdr.read_text().replace(
        "kWalRecCheckpoint = 0x03", "kWalRecCheckpoint = 0x04"))
    errors = constants.check(root2)
    assert any("WAL record tag: checkpoint" in e for e in errors), errors

    root3 = _shadow_tree(tmp_path / "c")
    cfg = root3 / "pbft_tpu" / "consensus" / "config.py"
    cfg.write_text(cfg.read_text().replace(
        "wal_fsync: bool = True", "wal_fsync: bool = False"))
    errors = constants.check(root3)
    assert any(
        "ClusterConfig default: wal_fsync" in e for e in errors
    ), errors


def test_blocking_call_in_async_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    fixture = root / "pbft_tpu" / "net" / "fixture_blocking.py"
    fixture.write_text(
        "import time\n"
        "\n"
        "\n"
        "async def stall_the_loop():\n"
        "    time.sleep(1)  # the violation\n"
    )
    errors = async_blocking.check(root)
    assert any("time.sleep" in e and "stall_the_loop" in e for e in errors), (
        errors)
    proc = _run_lint(root, passes="async-blocking")
    assert proc.returncode == 1
    assert "time.sleep" in proc.stdout


def test_blocking_socket_and_subprocess_trip(tmp_path):
    root = _shadow_tree(tmp_path)
    fixture = root / "pbft_tpu" / "net" / "fixture_blocking2.py"
    fixture.write_text(
        "import subprocess\n"
        "\n"
        "\n"
        "async def bad_subprocess():\n"
        "    subprocess.run(['true'])\n"
        "\n"
        "\n"
        "async def bad_socket(sock):\n"
        "    return sock.recv(4096)\n"
        "\n"
        "\n"
        "async def fine(loop, sock):\n"
        "    # passing the callable (not calling it) is loop-safe\n"
        "    await loop.run_in_executor(None, sock.close)\n"
        "\n"
        "\n"
        "async def nested_sync_ok():\n"
        "    def helper():\n"
        "        import time\n"
        "        time.sleep(0)  # runs wherever it's called, not the loop\n"
        "    return helper\n"
    )
    errors = async_blocking.check(root)
    assert any("subprocess.run" in e for e in errors), errors
    assert any("sock.recv" in e for e in errors), errors
    assert not any("nested_sync_ok" in e for e in errors), errors
    assert not any("'fine'" in e for e in errors), errors


def test_unregistered_metric_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    fixture = root / "pbft_tpu" / "fixture_metrics.py"
    fixture.write_text(
        "def emit(registry):\n"
        "    registry.counter('pbft_totally_unregistered_total').inc()\n"
    )
    errors = metrics_lint.check(root)
    assert any("pbft_totally_unregistered_total" in e for e in errors), errors
    proc = _run_lint(root, passes="metrics")
    assert proc.returncode == 1
    assert "pbft_totally_unregistered_total" in proc.stdout


def test_unregistered_metric_in_emitter_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    server = root / "pbft_tpu" / "net" / "server.py"
    text = server.read_text()
    anchor = '"pbft_frames_in_total"'
    assert anchor in text
    server.write_text(text.replace(anchor, '"pbft_frames_in_renamed_total"', 1))
    errors = metrics_lint.check(root)
    assert any("pbft_frames_in_renamed_total" in e for e in errors), errors


def test_wrong_metric_kind_trips(tmp_path):
    root = _shadow_tree(tmp_path)
    fixture = root / "pbft_tpu" / "fixture_kind.py"
    fixture.write_text(
        "def emit(registry):\n"
        "    registry.gauge('pbft_executed_total').set(1)\n"  # it's a counter
    )
    errors = metrics_lint.check(root)
    assert any("pbft_executed_total" in e and "gauge" in e for e in errors), (
        errors)


def test_untuned_python_dial_trips(tmp_path):
    """sockets pass (ISSUE 10): stripping the TCP_NODELAY setsockopt from
    the client's dial helper trips the socket-discipline lint."""
    root = _shadow_tree(tmp_path)
    cl = root / "pbft_tpu" / "net" / "client.py"
    text = cl.read_text()
    assert "TCP_NODELAY" in text
    cl.write_text(
        "\n".join(
            line
            for line in text.splitlines()
            if "TCP_NODELAY" not in line
        )
    )
    errors = sockets.check(root)
    assert any("client.py" in e and "TCP_NODELAY" in e for e in errors), errors
    proc = _run_lint(root, passes="sockets")
    assert proc.returncode == 1


def test_untuned_cxx_socket_trips(tmp_path):
    """sockets pass, C++ side: a stream socket() site whose tuning call
    is stripped fails the lint."""
    root = _shadow_tree(tmp_path)
    net = root / "core" / "net.cc"
    text = net.read_text()
    assert "tune_stream_socket(fd);" in text
    # Strip the tune call inside dial_socket (the first occurrence after
    # the AF_INET/SOCK_STREAM creation) — a new dial site forgetting the
    # call looks exactly like this.
    net.write_text(text.replace("  tune_stream_socket(fd);\n", "", 1))
    errors = sockets.check(root)
    assert any("net.cc" in e for e in errors), errors


def test_divergent_gateway_prefix_trips(tmp_path):
    """constants pass: the gateway routing-token prefix is a cross-runtime
    switch (reply fan-back vs dial-back) — drift fails the build."""
    root = _shadow_tree(tmp_path)
    gw = root / "pbft_tpu" / "net" / "gateway.py"
    gw.write_text(gw.read_text().replace(
        'GATEWAY_CLIENT_PREFIX = "gw/"', 'GATEWAY_CLIENT_PREFIX = "gx/"'))
    errors = constants.check(root)
    assert any("gateway client-token prefix" in e for e in errors), errors


def test_divergent_health_constants_trip(tmp_path):
    """ISSUE 16 pairs: the health-document version, the silent-stall
    threshold, and the snapshot cadence are operational contracts shared
    by pbftd's /status route, the detector library, and the pbft_top /
    endurance tooling — drift in any of them makes a gate judge one
    runtime by the other's thresholds."""
    root = _shadow_tree(tmp_path)
    ts = root / "pbft_tpu" / "utils" / "trace_schema.py"
    text = ts.read_text()
    assert "HEALTH_DOC_VERSION = 1" in text
    ts.write_text(text.replace(
        "HEALTH_DOC_VERSION = 1", "HEALTH_DOC_VERSION = 2"))
    errors = constants.check(root)
    assert any("health document version" in e for e in errors), errors

    root2 = _shadow_tree(tmp_path / "b")
    hp = root2 / "pbft_tpu" / "analysis" / "health.py"
    text = hp.read_text()
    assert "HEALTH_STALL_SECONDS = 5" in text
    hp.write_text(text.replace(
        "HEALTH_STALL_SECONDS = 5", "HEALTH_STALL_SECONDS = 9"))
    errors = constants.check(root2)
    assert any("health stall threshold seconds" in e for e in errors), errors

    root3 = _shadow_tree(tmp_path / "c")
    hdr = root3 / "core" / "net.h"
    text = hdr.read_text()
    assert "kHealthSnapshotIntervalS = 2" in text
    hdr.write_text(text.replace(
        "kHealthSnapshotIntervalS = 2", "kHealthSnapshotIntervalS = 4"))
    errors = constants.check(root3)
    assert any(
        "health snapshot interval seconds" in e for e in errors
    ), errors


def test_missing_health_gauge_in_cxx_table_trips(tmp_path):
    """A health gauge dropped from metrics.cc's kGaugeNames (so pbftd
    would stop exporting it) fails the manifest cross-check."""
    root = _shadow_tree(tmp_path)
    mc = root / "core" / "metrics.cc"
    text = mc.read_text()
    assert '"pbft_inbox_depth",' in text
    mc.write_text(text.replace('    "pbft_inbox_depth",\n', '', 1))
    errors = metrics_lint.check(root)
    assert any(
        "kGaugeNames" in e and "pbft_inbox_depth" in e for e in errors
    ), errors


def test_scanned_files_exist():
    """The shadow-tree contract: every scanned path exists in the repo
    (a rename must update the pass specs, not silently skip)."""
    for path in analysis.scanned_files(REPO):
        assert path.exists(), f"scanned file missing: {path}"


# -- 3. the sanitizer matrix (@slow) ------------------------------------------

@pytest.mark.slow
def test_sanitizer_matrix_clean(tmp_path):
    """Build + run the full flavor matrix (strict, TSan, ASan+UBSan) of
    core_test and core/race_stress.cc: zero unsuppressed findings and
    zero test failures, with the machine-readable summary intact."""
    summary_path = tmp_path / "sanitize_summary.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "sanitize.py"),
         "--json", str(summary_path)],
        capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(summary_path.read_text())
    assert summary["ok"]
    flavors = {f["flavor"] for f in summary["flavors"]}
    assert flavors == {"strict", "tsan", "asan-ubsan"}
    for flavor in summary["flavors"]:
        assert flavor["findings"] == 0, flavor
        for name, binary in flavor["binaries"].items():
            assert binary["exit"] == 0, (flavor["flavor"], name, binary)
