"""FFI determinism: C++ canonical message encoding == Python's, byte for byte
(SURVEY.md §7 — verifier results and digests must be identical across
backends, or replicas diverge)."""

import ctypes

import pytest

from pbft_tpu import native
from pbft_tpu.consensus.messages import (
    Checkpoint,
    ClientReply,
    ClientRequest,
    Commit,
    NewView,
    Prepare,
    PrePrepare,
    StateRequest,
    StateResponse,
    ViewChange,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not buildable"
)


def cxx_roundtrip(payload: bytes):
    lib = native.lib()
    lib.pbft_message_roundtrip.restype = ctypes.c_size_t
    buf = ctypes.create_string_buffer(len(payload) * 4 + 64)
    dig = ctypes.create_string_buffer(32)
    n = lib.pbft_message_roundtrip(payload, len(payload), buf, len(buf), dig)
    return buf.raw[:n], dig.raw


REQ = ClientRequest(
    operation='héllo ☃ "q" \\s\n\t\x01 \U0001f600', timestamp=1 << 40,
    client="127.0.0.1:9000",
)
_PP = PrePrepare(view=0, seq=17, digest=REQ.digest(), requests=(REQ,), replica=0, sig="ab" * 64)
REQ2 = ClientRequest(operation="op-2", timestamp=2, client="127.0.0.1:9001")
from pbft_tpu.consensus.messages import batch_digest
_PP_BATCH = PrePrepare(
    view=0, seq=18, digest=batch_digest((REQ, REQ2)), requests=(REQ, REQ2),
    replica=0, sig="ab" * 64,
)
_PP_EMPTY = PrePrepare(
    view=1, seq=19, digest=batch_digest(()), requests=(), replica=1,
    sig="cd" * 64,
)
_PREP = Prepare(view=0, seq=17, digest=REQ.digest(), replica=2, sig="cd" * 64)
_CP = Checkpoint(seq=16, digest="11" * 32, replica=1, sig="22" * 64)
_VC = ViewChange(
    new_view=1,
    last_stable_seq=16,
    checkpoint_proof=(_CP.to_dict(),),
    prepared_proofs=(
        {"pre_prepare": _PP.to_dict(), "prepares": [_PREP.to_dict()]},
    ),
    replica=2,
    sig="33" * 64,
)
MESSAGES = [
    REQ,
    ClientReply(view=0, timestamp=1, client="c:1", replica=3, result="awesome!"),
    PrePrepare(view=0, seq=7, digest=REQ.digest(), requests=(REQ,), replica=0, sig="ab" * 64),
    _PP_BATCH,  # batched pre-prepare (ISSUE 4): `requests` list form
    _PP_EMPTY,  # empty batch: the batched new-view gap filler
    Prepare(view=1, seq=2, digest="dd" * 32, replica=2, sig="cd" * 64),
    Commit(view=1, seq=2, digest="dd" * 32, replica=2, sig="ef" * 64),
    Checkpoint(seq=16, digest="11" * 32, replica=1, sig="22" * 64),
    _VC,
    NewView(
        new_view=1,
        view_changes=(_VC.to_dict(),),
        pre_prepares=(_PP.to_dict(),),
        replica=1,
        sig="44" * 64,
    ),
    StateRequest(seq=16, replica=3, sig="55" * 64),
    StateResponse(
        seq=16,
        # A checkpoint payload is itself canonical JSON carried as a string
        # field — the parity test covers its escaping both ways.
        snapshot='{"app":"7 ☃","chain":"00","replies":[],"seq":16,"timestamps":[["c:1",5]]}',
        replica=2,
        sig="66" * 64,
    ),
]


@pytest.mark.parametrize("msg", MESSAGES, ids=lambda m: type(m).__name__)
def test_canonical_and_signable_identical(msg):
    payload = msg.canonical()
    cxx_canon, cxx_digest = cxx_roundtrip(payload)
    assert cxx_canon == payload
    assert cxx_digest == msg.signable()


@pytest.mark.parametrize(
    "bad",
    [b"", b"{", b'{"type":"nope"}', b'{"type":"prepare"}', b"\xff\xfe garbage"],
)
def test_malformed_payload_rejected(bad):
    canon, _ = cxx_roundtrip(bad)
    assert canon == b""
