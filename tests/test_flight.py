"""Black-box flight recorder (ISSUE 9): cross-runtime dump format parity,
the branch-cheap-when-disabled overhead guard, and the end-to-end
contract — a replica killed mid-run ships a dump that decodes into
ordered protocol events, and a failing chaos-soak seed ships one per
replica."""

import re
import pathlib
import subprocess
import sys
import time

import pytest

from pbft_tpu import native
from pbft_tpu.utils import flight, trace_schema

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- format + overhead guard (satellite: tier-1, no cluster) -----------------


def test_python_recorder_roundtrip_byte_exact(tmp_path):
    rec = flight.FlightRecorder(capacity=8)
    for i in range(1, 6):
        rec.record("executed", view=0, seq=i, peer=-1, t_ns=1000 + i)
    rec.record("view_change_sent", view=1, t_ns=2000)
    path = tmp_path / "py.flight"
    assert rec.dump(str(path)) == 6
    raw = path.read_bytes()
    decoded = flight.decode_bytes(raw)
    assert [r["seq"] for r in decoded[:5]] == [1, 2, 3, 4, 5]
    assert decoded[5]["event"] == "view_change_sent"
    assert decoded[5]["view"] == 1
    # Byte-exact round trip: decode -> re-encode reproduces the file.
    rows = [(r["t_ns"], r["ev"], r["peer"], r["view"], r["seq"]) for r in decoded]
    assert flight.encode_records(rows) == raw


def test_python_recorder_ring_evicts_oldest():
    rec = flight.FlightRecorder(capacity=4)
    for i in range(1, 11):
        rec.record("committed", seq=i)
    snap = rec.snapshot()
    assert len(snap) == 4
    assert [r[4] for r in snap] == [7, 8, 9, 10]


def test_python_recorder_disabled_is_noop():
    rec = flight.FlightRecorder(capacity=4, enabled=False)
    rec.record("executed", seq=1)
    rec.record_phase("executed", 0, 1)
    assert len(rec) == 0


def test_decode_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.flight"
    bad.write_bytes(b"NOTAFLIGHTDUMP....")
    with pytest.raises(ValueError):
        flight.decode_file(str(bad))
    truncated = tmp_path / "trunc.flight"
    rec = flight.FlightRecorder(capacity=4)
    rec.record("executed", seq=1)
    truncated.write_bytes(rec.encode()[:-5])
    with pytest.raises(ValueError):
        flight.decode_file(str(truncated))


def test_cxx_record_path_checks_enabled_first():
    """The overhead guard's source half (mirrors the metrics rule: one
    attribute check when disabled): FlightRecorder::record must branch on
    the enabled flag BEFORE doing any work."""
    src = (REPO / "core" / "flight.cc").read_text()
    body = re.search(
        r"void FlightRecorder::record\([^)]*\)\s*\{(.*?)\n\}", src, re.S
    )
    assert body, "FlightRecorder::record not found"
    first_stmt = body.group(1).strip().splitlines()[0]
    assert "enabled_.load" in first_stmt and "return" in first_stmt, (
        "record() must open with the disabled check, got: " + first_stmt
    )


@pytest.mark.skipif(not native.available(), reason="native core not built")
def test_native_recorder_disabled_and_roundtrip(tmp_path):
    """The native ring through capi: disabled record is a no-op; an
    enabled ring dump decodes with the PYTHON decoder (cross-runtime
    format parity) and re-encodes byte-exactly."""
    lib = native.lib()
    for fn in ("pbft_flight_configure", "pbft_flight_dump"):
        if not hasattr(lib, fn):
            pytest.fail(f"stale libpbftcore.so: missing {fn}; rebuild")
    native.flight_configure(0)  # disabled
    native.flight_record(trace_schema.FLIGHT_EVENT_IDS["executed"], 0, 1, -1)
    assert native.flight_total() == 0
    try:
        native.flight_configure(8)
        for i in range(1, 13):  # wraps the ring: only the last 8 survive
            native.flight_record(
                trace_schema.FLIGHT_EVENT_IDS["executed"], 0, i, -1
            )
        path = tmp_path / "native.flight"
        assert native.flight_dump(str(path)) == 8
        decoded = flight.decode_file(str(path))
        assert [r["seq"] for r in decoded] == list(range(5, 13))
        assert all(r["event"] == "executed" for r in decoded)
        assert all(
            b["t_ns"] >= a["t_ns"] for a, b in zip(decoded, decoded[1:])
        )
        rows = [
            (r["t_ns"], r["ev"], r["peer"], r["view"], r["seq"])
            for r in decoded
        ]
        assert flight.encode_records(rows) == path.read_bytes()
    finally:
        native.flight_configure(0)


def test_flight_dump_cli(tmp_path):
    rec = flight.FlightRecorder(capacity=16)
    rec.record("pre_prepare", view=0, seq=1)
    rec.record("executed", view=0, seq=1)
    path = tmp_path / "cli.flight"
    rec.dump(str(path))
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "flight_dump.py"), str(path)],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "pre_prepare" in out.stdout and "executed" in out.stdout
    bad = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "flight_dump.py"),
            str(tmp_path / "missing.flight"),
        ],
        capture_output=True,
        text=True,
    )
    assert bad.returncode == 2


# -- the black-box contract against real daemons ------------------------------


PHASE_RANK = {
    "pre_prepare": 0,
    "prepared": 1,
    "committed": 2,
    "executed": 3,
}


def _assert_protocol_order(records):
    """Chronological ring + per-sequence phase ordering."""
    assert records, "empty black box"
    assert all(
        b["t_ns"] >= a["t_ns"] for a, b in zip(records, records[1:])
    ), "flight dump not chronological"
    per_seq = {}
    for r in records:
        if r["event"] in PHASE_RANK:
            per_seq.setdefault((r["view"], r["seq"]), []).append(
                PHASE_RANK[r["event"]]
            )
    assert per_seq, "no consensus-phase records in the black box"
    for key, ranks in per_seq.items():
        assert ranks == sorted(ranks), (
            f"phase order violated at (view, seq)={key}: {ranks}"
        )


@pytest.mark.skipif(not native.available(), reason="native core not built")
@pytest.mark.parametrize("impl", ["cxx", "py"])
def test_killed_replica_ships_black_box(impl, tmp_path):
    """Kill a replica mid-run (SIGTERM, the chaos-soak kill path): its
    flight dump exists, decodes, and shows ordered protocol events —
    request_rx through executed — from the dead process."""
    from pbft_tpu.net import LocalCluster, PbftClient

    flight_dir = tmp_path / "flight"
    with LocalCluster(
        n=4, verifier="cpu", impl=impl, flight_dir=str(flight_dir)
    ) as cluster:
        client = PbftClient(cluster.config)
        try:
            for i in range(3):
                req = client.request(f"op-{i}")
                assert client.wait_result(req.timestamp, timeout=20) == "awesome!"
            cluster.kill(2)  # a backup: SIGTERM -> dump on the way down
            deadline = time.monotonic() + 10
            dump = flight_dir / "replica-2.flight"
            while not dump.exists() and time.monotonic() < deadline:
                time.sleep(0.05)
            records = flight.decode_file(str(dump))
            _assert_protocol_order(records)
            events = {r["event"] for r in records}
            assert "executed" in events
            # The backup verified batches and replied to the client.
            assert "verify_batch" in events
            assert "reply_tx" in events
        finally:
            client.close()


def test_chaos_soak_failure_ships_black_boxes(tmp_path):
    """A failing soak seed collects one flight dump per replica (the
    acceptance contract: a failing seed ships with its black box). Over
    the fault budget — f+1 colluding equivocators — the run MUST fail
    (safety trip or liveness miss), and every dump must decode."""
    sys.path.insert(0, str(REPO / "scripts"))
    import chaos_soak
    from pbft_tpu.consensus.faults import FaultEvent, FaultSchedule

    schedule = FaultSchedule(
        [
            FaultEvent(1, "set_fault", (0, "equivocate")),
            FaultEvent(1, "set_fault", (1, "equivocate")),
        ]
    )
    res = chaos_soak.run_one(
        seed=1,
        n=4,
        steps=200,
        schedule=schedule,
        submit_every=4,
        recovery_steps=120,
        flight_dir=str(tmp_path / "bb"),
    )
    assert res["ok"] is False, "f+1 equivocators must break the run"
    dumps = res.get("flight_dumps")
    assert dumps and len(dumps) == 4
    saw_events = False
    for path in dumps:
        records = flight.decode_file(path)
        if records:
            saw_events = True
            assert all(
                b["t_ns"] >= a["t_ns"] for a, b in zip(records, records[1:])
            )
    assert saw_events, "no replica recorded any protocol event"
