"""RFC 8032 known-answer tests + independent-library cross-checks for the
pure-Python Ed25519 oracle (pbft_tpu.crypto.ref)."""

import secrets

import pytest

from pbft_tpu.crypto import ref

# RFC 8032 §7.1 test vectors: (secret seed, public key, message, signature).
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
    (
        "833fe62409237b9d62ec77587520911e9a759cec1d19755b7da901b96dca3d42",
        "ec172b93ad5e563bf4932c70e1245034c35467ef2efd4d64ebf819683467e2bf",
        "ddaf35a193617abacc417349ae20413112e6fa4e89a97ea20a9eeee64b55d39a"
        "2192992a274fc1a836ba3c23a3feebbd454d4423643ce80e2a9ac94fa54ca49f",
        "dc2a4459e7369633a52b1bf277839a00201009a3efbf3ecb69bea2186c26b589"
        "09351fc9ac90b3ecfdfbc7c66431e0303dca179c138ac17ad9bef1177331a704",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_sign(seed, pub, msg, sig):
    seed, pub, msg, sig = (bytes.fromhex(x) for x in (seed, pub, msg, sig))
    assert ref.public_key(seed) == pub
    assert ref.sign(seed, msg) == sig
    assert ref.verify(pub, msg, sig)


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_reject_corruption(seed, pub, msg, sig):
    pub, msg, sig = (bytes.fromhex(x) for x in (pub, msg, sig))
    bad_sig = bytes([sig[0] ^ 1]) + sig[1:]
    assert not ref.verify(pub, msg, bad_sig)
    bad_s = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
    assert not ref.verify(pub, msg, bad_s)
    assert not ref.verify(pub, msg + b"x", sig)
    bad_pub = bytes([pub[0] ^ 1]) + pub[1:]
    assert not ref.verify(bad_pub, msg, sig)


def test_reject_s_out_of_range():
    seed, pub = ref.keygen(b"\x07" * 32)
    msg = b"range check"
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    malleated = sig[:32] + int.to_bytes(s + ref.L, 32, "little")
    assert not ref.verify(pub, msg, malleated)


def test_keygen_roundtrip_random():
    for _ in range(8):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(48)
        sig = ref.sign(seed, msg)
        assert ref.verify(pub, msg, sig)
        assert not ref.verify(pub, msg[:-1], sig)


def test_cross_check_against_cryptography():
    """Independent oracle: pyca/cryptography (OpenSSL) must agree with us."""
    crypto = pytest.importorskip("cryptography.hazmat.primitives.asymmetric.ed25519")
    for i in range(8):
        seed = secrets.token_bytes(32)
        msg = secrets.token_bytes(32 + i)
        their_key = crypto.Ed25519PrivateKey.from_private_bytes(seed)
        from cryptography.hazmat.primitives import serialization

        their_pub = their_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        their_sig = their_key.sign(msg)
        assert ref.public_key(seed) == their_pub
        assert ref.sign(seed, msg) == their_sig
        assert ref.verify(their_pub, msg, their_sig)
