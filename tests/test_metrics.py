"""The observability layer (ISSUE 1): registry semantics, consensus-phase
span lifecycle, the /metrics scrape surface on both Python runtimes, the
cross-replica timeline analyzer against the checked-in r5 fixtures, and
the Tracer hot-loop hardening."""

import asyncio
import io
import json
import pathlib
import socket
import subprocess
import sys
import urllib.request

import pytest

from pbft_tpu.consensus.config import ClusterConfig, make_local_cluster
from pbft_tpu.utils import ConsensusSpans, MetricsRegistry, Tracer
from pbft_tpu.utils import trace_schema

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- registry semantics ------------------------------------------------------


def test_histogram_bucket_edges_le_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("pbft_verify_batch_size")
    assert h.edges == trace_schema.BATCH_SIZE_BUCKETS
    h.observe(1)  # exactly the first edge -> first bucket (le)
    h.observe(2)  # exactly the second edge
    h.observe(3)  # between 2 and 4 -> third bucket
    h.observe(5000)  # above the last edge -> +Inf slot
    assert h.counts[0] == 1 and h.counts[1] == 1 and h.counts[2] == 1
    assert h.counts[-1] == 1
    assert h.count == 4 and h.sum == 1 + 2 + 3 + 5000


def test_render_prometheus_shape():
    reg = MetricsRegistry(labels={"replica": "7"})
    reg.counter("pbft_frames_in_total").inc(3)
    reg.gauge("pbft_verify_queue_depth").set(2)
    h = reg.histogram("pbft_verify_seconds")
    h.observe(0.0004)
    h.observe(99.0)
    text = reg.render_prometheus()
    assert '# TYPE pbft_frames_in_total counter' in text
    assert 'pbft_frames_in_total{replica="7"} 3' in text
    assert 'pbft_verify_queue_depth{replica="7"} 2' in text
    # Cumulative buckets: the 0.0004 observation is in every le >= 0.0005
    # bucket; 99.0 only in +Inf.
    assert 'pbft_verify_seconds_bucket{replica="7",le="0.0005"} 1' in text
    assert 'pbft_verify_seconds_bucket{replica="7",le="10"} 1' in text
    assert 'pbft_verify_seconds_bucket{replica="7",le="+Inf"} 2' in text
    assert 'pbft_verify_seconds_count{replica="7"} 2' in text
    assert text.endswith("\n")


def test_disabled_registry_is_inert_and_unknown_names_fail():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("pbft_frames_in_total")
    h = reg.histogram("pbft_verify_seconds")
    c.inc(5)
    h.observe(1.0)
    assert c.value == 0 and h.count == 0  # one attribute check, no work
    reg.set_enabled(True)
    c.inc(5)
    assert c.value == 5
    with pytest.raises(KeyError):
        reg.counter("pbft_not_in_manifest_total")
    with pytest.raises(KeyError):
        reg.histogram("pbft_frames_in_total")  # wrong type for the name


# -- consensus-phase span lifecycle ------------------------------------------


def test_span_lifecycle_over_simulated_three_phase_commit():
    """A 4-replica simulated cluster commits one request; every replica's
    spans must close with per-phase observations, the primary's span must
    carry the request stamp, and the consensus_span events must match the
    manifest schema."""
    from pbft_tpu.consensus.simulation import Cluster

    sink = io.StringIO()
    tracer = Tracer(sink)
    cluster = Cluster(n=4)
    registries = []
    for i, replica in enumerate(cluster.replicas):
        reg = MetricsRegistry(labels={"replica": str(i)})
        replica.phase_hook = ConsensusSpans(
            reg, tracer=tracer, replica=i
        ).on_phase
        registries.append(reg)
    cluster.submit("op", timestamp=1)
    cluster.run()
    assert cluster.committed_result(1) == "awesome!"
    for i, reg in enumerate(registries):
        assert reg.counter("pbft_executed_total").value == 1
        assert reg.histogram("pbft_phase_prepare_seconds").count == 1
        assert reg.histogram("pbft_phase_commit_seconds").count == 1
        assert reg.histogram("pbft_phase_reply_seconds").count == 1
        assert reg.histogram("pbft_request_reply_seconds").count == 1
        # request -> pre-prepare exists only on the primary (replica 0).
        expected = 1 if i == 0 else 0
        assert reg.histogram("pbft_phase_pre_prepare_seconds").count == expected
    events = [json.loads(line) for line in sink.getvalue().splitlines()]
    spans = [e for e in events if e["ev"] == "consensus_span"]
    assert len(spans) == 4  # one closed span per replica
    schema = trace_schema.EVENT_SCHEMAS["consensus_span"]
    for e in spans:
        fields = set(e)
        assert schema["required"] <= fields
        assert fields <= schema["required"] | schema["optional"]
        assert (e["view"], e["seq"]) == (0, 1)
    assert sum("request" in e for e in spans) == 1  # primary only


def test_span_tracker_bounds_open_spans():
    reg = MetricsRegistry()
    spans = ConsensusSpans(reg, max_open=8)
    for seq in range(1, 50):
        spans.on_phase("pre_prepare", 0, seq)
    assert len(spans._open) == 8  # oldest evicted, no leak
    spans.on_phase("executed", 0, 1)  # evicted slot: closing is a no-op
    assert reg.counter("pbft_executed_total").value == 0


def test_span_clock_injection_measures_phase_deltas():
    t = [100.0]
    reg = MetricsRegistry()
    spans = ConsensusSpans(reg, clock=lambda: t[0])
    spans.on_phase("request", 0, 1)
    t[0] = 100.25
    spans.on_phase("pre_prepare", 0, 1)
    t[0] = 100.5
    spans.on_phase("prepared", 0, 1)
    t[0] = 101.0
    spans.on_phase("committed", 0, 1)
    t[0] = 101.5
    spans.on_phase("executed", 0, 1)
    for name, want in (
        ("pbft_phase_pre_prepare_seconds", 0.25),
        ("pbft_phase_prepare_seconds", 0.25),
        ("pbft_phase_commit_seconds", 0.5),
        ("pbft_phase_reply_seconds", 0.5),
        ("pbft_request_reply_seconds", 1.5),
    ):
        h = reg.histogram(name)
        assert h.count == 1 and abs(h.sum - want) < 1e-9, name


# -- /metrics scrape surface -------------------------------------------------


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        return resp.read().decode()


def test_async_cluster_metrics_endpoint_end_to_end():
    """A 4-replica in-process asyncio cluster with --metrics-port semantics:
    one committed client request must surface per-phase latency histograms
    and verify counters on the scrape endpoint, with manifest names."""
    from pbft_tpu.net.launcher import free_ports
    from pbft_tpu.net.server import AsyncReplicaServer

    async def scenario():
        config, seeds = make_local_cluster(4, base_port=0)
        ports = free_ports(4)
        config = ClusterConfig(
            replicas=[
                type(r)(r.replica_id, r.host, ports[i], r.pubkey)
                for i, r in enumerate(config.replicas)
            ]
        )
        servers = []
        for i in range(4):
            servers.append(
                await AsyncReplicaServer(
                    config, i, seeds[i], metrics_port=0
                ).start()
            )
        try:
            req = {
                "type": "client-request",
                "operation": "observe me",
                "timestamp": 1,
                "client": "127.0.0.1:1",  # dial-back dropped; irrelevant
            }
            _, w = await asyncio.open_connection("127.0.0.1", ports[0])
            w.write(json.dumps(req).encode() + b"\n")
            await w.drain()
            w.close()
            for _ in range(200):
                if all(s.replica.executed_upto >= 1 for s in servers):
                    break
                await asyncio.sleep(0.05)
            assert all(s.replica.executed_upto >= 1 for s in servers)
            loop = asyncio.get_running_loop()
            texts = [
                await loop.run_in_executor(
                    None, _scrape, s.metrics_listen_port
                )
                for s in servers
            ]
        finally:
            for s in servers:
                await s.stop()
        for i, text in enumerate(texts):
            label = '{replica="%d"}' % i
            assert f"pbft_request_reply_seconds_count{label} 1" in text
            assert f"pbft_phase_prepare_seconds_count{label} 1" in text
            assert f"pbft_phase_commit_seconds_count{label} 1" in text
            assert "# TYPE pbft_verify_batches_total counter" in text
            assert f"pbft_executed_total{label} 1" in text
        # The request stamp exists only on the primary.
        assert 'pbft_phase_pre_prepare_seconds_count{replica="0"} 1' in texts[0]
        assert 'pbft_phase_pre_prepare_seconds_count{replica="1"} 0' in texts[1]

    asyncio.run(scenario())


def test_verifier_service_metrics_endpoint():
    """The service's scrape surface: one wire batch must show up in the
    verify counters/histograms under replica="service"."""
    from pbft_tpu.net.service import VerifierService

    svc = VerifierService(backend="cpu", metrics_port=0).start()
    try:
        host, port = svc.address.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=5) as s:
            s.sendall(
                (2).to_bytes(4, "big") + bytes(128) * 2
            )  # two zero items: invalid, rejected
            verdicts = s.recv(2)
        assert verdicts == b"\x00\x00"
        text = _scrape(svc.metrics_listen_port)
    finally:
        svc.stop()
    label = '{replica="service"}'
    assert f"pbft_verify_batches_total{label} 1" in text
    assert f"pbft_verify_items_total{label} 2" in text
    assert f"pbft_verify_rejected_total{label} 2" in text
    assert f"pbft_verify_batch_size_count{label} 1" in text


# -- the timeline analyzer against the checked-in r5 fixtures ----------------


def test_consensus_timeline_on_r5_fixture():
    """scripts/consensus_timeline.py must produce a per-(view, seq) phase
    breakdown from benchmarks/traces_r5_svc_cfg2 WITHOUT modification
    (acceptance criterion: the legacy executed-counter estimates)."""
    fixture = REPO / "benchmarks" / "traces_r5_svc_cfg2"
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "consensus_timeline.py"),
            str(fixture),
            "--json",
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout)
    assert result["replicas"] == [0, 1, 2, 3, 4, 5, 6]
    assert len(result["slots"]) >= 100
    first = result["slots"][0]
    assert first["view"] == 0 and first["seq"] == 1
    # Every reporting replica carries an executed stamp (the estimate).
    for rep in first["replicas"].values():
        assert "executed" in rep and rep.get("estimated") is True
    assert "executed_spread_ms" in first


def test_consensus_timeline_merges_span_events(tmp_path):
    """Span-bearing traces get full per-phase durations and straggler
    flags across replicas."""
    base = 1000.0
    for rid, lag in ((0, 0.0), (1, 0.5)):  # replica 1 lags 500ms
        path = tmp_path / f"replica-{rid}.jsonl"
        ev = {
            "ts": base + lag + 0.04,
            "ev": "consensus_span",
            "replica": rid,
            "view": 0,
            "seq": 1,
            "pre_prepare": base + lag,
            "prepared": base + lag + 0.01,
            "committed": base + lag + 0.03,
            "executed": base + lag + 0.04,
        }
        path.write_text(json.dumps(ev) + "\n")
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "consensus_timeline.py"),
            str(tmp_path),
            "--json",
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout)
    (slot,) = result["slots"]
    assert slot["stragglers"] == [1]
    assert abs(slot["executed_spread_ms"] - 500.0) < 1.0
    assert slot["replicas"]["0"]["durations"]["prepared->committed"] == 0.02
    assert result["straggler_counts"] == {"1": 1}


# -- wedged-async-verifier deadline (ADVICE.md, core/net.cc) -----------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_pbftd_verify_deadline_unwedges_cluster(tmp_path):
    """A verifier service that accepts batches but never replies used to
    stall pbftd forever (verify_inflight_ stuck true). With
    --verify-deadline-ms the daemon drops the wedged connection, re-verifies
    on the CPU safety net, commits anyway, and records
    verify_deadline_fired (trace event + counter)."""
    from pbft_tpu import native
    from pbft_tpu.net.client import PbftClient
    from pbft_tpu.net.launcher import free_ports, pbftd_path

    if not native.available():
        pytest.skip("native core not built")

    # The black hole: accepts connections, reads requests, never answers.
    blackhole = socket.socket()
    blackhole.bind(("127.0.0.1", 0))
    blackhole.listen(16)
    blackhole.settimeout(0.2)
    import threading

    wedged = True
    accepted = []

    def swallow():
        while wedged:
            try:
                conn, _ = blackhole.accept()
                accepted.append(conn)  # keep alive: no EOF, no reply
            except socket.timeout:
                continue
            except OSError:
                return

    t = threading.Thread(target=swallow, daemon=True)
    t.start()

    config, seeds = make_local_cluster(4, base_port=0)
    ports = free_ports(4)
    config = ClusterConfig(
        replicas=[
            type(r)(r.replica_id, r.host, ports[i], r.pubkey)
            for i, r in enumerate(config.replicas)
        ]
    )
    cfg_path = tmp_path / "network.json"
    cfg_path.write_text(config.to_json())
    target = "127.0.0.1:%d" % blackhole.getsockname()[1]
    procs = []
    try:
        for i in range(4):
            procs.append(
                subprocess.Popen(
                    [
                        str(pbftd_path()),
                        "--config", str(cfg_path),
                        "--id", str(i),
                        "--seed", seeds[i].hex(),
                        "--verifier", target,
                        "--verify-deadline-ms", "300",
                        "--trace", str(tmp_path / f"trace-{i}.jsonl"),
                    ],
                    stderr=subprocess.DEVNULL,
                )
            )
        client = PbftClient(config)
        try:
            # Commits despite every replica's verifier being wedged: each
            # batch unwedges via the 300 ms deadline + CPU safety net.
            assert client.request_with_retry("unwedge", timeout=60) == "awesome!"
        finally:
            client.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
        wedged = False
        blackhole.close()
    fired = []
    for i in range(4):
        for line in (tmp_path / f"trace-{i}.jsonl").read_text().splitlines():
            e = json.loads(line)
            if e["ev"] == "verify_deadline_fired":
                fired.append(e)
                assert e["size"] >= 1 and e["age_secs"] >= 0.3
    assert fired, "no replica recorded a verify_deadline_fired event"


# -- Tracer hot-loop hardening (satellite) -----------------------------------


def test_tracer_survives_non_serializable_fields():
    class Weird:
        def __repr__(self):
            return "<weird>"

        __str__ = __repr__

    sink = io.StringIO()
    tracer = Tracer(sink)
    tracer.event("verify_batch", replica=0, size=1, rejected=0, secs=0.1,
                 oops=Weird(), raw=b"\xff")
    rec = json.loads(sink.getvalue())
    assert rec["oops"] == "<weird>"  # degraded via default=str, no throw
    assert rec["ev"] == "verify_batch"
