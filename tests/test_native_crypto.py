"""Equivalence: C++ core crypto (via ctypes) vs hashlib and the Python
oracle — SURVEY.md §4 item 3, native edition."""

import hashlib
import secrets

import subprocess

import pytest

from pbft_tpu import native


def test_native_ctest_binary():
    """The pure-C++ unit suite (core_test) passes — crypto known answers,
    canonical JSON, 4-replica commit, and a native view change."""
    native.build()
    binary = native._BUILD_DIR / "core_test"
    if not binary.exists():
        pytest.skip("core_test not built")
    out = subprocess.run([str(binary)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "all native tests passed" in out.stdout
from pbft_tpu.crypto import ref
from tests.test_crypto_ref import RFC8032_VECTORS

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not buildable"
)


@pytest.mark.parametrize("n", [0, 1, 64, 111, 128, 129, 300, 1000])
def test_blake2b_matches_hashlib(n):
    data = secrets.token_bytes(n)
    assert native.blake2b(data) == hashlib.blake2b(data, digest_size=32).digest()
    assert native.blake2b(data, 64) == hashlib.blake2b(data).digest()


@pytest.mark.parametrize("n", [0, 1, 95, 96, 111, 112, 127, 128, 129, 300])
def test_sha512_matches_hashlib(n):
    data = secrets.token_bytes(n)
    assert native.sha512(data) == hashlib.sha512(data).digest()


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032(seed, pub, msg, sig):
    seed, pub, msg, sig = (bytes.fromhex(x) for x in (seed, pub, msg, sig))
    assert native.public_key(seed) == pub
    assert native.sign(seed, msg) == sig
    assert native.verify(pub, msg, sig)
    assert not native.verify(pub, msg + b"x", sig)


def test_native_vs_oracle_random():
    for i in range(6):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        assert native.public_key(seed) == pub
        sig_native = native.sign(seed, msg)
        assert sig_native == ref.sign(seed, msg)
        assert native.verify(pub, msg, sig_native)
        bad = bytes([sig_native[0] ^ 1]) + sig_native[1:]
        assert not native.verify(pub, msg, bad)
        assert native.verify(pub, msg, sig_native) == ref.verify(pub, msg, sig_native)


def test_native_rejects_malleated_s():
    seed, pub = ref.keygen()
    msg = secrets.token_bytes(32)
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + int.to_bytes(s + ref.L, 32, "little")
    assert not native.verify(pub, msg, mall)


def test_native_rejects_bad_pubkeys():
    msg = secrets.token_bytes(32)
    sig = bytes(64)
    noncanon = int.to_bytes(ref.P, 32, "little")
    assert not native.verify(noncanon, msg, sig)
    assert not native.verify(int.to_bytes(2, 32, "little"), msg, sig) or \
        ref.point_decompress(int.to_bytes(2, 32, "little")) is not None


def test_native_batch():
    items, want = [], []
    for i in range(7):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        sig = ref.sign(seed, msg)
        if i % 3 == 0:
            sig = sig[:33] + bytes([sig[33] ^ 0x80]) + sig[34:]
        items.append((pub, msg, sig))
        want.append(i % 3 != 0)
    assert native.verify_batch(items) == want
    assert native.verify_batch([]) == []
