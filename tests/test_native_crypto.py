"""Equivalence: C++ core crypto (via ctypes) vs hashlib and the Python
oracle — SURVEY.md §4 item 3, native edition."""

import hashlib
import secrets

import subprocess

import pytest

from pbft_tpu import native


def test_native_ctest_binary():
    """The pure-C++ unit suite (core_test) passes — crypto known answers,
    canonical JSON, 4-replica commit, and a native view change."""
    native.build()
    binary = native._BUILD_DIR / "core_test"
    if not binary.exists():
        pytest.skip("core_test not built")
    out = subprocess.run([str(binary)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "all native tests passed" in out.stdout
from pbft_tpu.crypto import ref
from tests.test_crypto_ref import RFC8032_VECTORS

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not buildable"
)


@pytest.mark.parametrize("n", [0, 1, 64, 111, 128, 129, 300, 1000])
def test_blake2b_matches_hashlib(n):
    data = secrets.token_bytes(n)
    assert native.blake2b(data) == hashlib.blake2b(data, digest_size=32).digest()
    assert native.blake2b(data, 64) == hashlib.blake2b(data).digest()


@pytest.mark.parametrize("n", [0, 1, 95, 96, 111, 112, 127, 128, 129, 300])
def test_sha512_matches_hashlib(n):
    data = secrets.token_bytes(n)
    assert native.sha512(data) == hashlib.sha512(data).digest()


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032(seed, pub, msg, sig):
    seed, pub, msg, sig = (bytes.fromhex(x) for x in (seed, pub, msg, sig))
    assert native.public_key(seed) == pub
    assert native.sign(seed, msg) == sig
    assert native.verify(pub, msg, sig)
    assert not native.verify(pub, msg + b"x", sig)


def test_native_vs_oracle_random():
    for i in range(6):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        assert native.public_key(seed) == pub
        sig_native = native.sign(seed, msg)
        assert sig_native == ref.sign(seed, msg)
        assert native.verify(pub, msg, sig_native)
        bad = bytes([sig_native[0] ^ 1]) + sig_native[1:]
        assert not native.verify(pub, msg, bad)
        assert native.verify(pub, msg, sig_native) == ref.verify(pub, msg, sig_native)


def test_native_rejects_malleated_s():
    seed, pub = ref.keygen()
    msg = secrets.token_bytes(32)
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + int.to_bytes(s + ref.L, 32, "little")
    assert not native.verify(pub, msg, mall)


def test_native_rejects_bad_pubkeys():
    msg = secrets.token_bytes(32)
    sig = bytes(64)
    noncanon = int.to_bytes(ref.P, 32, "little")
    assert not native.verify(noncanon, msg, sig)
    assert not native.verify(int.to_bytes(2, 32, "little"), msg, sig) or \
        ref.point_decompress(int.to_bytes(2, 32, "little")) is not None


def test_native_batch():
    items, want = [], []
    for i in range(7):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        sig = ref.sign(seed, msg)
        if i % 3 == 0:
            sig = sig[:33] + bytes([sig[33] ^ 0x80]) + sig[34:]
        items.append((pub, msg, sig))
        want.append(i % 3 != 0)
    assert native.verify_batch(items) == want
    assert native.verify_batch([]) == []


def _torsion_point():
    """A nonzero small-order point: [L]P for an arbitrary curve point P
    outside the prime subgroup (every nonzero torsion point has order
    dividing 8 on edwards25519)."""
    from pbft_tpu.crypto import ref

    for y in range(2, 60):
        enc = y.to_bytes(32, "little")
        pt = ref.point_decompress(enc)
        if pt is None:
            continue
        t = ref.scalar_mult(ref.L, pt)
        if t != (0, 1):  # not the identity -> genuine torsion
            return t
    raise AssertionError("no torsion point found in scan range")


def _craft_torsion_sig(seed: bytes, msg: bytes, defect):
    """A signature with verification defect exactly -defect (a Byzantine
    SIGNER crafting with its own secret key): R' = [r]B + defect,
    s = r + H(R',A,M)*a, so [s]B - [h]A - R' = -defect — torsion-only,
    invisible to any check that multiplies by the cofactor."""
    from pbft_tpu.crypto import ref

    a, _prefix = ref.secret_expand(seed)
    pub_pt = ref.scalar_mult(a, ref.BASE)
    pub = ref.point_compress(pub_pt)
    r = 0x1234567  # any fixed nonce: determinism keeps the test stable
    big_r = ref.point_compress(
        ref.point_add(ref.scalar_mult(r, ref.BASE), defect)
    )
    h = ref._h512_int(big_r, pub, msg) % ref.L
    s = (r + h * a) % ref.L
    return pub, big_r + s.to_bytes(32, "little")


def test_batch_rejects_a_lone_torsion_defect_deterministically():
    """A crafted signature whose defect is a small-order point must be
    rejected by the batch path exactly like per-item verify: the RLC
    coefficients are forced === 1 (mod 8), so a lone torsion defect can
    never cancel out of the combination (core/ed25519.cc accept-set
    note). Repeated runs pin determinism across random coefficients."""
    from pbft_tpu import native
    from pbft_tpu.crypto import ref

    t = _torsion_point()
    seed = bytes(range(32))
    msg = b"\x51" * 32
    pub, crafted = _craft_torsion_sig(seed, msg, t)
    assert not native.verify(pub, msg, crafted)
    assert not ref.verify(pub, msg, crafted)

    honest = []
    for i in range(15):
        s = bytes([i + 3]) * 32
        m = bytes([0xC0 ^ i]) * 32
        honest.append((native.public_key(s), m, native.sign(s, m)))
    for _ in range(8):  # fresh random z_i every call
        verdicts = native.verify_batch(honest[:7] + [(pub, msg, crafted)] + honest[7:])
        assert verdicts[7] == 0 and sum(verdicts) == 15, verdicts


def test_batch_torsion_pair_caveat_is_exactly_as_documented():
    """The documented accept-set caveat (core/ed25519.cc): TWO crafted
    signatures with cancelling torsion defects in ONE window pass the
    RLC check — equivalent in power to sender equivocation, which PBFT
    already tolerates. Per-item verify still rejects both; this test
    pins the caveat so any change to the batch semantics is loud."""
    from pbft_tpu import native
    from pbft_tpu.crypto import ref

    t = _torsion_point()
    neg_t = (ref.P - t[0], t[1])  # -T: negate x
    crafted = []
    for i, defect in ((0, t), (1, neg_t)):
        seed = bytes([i + 1]) * 32
        msg = bytes([0xE0 + i]) * 32
        pub, bad = _craft_torsion_sig(seed, msg, defect)
        assert not native.verify(pub, msg, bad)  # per-item: rejected
        crafted.append((pub, msg, bad))
    honest = []
    for i in range(10):
        s = bytes([i + 9]) * 32
        m = bytes([0x99 ^ i]) * 32
        honest.append((native.public_key(s), m, native.sign(s, m)))
    # Same window: the pair's defects cancel ((z1 - z2) T = 0 since
    # 8 | z1 - z2 and T has order dividing 8) -> batch accepts the pair.
    verdicts = native.verify_batch(honest + crafted)
    assert verdicts == [True] * 12, verdicts
    # Split windows (bisect below the RLC threshold): per-item authority
    # rejects each crafted signature alone.
    assert native.verify_batch([crafted[0]]) == [False]
    assert native.verify_batch([crafted[1]]) == [False]


hypothesis = __import__("pytest").importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402


@settings(max_examples=25, deadline=None)
@given(
    n=st.integers(min_value=0, max_value=48),
    corruption=st.lists(
        st.tuples(
            st.integers(min_value=0, max_value=47),  # item (mod n)
            st.sampled_from(["sig_r", "sig_s", "pub", "msg", "s_ge_l"]),
            st.integers(min_value=0, max_value=31),  # byte offset
        ),
        max_size=6,
    ),
)
def test_batch_verify_matches_per_item_under_fuzz(n, corruption):
    """Property: for ANY mix of corruptions, the batch path's verdict
    equals per-item native.verify for every item. (The only documented
    exception — colluding torsion-defect pairs — needs secret-key
    crafting that byte-level corruption cannot produce.)"""
    from pbft_tpu import native

    if n == 0:
        assert native.verify_batch([]) == []
        return
    items = []
    for i in range(n):
        seed = bytes([i + 1, 0x33]) * 16
        msg = bytes([0x70 ^ i]) * 32
        items.append((native.public_key(seed), msg, native.sign(seed, msg)))
    for which, kind, off in corruption:
        i = which % n
        pub, msg, sig = items[i]
        if kind == "sig_r":
            sig = sig[:off] + bytes([sig[off] ^ 0x80]) + sig[off + 1 :]
        elif kind == "sig_s":
            j = 32 + off
            sig = sig[:j] + bytes([sig[j] ^ 0x40]) + sig[j + 1 :]
        elif kind == "pub":
            pub = pub[:off] + bytes([pub[off] ^ 0x20]) + pub[off + 1 :]
        elif kind == "msg":
            msg = msg[:off] + bytes([msg[off] ^ 0x10]) + msg[off + 1 :]
        else:  # s >= L: a non-canonical scalar must be rejected pre-RLC
            sig = sig[:32] + b"\xff" * 31 + b"\x1f"
        items[i] = (pub, msg, sig)
    batch = native.verify_batch(items)
    single = [native.verify(p, m, s) for p, m, s in items]
    assert batch == single
