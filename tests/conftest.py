"""Test configuration: force pure-CPU JAX with 8 virtual devices.

Two subtleties of this environment:

1. A sitecustomize hook registers the TPU PJRT plugin at interpreter startup
   (before conftest runs) whenever the TPU pool env vars are set, and jax
   initializes registered plugin backends even when jax_platforms=cpu.
   Initializing the TPU client here would serialize every test process
   through the single TPU tunnel (and wedge if another process holds it), so
   tests must drop the plugin factory before the first backend init.
2. The virtual 8-device CPU mesh (for the multi-chip sharding tests,
   mirroring the driver's dryrun of __graft_entry__.dryrun_multichip) needs
   XLA_FLAGS before backend init too.

The logic lives in tests/_cpu_backend.py so subprocess workers (which never
see conftest) share it. The TPU path itself is exercised by bench.py /
__graft_entry__.py, not by unit tests.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

from _cpu_backend import force_cpu

force_cpu(n_devices=8)
