"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so that the multi-chip sharding
path (pbft_tpu.parallel) is exercised without TPU hardware, mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. Must be set before jax
initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
