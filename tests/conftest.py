"""Test configuration: force pure-CPU JAX with 8 virtual devices.

Two subtleties of this environment:

1. A sitecustomize hook registers the TPU PJRT plugin at interpreter startup
   (before conftest runs) whenever the TPU pool env vars are set, and jax
   initializes registered plugin backends even when jax_platforms=cpu.
   Initializing the TPU client here would serialize every test process
   through the single TPU tunnel (and wedge if another process holds it), so
   tests must drop the plugin factory before the first backend init.
2. The virtual 8-device CPU mesh (for the multi-chip sharding tests,
   mirroring the driver's dryrun of __graft_entry__.dryrun_multichip) needs
   XLA_FLAGS before backend init too.

The TPU path itself is exercised by bench.py / __graft_entry__.py, not by
unit tests.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the crypto kernels are compile-heavy (256-step
# ladders); caching cuts repeat suite runs from minutes to seconds.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
try:  # drop non-cpu plugin factories registered before conftest ran
    from jax._src import xla_bridge

    for _name in list(getattr(xla_bridge, "_backend_factories", {})):
        if _name != "cpu":
            xla_bridge._backend_factories.pop(_name)
except Exception:  # pragma: no cover - jax internals may move
    pass
