"""Mesh-sharded verification + distributed quorum certification.

Runs on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8), mirroring the driver's multi-chip
dryrun — the same code paths run on a real TPU slice.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pbft_tpu.crypto import ref
from pbft_tpu.crypto.batch import pad_batch
from pbft_tpu.parallel import make_mesh, sharded_verify, quorum_certify, round_step

# Kernel-compile-heavy: slow tier (pytest -m slow).
pytestmark = pytest.mark.slow


def _signed_items(count, bad=()):
    items = []
    for i in range(count):
        seed = bytes([i]) * 32
        msg = bytes([0xA0 ^ i]) * 32
        sig = ref.sign(seed, msg)
        if i in bad:
            sig = sig[:10] + bytes([sig[10] ^ 0xFF]) + sig[11:]
        items.append((ref.public_key(seed), msg, sig))
    return items


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_verify_matches_oracle():
    mesh = make_mesh(8)
    fn = sharded_verify(mesh)
    items = _signed_items(16, bad={3, 11})
    pubs, msgs, sigs, n = pad_batch(items, 16)
    out = np.asarray(fn(pubs, msgs, sigs))
    expect = [i not in {3, 11} for i in range(16)]
    assert out.tolist() == expect


def test_verify_many_sharded_serving_path():
    """The host serving API (used by the verifier service / asyncio
    runtime on multi-device hosts): same verdicts as the single-device
    verify_many, including mixed validity, odd batch sizes (padded to a
    mesh-divisible shape), and the empty batch."""
    from pbft_tpu.parallel import verify_many_sharded

    items = _signed_items(11, bad={2, 7})
    out = verify_many_sharded(items)
    assert out == [i not in {2, 7} for i in range(11)]
    assert verify_many_sharded([]) == []
    # Second call reuses the compiled mesh fn (no retrace): same verdicts.
    assert verify_many_sharded(items[:5]) == [i not in {2} for i in range(5)]


def test_verify_many_auto_selects_and_agrees(monkeypatch):
    """The serving-path selector: sharded on this 8-device mesh, and the
    single-device fallback (never reached naturally under conftest's
    virtual mesh) produces identical verdicts when forced."""
    from pbft_tpu.parallel import verifier as V

    items = _signed_items(9, bad={4})
    expect = [i != 4 for i in range(9)]
    assert V.verify_many_auto(items) == expect  # sharded branch
    monkeypatch.setattr(jax, "local_device_count", lambda: 1)
    assert V.verify_many_auto(items) == expect  # single-device fallback


def test_quorum_certify_counts_and_thresholds():
    mesh = make_mesh(8)
    R = 4
    certify = quorum_certify(mesh, R)
    # 16 signatures: rounds 0..3 get 4 each; corrupt one sig in round 1,
    # two in round 2. Pad rows -> round_id R.
    items = _signed_items(16, bad={5, 9, 10})
    pubs, msgs, sigs, n = pad_batch(items, 16)
    round_ids = np.arange(16) // 4
    thresholds = np.array([4, 4, 3, 3], np.int32)
    res = certify(pubs, msgs, sigs, round_ids, thresholds)
    assert np.asarray(res.counts).tolist() == [4, 3, 2, 4]
    assert np.asarray(res.certified).tolist() == [True, False, False, True]
    assert np.asarray(res.valid).sum() == 13


def test_quorum_certify_pad_slots_ignored():
    mesh = make_mesh(8)
    R = 2
    certify = quorum_certify(mesh, R)
    items = _signed_items(8)
    pubs, msgs, sigs, n = pad_batch(items, 16)  # 8 pad rows (valid pad sig)
    round_ids = np.concatenate([np.arange(8) // 4, np.full(8, R)])
    thresholds = np.array([3, 3], np.int32)
    res = certify(pubs, msgs, sigs, round_ids, thresholds)
    # Pad rows verify True but must not leak into any round's count.
    assert np.asarray(res.counts).tolist() == [4, 4]


def test_round_step_runs_and_is_deterministic():
    mesh = make_mesh(8)
    R = 4
    step = round_step(mesh, R)
    items = _signed_items(16, bad={2})
    pubs, msgs, sigs, n = pad_batch(items, 16)
    round_ids = np.arange(16) // 4
    thresholds = np.full(R, 3, np.int32)
    state = jnp.zeros(8, jnp.int32)
    s1, res1 = step(state, pubs, msgs, sigs, round_ids, thresholds)
    s2, res2 = step(state, pubs, msgs, sigs, round_ids, thresholds)
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    assert np.asarray(res1.certified).all()
    # State advanced (some certified rounds folded in).
    assert not np.array_equal(np.asarray(s1), np.zeros(8, np.int32))


def test_multihost_helpers_single_process():
    """The multi-host helpers degrade to single-process correctly (the
    same code path a one-host deployment runs)."""
    from pbft_tpu.parallel import (
        global_mesh,
        host_shard_to_global,
        initialize_distributed,
        partition_items,
    )

    initialize_distributed()  # no-op single process
    mesh = global_mesh()
    assert mesh.devices.size == 8
    local = np.arange(16 * 32, dtype=np.uint8).reshape(16, 32)
    arr = host_shard_to_global(mesh, local)
    assert arr.shape == (16, 32)
    assert np.array_equal(np.asarray(arr), local)
    items = list(range(10))
    assert partition_items(items, process_id=0, num=2) == [0, 2, 4, 6, 8]
    assert partition_items(items, process_id=1, num=2) == [1, 3, 5, 7, 9]
    assert partition_items(items) == items  # single process keeps all


def test_sharded_matches_unsharded():
    from pbft_tpu.crypto.batch import verify_batch

    mesh = make_mesh(8)
    fn = sharded_verify(mesh)
    items = _signed_items(8, bad={1, 6})
    pubs, msgs, sigs, n = pad_batch(items, 8)
    assert np.asarray(fn(pubs, msgs, sigs)).tolist() == np.asarray(
        verify_batch(pubs, msgs, sigs)
    ).tolist()


def test_persistent_engine_matches_oracle_and_native(tmp_path):
    """ISSUE 7 parity pin: the persistent service's AOT-compiled,
    donated-buffer engine must produce the SAME accept set as the Python
    oracle (and the native C++ pool when built) with the REAL Ed25519
    kernel — invalid items planted at window boundaries and pad slots
    exercised by an off-ladder batch size."""
    from pbft_tpu.net import ShardedVerifyEngine

    # 11 items over an (8, 16) ladder: chunk boundary at 8, pad slots
    # 11..15 in the second window; invalids straddle the boundary.
    bad = {0, 7, 8, 10}
    items = _signed_items(11, bad=bad)
    want = [i not in bad for i in range(11)]

    eng = ShardedVerifyEngine(shapes=(8, 16), export_dir=str(tmp_path))
    stats = eng.warm()
    assert stats["shapes"] == [8, 16]
    got = eng.verify(items)
    assert got == want  # vs the oracle-signed construction

    from pbft_tpu.crypto import ref

    assert [ref.verify(p, m, s) for p, m, s in items] == want
    try:
        from pbft_tpu import native

        native_ok = native.available()
    except Exception:
        native_ok = False
    if native_ok:
        assert native.verify_batch(items) == want

    # Warm restart over the serialized export: zero compiles, same bits.
    eng2 = ShardedVerifyEngine(shapes=(8, 16), export_dir=str(tmp_path))
    s2 = eng2.warm()
    assert s2["compiled"] == 0 and s2["aot_loaded"] == 2, s2
    assert eng2.verify(items) == want
