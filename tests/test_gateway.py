"""Gateway tier (ISSUE 10): exactly-once and reply-quorum fan-back through
the client-gateway in front of real daemon clusters.

The tier's contract: a client identity is a ``gw/`` routing token, not a
dialable address; requests multiplex over one gateway connection onto a
few persistent replica links; every replica's reply copy fans BACK over
those links and the client still counts its own f+1 signature-verified
quorum. Duplicate/retransmitted requests must hit the replicas'
per-(client, ts) reply caches — executed exactly once, same result bytes
every time.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from pbft_tpu.net.gateway import (
    GATEWAY_CLIENT_PREFIX,
    GatewayClient,
    next_token,
)
from pbft_tpu.net.launcher import LocalCluster

REPO = Path(__file__).resolve().parent.parent


def _start_gateway(cluster: LocalCluster, name: str = "gateway", extra=()):
    """One gateway subprocess in front of ``cluster``; returns
    (Popen, "host:port"). ``name`` keys the log file so several gateways
    can front one cluster; ``extra`` appends CLI flags (admission knobs)."""
    cfg = Path(cluster.tmpdir.name) / "network.json"
    log_path = Path(cluster.tmpdir.name) / f"{name}.log"
    log = open(log_path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "pbft_tpu.net.gateway", "--config", str(cfg),
         "--port", "0", *extra],
        stdout=log, stderr=log, close_fds=True,
        env=dict(os.environ, PYTHONPATH=str(REPO)),
    )
    deadline = time.monotonic() + 20
    while True:
        text = log_path.read_text(errors="replace") if log_path.exists() else ""
        m = re.search(r"gateway listening on (\d+)", text)
        if m:
            return proc, f"127.0.0.1:{m.group(1)}"
        if proc.poll() is not None or time.monotonic() > deadline:
            raise TimeoutError(f"gateway never listened:\n{text}")
        time.sleep(0.05)


def _stop(proc) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def _replica_metric(cluster: LocalCluster, rid: int, key: str):
    log = (Path(cluster.tmpdir.name) / f"replica-{rid}.log").read_text(
        errors="replace"
    )
    hits = re.findall(rf'"{key}":\s*(-?\d+)', log)
    return int(hits[-1]) if hits else None


def test_gateway_exactly_once_and_quorum_fan_back():
    """The acceptance pin: duplicates/retransmissions through the gateway
    execute once, the reply quorum is f+1 DISTINCT signature-verified
    replicas, and the reply route is the gateway link (no dial-back)."""
    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1, batch_max_items=8,
        batch_flush_us=2000,
    ) as cluster:
        proc, addr = _start_gateway(cluster)
        try:
            client = GatewayClient(cluster.config, addr)
            assert client.address.startswith(GATEWAY_CLIENT_PREFIX)
            req = client.request("gw-op-1")
            result = client.wait_result(req.timestamp, timeout=30)

            # Retransmit the SAME (token, ts) three times: the replicas'
            # reply caches must answer with the SAME result, and the
            # executed counter must not advance for any of them.
            time.sleep(1.2)  # let a metrics tick capture the first exec
            executed_before = _replica_metric(cluster, 0, "executed")
            for _ in range(3):
                # Clear BEFORE retransmitting: cached replies can land
                # within microseconds of the send, and clearing after
                # would wipe them (then nothing retransmits again inside
                # wait_result — a guaranteed 30 s timeout).
                with client._lock:
                    client.replies.clear()
                client.request("gw-op-1", timestamp=req.timestamp)
                assert client.wait_result(req.timestamp, timeout=30) == result
            time.sleep(1.5)
            executed_after = _replica_metric(cluster, 0, "executed")
            assert executed_before == executed_after, (
                f"duplicates executed: {executed_before} -> {executed_after}"
            )

            # The quorum really was distinct replicas (not one replica's
            # retransmissions): wait_result already requires f+1 distinct
            # ids with valid signatures; double-check the vote spread.
            with client._lock:
                voters = {
                    r.get("replica")
                    for r in client.replies
                    if r.get("timestamp") == req.timestamp
                }
            assert len(voters) >= cluster.config.f + 1
            client.close()
        finally:
            _stop(proc)
        # Replica-side accounting: the primary saw gateway-forwarded
        # requests on a gateway link.
        fwd = _replica_metric(cluster, 0, "gateway_forwarded")
        assert fwd is not None and fwd >= 1


def test_gateway_pipelined_many_and_replica_counters():
    """request_many through the gateway: pipelined submission over ONE
    socket completes every request, and the cluster's connection count
    stays O(n + gateways) — no per-client or per-reply sockets."""
    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1, batch_max_items=16,
        batch_flush_us=2000,
    ) as cluster:
        proc, addr = _start_gateway(cluster)
        try:
            clients = [GatewayClient(cluster.config, addr) for _ in range(4)]
            results = []
            for ci, c in enumerate(clients):
                results.append(
                    c.request_many(
                        [f"gw-{ci}-{k}" for k in range(12)], window=6,
                        timeout=45,
                    )
                )
            assert all(len(r) == 12 for r in results)
            for c in clients:
                c.close()
            time.sleep(1.5)
            # conns on replica 0: 3 dialed peer links + up to 3 accepted
            # peer links + 1 gateway link (+ slack for handshake churn) —
            # NOT 4 clients x anything.
            conns = _replica_metric(cluster, 0, "connections_open")
            assert conns is not None and conns <= 10, conns
        finally:
            _stop(proc)


def test_gateway_mixed_runtime_trust():
    """The asyncio replica honors role=gateway links the same way the C++
    daemon does: a mixed cluster serves a gateway client with replies
    fanning back from BOTH runtimes."""
    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1,
        impl=["cxx", "py", "cxx", "py"],
    ) as cluster:
        proc, addr = _start_gateway(cluster)
        try:
            client = GatewayClient(cluster.config, addr)
            req = client.request("mixed-gw")
            assert client.wait_result(req.timestamp, timeout=40)
            # Replies crossed back from at least one replica of EACH
            # runtime (0/2 are cxx, 1/3 are py). The quorum may be met by
            # the fastest f+1, so poll briefly for the slower runtime's
            # fan-back instead of asserting on the first snapshot.
            deadline = time.monotonic() + 10
            while True:
                with client._lock:
                    voters = {
                        r.get("replica")
                        for r in client.replies
                        if r.get("timestamp") == req.timestamp
                    }
                if voters & {0, 2} and voters & {1, 3}:
                    break
                assert time.monotonic() < deadline, voters
                time.sleep(0.1)
            client.close()
        finally:
            _stop(proc)


def test_gateway_rejects_non_gateway_identity():
    """A dialable client address through the gateway is dropped (it would
    reopen the per-client socket cost and an unauthenticated redirect
    channel); a gw/ token on the same connection still works."""
    with LocalCluster(n=4, verifier="cpu") as cluster:
        proc, addr = _start_gateway(cluster)
        try:
            host, _, port = addr.rpartition(":")
            s = socket.create_connection((host, int(port)), timeout=10)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            bad = {
                "type": "client-request",
                "operation": "evil",
                "timestamp": 1,
                "client": "127.0.0.1:9999",  # dialable: must be dropped
            }
            s.sendall(json.dumps(bad).encode() + b"\n")
            s.close()
            client = GatewayClient(cluster.config, addr)
            req = client.request("good")
            assert client.wait_result(req.timestamp, timeout=30)
            client.close()
        finally:
            _stop(proc)


def test_gateway_secure_cluster_refused():
    """A gateway link on a secure cluster is rejected by the replicas
    (no replica identity to authenticate) and by the ClientGateway
    constructor itself."""
    from pbft_tpu.consensus.config import make_local_cluster
    import dataclasses

    from pbft_tpu.net.gateway import ClientGateway

    config, _ = make_local_cluster(4, base_port=0)
    secure_cfg = dataclasses.replace(config, secure=True)
    with pytest.raises(ValueError):
        ClientGateway(secure_cfg)


def test_token_uniqueness():
    tokens = {next_token() for _ in range(256)}
    assert len(tokens) == 256
    assert all(t.startswith(GATEWAY_CLIENT_PREFIX) for t in tokens)


@pytest.mark.slow
def test_gateway_many_clients_sustained():
    """A few hundred concurrent identities through one gateway on an n=4
    cluster (the 10k shape, sized for CI): sustained traffic, no FD
    exhaustion, every request completes."""
    import asyncio

    sys.path.insert(0, str(REPO / "scripts"))
    import scale_curve

    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1, batch_max_items=64,
        batch_flush_us=2000,
    ) as cluster:
        proc, addr = _start_gateway(cluster)
        try:
            _, _, port = addr.rpartition(":")
            done, elapsed, lat = asyncio.run(
                scale_curve.run_load(
                    "127.0.0.1", [int(port)], clients=200, requests_each=3,
                    window=3, quorum=cluster.config.f + 1, deadline_s=240,
                )
            )
            assert done == 200 * 3, f"completed {done}/600"
        finally:
            _stop(proc)


# -- gateway HA + admission control (ISSUE 12) --------------------------------


def test_gateway_client_failover_exactly_once():
    """Kill the gateway a client is attached to MID-REQUEST: the client
    fails over to the second gateway under the SAME gw/ token, replays
    its in-flight lines, and completion stays 100% — with the replicas'
    per-(client, ts) exactly-once guard proving the replay executed
    nothing twice (the ISSUE 12 gateway-HA acceptance pin)."""
    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1, batch_max_items=8,
        batch_flush_us=2000,
    ) as cluster:
        proc_a, addr_a = _start_gateway(cluster, name="gateway-a")
        proc_b, addr_b = _start_gateway(cluster, name="gateway-b")
        procs = {addr_a: proc_a, addr_b: proc_b}
        client = None
        try:
            client = GatewayClient(cluster.config, [addr_a, addr_b])
            req1 = client.request("ha-op-1")
            result1 = client.wait_result(req1.timestamp, timeout=30)
            assert result1 == "awesome!"
            time.sleep(1.2)  # one metrics tick captures the execution
            executed_before = _replica_metric(cluster, 0, "executed")
            # Fire a request and kill the attached gateway before waiting:
            # the death lands mid-request, the failover replay (same
            # token, same ts) must complete it through the survivor.
            attached = [addr_a, addr_b][client._addr_idx]
            req2 = client.request("ha-op-2")
            _stop(procs[attached])
            result2 = client.wait_result(req2.timestamp, timeout=45)
            assert result2 == "awesome!"
            assert client.failovers >= 1
            # Exactly-once across the failover: explicitly retransmit
            # req2 (the request that rode the failover replay) through
            # the surviving gateway — the replicas' reply caches answer
            # with the SAME bytes and nothing re-executes. (req2 is the
            # client's LATEST request: PBFT's reply cache holds exactly
            # one reply per client, so only the latest ts can be
            # re-answered.)
            with client._lock:  # clear BEFORE the send (see above test)
                client.replies.clear()
            client.request("ha-op-2", timestamp=req2.timestamp)
            assert client.wait_result(req2.timestamp, timeout=30) == result2
            time.sleep(1.5)
            executed_after = _replica_metric(cluster, 0, "executed")
            # ha-op-2 executed once; neither the failover replay nor the
            # explicit retransmission executed anything more.
            assert executed_after == executed_before + 1, (
                f"replay re-executed: {executed_before} -> {executed_after}"
            )
        finally:
            if client is not None:
                client.close()
            for p in procs.values():
                _stop(p)


def test_gateway_admission_rejects_past_inflight_cap():
    """Admission control at the gateway (ISSUE 12): with --max-inflight 2
    and a cluster that never answers (nothing listening), the third
    fresh request gets an explicit overloaded line back — not silence."""
    import tempfile

    from pbft_tpu.consensus.config import make_local_cluster

    config, _seeds = make_local_cluster(4, base_port=1)  # ports 1-4: dead
    with tempfile.TemporaryDirectory(prefix="gwadm-") as tmp:
        cfg_path = Path(tmp) / "network.json"
        cfg_path.write_text(config.to_json())
        log_path = Path(tmp) / "gateway.log"
        log = open(log_path, "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "pbft_tpu.net.gateway", "--config",
             str(cfg_path), "--port", "0", "--max-inflight", "2"],
            stdout=log, stderr=log, close_fds=True,
            env=dict(os.environ, PYTHONPATH=str(REPO)),
        )
        try:
            deadline = time.monotonic() + 20
            port = None
            while port is None:
                text = (
                    log_path.read_text(errors="replace")
                    if log_path.exists()
                    else ""
                )
                m = re.search(r"gateway listening on (\d+)", text)
                if m:
                    port = int(m.group(1))
                elif proc.poll() is not None or time.monotonic() > deadline:
                    raise TimeoutError(f"gateway never listened:\n{text}")
                else:
                    time.sleep(0.05)
            token = next_token("adm")
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.settimeout(10)
            for ts in range(1, 6):  # 5 fresh requests, cap 2
                line = json.dumps({
                    "type": "client-request", "operation": f"op-{ts}",
                    "timestamp": ts, "client": token,
                }, separators=(",", ":")).encode() + b"\n"
                s.sendall(line)
            buf = b""
            overloaded = []
            deadline = time.monotonic() + 15
            while len(overloaded) < 3 and time.monotonic() < deadline:
                try:
                    chunk = s.recv(65536)
                except socket.timeout:
                    break
                if not chunk:
                    break
                buf += chunk
                overloaded = [
                    json.loads(ln)
                    for ln in buf.split(b"\n")
                    if ln.strip()
                    and json.loads(ln).get("type") == "overloaded"
                ]
            s.close()
            # Requests 3, 4, 5 were past the cap (1 and 2 hold the two
            # in-flight slots forever — the cluster is dead).
            assert len(overloaded) == 3, overloaded
            assert {o["timestamp"] for o in overloaded} == {3, 4, 5}
            assert all(o["client"] == token for o in overloaded)
        finally:
            _stop(proc)


@pytest.mark.parametrize("impl", ["cxx", "py"])
def test_replica_admission_inflight_cap_and_recovery(impl):
    """Admission control at the REPLICA (both runtimes, ISSUE 12): with
    admission_inflight=3 in network.json and a long batch-flush window, a
    burst of 10 fresh requests gets explicit overloaded replies past the
    cap — and the rejected requests still complete once the client
    retries after the backlog drains (liveness is never admission-gated,
    retransmissions always pass)."""
    with LocalCluster(
        n=4, verifier="cpu", metrics_every=1, impl=impl,
        batch_max_items=64, batch_flush_us=500000, admission_inflight=3,
    ) as cluster:
        proc, addr = _start_gateway(cluster)
        client = None
        try:
            client = GatewayClient(cluster.config, addr)
            reqs = [client.request(f"burst-{k}") for k in range(10)]
            # The primary's overloaded lines route back over the gateway.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                with client._lock:
                    rejected = [
                        r for r in client.replies
                        if r.get("type") == "overloaded"
                    ]
                if rejected:
                    break
                time.sleep(0.1)
            assert rejected, "no overloaded reply reached the client"
            assert all(r["timestamp"] > 3 for r in rejected)
            # The admitted prefix completes untouched.
            assert client.wait_result(reqs[0].timestamp, timeout=30) == (
                "awesome!"
            )
            # Rejected requests complete on retry as the backlog drains.
            done = {}
            deadline = time.monotonic() + 90
            while len(done) < 10 and time.monotonic() < deadline:
                for r in reqs:
                    if r.timestamp in done:
                        continue
                    try:
                        done[r.timestamp] = client.wait_result(
                            r.timestamp, timeout=2
                        )
                    except TimeoutError:
                        client.request(r.operation, timestamp=r.timestamp)
            assert len(done) == 10
            time.sleep(1.5)
            rej = _replica_metric(cluster, 0, "overload_rejections")
            assert rej is not None and rej >= 1
        finally:
            if client is not None:
                client.close()
            _stop(proc)
