"""Per-request latency waterfalls (ISSUE 9): the pure join logic over
synthetic events, the real-cluster join through consensus_timeline
--waterfall, and the verify_status introspection CLI."""

import json
import pathlib
import subprocess
import sys
import time

import pytest

from pbft_tpu import native
from pbft_tpu.utils import waterfall

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- the join, on synthetic events -------------------------------------------


def _synthetic_events():
    """One request through the whole pipeline with known segment times:
    client_queue 10ms, batch_wait 20ms, prepared 30ms, committed 40ms,
    execute 50ms, reply 60ms (e2e 210ms)."""
    send = 100.0
    events = [
        {"ts": send + 0.010, "ev": "request_rx", "replica": 0,
         "client": "c:1", "req_ts": 7},
        {"ts": send + 0.030, "ev": "batch_sealed", "replica": 0, "view": 0,
         "seq": 3, "batch": 2, "wait_s": 0.02, "reqs": [["c:1", 7], ["c:2", 4]]},
        {"ts": send + 0.150, "ev": "consensus_span", "replica": 0, "view": 0,
         "seq": 3, "request": send + 0.030, "pre_prepare": send + 0.030,
         "prepared": send + 0.060, "committed": send + 0.100,
         "executed": send + 0.150},
    ]
    client = [{"client": "c:1", "req_ts": 7, "send": send,
               "first_reply": send + 0.190, "quorum": send + 0.210}]
    return events, client


def test_build_waterfall_segments():
    events, client = _synthetic_events()
    wf = waterfall.build_waterfall(events, client)
    assert wf["requests"] == 1
    assert wf["mean_batch"] == 2.0
    seg = wf["segments_ms"]
    assert seg["client_queue"]["p50"] == pytest.approx(10.0, abs=0.01)
    assert seg["batch_wait"]["p50"] == pytest.approx(20.0, abs=0.01)
    assert seg["prepared"]["p50"] == pytest.approx(30.0, abs=0.01)
    assert seg["committed"]["p50"] == pytest.approx(40.0, abs=0.01)
    assert seg["execute"]["p50"] == pytest.approx(50.0, abs=0.01)
    assert seg["reply"]["p50"] == pytest.approx(60.0, abs=0.01)
    assert wf["e2e_ms"]["p50"] == pytest.approx(210.0, abs=0.01)
    # Render covers every segment row.
    text = waterfall.render(wf)
    for name in waterfall.SEGMENTS + ("e2e",):
        assert name in text


def test_build_waterfall_partial_evidence_degrades_gracefully():
    """A request with client stamps but no replica trace contributes
    nothing; one with only request_rx still yields client_queue."""
    events = [{"ts": 5.0, "ev": "request_rx", "replica": 0,
               "client": "c:9", "req_ts": 1}]
    client = [
        {"client": "c:9", "req_ts": 1, "send": 4.99, "quorum": 5.2},
        {"client": "ghost:0", "req_ts": 8, "send": 1.0},
    ]
    wf = waterfall.build_waterfall(events, client)
    assert wf["requests"] == 1
    assert wf["segments_ms"]["client_queue"]["count"] == 1
    assert wf["segments_ms"]["prepared"]["count"] == 0


# -- real cluster -> consensus_timeline --waterfall ---------------------------


@pytest.mark.skipif(not native.available(), reason="native core not built")
def test_waterfall_from_real_cluster_traces(tmp_path):
    """Drive a batching mixed-runtime cluster with traces on, write the
    client trace next to the replica traces, and require
    consensus_timeline --waterfall to join them: every segment populated,
    requests joined, mean batch surfaced."""
    from pbft_tpu.net import LocalCluster, PbftClient

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    with LocalCluster(
        n=4,
        verifier="cpu",
        impl=["cxx", "py", "cxx", "py"],
        trace_dir=str(trace_dir),
        batch_max_items=4,
        batch_flush_us=2000,
    ) as cluster:
        client = PbftClient(cluster.config)
        try:
            results = client.request_many(
                [f"op-{i}" for i in range(24)], window=8, timeout=30
            )
            assert results == ["awesome!"] * 24
        finally:
            client.write_trace(str(trace_dir / "client-0.jsonl"))
            client.close()
        time.sleep(0.3)  # let the last trace lines flush

    sys.path.insert(0, str(REPO / "scripts"))
    import consensus_timeline

    res = consensus_timeline.main([str(trace_dir), "--waterfall", "--json"])
    wf = res["waterfall"]
    assert wf["requests"] >= 20
    assert wf["mean_batch"] > 1.0  # the batching knobs actually batched
    seg = wf["segments_ms"]
    for name in ("client_queue", "batch_wait", "prepared", "committed",
                 "execute", "reply"):
        assert seg[name]["count"] > 0, f"segment {name} never measured"
        assert seg[name]["p99"] >= seg[name]["p50"] >= 0.0
    assert res.get("mean_batch") and res["mean_batch"] > 1.0


# -- verify_status CLI (satellite) -------------------------------------------


def test_verify_status_cli_against_live_service():
    from pbft_tpu.net import VerifierService

    svc = VerifierService(backend="cpu").start()
    try:
        out = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "verify_status.py"),
                svc.address,
            ],
            capture_output=True,
            text=True,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "state" in out.stdout
        js = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "verify_status.py"),
                svc.address,
                "--json",
            ],
            capture_output=True,
            text=True,
        )
        assert js.returncode == 0
        status = json.loads(js.stdout)
        assert "state" in status
    finally:
        svc.stop()


def test_verify_status_cli_unreachable_exits_1():
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "verify_status.py"),
            "127.0.0.1:1",  # nothing listens here
            "--timeout",
            "0.3",
        ],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 1
