"""Equivalence tests: JAX Ed25519 verifier vs the pure-Python RFC 8032 oracle.

This is SURVEY.md §4 item 3 — the crypto-equivalence leg of the test pyramid:
known-answer RFC 8032 vectors, random valid signatures, deliberately
corrupted signatures, malleated S, bad pubkeys, and batch padding.
"""

import secrets

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pbft_tpu.crypto import ref
from pbft_tpu.crypto import batch as B
from pbft_tpu.crypto import ed25519 as E
from pbft_tpu.crypto import field as F
from tests.test_crypto_ref import RFC8032_VECTORS

# Kernel-compile-heavy: slow tier (pytest -m slow).
pytestmark = pytest.mark.slow

# jit wrappers: eager-mode dispatch of the limb arithmetic is far too slow
# for tests; compile once per shape and reuse.
_jit_verify = jax.jit(E.verify_kernel)
_jit_compress = jax.jit(E.compress)
_jit_decompress = jax.jit(E.decompress)
_jit_add = jax.jit(E.point_add)


def as_u8(b: bytes):
    return np.frombuffer(b, np.uint8)


def jax_verify_one(pub: bytes, msg: bytes, sig: bytes) -> bool:
    return bool(_jit_verify(as_u8(pub), as_u8(msg), as_u8(sig)))


def test_point_roundtrip_and_add():
    # decompress(compress(.)) and additions agree with the oracle.
    seed, pub = ref.keygen(b"\x11" * 32)
    a = ref.point_decompress(pub)
    ok, pt = _jit_decompress(as_u8(pub))
    assert bool(ok)
    assert bytes(np.asarray(_jit_compress(pt))) == pub

    twice_oracle = ref.point_add(a, a)
    twice = _jit_add(pt, pt)
    assert bytes(np.asarray(_jit_compress(twice))) == ref.point_compress(twice_oracle)

    plus_base_oracle = ref.point_add(a, ref.BASE)
    plus_base = _jit_add(pt, E.base_point())
    assert (
        bytes(np.asarray(_jit_compress(plus_base)))
        == ref.point_compress(plus_base_oracle)
    )


def test_identity_handling():
    ident = E.identity()
    assert bytes(np.asarray(_jit_compress(ident))) == ref.point_compress((0, 1))
    pt = E.base_point()
    moved = _jit_add(pt, ident)
    assert bytes(np.asarray(_jit_compress(moved))) == ref.point_compress(ref.BASE)


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS[:2])
def test_rfc8032_vectors_32byte_variants(seed, pub, msg, sig):
    # The TPU pipeline always signs 32-byte digests; re-sign the vector
    # seeds over 32-byte messages and check JAX vs oracle.
    seed = bytes.fromhex(seed)
    pub = ref.public_key(seed)
    digest = secrets.token_bytes(32)
    good = ref.sign(seed, digest)
    assert ref.verify(pub, digest, good)
    assert jax_verify_one(pub, digest, good)
    bad = bytes([good[0] ^ 1]) + good[1:]
    assert not jax_verify_one(pub, digest, bad)


def test_random_equivalence():
    rng_cases = []
    for _ in range(4):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        sig = ref.sign(seed, msg)
        rng_cases.append((pub, msg, sig, True))
        # corrupted sig R
        rng_cases.append((pub, msg, bytes([sig[0] ^ 0x40]) + sig[1:], False))
        # corrupted msg
        rng_cases.append((pub, secrets.token_bytes(32), sig, False))
    for pub, msg, sig, want in rng_cases:
        assert ref.verify(pub, msg, sig) == want
        assert jax_verify_one(pub, msg, sig) == want


def test_malleated_s_rejected():
    seed, pub = ref.keygen()
    msg = secrets.token_bytes(32)
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + int.to_bytes(s + ref.L, 32, "little")
    assert not jax_verify_one(pub, msg, mall)
    assert not ref.verify(pub, msg, mall)


def test_bad_pubkeys_rejected():
    msg = secrets.token_bytes(32)
    sig = bytes(64)
    # non-canonical y (y = p), and an off-curve y
    noncanon = int.to_bytes(F.P, 32, "little")
    assert not jax_verify_one(noncanon, msg, sig)
    off_curve = None
    k = 0
    while off_curve is None:
        cand = int.to_bytes(2 + k, 32, "little")
        if ref.point_decompress(cand) is None:
            off_curve = cand
        k += 1
    assert not jax_verify_one(off_curve, msg, sig)


def test_batch_mixed_validity():
    items = []
    want = []
    for i in range(5):
        seed, pub = ref.keygen()
        msg = secrets.token_bytes(32)
        sig = ref.sign(seed, msg)
        if i % 2 == 1:  # corrupt odd entries
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append((pub, msg, sig))
        want.append(i % 2 == 0)
    got = B.verify_many(items, pad_to=8)
    assert got == want


def test_batch_empty_and_padding_slots():
    assert B.verify_many([]) == []
    pubs, msgs, sigs, n = B.pad_batch([], 4)
    out = np.asarray(B.verify_batch(pubs, msgs, sigs))
    assert n == 0 and out.all(), "padding triple must verify"
