"""chaos_bench (ISSUE 12): perf-under-faults on real clusters.

Tier-1 keeps a fast smoke (fault-free arm end to end: cluster + gateway
+ firehose + bench_compare-shaped row) plus the pure join/latency units;
the full fault schedules (crash+heal, mute primary, gateway kill) run
behind @slow.
"""

from __future__ import annotations

import pathlib
import sys

import pytest

from pbft_tpu import native

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "scripts"))

import chaos_bench  # noqa: E402

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not built"
)


def test_view_change_latency_join():
    """The cross-replica convergence span: first view_timer_fired opens,
    first new_view_installed closes; interleaved fires (other replicas'
    timers) do not reopen or double-count."""
    events = [
        {"ts": 10.0, "ev": "view_timer_fired", "replica": 1},
        {"ts": 10.1, "ev": "view_timer_fired", "replica": 2},
        {"ts": 10.5, "ev": "new_view_installed", "replica": 1},
        {"ts": 10.6, "ev": "new_view_installed", "replica": 2},  # no span open
        {"ts": 20.0, "ev": "view_timer_fired", "replica": 3},
        {"ts": 20.25, "ev": "new_view_installed", "replica": 3},
        {"ts": 30.0, "ev": "view_timer_fired", "replica": 1},  # never resolves
        {"ts": 31.0, "ev": "verify_batch", "replica": 1},  # ignored
    ]
    lat = chaos_bench.view_change_latencies_ms(events)
    assert lat == [pytest.approx(500.0), pytest.approx(250.0)]
    assert chaos_bench.view_change_latencies_ms([]) == []


def test_completion_bars_cover_every_arm():
    assert set(chaos_bench.COMPLETION_BAR) == set(chaos_bench.ARMS)
    assert chaos_bench.COMPLETION_BAR["crash-backup"] == 100.0
    assert chaos_bench.COMPLETION_BAR["gateway-kill"] == 100.0


def _run(arm, **kw):
    args = dict(
        n=4, clients=4, requests_each=15, window=8, batch=32,
        batch_flush_us=2000, impl="cxx", gateways=1, vc_timeout_ms=500,
        admission_inflight=0, admission_backlog=0, fault_at_s=0.5,
        heal_at_s=1.5, deadline_s=150.0, seed=7, blackbox_dir=None,
    )
    args.update(kw)
    return chaos_bench.run_arm_traced(
        arm, args["n"], args["clients"], args["requests_each"],
        args["window"], args["batch"], args["batch_flush_us"],
        args["impl"], args["gateways"], args["vc_timeout_ms"],
        args["admission_inflight"], args["admission_backlog"],
        args["fault_at_s"], args["heal_at_s"], args["deadline_s"],
        args["seed"], args["blackbox_dir"],
    )


def test_chaos_bench_smoke_fault_free():
    """Tier-1 smoke: the fault-free arm end to end — a real cluster, a
    real gateway, the failover-capable load driver, and a
    bench_compare-compatible row with the ISSUE 12 fields."""
    row = _run("fault-free")
    assert row["ok"] and row["completed_pct"] == 100.0
    assert row["requests"] == 4 * 15
    for field in (
        "requests_per_sec", "rounds_per_sec", "reply_p50_ms",
        "reply_p99_ms", "view_changes_started", "overload_rejections",
        "gateway_failovers", "client_failovers", "vc_latency_ms",
    ):
        assert field in row, field
    assert row["view_changes_started"] == 0  # fault-free: no storm
    # bench_compare accepts the row (shape contract with scale_curve).
    import json
    import tempfile

    import bench_compare

    with tempfile.TemporaryDirectory() as tmp:
        p = pathlib.Path(tmp)
        (p / "new.jsonl").write_text(json.dumps(row) + "\n")
        (p / "old.jsonl").write_text(json.dumps(row) + "\n")
        assert (
            bench_compare.main(
                [str(p / "old.jsonl"), str(p / "new.jsonl")]
            )
            == 0
        )


@pytest.mark.slow
def test_chaos_bench_full_schedules():
    """The full fault schedules: crash-a-backup + heal completes 100%
    with a measured recovery; the mute ("stuttering") primary converges
    with BOUNDED view changes and a reported latency distribution; the
    gateway kill keeps completion at 100% through client failovers."""
    # Loads sized to OUTLAST the fault offsets (~1.4k req/s on this box:
    # 8 x 400 ~= 2.3 s of sustained fire vs a 0.5 s fault) so the fault
    # genuinely lands mid-run.
    crash = _run(
        "crash-backup", clients=8, requests_each=400, fault_at_s=0.5,
        heal_at_s=1.2,
    )
    assert crash["ok"] and crash["completed_pct"] == 100.0
    assert crash["killed_replica"] == 3

    storm = _run("stutter-primary", clients=8, requests_each=40)
    assert storm["ok"]
    assert storm["view_changes_started"] >= 1
    # Bounded: backoff + retransmission + forwarded-request re-aim —
    # never an unbounded escalation storm (generous bound; each of 3
    # honest replicas suspects once or twice).
    assert storm["view_changes_started"] <= 24
    assert storm["vc_latency_ms"]["count"] >= 1

    kill = _run(
        "gateway-kill", clients=8, requests_each=400, gateways=2,
        fault_at_s=0.5,
    )
    assert kill["ok"] and kill["completed_pct"] == 100.0
    assert kill["client_failovers"] >= 1
