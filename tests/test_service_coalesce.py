"""Cross-connection coalescing in the VerifierService: concurrent batch
submissions from separate connections must merge into fewer backend calls
(one XLA launch per window on TPU) with per-request verdict slices intact."""

import socket
import threading
import time

from pbft_tpu.net import VerifierService


def _send_batch(addr: str, items):
    host, port = addr.rsplit(":", 1)
    payload = b"".join(p + m + s for p, m, s in items)
    with socket.create_connection((host, int(port)), timeout=10) as sock:
        sock.sendall(len(items).to_bytes(4, "big") + payload)
        out = b""
        while len(out) < len(items):
            chunk = sock.recv(len(items) - len(out))
            assert chunk
            out += chunk
    return [bool(b) for b in out]


def _item(tag: int, valid: bool):
    # The fake backend below deems an item valid iff sig[0] == pub[0];
    # tag makes every item distinguishable so slicing bugs can't hide.
    pub = bytes([tag]) * 32
    msg = bytes([tag ^ 0xFF]) * 32
    sig = (bytes([tag]) if valid else bytes([tag ^ 1])) + bytes(63)
    return pub, msg, sig


def test_concurrent_requests_coalesce_into_fewer_launches():
    calls = []
    gate = threading.Event()

    def slow_backend(items):
        calls.append(len(items))
        if len(calls) == 1:
            gate.wait(10)  # hold the first launch so others queue behind it
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=slow_backend).start()
    try:
        results = {}

        def client(cid: int):
            items = [_item(cid, True), _item(cid, cid % 2 == 0)]
            results[cid] = _send_batch(svc.address, items)

        threads = [threading.Thread(target=client, args=(c,)) for c in range(1, 5)]
        threads[0].start()
        while not calls:  # first request is inside the backend now
            time.sleep(0.01)
        for t in threads[1:]:
            t.start()
        # Give the three remaining requests time to queue, then release.
        deadline = time.monotonic() + 5
        while svc.requests < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(timeout=10)

        assert svc.requests == 4
        # 1 held launch + 1 merged launch for the 3 queued requests.
        assert svc.batches < 4, f"no coalescing happened: {calls}"
        assert sum(calls) == 8 and svc.items == 8
        for cid in range(1, 5):
            assert results[cid] == [True, cid % 2 == 0], (cid, results[cid])
    finally:
        gate.set()
        svc.stop()


def test_uncoalesced_mode_still_works():
    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend, coalesce=False).start()
    try:
        out = _send_batch(svc.address, [_item(7, True), _item(9, False)])
        assert out == [True, False]
        assert svc.batches == svc.requests == 1
    finally:
        svc.stop()


def test_poison_batch_only_fails_its_own_connection(tmp_path):
    """A backend failure on a merged launch must not false-reject other
    clients' honest signatures: the window is retried per-request and only
    the poisoned connection errors out. The trace must stay honest too:
    the failed merge is verify_window_failed (NOT verify_batch, whose
    sizes the launch-cost model reads as items-per-launch) and the
    retries are traced as singleton launches."""
    import json

    gate = threading.Event()
    first = threading.Event()

    def backend(items):
        if not first.is_set():
            first.set()
            gate.wait(10)
            # fall through: the held first request itself verifies fine
        if any(p[0] == 66 for p, m, s in items):
            raise RuntimeError("poison")
        return [p[0] == s[0] for p, m, s in items]

    trace = tmp_path / "service.jsonl"
    svc = VerifierService(backend=backend, trace_path=str(trace)).start()
    try:
        results = {}

        def client(cid: int):
            try:
                results[cid] = _send_batch(svc.address, [_item(cid, True)])
            except (AssertionError, ConnectionError, OSError):
                results[cid] = "error"

        t1 = threading.Thread(target=client, args=(1,))
        t1.start()
        while not first.is_set():
            time.sleep(0.01)
        others = [threading.Thread(target=client, args=(c,)) for c in (65, 66, 67)]
        for t in others:
            t.start()
        deadline = time.monotonic() + 5
        while svc.requests < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        t1.join(timeout=10)
        for t in others:
            t.join(timeout=10)
        assert results[1] == [True]
        assert results[65] == [True]
        assert results[66] == "error"  # the poisoned one, and only it
        assert results[67] == [True]
    finally:
        gate.set()
        svc.stop()
    events = [json.loads(line) for line in trace.read_text().splitlines()]
    vb = [e for e in events if e["ev"] == "verify_batch"]
    failed = [e for e in events if e["ev"] == "verify_window_failed"]
    errored = [e for e in events if e["ev"] == "verify_batch_error"]
    assert len(failed) == 1 and failed[0]["size"] == 3, failed
    # 1 clean launch (the held first request) + 2 clean singleton
    # retries; the poisoned retry is verify_batch_error (it produced no
    # verdicts, so it must not enter the items-per-launch or rejected
    # sums trace_report computes over verify_batch events).
    assert sum(e["size"] for e in vb) == 3, vb
    assert sum(e["rejected"] for e in vb) == 0, vb
    assert len(errored) == 1 and errored[0]["size"] == 1, errored
    assert all(e["requests"] == 1 for e in vb if e["size"] == 1), vb


def test_wrong_length_verdicts_fail_loudly():
    """A backend returning the wrong number of verdicts must error the
    affected connections, never mis-slice across a merged window or
    desync the wire protocol (each response is exactly N bytes)."""

    def backend(items):
        return [True] * (len(items) - 1)  # one verdict short

    svc = VerifierService(backend=backend).start()
    try:
        try:
            out = _send_batch(svc.address, [_item(1, True), _item(2, True)])
            raised = False
        except (ConnectionError, OSError, AssertionError):
            raised = True
        assert raised, f"short verdicts accepted: {out}"
    finally:
        svc.stop()

    # Same contract without coalescing (the handler-thread direct path).
    svc2 = VerifierService(backend=backend, coalesce=False).start()
    try:
        try:
            out2 = _send_batch(svc2.address, [_item(3, True), _item(4, True)])
            raised2 = False
        except (ConnectionError, OSError, AssertionError):
            raised2 = True
        assert raised2, f"short verdicts accepted uncoalesced: {out2}"
    finally:
        svc2.stop()


def test_window_respects_pad_ladder_cap():
    """Merged windows never exceed MAX_WINDOW items (the top of the XLA
    pad ladder) — oversized merges would compile new shapes at runtime."""
    calls = []
    gate = threading.Event()

    def backend(items):
        calls.append(len(items))
        if len(calls) == 1:
            gate.wait(10)
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend).start()
    svc.MAX_WINDOW = 4  # instance override for the test
    try:
        threads = [
            threading.Thread(
                target=lambda c=c: _send_batch(
                    svc.address, [_item(c, True), _item(c, True)]
                )
            )
            for c in range(1, 8)
        ]
        threads[0].start()
        while not calls:
            time.sleep(0.01)
        for t in threads[1:]:
            t.start()
        deadline = time.monotonic() + 5
        while svc.requests < 7 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(timeout=10)
        assert all(size <= 4 for size in calls), calls
        assert sum(calls) == 14
    finally:
        gate.set()
        svc.stop()


def test_bounded_accumulation_merges_a_trickle():
    """flush_us holds the window open so requests arriving a few ms apart
    merge into ONE backend launch instead of one launch each — the f=1
    occupancy lever (BASELINE north star): the window trades bounded
    latency for items-per-launch."""
    calls = []

    def backend(items):
        calls.append(len(items))
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend, flush_us=1_500_000).start()
    try:
        results = {}

        def client(cid: int, delay: float):
            time.sleep(delay)
            results[cid] = _send_batch(svc.address, [_item(cid, True)])

        threads = [
            threading.Thread(target=client, args=(c, 0.05 * c))
            for c in range(1, 4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert svc.requests == 3
        # An instant backend would have dispatched each trickle item alone
        # without the accumulation window.
        assert svc.batches == 1, f"window did not hold: {calls}"
        assert calls == [3]
        for cid in range(1, 4):
            assert results[cid] == [True]
    finally:
        svc.stop()


def test_flush_items_short_circuits_the_deadline():
    """Hitting the item target flushes immediately — the deadline is a
    bound, not a tax on every window."""

    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    # Deadline absurdly long: only the item target can explain a flush.
    svc = VerifierService(
        backend=backend, flush_us=60_000_000, flush_items=4
    ).start()
    try:
        results = {}

        def client(cid: int):
            results[cid] = _send_batch(
                svc.address, [_item(cid, True), _item(cid, False)]
            )

        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(c,)) for c in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        elapsed = time.monotonic() - t0
        assert elapsed < 20, "flush_items target never fired"
        assert results[1] == [True, False] and results[2] == [True, False]
        assert svc.items == 4
    finally:
        svc.stop()


def test_service_trace_records_merged_windows(tmp_path):
    """The per-dispatch trace is the honest items-per-LAUNCH record for
    the launch-cost model (per-replica traces only see each daemon's
    share of a merged window)."""
    import json

    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    trace = tmp_path / "service.jsonl"
    svc = VerifierService(
        backend=backend, flush_us=1_000_000, trace_path=str(trace)
    ).start()
    try:
        threads = [
            threading.Thread(
                target=lambda c=c: _send_batch(
                    svc.address, [_item(c, True), _item(c, c % 2 == 0)]
                )
            )
            for c in (2, 3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
    finally:
        svc.stop()
    events = [json.loads(line) for line in trace.read_text().splitlines()]
    batches = [e for e in events if e["ev"] == "verify_batch"]
    assert batches, "no verify_batch events traced"
    assert sum(e["size"] for e in batches) == 4
    assert sum(e["requests"] for e in batches) == 2
    assert sum(e["rejected"] for e in batches) == 1
    assert all(e["secs"] >= 0 and e["replica"] == "service" for e in batches)



def test_overlapped_launches_hide_launch_latency():
    """inflight=2: window N+1 ships while N executes, so two slow
    launches overlap in wall time; the serial default cannot. Verdict
    slicing stays per-request in both modes."""

    def run(inflight: int):
        first_launch_started = threading.Event()
        spans = []  # (start, end) per backend call, appended at the end

        def slow_backend(items):
            start = time.monotonic()
            first_launch_started.set()
            time.sleep(0.35)  # stands in for launch RTT; releases the GIL
            spans.append((start, time.monotonic()))
            return [p[0] == s[0] for p, m, s in items]

        svc = VerifierService(backend=slow_backend, inflight=inflight).start()
        try:
            results = {}

            def client(cid: int):
                if cid == 2:
                    # Only submit once launch 1 is provably in flight, so
                    # the requests deterministically form TWO windows (a
                    # sleep-based stagger could coalesce on a loaded box).
                    assert first_launch_started.wait(10)
                results[cid] = _send_batch(svc.address, [_item(cid, True)])

            threads = [
                threading.Thread(target=client, args=(c,)) for c in (1, 2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert results[1] == [True] and results[2] == [True]
            assert svc.batches == 2, svc.batches
            assert len(spans) == 2, spans
            return sorted(spans)
        finally:
            svc.stop()

    # Load-immune assertion: compare launch SPANS, not wall-clock totals
    # (the box's shared core can stall either run arbitrarily). Serial
    # mode must not start launch 2 before launch 1 returned; overlapped
    # mode must.
    serial = run(1)
    assert serial[1][0] >= serial[0][1], f"serial launches overlapped: {serial}"
    overlapped = run(2)
    assert overlapped[1][0] < overlapped[0][1], (
        f"overlapped launches serialized: {overlapped}"
    )
