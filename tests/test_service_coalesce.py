"""Cross-connection coalescing in the VerifierService: concurrent batch
submissions from separate connections must merge into fewer backend calls
(one XLA launch per window on TPU) with per-request verdict slices intact.

Plus the persistent-service lifecycle (ISSUE 7): readiness handshake,
warming -> ready transitions, the ServiceVerifier client's native-pool
fallback when the service is warming / killed mid-stream, and the
warm-restart path that reloads serialized executables instead of
compiling."""

import socket
import threading
import time

from pbft_tpu.net import (
    ServiceVerifier,
    ShardedVerifyEngine,
    VerifierService,
    VerifyServiceDaemon,
    probe_status,
    probe_status_json,
)
from pbft_tpu.net.service import (
    STATE_CPU_ONLY,
    STATE_READY,
    STATE_WARMING,
)


def _send_batch(addr: str, items):
    host, port = addr.rsplit(":", 1)
    payload = b"".join(p + m + s for p, m, s in items)
    with socket.create_connection((host, int(port)), timeout=10) as sock:
        sock.sendall(len(items).to_bytes(4, "big") + payload)
        out = b""
        while len(out) < len(items):
            chunk = sock.recv(len(items) - len(out))
            assert chunk
            out += chunk
    return [bool(b) for b in out]


def _item(tag: int, valid: bool):
    # The fake backend below deems an item valid iff sig[0] == pub[0];
    # tag makes every item distinguishable so slicing bugs can't hide.
    pub = bytes([tag]) * 32
    msg = bytes([tag ^ 0xFF]) * 32
    sig = (bytes([tag]) if valid else bytes([tag ^ 1])) + bytes(63)
    return pub, msg, sig


def test_concurrent_requests_coalesce_into_fewer_launches():
    calls = []
    gate = threading.Event()

    def slow_backend(items):
        calls.append(len(items))
        if len(calls) == 1:
            gate.wait(10)  # hold the first launch so others queue behind it
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=slow_backend).start()
    try:
        results = {}

        def client(cid: int):
            items = [_item(cid, True), _item(cid, cid % 2 == 0)]
            results[cid] = _send_batch(svc.address, items)

        threads = [threading.Thread(target=client, args=(c,)) for c in range(1, 5)]
        threads[0].start()
        while not calls:  # first request is inside the backend now
            time.sleep(0.01)
        for t in threads[1:]:
            t.start()
        # Give the three remaining requests time to queue, then release.
        deadline = time.monotonic() + 5
        while svc.requests < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(timeout=10)

        assert svc.requests == 4
        # 1 held launch + 1 merged launch for the 3 queued requests.
        assert svc.batches < 4, f"no coalescing happened: {calls}"
        assert sum(calls) == 8 and svc.items == 8
        for cid in range(1, 5):
            assert results[cid] == [True, cid % 2 == 0], (cid, results[cid])
    finally:
        gate.set()
        svc.stop()


def test_uncoalesced_mode_still_works():
    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend, coalesce=False).start()
    try:
        out = _send_batch(svc.address, [_item(7, True), _item(9, False)])
        assert out == [True, False]
        assert svc.batches == svc.requests == 1
    finally:
        svc.stop()


def test_poison_batch_only_fails_its_own_connection(tmp_path):
    """A backend failure on a merged launch must not false-reject other
    clients' honest signatures: the window is retried per-request and only
    the poisoned connection errors out. The trace must stay honest too:
    the failed merge is verify_window_failed (NOT verify_batch, whose
    sizes the launch-cost model reads as items-per-launch) and the
    retries are traced as singleton launches."""
    import json

    gate = threading.Event()
    first = threading.Event()

    def backend(items):
        if not first.is_set():
            first.set()
            gate.wait(10)
            # fall through: the held first request itself verifies fine
        if any(p[0] == 66 for p, m, s in items):
            raise RuntimeError("poison")
        return [p[0] == s[0] for p, m, s in items]

    trace = tmp_path / "service.jsonl"
    svc = VerifierService(backend=backend, trace_path=str(trace)).start()
    try:
        results = {}

        def client(cid: int):
            try:
                results[cid] = _send_batch(svc.address, [_item(cid, True)])
            except (AssertionError, ConnectionError, OSError):
                results[cid] = "error"

        t1 = threading.Thread(target=client, args=(1,))
        t1.start()
        while not first.is_set():
            time.sleep(0.01)
        others = [threading.Thread(target=client, args=(c,)) for c in (65, 66, 67)]
        for t in others:
            t.start()
        deadline = time.monotonic() + 5
        while svc.requests < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        t1.join(timeout=10)
        for t in others:
            t.join(timeout=10)
        assert results[1] == [True]
        assert results[65] == [True]
        assert results[66] == "error"  # the poisoned one, and only it
        assert results[67] == [True]
    finally:
        gate.set()
        svc.stop()
    events = [json.loads(line) for line in trace.read_text().splitlines()]
    vb = [e for e in events if e["ev"] == "verify_batch"]
    failed = [e for e in events if e["ev"] == "verify_window_failed"]
    errored = [e for e in events if e["ev"] == "verify_batch_error"]
    assert len(failed) == 1 and failed[0]["size"] == 3, failed
    # 1 clean launch (the held first request) + 2 clean singleton
    # retries; the poisoned retry is verify_batch_error (it produced no
    # verdicts, so it must not enter the items-per-launch or rejected
    # sums trace_report computes over verify_batch events).
    assert sum(e["size"] for e in vb) == 3, vb
    assert sum(e["rejected"] for e in vb) == 0, vb
    assert len(errored) == 1 and errored[0]["size"] == 1, errored
    assert all(e["requests"] == 1 for e in vb if e["size"] == 1), vb


def test_wrong_length_verdicts_fail_loudly():
    """A backend returning the wrong number of verdicts must error the
    affected connections, never mis-slice across a merged window or
    desync the wire protocol (each response is exactly N bytes)."""

    def backend(items):
        return [True] * (len(items) - 1)  # one verdict short

    svc = VerifierService(backend=backend).start()
    try:
        try:
            out = _send_batch(svc.address, [_item(1, True), _item(2, True)])
            raised = False
        except (ConnectionError, OSError, AssertionError):
            raised = True
        assert raised, f"short verdicts accepted: {out}"
    finally:
        svc.stop()

    # Same contract without coalescing (the handler-thread direct path).
    svc2 = VerifierService(backend=backend, coalesce=False).start()
    try:
        try:
            out2 = _send_batch(svc2.address, [_item(3, True), _item(4, True)])
            raised2 = False
        except (ConnectionError, OSError, AssertionError):
            raised2 = True
        assert raised2, f"short verdicts accepted uncoalesced: {out2}"
    finally:
        svc2.stop()


def test_window_respects_pad_ladder_cap():
    """Merged windows never exceed MAX_WINDOW items (the top of the XLA
    pad ladder) — oversized merges would compile new shapes at runtime."""
    calls = []
    gate = threading.Event()

    def backend(items):
        calls.append(len(items))
        if len(calls) == 1:
            gate.wait(10)
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend).start()
    svc.MAX_WINDOW = 4  # instance override for the test
    try:
        threads = [
            threading.Thread(
                target=lambda c=c: _send_batch(
                    svc.address, [_item(c, True), _item(c, True)]
                )
            )
            for c in range(1, 8)
        ]
        threads[0].start()
        while not calls:
            time.sleep(0.01)
        for t in threads[1:]:
            t.start()
        deadline = time.monotonic() + 5
        while svc.requests < 7 and time.monotonic() < deadline:
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(timeout=10)
        assert all(size <= 4 for size in calls), calls
        assert sum(calls) == 14
    finally:
        gate.set()
        svc.stop()


def test_bounded_accumulation_merges_a_trickle():
    """flush_us holds the window open so requests arriving a few ms apart
    merge into ONE backend launch instead of one launch each — the f=1
    occupancy lever (BASELINE north star): the window trades bounded
    latency for items-per-launch."""
    calls = []

    def backend(items):
        calls.append(len(items))
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend, flush_us=1_500_000).start()
    try:
        results = {}

        def client(cid: int, delay: float):
            time.sleep(delay)
            results[cid] = _send_batch(svc.address, [_item(cid, True)])

        threads = [
            threading.Thread(target=client, args=(c, 0.05 * c))
            for c in range(1, 4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert svc.requests == 3
        # An instant backend would have dispatched each trickle item alone
        # without the accumulation window.
        assert svc.batches == 1, f"window did not hold: {calls}"
        assert calls == [3]
        for cid in range(1, 4):
            assert results[cid] == [True]
    finally:
        svc.stop()


def test_flush_items_short_circuits_the_deadline():
    """Hitting the item target flushes immediately — the deadline is a
    bound, not a tax on every window."""

    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    # Deadline absurdly long: only the item target can explain a flush.
    svc = VerifierService(
        backend=backend, flush_us=60_000_000, flush_items=4
    ).start()
    try:
        results = {}

        def client(cid: int):
            results[cid] = _send_batch(
                svc.address, [_item(cid, True), _item(cid, False)]
            )

        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(c,)) for c in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        elapsed = time.monotonic() - t0
        assert elapsed < 20, "flush_items target never fired"
        assert results[1] == [True, False] and results[2] == [True, False]
        assert svc.items == 4
    finally:
        svc.stop()


def test_service_trace_records_merged_windows(tmp_path):
    """The per-dispatch trace is the honest items-per-LAUNCH record for
    the launch-cost model (per-replica traces only see each daemon's
    share of a merged window)."""
    import json

    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    trace = tmp_path / "service.jsonl"
    svc = VerifierService(
        backend=backend, flush_us=1_000_000, trace_path=str(trace)
    ).start()
    try:
        threads = [
            threading.Thread(
                target=lambda c=c: _send_batch(
                    svc.address, [_item(c, True), _item(c, c % 2 == 0)]
                )
            )
            for c in (2, 3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
    finally:
        svc.stop()
    events = [json.loads(line) for line in trace.read_text().splitlines()]
    batches = [e for e in events if e["ev"] == "verify_batch"]
    assert batches, "no verify_batch events traced"
    assert sum(e["size"] for e in batches) == 4
    assert sum(e["requests"] for e in batches) == 2
    assert sum(e["rejected"] for e in batches) == 1
    assert all(e["secs"] >= 0 and e["replica"] == "service" for e in batches)



def test_overlapped_launches_hide_launch_latency():
    """inflight=2: window N+1 ships while N executes, so two slow
    launches overlap in wall time; the serial default cannot. Verdict
    slicing stays per-request in both modes."""

    def run(inflight: int):
        first_launch_started = threading.Event()
        spans = []  # (start, end) per backend call, appended at the end

        def slow_backend(items):
            start = time.monotonic()
            first_launch_started.set()
            time.sleep(0.35)  # stands in for launch RTT; releases the GIL
            spans.append((start, time.monotonic()))
            return [p[0] == s[0] for p, m, s in items]

        svc = VerifierService(backend=slow_backend, inflight=inflight).start()
        try:
            results = {}

            def client(cid: int):
                if cid == 2:
                    # Only submit once launch 1 is provably in flight, so
                    # the requests deterministically form TWO windows (a
                    # sleep-based stagger could coalesce on a loaded box).
                    assert first_launch_started.wait(10)
                results[cid] = _send_batch(svc.address, [_item(cid, True)])

            threads = [
                threading.Thread(target=client, args=(c,)) for c in (1, 2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert results[1] == [True] and results[2] == [True]
            assert svc.batches == 2, svc.batches
            assert len(spans) == 2, spans
            return sorted(spans)
        finally:
            svc.stop()

    # Load-immune assertion: compare launch SPANS, not wall-clock totals
    # (the box's shared core can stall either run arbitrarily). Serial
    # mode must not start launch 2 before launch 1 returned; overlapped
    # mode must.
    serial = run(1)
    assert serial[1][0] >= serial[0][1], f"serial launches overlapped: {serial}"
    overlapped = run(2)
    assert overlapped[1][0] < overlapped[0][1], (
        f"overlapped launches serialized: {overlapped}"
    )


# -- persistent-service lifecycle (ISSUE 7) ----------------------------------


def _fake_kernel(pubs, msgs, sigs):
    """Cheap jit-able stand-in for the Ed25519 kernel (compiles in ms):
    valid iff sig[0] == pub[0] — same rule as the fake socket backends."""
    return pubs[:, 0] == sigs[:, 0]


def test_status_probe_reports_state_and_traffic_continues():
    """The readiness handshake: count-0 returns the 8-byte status, the
    JSON probe returns the rich status, and a batch on the SAME connection
    after a probe still verifies (probes must not desync the stream)."""

    def backend(items):
        return [p[0] == s[0] for p, m, s in items]

    svc = VerifierService(backend=backend).start()
    try:
        assert probe_status(svc.address) == (STATE_CPU_ONLY, 0, 0)
        js = probe_status_json(svc.address)
        assert js["state"] == "cpu-only" and js["backend"] == "custom"
        host, port = svc.address.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=10) as sock:
            sock.sendall((0).to_bytes(4, "big"))  # binary probe
            status = b""
            while len(status) < 8:
                status += sock.recv(8 - len(status))
            assert status[:2] == b"VS"
            p, m, s = _item(9, True)
            sock.sendall((1).to_bytes(4, "big") + p + m + s)
            assert sock.recv(1) == b"\x01"
    finally:
        svc.stop()
    # The jax-string backend (no daemon lifecycle) reports ready: it warms
    # lazily on first traffic, which is exactly the pre-daemon contract.
    svc2 = VerifierService(backend="jax").start()
    try:
        assert probe_status(svc2.address) == (STATE_READY, 0, 0)
    finally:
        svc2.stop()


class _StubEngine:
    """Engine double with a gated warmup and a distinguishable verdict."""

    def __init__(self, gate):
        self.gate = gate
        self.device_count = 5
        self.stats = {}
        self._warmed = ()

    @property
    def warmed_sizes(self):
        return self._warmed

    def warm(self):
        assert self.gate.wait(10)
        self._warmed = (16, 64)
        self.stats = {"cold_compile_s": 0.5, "warm_load_s": 0.0}
        return self.stats

    def verify(self, items):
        return [True] * len(items)  # accept-all: provably not the fallback


def test_daemon_warming_serves_fallback_then_flips_ready():
    """While the accelerator warms, traffic is served by the fallback
    (never queued behind the warmup); once warm, the readiness handshake
    flips and the engine takes over."""
    gate = threading.Event()
    engine = _StubEngine(gate)
    daemon = VerifyServiceDaemon(
        backend="auto",
        engine=engine,
        fallback=lambda items: [False] * len(items),  # reject-all fallback
    )
    daemon.start()
    try:
        st = probe_status(daemon.address)
        assert st is not None and st[0] == STATE_WARMING
        # Warming: the reject-all fallback answers, the engine does not.
        sv = ServiceVerifier(
            daemon.address,
            fallback=lambda items: [None] * len(items),
            retry_s=0.05,
        )
        # ServiceVerifier consumed the handshake: warming -> its LOCAL
        # fallback (the replica-side contract), not the daemon's.
        assert sv.verify_batch([_item(1, True)]) == [None]
        assert sv.used_fallback == 1
        # A pre-handshake client shipping anyway gets the daemon fallback.
        assert _send_batch(daemon.address, [_item(2, True)]) == [False]
        gate.set()
        deadline = time.monotonic() + 10
        while daemon.state != STATE_READY and time.monotonic() < deadline:
            time.sleep(0.02)
        assert probe_status(daemon.address) == (STATE_READY, 5, 2)
        # The client's periodic re-probe flips it onto the service.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sv.verify_batch([_item(3, False)]) == [True]:
                break  # accept-all engine answered
            time.sleep(0.05)
        else:
            raise AssertionError("client never flipped onto the ready engine")
        js = probe_status_json(daemon.address)
        assert js["state"] == "ready" and js["devices"] == 5
        assert js["warm_stats"]["cold_compile_s"] == 0.5
    finally:
        gate.set()
        daemon.stop()


def test_service_verifier_falls_back_when_killed_mid_stream():
    """The liveness contract at the client: a service that dies (or
    wedges) with a batch in flight costs ONE bounded timeout, the batch
    completes on the local fallback, and a later healthy service is
    picked back up — the verify loop never stalls."""
    gate = threading.Event()
    released = threading.Event()

    def backend(items):
        if not released.is_set():
            gate.wait(30)
        return [p[0] == s[0] for p, m, s in items]

    from pbft_tpu.consensus.replica import host_batch_verify

    svc = VerifierService(backend=backend).start()
    sv = ServiceVerifier(
        svc.address, fallback=host_batch_verify, io_timeout=1.0, retry_s=0.05
    )
    try:
        # In flight against the wedged backend -> io timeout -> fallback.
        # host_batch_verify rejects the garbage triples (real crypto).
        t0 = time.monotonic()
        out = sv.verify_batch([_item(1, True), _item(2, False)])
        elapsed = time.monotonic() - t0
        assert out == [False, False]  # fallback's REAL accept set
        assert sv.used_fallback == 1
        assert elapsed < 10, f"fallback stalled {elapsed:.1f}s"
        # Service recovers; the client reconnects and uses it again.
        released.set()
        gate.set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sv.verify_batch([_item(3, True)]) == [True]:
                break  # fake backend accepted -> the service answered
            time.sleep(0.05)
        else:
            raise AssertionError("client never reconnected to the service")
    finally:
        gate.set()
        released.set()
        sv.close()
        svc.stop()
    # Fully dead service: connect refused within the short deadline.
    t0 = time.monotonic()
    assert sv.verify_batch([_item(4, True)]) == [False]
    assert time.monotonic() - t0 < 5


def test_cluster_falls_back_when_service_killed_mid_stream(tmp_path):
    """The satellite contract end to end: a MIXED C++/asyncio cluster
    dials a real verifyd subprocess; SIGKILL it mid-run; replicas must
    keep committing via their native pools with no liveness stall."""
    import os
    import signal
    import subprocess
    import sys

    import pytest

    from pbft_tpu import native

    if not native.available():  # pragma: no cover - unbuilt container
        pytest.skip("native core not built")
    from pbft_tpu.net import LocalCluster, PbftClient
    from pbft_tpu.net.launcher import free_ports

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = free_ports(1)[0]
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.join(repo, "scripts", "verifyd.py"),
            "--backend",
            "native",
            "--port",
            str(port),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo),
    )
    target = f"127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 30
        while probe_status(target) is None:
            assert time.monotonic() < deadline, "verifyd never listened"
            assert proc.poll() is None, "verifyd died at startup"
            time.sleep(0.1)
        with LocalCluster(
            n=4, verifier=target, impl=["cxx", "py", "cxx", "py"]
        ) as cluster:
            client = PbftClient(cluster.config)
            try:
                req = client.request("with-service")
                assert client.wait_result(req.timestamp, timeout=20) == "awesome!"
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)
                # No stall: every post-kill request commits on the
                # native-pool fallback well inside the timeout.
                for i in range(3):
                    req = client.request(f"after-kill-{i}")
                    assert (
                        client.wait_result(req.timestamp, timeout=20)
                        == "awesome!"
                    ), cluster.logs()
            finally:
                client.close()
    finally:
        if proc.poll() is None:
            proc.kill()


def test_engine_parity_pad_slots_and_window_boundaries():
    """Sharded-engine verdicts must be bit-identical to the plain
    evaluation of the same rule across pad slots, shape boundaries, and
    the multi-window chunking path (the real-kernel equivalence against
    the oracle/native arms is pinned in test_parallel.py's slow tier)."""
    import tempfile

    eng = ShardedVerifyEngine(
        shapes=(8, 16),
        export_dir=tempfile.mkdtemp(),
        kernel=_fake_kernel,
        kernel_tag="fake-parity",
    )
    eng.warm()
    assert eng.device_count >= 1
    # 11 items -> padded to 16: pad slots must be sliced off, invalid
    # items at the boundary must stay invalid.
    items = [_item(i + 1, i % 3 != 0) for i in range(11)]
    want = [i % 3 != 0 for i in range(11)]
    assert eng.verify(items) == want
    # Exactly one shape (8) and one item past it (9 -> 16).
    assert eng.verify(items[:8]) == want[:8]
    assert eng.verify(items[:9]) == want[:9]
    # Oversized: chunks into top-of-ladder windows, order preserved.
    big = [_item((i % 23) + 1, i % 5 != 0) for i in range(40)]
    assert eng.verify(big) == [i % 5 != 0 for i in range(40)]


def test_warm_restart_reloads_exports_instead_of_compiling(tmp_path):
    """Warm-restart contract: the FIRST startup compiles (and exports
    serialized executables); a second startup over the same export dir
    loads every shape without tracing — zero cold-compile seconds — and
    verdicts survive the reload bit-for-bit."""
    export_dir = str(tmp_path / "executables")
    eng1 = ShardedVerifyEngine(
        shapes=(8, 16),
        export_dir=export_dir,
        kernel=_fake_kernel,
        kernel_tag="fake-restart",
    )
    s1 = eng1.warm()
    assert s1["compiled"] == 2 and s1["aot_loaded"] == 0
    items = [_item(i + 1, i % 2 == 0) for i in range(10)]
    want = eng1.verify(items)

    eng2 = ShardedVerifyEngine(
        shapes=(8, 16),
        export_dir=export_dir,
        kernel=_fake_kernel,
        kernel_tag="fake-restart",
    )
    s2 = eng2.warm()
    assert s2["aot_loaded"] == 2 and s2["compiled"] == 0, s2
    assert s2["cold_compile_s"] == 0.0  # cache-hit cheap, by construction
    assert eng2.verify(items) == want
    # A corrupt export must cost a recompile, never a crash.
    import os

    victim = sorted(os.listdir(export_dir))[0]
    with open(os.path.join(export_dir, victim), "wb") as fh:
        fh.write(b"not an executable")
    eng3 = ShardedVerifyEngine(
        shapes=(8, 16),
        export_dir=export_dir,
        kernel=_fake_kernel,
        kernel_tag="fake-restart",
    )
    s3 = eng3.warm()
    assert s3["aot_loaded"] == 1 and s3["compiled"] == 1
    assert eng3.verify(items) == want
