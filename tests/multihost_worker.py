"""Worker process for the 2-host jax.distributed test (test_multihost.py).

Each worker is one "host": 4 virtual CPU devices, gloo collectives over
loopback. Both hosts build the SAME deterministic signature batch, feed
their process-local shard (pbft_tpu.parallel.partition_items +
host_shard_to_global), and run the distributed quorum_certify — the psum
then crosses the process boundary, exercising the non-degenerate branches
of pbft_tpu/parallel/multihost.py for real.

Usage: multihost_worker.py <coordinator_port> <process_id> <num_processes>
Prints one JSON line with the globally-replicated verdicts.
"""

import json
import os
import sys


def main() -> None:
    port, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _cpu_backend import force_cpu

    force_cpu(n_devices=4)

    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np

    from pbft_tpu.crypto import ref
    from pbft_tpu.parallel import (
        global_mesh,
        host_shard_to_global,
        initialize_distributed,
        partition_items,
        quorum_certify,
    )

    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert jax.process_count() == nprocs, jax.process_count()

    mesh = global_mesh()
    assert mesh.devices.size == 4 * nprocs, mesh.devices.size

    # Deterministic batch, identical on every host: 16 signatures over
    # R=4 rounds; round 2's quorum is broken by two corrupted signatures.
    B, R = 16, 4
    items = []
    for i in range(B):
        seed = bytes([i + 1]) * 32
        msg = bytes([0xA5 ^ i]) * 32
        sig = ref.sign(seed, msg)
        if i in (2, 6):  # both in round 2 (i % R)
            sig = bytes(64)
        items.append((ref.public_key(seed), msg, sig))
    round_ids = np.arange(B, dtype=np.int32) % R
    thresholds = np.full(R, 3, np.int32)  # 4 sigs/round; round 2 has 2 valid

    rows = list(range(B))
    local_rows = partition_items(rows)
    pubs = np.stack([np.frombuffer(items[r][0], np.uint8) for r in local_rows])
    msgs = np.stack([np.frombuffer(items[r][1], np.uint8) for r in local_rows])
    sigs = np.stack([np.frombuffer(items[r][2], np.uint8) for r in local_rows])
    rids = round_ids[local_rows]

    certify = quorum_certify(mesh, R)
    args = (
        host_shard_to_global(mesh, pubs),
        host_shard_to_global(mesh, msgs),
        host_shard_to_global(mesh, sigs),
        host_shard_to_global(mesh, rids),
        thresholds,
    )
    # AOT-compile BEFORE the first collective executes, then meet at the
    # coordinator barrier: gloo's rendezvous has a ~30s deadline, and the
    # (multi-minute, cold) kernel compile would otherwise skew the two
    # processes' arrival far past it.
    #
    # STAGGERED: every process compiles the identical program with the
    # identical cache key, so process 0 compiles first (alone on this
    # box's single core) while the others wait at a barrier, then they
    # compile from the just-written persistent cache in seconds — one
    # compile total instead of N concurrent ones at 1/N speed each.
    def barrier(name: str) -> None:
        try:
            from jax._src import distributed

            distributed.global_state.client.wait_at_barrier(
                name, timeout_in_ms=900_000
            )
        except Exception as e:  # pragma: no cover - barrier API moved
            print(f"barrier {name} unavailable ({e}); unsynchronized",
                  file=sys.stderr)

    if pid != 0:
        barrier("pbft_p0_compiled")
    compiled = certify.lower(*args).compile()
    if pid == 0:
        barrier("pbft_p0_compiled")
    barrier("pbft_multihost_compiled")
    res = compiled(*args)
    counts = np.asarray(res.counts).tolist()
    certified = np.asarray(res.certified).tolist()
    print(
        json.dumps(
            {
                "process": pid,
                "devices": int(mesh.devices.size),
                "counts": counts,
                "certified": certified,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
