"""Multi-host distributed verification: 2 REAL jax processes over gloo.

Exercises the non-degenerate branches of pbft_tpu/parallel/multihost.py
(jax.distributed.initialize, make_array_from_process_local_data, the psum
crossing a process boundary) that the single-process tests cannot reach —
VERDICT r2 weak #5 / next-round item #8. Each process is one "host" with 4
virtual CPU devices; the 8-device mesh spans both, and both must read back
identical globally-replicated quorum verdicts.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow  # two cold kernel compiles in subprocesses

_WORKER = Path(__file__).parent / "multihost_worker.py"
_REPO = str(Path(__file__).resolve().parent.parent)
sys.path.insert(0, _REPO)
from pbft_tpu.utils.cache import host_keyed_cache_dir  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_quorum_certify_agrees(tmp_path):
    port = _free_port()
    env = dict(
        os.environ,
        PYTHONPATH=_REPO,
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=host_keyed_cache_dir(
            str(Path(_REPO) / ".jax_cache")
        ),
    )
    # stdout/stderr go to FILES, not pipes: a worker spewing more than a
    # pipe buffer of JAX warnings before the gloo barrier would otherwise
    # block on write while the sibling blocks at the barrier.
    procs, logs = [], []
    for pid in range(2):
        out = open(tmp_path / f"worker-{pid}.out", "w+")
        err = open(tmp_path / f"worker-{pid}.err", "w+")
        logs.append((out, err))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(_WORKER), str(port), str(pid), "2"],
                stdout=out,
                stderr=err,
                env=env,
                text=True,
            )
        )
    outs = []
    try:
        for p, (out, err) in zip(procs, logs):
            rc = p.wait(timeout=600)
            out.seek(0), err.seek(0)
            assert rc == 0, f"worker failed:\n{err.read()[-4000:]}"
            outs.append(json.loads(out.read().strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for out, err in logs:
            out.close(), err.close()

    for o in outs:
        assert o["devices"] == 8  # the mesh spans both processes
        # Rounds 0,1,3: 4 valid sigs each (>= threshold 3). Round 2: two
        # corrupted signatures leave 2 valid (< 3) -> not certified.
        assert o["counts"] == [4, 4, 2, 4]
        assert o["certified"] == [True, True, False, True]
    # Both hosts read back the SAME replicated verdicts.
    assert outs[0]["counts"] == outs[1]["counts"]
    assert outs[0]["certified"] == outs[1]["certified"]
