"""View change (PBFT §4.4) — the capability the reference stubbed entirely
(its View was a constant with no mutation API, reference src/view.rs:1-13).

Covers: primary failure -> new view elects primary 1 and in-flight requests
survive; O-computation with prepared certificates and null gaps; the f+1
join rule; Byzantine new-primary rejection (forged O); checkpoint-anchored
view changes."""

import dataclasses

from pbft_tpu.consensus.config import make_local_cluster
from pbft_tpu.consensus.messages import (
    Message,
    NewView,
    PrePrepare,
    ViewChange,
    null_request,
)
from pbft_tpu.consensus.replica import Broadcast, Replica
from pbft_tpu.consensus.simulation import Cluster


def test_view_change_after_primary_crash():
    c = Cluster(n=4)
    c.crash(0)
    # Backups' request timers fire (runtime responsibility) -> view change.
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    live = [c.replicas[i] for i in (1, 2, 3)]
    assert all(r.view == 1 for r in live)
    assert all(not r.in_view_change for r in live)
    assert c.primary_id == 1
    # The cluster commits client requests in the new view.
    req = c.submit("after view change")
    c.run(max_steps=500)
    assert c.committed_result(req.timestamp) == "awesome!"
    assert len({r.state_digest for r in live}) == 1


def test_in_flight_prepared_request_survives_view_change():
    """A request prepared (but not committed) in view 0 must be re-issued
    in view 1 and execute exactly once (PBFT §4.4 safety across views)."""
    c = Cluster(n=4)
    req = c.submit("survivor")
    # Deliver pre-prepares + prepares, but drop every COMMIT so the round
    # prepares without committing anywhere.
    c.outbound_mutator = lambda src, msg: (
        None if type(msg).__name__ == "Commit" else msg
    )
    c.run(max_steps=500)
    assert all(r.executed_upto == 0 for r in c.replicas)
    prepared_somewhere = [
        r.id for r in c.replicas if r._prepared((0, 1))
    ]
    assert prepared_somewhere, "at least one replica must have prepared"
    # Primary goes silent; commits flow again in the new view.
    c.outbound_mutator = None
    c.crash(0)
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    live = [c.replicas[i] for i in (1, 2, 3)]
    assert all(r.view == 1 for r in live)
    # The survivor executed in the new view, exactly once.
    assert c.committed_result(req.timestamp) == "awesome!"
    assert all(r.executed_upto >= 1 for r in live)
    assert all(r.counters["executed"] == 1 for r in live)
    assert len({r.state_digest for r in live}) == 1


def test_join_rule_f_plus_one():
    """A replica whose timer never fired joins once f+1 others moved
    (PBFT §4.5.2): only replicas 1 and 2 trigger; replica 3 follows."""
    c = Cluster(n=4)
    c.crash(0)
    c.trigger_view_change([1, 2])  # f+1 = 2 explicit triggers
    c.run(max_steps=500)
    live = [c.replicas[i] for i in (1, 2, 3)]
    assert all(r.view == 1 for r in live)
    assert c.replicas[3].counters["view_changes_started"] == 1


def test_new_view_with_forged_o_rejected():
    """A Byzantine new primary cannot smuggle an unprepared request into O:
    backups recompute O from V and drop a mismatched NEW-VIEW."""
    config, seeds = make_local_cluster(4)
    replicas = [Replica(config, i, seeds[i]) for i in range(4)]
    # Gather legitimate VIEW-CHANGE messages for view 1 from replicas 2, 3
    # plus primary-elect 1's own.
    vcs = []
    for rid in (1, 2, 3):
        acts = replicas[rid].start_view_change()
        for a in acts:
            if isinstance(a, Broadcast) and isinstance(a.msg, ViewChange):
                vcs.append(a.msg)
    assert len(vcs) == 3
    # Replica 1 (new primary) would send O = [] (nothing prepared). Forge a
    # NEW-VIEW that injects a pre-prepare for an invented request.
    evil_req = null_request()
    forged_pp = replicas[1]._sign(
        PrePrepare(view=1, seq=1, digest=evil_req.digest(), requests=(evil_req,), replica=1)
    )
    forged = replicas[1]._sign(
        NewView(
            new_view=1,
            view_changes=tuple(vc.to_dict() for vc in vcs),
            pre_prepares=(forged_pp.to_dict(),),
            replica=1,
        )
    )
    out = replicas[2]._on_new_view(forged)
    assert out == []
    assert replicas[2].in_view_change  # still waiting for a valid NEW-VIEW
    assert replicas[2].view == 0


def test_view_change_after_checkpoint_anchors_min_s():
    """View change above a stable checkpoint: min-s comes from C and the
    new view resumes after it."""
    c = Cluster(n=4)
    interval = c.config.checkpoint_interval
    for i in range(interval):
        c.submit(f"op-{i}")
        c.run(max_steps=500)
    assert all(r.low_mark == interval for r in c.replicas)
    c.crash(0)
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    live = [c.replicas[i] for i in (1, 2, 3)]
    assert all(r.view == 1 for r in live)
    req = c.submit("post-checkpoint-vc")
    c.run(max_steps=500)
    assert c.committed_result(req.timestamp) == "awesome!"
    assert all(r.executed_upto == interval + 1 for r in live)


def test_cascading_view_change_skips_failed_primary():
    """If the new primary is also dead, a second view change reaches
    replica 2 (view 2). Needs f=2 (n=7) so two crashed replicas stay
    within the fault budget."""
    c = Cluster(n=7)
    c.crash(0)
    c.crash(1)
    live_ids = [2, 3, 4, 5, 6]
    c.trigger_view_change(live_ids, new_view=1)
    c.run(max_steps=1000)
    # View 1's primary (replica 1) is dead: no NEW-VIEW arrives; timers
    # fire again for view 2.
    c.trigger_view_change(live_ids, new_view=2)
    c.run(max_steps=1000)
    live = [c.replicas[i] for i in live_ids]
    assert all(r.view == 2 for r in live)
    assert all(not r.in_view_change for r in live)
    assert c.primary_id == 2
    req = c.submit("two hops later")
    c.run(max_steps=1000)
    assert c.committed_result(req.timestamp) == "awesome!"


def test_watermark_jump_adopts_checkpoint_certificate():
    """Chaos-soak regression (ISSUE 5, seed 13): a replica whose watermark
    advances through a NEW-VIEW's min-s (not its own 2f+1 checkpoint
    collection) must ADOPT the certifying checkpoint proof. Before the
    fix it kept the stale pre-jump proof, so its next VIEW-CHANGE claimed
    last_stable_seq = min_s with a certificate for the OLD seq — honest
    validators reject that, and with two such replicas in an f=1 cluster
    no view change can ever gather 2f+1 valid votes again (a permanent
    liveness loss)."""
    c = Cluster(n=4)
    interval = c.config.checkpoint_interval
    # Replica 3 misses a whole checkpoint interval.
    c.crash(3)
    for i in range(interval):
        c.submit(f"op-{i}")
        c.run(max_steps=500)
    assert all(c.replicas[i].low_mark == interval for i in (0, 1, 2))
    assert c.replicas[3].low_mark == 0
    # It returns and joins a view change: min-s (= interval) reaches it
    # via the NEW-VIEW evidence, not via 2f+1 checkpoints of its own.
    c.uncrash(3)
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    r3 = c.replicas[3]
    assert r3.view == 1 and r3.low_mark == interval
    # The adopted certificate must certify the NEW stable seq...
    assert r3.stable_proof, "no certificate adopted on the watermark jump"
    assert all(d["seq"] == interval for d in r3.stable_proof)
    assert len(r3.stable_proof) >= 2 * c.config.f + 1
    # ...so its next VIEW-CHANGE validates at its peers.
    acts = r3.start_view_change()
    vcs = [
        a.msg
        for a in acts
        if isinstance(a, Broadcast) and isinstance(a.msg, ViewChange)
    ]
    assert vcs and vcs[0].last_stable_seq == interval
    assert c.replicas[1]._validate_view_change(vcs[0])


def test_view_change_message_roundtrip():
    config, seeds = make_local_cluster(4)
    r = Replica(config, 1, seeds[1])
    [bcast] = [
        a
        for a in r.start_view_change()
        if isinstance(a, Broadcast) and isinstance(a.msg, ViewChange)
    ]
    from pbft_tpu.consensus.messages import from_wire, to_wire

    frame = to_wire(bcast.msg)
    back = from_wire(frame[4:])
    assert back == bcast.msg
    assert back.signable() == bcast.msg.signable()


def test_stable_digest_ignores_byzantine_first_checkpoint():
    """A view-change proof may carry extra correctly-signed checkpoints with
    a bogus digest; the adopted stable digest must be the one with a 2f+1
    majority, not whichever entry the (possibly Byzantine) sender listed
    first (PBFT §4.4 / §5.3 — digest adoption during the watermark jump)."""
    from pbft_tpu.consensus.messages import Checkpoint

    config, seeds = make_local_cluster(4)
    replicas = [Replica(config, i, seeds[i]) for i in range(4)]
    good = "ab" * 32
    evil = "cd" * 32
    # Replicas 1..3 certify `good` at seq 10; Byzantine replica 0 signs
    # `evil` for the same seq. All four signatures are genuine.
    proof = [
        replicas[0]._sign(Checkpoint(seq=10, digest=evil, replica=0)).to_dict()
    ] + [
        replicas[i]._sign(Checkpoint(seq=10, digest=good, replica=i)).to_dict()
        for i in (1, 2, 3)
    ]
    vc = replicas[1]._sign(
        ViewChange(
            new_view=1,
            last_stable_seq=10,
            checkpoint_proof=tuple(proof),
            prepared_proofs=(),
            replica=1,
        )
    )
    # The proof as a whole is valid (a 2f+1 majority on `good` exists)...
    assert replicas[2]._validate_view_change(vc)
    # ...but the stable digest must be the majority one, not proof[0]'s —
    # and the adopted certificate must carry ONLY the majority entries.
    digest, proof = replicas[2]._stable_cert_for([vc], 10)
    assert digest == good
    assert len(proof) == 3
    assert all(d["digest"] == good for d in proof)


def _signed_reply_dict(seeds, rid, ts, result="awesome!", view=0, client="c:1"):
    from pbft_tpu.consensus.messages import ClientReply
    from pbft_tpu.crypto import ref

    rep = ClientReply(
        view=view, timestamp=ts, client=client, replica=rid, result=result
    )
    return {**rep.to_dict(), "sig": ref.sign(seeds[rid], rep.signable()).hex()}


def test_client_reply_quorum_one_vote_per_replica():
    """f+1 reply quorum must count distinct replicas: duplicate replies from
    one replica (retransmissions) do not satisfy it (PBFT §4.1)."""
    import pytest

    from pbft_tpu.net.client import PbftClient

    config, seeds = make_local_cluster(4)
    client = PbftClient.__new__(PbftClient)
    client.config = config
    import threading

    client._new_reply = threading.Condition()
    # Three copies of replica 2's reply: one vote, no quorum.
    client.replies = [_signed_reply_dict(seeds, 2, 7)] * 3
    with pytest.raises(TimeoutError):
        client.wait_result(7, timeout=0.2)
    # A second distinct replica completes the f+1 = 2 quorum.
    client.replies.append(_signed_reply_dict(seeds, 3, 7))
    assert client.wait_result(7, timeout=0.2) == "awesome!"


def test_client_reply_quorum_rejects_forged_signatures():
    """The dial-back channel is forgeable; votes only count with a valid
    signature from the claimed replica. A forger who controls one replica
    (or none) cannot mint the f+1 quorum (PBFT §4.1, done for real —
    the reference had no signatures anywhere, src/behavior.rs:127)."""
    import pytest

    from pbft_tpu.net.client import PbftClient

    config, seeds = make_local_cluster(4)
    client = PbftClient.__new__(PbftClient)
    client.config = config
    import threading

    client._new_reply = threading.Condition()
    good = _signed_reply_dict(seeds, 2, 9)
    # Forgeries: replica 3's vote signed with replica 2's key; an unsigned
    # vote; a garbage signature. None may complete the quorum.
    wrong_key = dict(_signed_reply_dict(seeds, 2, 9))
    wrong_key["replica"] = 3
    unsigned = {**_signed_reply_dict(seeds, 3, 9), "sig": ""}
    garbage = {**_signed_reply_dict(seeds, 3, 9), "sig": "ab" * 64}
    client.replies = [good, wrong_key, unsigned, garbage]
    with pytest.raises(TimeoutError):
        client.wait_result(9, timeout=0.2)
    # The genuine second vote still works.
    client.replies.append(_signed_reply_dict(seeds, 3, 9))
    assert client.wait_result(9, timeout=0.2) == "awesome!"


def test_view_change_span_ordering_via_timeline(tmp_path):
    """View-change spans end to end in the simulator (ISSUE 9): wire each
    replica's phase/view hooks to per-replica tracers, crash the primary,
    and require consensus_timeline --check-invariants to (a) see the
    view events and (b) certify view_timer_fired -> view_change_sent ->
    new_view_installed ordering."""
    import pathlib
    import sys as _sys

    from pbft_tpu.utils.metrics import ConsensusSpans, MetricsRegistry
    from pbft_tpu.utils.trace import Tracer

    c = Cluster(n=4)
    files, tracers = {}, {}
    for r in c.replicas:
        fh = open(tmp_path / f"replica-{r.id}.jsonl", "w")
        files[r.id] = fh
        tracer = Tracer(fh)
        tracers[r.id] = tracer
        spans = ConsensusSpans(
            MetricsRegistry(enabled=False), tracer=tracer, replica=r.id
        )
        r.phase_hook = spans.on_phase

        def view_hook(ev, v, _t=tracer, _rid=r.id):
            if ev == "view_change_sent":
                _t.event("view_change_sent", replica=_rid, pending_view=v)
            else:
                _t.event("new_view_installed", replica=_rid, view=v)

        r.view_hook = view_hook
    # A committed request in view 0 produces spans on every replica.
    req0 = c.submit("before")
    c.run(max_steps=500)
    assert c.committed_result(req0.timestamp) == "awesome!"
    # Primary dies; the runtime-owned timers fire (emitted here, as the
    # real daemons do) and the view change runs.
    c.crash(0)
    for rid in (1, 2, 3):
        tracers[rid].event(
            "view_timer_fired", replica=rid, view=c.replicas[rid].view,
            backoff=2,
        )
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    req1 = c.submit("after")
    c.run(max_steps=500)
    assert c.committed_result(req1.timestamp) == "awesome!"
    for fh in files.values():
        fh.close()

    _sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "scripts"))
    import consensus_timeline

    result = consensus_timeline.main(
        [str(tmp_path), "--check-invariants", "--json", "--no-spread"]
    )
    assert result["invariant_problems"] == []
    assert result["view_events"] >= 9  # 3 fired + 3 sent + >=3 installed
    # Every live replica both campaigned and installed view 1.
    import json as _json

    events = []
    for p in sorted(tmp_path.glob("replica-*.jsonl")):
        events += [_json.loads(line) for line in p.read_text().splitlines()]
    installed = {
        e["replica"] for e in events if e["ev"] == "new_view_installed"
    }
    assert installed == {1, 2, 3}


def test_view_event_ordering_violations_flagged():
    """The checker is not vacuous: installed-before-fired and a backwards
    pending_view both trip check_view_events."""
    from pbft_tpu.consensus.invariants import check_view_events

    clean = [
        {"ts": 1.0, "ev": "view_timer_fired", "replica": 1, "view": 0,
         "backoff": 2},
        {"ts": 1.1, "ev": "view_change_sent", "replica": 1,
         "pending_view": 1},
        {"ts": 1.5, "ev": "new_view_installed", "replica": 1, "view": 1},
    ]
    assert check_view_events(clean) == []
    backwards = [
        {"ts": 0.5, "ev": "new_view_installed", "replica": 1, "view": 1},
        {"ts": 1.0, "ev": "view_timer_fired", "replica": 1, "view": 0,
         "backoff": 2},
    ]
    assert check_view_events(backwards)
    regressing = [
        {"ts": 1.0, "ev": "view_change_sent", "replica": 2,
         "pending_view": 3},
        {"ts": 2.0, "ev": "view_change_sent", "replica": 2,
         "pending_view": 2},
    ]
    assert check_view_events(regressing)
    installed_before_sent = [
        {"ts": 1.0, "ev": "view_change_sent", "replica": 3,
         "pending_view": 2},
        {"ts": 0.4, "ev": "new_view_installed", "replica": 3, "view": 2},
    ]
    assert check_view_events(installed_before_sent)


# -- view-timer backoff + retransmission (ISSUE 12) ---------------------------


def test_view_timer_backoff_policy_escalates_and_caps():
    """§4.5.2 exponential backoff, as the runtimes run it (server.py
    ViewTimerBackoff; core/net.cc mirrors the state machine): arm at
    T x level, double per consecutive no-progress expiry, cap at 64."""
    from pbft_tpu.net.server import ViewTimerBackoff

    p = ViewTimerBackoff(1.0)
    assert p.poll(0.0, 0, 0, False) == "armed"
    assert p.deadline == 1.0
    assert p.poll(0.5, 0, 0, False) == "idle"
    assert p.poll(1.1, 0, 0, False) == "escalate"
    assert p.level == 2
    assert p.poll(1.2, 0, 0, False) == "armed"
    assert p.deadline == 1.2 + 2.0  # T x level
    now = 1.2
    for _ in range(10):  # drive to the cap
        now = p.deadline + 0.1
        assert p.poll(now, 0, 0, False) == "escalate"
        assert p.poll(now, 0, 0, False) == "armed"
    assert p.level == ViewTimerBackoff.MAX_LEVEL == 64
    p.clear()
    assert p.level == 1 and p.deadline is None


def test_view_timer_backoff_resets_on_progress():
    from pbft_tpu.net.server import ViewTimerBackoff

    p = ViewTimerBackoff(1.0)
    assert p.poll(0.0, 5, 2, False) == "armed"
    assert p.poll(2.0, 6, 2, False) == "progress"  # executed advanced
    assert p.level == 1
    assert p.poll(2.1, 6, 2, False) == "armed"
    assert p.poll(3.5, 6, 3, False) == "progress"  # view advanced
    assert p.level == 1


def test_view_timer_backoff_retransmits_before_escalating():
    """Mid-view-change, the FIRST no-progress expiry retransmits the
    pending VIEW-CHANGE (same view, lost-frame recovery); only the next
    one escalates and doubles — repeated timer fires must not burn a
    view number each (ISSUE 12)."""
    from pbft_tpu.net.server import ViewTimerBackoff

    p = ViewTimerBackoff(1.0)
    assert p.poll(0.0, 0, 0, True) == "armed"
    assert p.poll(1.1, 0, 0, True) == "retransmit"
    assert p.level == 1  # retransmission never doubles
    assert p.poll(1.2, 0, 0, True) == "armed"
    assert p.poll(2.3, 0, 0, True) == "escalate"
    assert p.level == 2
    # After escalation the cycle repeats: retransmit, then escalate.
    assert p.poll(2.4, 0, 0, True) == "armed"
    assert p.poll(4.5, 0, 0, True) == "retransmit"
    assert p.poll(4.6, 0, 0, True) == "armed"
    assert p.poll(6.7, 0, 0, True) == "escalate"
    assert p.level == 4


def _direct_replicas(n=4):
    config, seeds = make_local_cluster(n, base_port=0)
    return [Replica(config, i, seeds[i]) for i in range(n)], config


def _deliver(replica, msg):
    """Feed one replica-to-replica message through the verify queue."""
    out = list(replica.receive(msg))
    out += replica.deliver_verdicts([True] * replica.pending_count())
    return out


def _own_view_change(actions):
    for a in actions:
        if isinstance(a, Broadcast) and isinstance(a.msg, ViewChange):
            return a.msg
    raise AssertionError("no ViewChange broadcast in actions")


def test_retransmit_view_change_is_verbatim_and_free():
    """retransmit_view_change re-broadcasts the SAME signed message: no
    counter moves, no re-signing, and outside a view change it is a
    no-op (ISSUE 12)."""
    replicas, _ = _direct_replicas()
    r = replicas[2]
    assert r.retransmit_view_change() == []  # not in a view change
    vc = _own_view_change(r.start_view_change())
    started = r.counters["view_changes_started"]
    out = r.retransmit_view_change()
    assert len(out) == 1 and isinstance(out[0], Broadcast)
    assert out[0].msg == vc  # verbatim: same content, same signature
    assert r.counters["view_changes_started"] == started


def test_primary_resends_cached_new_view_to_laggard():
    """A VIEW-CHANGE arriving for a view the receiver already LEADS is a
    laggard signalling it missed the NEW-VIEW broadcast: the primary
    answers with the cached NEW-VIEW, point-to-point, without
    recomputing O or re-broadcasting (ISSUE 12)."""
    replicas, config = _direct_replicas()
    r1, r2, r3 = replicas[1], replicas[2], replicas[3]
    vc2 = _own_view_change(r2.start_view_change())
    vc3 = _own_view_change(r3.start_view_change())
    out = list(r1.start_view_change())  # r1 logs its own VC
    out += _deliver(r1, vc2)
    out += _deliver(r1, vc3)  # 2f+1 = 3 -> NEW-VIEW built + view entered
    assert r1.view == 1 and not r1.in_view_change
    nv_broadcasts = [
        a
        for a in out
        if isinstance(a, Broadcast) and isinstance(a.msg, NewView)
    ]
    assert len(nv_broadcasts) == 1
    # Laggard r2 retransmits its VIEW-CHANGE (its timer fired again):
    # the primary resends the cached NEW-VIEW to r2 alone.
    from pbft_tpu.consensus.replica import Send

    resend = _deliver(r1, vc2)
    sends = [a for a in resend if isinstance(a, Send)]
    assert len(sends) == 1
    assert sends[0].dest == 2
    assert isinstance(sends[0].msg, NewView)
    assert sends[0].msg == nv_broadcasts[0].msg  # cached, not recomputed
    # No second broadcast, no double-entry.
    assert not any(
        isinstance(a, Broadcast) and isinstance(a.msg, NewView)
        for a in resend
    )
    assert r1.counters["view_changes_completed"] == 1
    # The resent NEW-VIEW actually installs the view on the laggard.
    for a in _deliver(r2, vc3):
        pass
    entered = _deliver(r2, sends[0].msg)
    del entered
    assert r2.view == 1 and not r2.in_view_change
