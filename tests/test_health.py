"""Cluster-health introspection (ISSUE 16).

Two halves:

1. The detector library is pure — every detector must TRIP on a
   synthetic bad history (a silent stall, an fd ramp, a forked chain
   digest, a wedged view change, a saturated inbox) and stay QUIET on a
   healthy one. The synthetic histories are built from the same
   health-document shape both runtimes serve on /status.
2. Live smoke: ``pbft_top --gate --once`` against a real LocalCluster —
   exit 0 on a healthy loaded cluster, exit 1 with a machine-readable
   silent-stall verdict when the primary is muted and holds sealed work
   it can never execute.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from pbft_tpu import native  # noqa: E402
from pbft_tpu.analysis import health  # noqa: E402

PBFT_TOP = REPO / "scripts" / "pbft_top.py"


# -- synthetic history builders ----------------------------------------------

def _doc(executed=0, committed=None, inbox=0, sealed=0, waiting=0,
         view=0, in_vc=False, rss=100 << 20, fds=20, wal=4096,
         digest="aa" * 32):
    return {
        "health_version": 1,
        "executed_upto": executed,
        "committed_upto": executed if committed is None else committed,
        "inbox_depth": inbox,
        "sealed_unexecuted": sealed,
        "waiting_requests": waiting,
        "view": view,
        "in_view_change": in_vc,
        "rss_bytes": rss,
        "open_fds": fds,
        "wal_disk_bytes": wal,
        "chain_digest": digest,
    }


def _history(per_tick, n=4, dt=1.0):
    """history from per_tick(t_index, rid) -> doc (or None to omit)."""
    out = []
    t = 0.0
    i = 0
    while True:
        docs = {}
        for rid in range(n):
            doc = per_tick(i, rid)
            if doc is not None:
                docs[rid] = doc
        if not docs and i > 0:
            break
        out.append({"t": t, "replicas": docs})
        t += dt
        i += 1
    return out


def _healthy_history(ticks=12, n=4):
    """Steady execution, flat resources, matching digests."""
    return _history(
        lambda i, rid: _doc(executed=10 * i, inbox=2 if i % 3 else 0)
        if i < ticks else None,
        n=n,
    )


# -- 1. detectors ------------------------------------------------------------

def test_detectors_quiet_on_healthy_history():
    assert health.run_detectors(_healthy_history()) == []


def test_silent_stall_trips_and_names_the_replica():
    """Replica 2's executed_upto goes flat with sealed work pending; the
    others keep executing. One verdict, pinned to replica 2."""
    def tick(i, rid):
        if i >= 10:
            return None
        if rid == 2:
            return _doc(executed=30, sealed=4)
        return _doc(executed=30 + 10 * i)
    verdicts = health.detect_silent_stall(_history(tick), stall_seconds=5)
    assert [v["replica"] for v in verdicts] == [2]
    v = verdicts[0]
    assert v["detector"] == "silent-stall"
    assert v["evidence"]["flat_seconds"] >= 5
    assert v["evidence"]["pending"] == 4


def test_silent_stall_quiet_when_idle():
    """Flat executed_upto with NOTHING pending is an idle cluster, not a
    stall — and a momentarily-drained queue resets the clock."""
    hist = _history(lambda i, rid: _doc(executed=30) if i < 10 else None)
    assert health.detect_silent_stall(hist, stall_seconds=5) == []
    # pending blips that never span the threshold: quiet too
    hist = _history(
        lambda i, rid: _doc(executed=30, inbox=1 if i % 2 else 0)
        if i < 10 else None)
    assert health.detect_silent_stall(hist, stall_seconds=5) == []


def test_resource_leak_trips_on_fd_ramp():
    """Six fds/second, forever climbing: robust slope over the floor."""
    def tick(i, rid):
        if i >= 12:
            return None
        return _doc(executed=10 * i, fds=20 + (6 * i if rid == 1 else 0))
    verdicts = health.detect_resource_leak(_history(tick))
    assert [v["replica"] for v in verdicts] == [1]
    assert verdicts[0]["evidence"]["metric"] == "open_fds"
    assert verdicts[0]["evidence"]["slope_per_s"] > 0


def test_resource_leak_quiet_on_noise_and_transients():
    # breathing RSS around a flat baseline
    def breathe(i, rid):
        if i >= 12:
            return None
        return _doc(executed=10 * i, rss=(100 << 20) + (i % 3) * (1 << 20))
    assert health.detect_resource_leak(_history(breathe)) == []
    # one wild reading cannot fake a trend past the median slope
    def spike(i, rid):
        if i >= 12:
            return None
        return _doc(executed=10 * i, fds=200 if i == 6 else 20)
    assert health.detect_resource_leak(_history(spike)) == []
    # zero readings mean "no data", never a growth baseline
    def zeros(i, rid):
        if i >= 12:
            return None
        return _doc(executed=10 * i, rss=0, wal=0)
    assert health.detect_resource_leak(_history(zeros)) == []


def test_divergence_trips_on_forked_digest():
    """Same committed_upto, different chain digests — a safety violation
    the moment it appears, reported once per (floor, grouping)."""
    def tick(i, rid):
        if i >= 6:
            return None
        return _doc(executed=50, digest="bb" * 32 if rid == 3 else "aa" * 32)
    verdicts = health.detect_divergence(_history(tick))
    assert len(verdicts) == 1  # deduped across the 6 identical snapshots
    v = verdicts[0]
    assert v["detector"] == "divergence"
    groups = v["evidence"]["groups"]
    assert groups[0]["replicas"] == ["0", "1", "2"]  # majority first
    assert groups[1]["replicas"] == ["3"]


def test_divergence_quiet_on_lag():
    """A replica BEHIND the others (different committed_upto) is lag,
    not divergence."""
    def tick(i, rid):
        if i >= 6:
            return None
        return _doc(executed=20 if rid == 3 else 50,
                    digest="cc" * 32 if rid == 3 else "aa" * 32)
    assert health.detect_divergence(_history(tick)) == []


def test_stuck_view_change_trips():
    def tick(i, rid):
        if i >= 10:
            return None
        return _doc(executed=30, view=4, in_vc=(rid == 0))
    verdicts = health.detect_stuck_view_change(_history(tick), stall_seconds=5)
    assert [v["replica"] for v in verdicts] == [0]
    assert verdicts[0]["evidence"]["view"] == 4


def test_stuck_view_change_quiet_when_views_advance():
    """in_view_change held but the view number climbing = the backoff
    ladder doing its job, not a wedge."""
    def tick(i, rid):
        if i >= 10:
            return None
        return _doc(executed=30, view=i // 2, in_vc=True)
    assert health.detect_stuck_view_change(
        _history(tick), stall_seconds=5) == []


def test_queue_saturation_trips_and_clears():
    def tick(i, rid):
        if i >= 10:
            return None
        return _doc(executed=10 * i, inbox=600 if rid == 1 else 3)
    verdicts = health.detect_queue_saturation(_history(tick))
    assert [v["replica"] for v in verdicts] == [1]
    # dips below the watermark reset the sustain clock
    def dip(i, rid):
        if i >= 10:
            return None
        return _doc(executed=10 * i, inbox=600 if i % 3 else 10)
    assert health.detect_queue_saturation(_history(dip)) == []


def test_run_detectors_concatenates_and_threads_thresholds():
    """One history carrying a stall AND a fork yields both verdicts; a
    looser stall threshold silences the stall but not the fork."""
    def tick(i, rid):
        if i >= 10:
            return None
        return _doc(executed=30, sealed=2 if rid == 0 else 0,
                    digest="dd" * 32 if rid == 1 else "aa" * 32)
    verdicts = health.run_detectors(_history(tick), stall_seconds=5)
    assert {v["detector"] for v in verdicts} == {"silent-stall", "divergence"}
    loose = health.run_detectors(_history(tick), stall_seconds=100)
    assert {v["detector"] for v in loose} == {"divergence"}


def test_theil_sen_slope():
    assert health.theil_sen_slope([]) is None
    assert health.theil_sen_slope([(0, 1)]) is None
    assert health.theil_sen_slope(
        [(0, 0), (1, 2), (2, 4), (3, 6)]) == pytest.approx(2.0)
    # median robustness: one outlier does not drag the slope
    pts = [(0, 0), (1, 1), (2, 2), (3, 3), (4, 1000)]
    assert health.theil_sen_slope(pts) < 10


def test_dead_replica_is_no_data_not_zeros():
    """Snapshots missing a replica (down mid-poll) contribute no points:
    no detector may fabricate a verdict from absence."""
    def tick(i, rid):
        if i >= 10:
            return None
        if rid == 3 and i >= 3:
            return None  # replica 3 dies after t=2
        return _doc(executed=10 * i, inbox=1)
    assert health.run_detectors(_history(tick)) == []


def _sim_history(mute_primary, ticks=60):
    """Drive the deterministic simulator and snapshot the same document
    shape chaos_soak's --health-gate builds (sealed_unexecuted is the
    primary's assigned-but-unexecuted watermark)."""
    from pbft_tpu.consensus.simulation import Cluster

    c = Cluster(n=4, seed=16, app=lambda op, seq: op)
    if mute_primary:
        c.set_fault(0, "mute")
    c.submit("sim-doomed", to_replica=0)
    history = []
    for t in range(ticks):
        c.run(max_steps=5)
        history.append({
            "t": float(t),
            "replicas": {
                r.id: {
                    "executed_upto": r.executed_upto,
                    "committed_upto": r.committed_upto,
                    "inbox_depth": r.pending_count(),
                    "sealed_unexecuted": max(
                        0, r.seq_counter - r.executed_upto),
                    "waiting_requests": 0,
                    "chain_digest": r.committed_chain.hex(),
                }
                for r in c.replicas
            },
        })
    return history


def test_silent_stall_trips_on_simulated_muted_primary():
    """The injected-stall validity check: a muted sim primary seals a
    targeted request it can never broadcast — the detector must trip on
    replica 0, and the identical un-muted run must stay quiet."""
    stalled = _sim_history(mute_primary=True)
    verdicts = health.detect_silent_stall(stalled, stall_seconds=20)
    assert any(v["replica"] == 0 for v in verdicts), verdicts
    assert health.detect_divergence(stalled) == []

    clean = _sim_history(mute_primary=False)
    assert health.detect_silent_stall(clean, stall_seconds=20) == []
    assert health.detect_divergence(clean) == []


# -- 2. live pbft_top gate smoke ---------------------------------------------

pytestmark_live = pytest.mark.skipif(
    not native.available(), reason="native core not built")


def _run_top_gate(ports, stall_seconds=2, window_s=4):
    targets = ",".join(f"127.0.0.1:{p}" for p in ports)
    return subprocess.run(
        [sys.executable, str(PBFT_TOP), "--targets", targets,
         "--gate", "--once", "--interval", "0.5",
         "--stall-seconds", str(stall_seconds), "--window-s", str(window_s)],
        capture_output=True, text=True, timeout=120,
    )


@pytestmark_live
def test_pbft_top_gate_passes_healthy_cluster():
    from pbft_tpu.net.client import PbftClient
    from pbft_tpu.net.launcher import LocalCluster

    with LocalCluster(n=4, impl="cxx", metrics_ports=True) as c:
        cl = PbftClient(c.config)
        req = cl.request("health-smoke")
        assert cl.wait_result(req.timestamp, timeout=30) is not None
        proc = _run_top_gate(c.metrics_ports)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True and verdict["verdicts"] == []
    assert verdict["snapshots"] >= 2


@pytestmark_live
def test_pbft_top_gate_catches_muted_primary_stall():
    """The acceptance scenario: primary muted at launch seals a request
    it can never execute — completion metrics are silent, but the gate
    must exit 1 with a silent-stall verdict naming replica 0."""
    from pbft_tpu.net.client import PbftClient
    from pbft_tpu.net.launcher import LocalCluster

    with LocalCluster(n=4, impl="cxx", metrics_ports=True,
                      faults={0: "mute"}) as c:
        cl = PbftClient(c.config)
        cl.request("doomed", to_replica=0)  # sealed by 0, never executed
        proc = _run_top_gate(c.metrics_ports)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is False
    stalls = [v for v in verdict["verdicts"]
              if v["detector"] == "silent-stall"]
    assert any(str(v["replica"]) == "0" for v in stalls), verdict
