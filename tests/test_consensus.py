"""Unit tests for the deterministic replica core + in-memory cluster sims.

Covers SURVEY.md §4 items 1-2: message-in/message-out truth tables, the
4-replica happy path, quorum thresholds, duplicate/conflicting pre-prepares,
exactly-once timestamps, reordering, Byzantine signers, and checkpoint GC.
"""

import dataclasses

import pytest

from pbft_tpu.consensus import (
    Checkpoint,
    ClientRequest,
    Commit,
    Prepare,
    PrePrepare,
    from_wire,
    to_wire,
)
from pbft_tpu.consensus.config import make_local_cluster
from pbft_tpu.consensus.replica import Broadcast, Replica, Reply, Send
from pbft_tpu.consensus.simulation import Cluster, cpu_verifier
from pbft_tpu.crypto import ref


def mk_request(op="op", t=1, client="127.0.0.1:9000"):
    return ClientRequest(operation=op, timestamp=t, client=client)


def test_wire_roundtrip():
    req = mk_request()
    for msg in [
        req,
        PrePrepare(view=0, seq=1, digest=req.digest(), requests=(req,), replica=0, sig="ab"),
        Prepare(view=0, seq=1, digest="d", replica=2, sig="cd"),
        Commit(view=0, seq=1, digest="d", replica=3, sig="ef"),
        Checkpoint(seq=16, digest="s", replica=1, sig="01"),
    ]:
        frame = to_wire(msg)
        assert int.from_bytes(frame[:4], "big") == len(frame) - 4
        assert from_wire(frame[4:]) == msg


def test_signable_excludes_signature():
    p1 = Prepare(view=0, seq=1, digest="d", replica=2, sig="")
    p2 = Prepare(view=0, seq=1, digest="d", replica=2, sig="aabb")
    assert p1.signable() == p2.signable()
    assert p1.signable() != Prepare(view=0, seq=2, digest="d", replica=2).signable()


def fresh_replica(n=4, rid=0):
    config, seeds = make_local_cluster(n)
    return Replica(config, rid, seeds[rid]), config, seeds


def test_primary_pre_prepare_broadcast():
    r, config, _ = fresh_replica(rid=0)
    actions = r.on_client_request(mk_request())
    # Exactly one PrePrepare broadcast; the primary sends NO prepare — its
    # pre-prepare stands in for it (PBFT §4.2), so prepared certificates
    # always contain 2f+1 distinct replicas. (The reference had the primary
    # log its own prepare, reference src/behavior.rs:63-124, which shrinks
    # the certificate to 2f distinct members.)
    assert [type(a).__name__ for a in actions] == ["Broadcast"]
    assert isinstance(actions[0].msg, PrePrepare)
    assert r.pre_prepares[(0, 1)].digest == actions[0].msg.digest
    assert (0, 1) not in r.prepares


def test_backup_forwards_request_to_primary():
    r, _, _ = fresh_replica(rid=1)
    actions = r.on_client_request(mk_request())
    assert actions == [Send(0, mk_request())]


def test_quorum_thresholds_exact():
    """prepared needs 2f PREPAREs; committed-local needs 2f+1 COMMITs."""
    r, config, seeds = fresh_replica(n=4, rid=1)  # backup; f=1
    primary = Replica(config, 0, seeds[0])
    [pp_bcast] = primary.on_client_request(mk_request())
    pp = pp_bcast.msg

    out = r._dispatch(pp)
    assert any(isinstance(a.msg, Prepare) for a in out if isinstance(a, Broadcast))
    key = (0, 1)
    assert not r._prepared(key)  # own prepare only: 1 < 2f=2

    def signed_prepare(rid):
        other = Replica(config, rid, seeds[rid])
        return other._sign(Prepare(view=0, seq=1, digest=pp.digest, replica=rid))

    out = r._dispatch(signed_prepare(2))
    # second matching prepare reaches 2f -> replica multicasts COMMIT
    assert r._prepared(key)
    assert any(isinstance(a.msg, Commit) for a in out if isinstance(a, Broadcast))
    assert not r._committed_local(key)  # 1 own commit < 2f+1

    def signed_commit(rid):
        other = Replica(config, rid, seeds[rid])
        return other._sign(Commit(view=0, seq=1, digest=pp.digest, replica=rid))

    r._dispatch(signed_commit(0))
    assert not r._committed_local(key)  # 2 < 3
    out = r._dispatch(signed_commit(3))
    assert r._committed_local(key)  # 3 == 2f+1
    assert [a for a in out if isinstance(a, Reply)], "execution must reply"


def test_conflicting_pre_prepare_rejected():
    r, config, seeds = fresh_replica(n=4, rid=1)
    primary = Replica(config, 0, seeds[0])
    [pp_bcast] = primary.on_client_request(mk_request(op="first"))
    r._dispatch(pp_bcast.msg)
    # Equivocation: same (v, n), different digest.
    req2 = mk_request(op="second", t=2)
    evil = primary._sign(
        PrePrepare(view=0, seq=1, digest=req2.digest(), requests=(req2,), replica=0)
    )
    assert r._dispatch(evil) == []
    assert r.pre_prepares[(0, 1)].digest == pp_bcast.msg.digest


def test_pre_prepare_from_non_primary_rejected():
    r, config, seeds = fresh_replica(n=4, rid=2)
    backup = Replica(config, 1, seeds[1])
    req = mk_request()
    fake = backup._sign(
        PrePrepare(view=0, seq=1, digest=req.digest(), requests=(req,), replica=1)
    )
    assert r._dispatch(fake) == []
    assert (0, 1) not in r.pre_prepares


def test_watermark_rejects_out_of_window():
    r, config, seeds = fresh_replica(n=4, rid=1)
    primary = Replica(config, 0, seeds[0])
    req = mk_request()
    beyond = primary._sign(
        PrePrepare(
            view=0,
            seq=config.watermark_window + 1,
            digest=req.digest(),
            requests=(req,),
            replica=0,
        )
    )
    assert r._dispatch(beyond) == []


def test_bad_signature_dropped_via_verdicts():
    r, config, seeds = fresh_replica(n=4, rid=1)
    primary = Replica(config, 0, seeds[0])
    [pp_bcast] = primary.on_client_request(mk_request())
    tampered = dataclasses.replace(pp_bcast.msg, sig="00" * 64)
    r.receive(tampered)
    items = r.pending_items()
    verdicts = cpu_verifier(items)
    assert verdicts == [False]
    assert r.deliver_verdicts(verdicts) == []
    assert r.counters["sig_rejected"] == 1
    assert (0, 1) not in r.pre_prepares


# -- cluster simulations ----------------------------------------------------


def test_happy_path_f1():
    c = Cluster(n=4)
    req = c.submit("deposit 100")
    c.run()
    assert c.committed_result(req.timestamp) == "awesome!"
    # every replica executed once, identical state digests
    assert [r.executed_upto for r in c.replicas] == [1, 1, 1, 1]
    digests = {r.state_digest for r in c.replicas}
    assert len(digests) == 1
    # all 4 replicas replied (client needs only f+1=2 to match)
    assert len(c.replies_for(req.timestamp)) == 4


def test_happy_path_f2_multiple_requests():
    c = Cluster(n=7)
    reqs = [c.submit(f"op-{i}", client=f"127.0.0.1:{9000+i%4}") for i in range(5)]
    c.run(max_steps=500)
    for req in reqs:
        c.committed_result(req.timestamp)
    assert all(r.executed_upto == 5 for r in c.replicas)
    assert len({r.state_digest for r in c.replicas}) == 1


def test_request_to_backup_is_forwarded():
    c = Cluster(n=4)
    req = c.submit("via-backup", to_replica=2)
    c.run()
    assert c.committed_result(req.timestamp) == "awesome!"


def test_duplicate_request_cached_reply():
    c = Cluster(n=4)
    req = c.submit("pay", timestamp=7)
    c.run()
    first_replies = len(c.replies_for(7))
    c.submit("pay", timestamp=7)  # exact retransmission
    c.run()
    assert c.replicas[0].counters["duplicate_requests"] >= 1
    # primary resends its cached reply; no replica re-executes
    assert len(c.replies_for(7)) == first_replies + 1
    assert all(r.executed_upto == 1 for r in c.replicas)


def test_reordered_delivery_still_commits():
    # Distinct clients: a PBFT client has one outstanding request at a time;
    # concurrent requests from one client may legitimately be deduplicated
    # by the timestamp guard when reordered.
    c = Cluster(n=4, shuffle=True, seed=1234)
    reqs = [c.submit(f"op-{i}", client=f"127.0.0.1:{9100+i}") for i in range(4)]
    c.run(max_steps=500)
    for req in reqs:
        c.committed_result(req.timestamp)
    assert len({r.state_digest for r in c.replicas}) == 1


def test_byzantine_signer_isolated():
    """BASELINE.md config 5 in miniature: replica 3 corrupts every signature;
    consensus proceeds (f=1 tolerates it) and rejections are counted."""
    c = Cluster(n=4)

    def corrupt(src, msg):
        if src == 3 and getattr(msg, "sig", ""):
            return dataclasses.replace(msg, sig="ff" * 64)
        return msg

    c.outbound_mutator = corrupt
    req = c.submit("survive")
    c.run()
    assert c.committed_result(req.timestamp) == "awesome!"
    rejected = sum(r.counters["sig_rejected"] for r in c.replicas)
    assert rejected > 0


def test_crashed_replica_tolerated():
    c = Cluster(n=4)
    for dst in range(4):
        c.dropped_links.add((3, dst))
        c.dropped_links.add((dst, 3))
    req = c.submit("minority-crash")
    c.run()
    assert c.committed_result(req.timestamp) == "awesome!"
    assert c.replicas[3].executed_upto == 0


def test_checkpoint_advances_watermark_and_truncates():
    c = Cluster(n=4)
    interval = c.config.checkpoint_interval
    for i in range(interval):
        c.submit(f"op-{i}")
        c.run(max_steps=500)
    for r in c.replicas:
        assert r.executed_upto == interval
        assert r.low_mark == interval
        assert all(k[1] > interval for k in r.pre_prepares)
        assert all(k[1] > interval for k in r.prepares)
        assert all(k[1] > interval for k in r.commits)
        assert r.counters["checkpoints_stable"] == 1


def test_prepared_certificate_excludes_primary_prepare():
    """A forged 'prepare' claiming to be from the primary must not count
    toward the 2f threshold (quorum-intersection regression)."""
    r, config, seeds = fresh_replica(n=4, rid=1)
    primary = Replica(config, 0, seeds[0])
    [pp_bcast] = primary.on_client_request(mk_request())
    pp = pp_bcast.msg
    r._dispatch(pp)  # r logs its own prepare (1 backup prepare)
    key = (0, 1)
    # A prepare from the primary (even correctly signed) does not count.
    primary_prep = primary._sign(
        Prepare(view=0, seq=1, digest=pp.digest, replica=0)
    )
    r._dispatch(primary_prep)
    assert not r._prepared(key)
    # A second *backup* prepare does.
    other = Replica(config, 2, seeds[2])
    r._dispatch(other._sign(Prepare(view=0, seq=1, digest=pp.digest, replica=2)))
    assert r._prepared(key)


def test_lagging_replica_adopts_stable_checkpoint():
    """Watermark advancement past unexecuted seqs must not deadlock
    execution (regression: pruning pending_execution without adopting the
    proven checkpoint left executed_upto stuck forever)."""
    c = Cluster(n=4)
    interval = c.config.checkpoint_interval
    # Replica 3 misses everything up to the checkpoint.
    for dst in range(3):
        c.dropped_links.add((dst, 3))
        c.dropped_links.add((3, dst))
    for i in range(interval):
        c.submit(f"op-{i}")
        c.run(max_steps=500)
    assert c.replicas[3].executed_upto == 0
    # Reconnect and run through the NEXT checkpoint boundary: checkpoints
    # are broadcast at execution time, so the healed replica adopts the
    # stable checkpoint (state-transfer-lite) when the cluster next
    # checkpoints — the lag is bounded by one interval instead of forever.
    c.dropped_links.clear()
    reqs = [c.submit(f"healed-{i}") for i in range(interval)]
    for _ in range(interval):
        c.run(max_steps=1000)
    for req in reqs:
        c.committed_result(req.timestamp)
    r3 = c.replicas[3]
    assert r3.low_mark == 2 * interval
    assert r3.executed_upto == 2 * interval
    assert r3.state_digest == c.replicas[0].state_digest


@pytest.mark.slow  # compiles the batch verifier inside the sim (~3 min cold)
def test_jax_verifier_cluster_equivalence():
    """Same scenario through the JAX batch verifier: identical outcome
    (SURVEY.md §7 'determinism at the FFI boundary')."""
    c = Cluster(n=4, verifier="jax")
    req = c.submit("tpu-arm")
    c.run()
    assert c.committed_result(req.timestamp) == "awesome!"
    assert len({r.state_digest for r in c.replicas}) == 1
