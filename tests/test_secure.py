"""Encrypted replica links (VERDICT r3 missing #1 + #3): signed-ephemeral-DH
handshake, keyed-BLAKE2b AEAD framing, and protocol-version negotiation —
unit round trips, C++/Python byte-identity, wire-level rejection cases, and
end-to-end secure clusters in both runtimes.

The reference secures every libp2p link with development_transport (Noise +
yamux, reference src/main.rs:42) and names its protocol
/ackintosh/pbft/1.0.0 (reference src/protocol_config.rs:24); these tests
pin the rebuild's equivalent (pbft_tpu/net/secure.py + core/secure.cc)."""

import hashlib
import json
import os
import socket

import pytest

from pbft_tpu import native
from pbft_tpu.crypto import ref
from pbft_tpu.net import secure

needs_native = pytest.mark.skipif(
    not native.available(), reason="native core not built"
)


def _pair(secure_mode=True):
    seeds = {0: bytes([1]) * 32, 1: bytes([2]) * 32}
    pubs = {i: ref.public_key(s) for i, s in seeds.items()}
    a = secure.SecureChannel(
        0, seeds[0], pubs.get, initiator=True, expected_peer=1
    )
    b = secure.SecureChannel(1, seeds[1], pubs.get, initiator=False)
    return a, b, seeds, pubs


# -- handshake state machine (pure Python, no sockets) -----------------------


def test_handshake_round_trip_and_sealed_frames():
    a, b, _, _ = _pair()
    auth = a.on_hello_reply(b.on_hello(a.initiator_hello()))
    b.on_auth(auth)
    assert a.established and b.established
    assert a.peer_id == 1 and b.peer_id == 0
    for i in range(5):  # counters advance in lockstep per direction
        payload = b"frame-%d " % i * 20
        assert b.open_frame(a.seal_frame(payload)) == payload
        assert a.open_frame(b.seal_frame(payload[::-1])) == payload[::-1]


def test_tampered_frame_rejected():
    a, b, _, _ = _pair()
    b.on_auth(a.on_hello_reply(b.on_hello(a.initiator_hello())))
    sealed = bytearray(a.seal_frame(b"payload"))
    sealed[3] ^= 0x40
    with pytest.raises(secure.HandshakeError, match="AEAD tag mismatch"):
        b.open_frame(bytes(sealed))


def test_replayed_frame_rejected():
    """Implicit counters: the same sealed frame cannot be accepted twice."""
    a, b, _, _ = _pair()
    b.on_auth(a.on_hello_reply(b.on_hello(a.initiator_hello())))
    sealed = a.seal_frame(b"once")
    assert b.open_frame(sealed) == b"once"
    with pytest.raises(secure.HandshakeError):
        b.open_frame(sealed)


def test_version_mismatch_rejected_with_clear_error():
    a, b, _, _ = _pair()
    hello = a.initiator_hello()
    hello["ver"] = "pbft-tpu/9.9.9"
    with pytest.raises(secure.HandshakeError, match="version mismatch"):
        b.on_hello(hello)


def test_plaintext_hello_rejected_by_secure_responder():
    _, b, _, _ = _pair()
    with pytest.raises(secure.HandshakeError, match="plaintext peer rejected"):
        b.on_hello(secure.plain_hello(0))


def test_wrong_identity_signature_rejected():
    """A peer signing with a key not in the table (an impostor dialing in)
    fails the handshake even with a valid DH exchange."""
    seeds = {0: bytes([1]) * 32, 1: bytes([2]) * 32}
    pubs = {i: ref.public_key(s) for i, s in seeds.items()}
    imposter = secure.SecureChannel(
        1, bytes([9]) * 32, pubs.get, initiator=False  # wrong seed for id 1
    )
    a = secure.SecureChannel(
        0, seeds[0], pubs.get, initiator=True, expected_peer=1
    )
    reply = imposter.on_hello(a.initiator_hello())
    with pytest.raises(secure.HandshakeError, match="bad handshake signature"):
        a.on_hello_reply(reply)


def test_malformed_hex_fields_are_protocol_errors():
    """Non-hex eph/sig must surface as HandshakeError (-> a reject frame),
    never a stray ValueError escaping the connection handler."""
    a, b, _, _ = _pair()
    hello = a.initiator_hello()
    hello["eph"] = "zz" * 32
    with pytest.raises(secure.HandshakeError, match="non-hex"):
        b.on_hello(hello)
    a2, b2, _, _ = _pair()
    reply = b2.on_hello(a2.initiator_hello())
    reply["sig"] = "q" * 128
    with pytest.raises(secure.HandshakeError, match="non-hex"):
        a2.on_hello_reply(reply)


def test_small_order_ephemeral_rejected():
    # Compressed identity point (y=1): clamped-scalar multiply collapses to
    # the identity; the handshake must refuse the null key contribution.
    assert secure.dh_shared(os.urandom(32), (1).to_bytes(32, "little")) is None


# -- C++ / Python byte-identity ----------------------------------------------


@needs_native
def test_keyed_blake2b_matches_hashlib():
    for key, data in [(b"k" * 32, b"abc"), (b"x" * 64, b""), (b"y" * 17, b"z" * 300)]:
        for size in (16, 32, 64):
            assert native.blake2b_keyed(key, data, size) == hashlib.blake2b(
                data, key=key, digest_size=size
            ).digest()


@needs_native
def test_dh_cross_implementation_agreement():
    for i in range(3):
        sa, sb = bytes([i + 1]) * 32, bytes([i + 7]) * 32
        assert native.dh_public(sa) == secure.dh_keypair(sa)[1]
        # Python side computes with C++'s public key and vice versa.
        shared_py = secure.dh_shared(sa, native.dh_public(sb))
        shared_c = native.dh_shared(sb, secure.dh_keypair(sa)[1])
        assert shared_py == shared_c is not None


@needs_native
def test_aead_cross_implementation_agreement():
    key = bytes(range(64))
    for ctr in (0, 7, 2**40):
        for pt in (b"", b"a", b"x" * 64, b"frame " * 100):
            assert native.aead_seal(key, ctr, pt) == secure.seal(key, ctr, pt)
            assert native.aead_open(key, ctr, secure.seal(key, ctr, pt)) == pt
            assert secure.open_sealed(key, ctr, native.aead_seal(key, ctr, pt)) == pt
            assert native.aead_open(key, ctr + 1, secure.seal(key, ctr, pt)) is None


# -- wire-level rejection against real daemons -------------------------------


def _read_frames(sock, timeout=10.0):
    """Collect complete frames until the peer closes; returns payloads."""
    sock.settimeout(timeout)
    buf = b""
    try:
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
    except (socket.timeout, ConnectionError):
        pass
    out = []
    while len(buf) >= 4:
        n = int.from_bytes(buf[:4], "big")
        if len(buf) < 4 + n:
            break
        out.append(buf[4 : 4 + n])
        buf = buf[4 + n :]
    return out


def _frame(obj) -> bytes:
    payload = json.dumps(obj).encode()
    return len(payload).to_bytes(4, "big") + payload


@needs_native
@pytest.mark.parametrize("impl", ["cxx", "py"])
def test_version_mismatch_rejected_on_the_wire(impl):
    """A peer speaking a different protocol version gets a clean reject
    frame naming both versions, then the connection closes — in BOTH
    runtimes (the reference's protocol id /ackintosh/pbft/1.0.0 had no
    negotiation at all)."""
    from pbft_tpu.net import LocalCluster

    with LocalCluster(n=4, verifier="cpu", impl=impl, secure=True) as cluster:
        ident = cluster.config.replicas[0]
        with socket.create_connection((ident.host, ident.port), timeout=5) as s:
            s.sendall(
                _frame(
                    {
                        "type": "hello",
                        "ver": "pbft-tpu/0.0.1",
                        "node": 1,
                        "eph": "00" * 32,
                    }
                )
            )
            frames = _read_frames(s)
        rejects = [json.loads(f) for f in frames]
        assert rejects and rejects[-1]["type"] == "reject"
        assert "version mismatch" in rejects[-1]["reason"]
        assert rejects[-1]["ver"] == secure.PROTOCOL_VERSION


@needs_native
@pytest.mark.parametrize("impl", ["cxx", "py"])
def test_plaintext_peer_rejected_by_secure_cluster(impl):
    """A plaintext (no-ephemeral) hello into a secure cluster is refused
    with a reject frame, not silently ignored."""
    from pbft_tpu.net import LocalCluster

    with LocalCluster(n=4, verifier="cpu", impl=impl, secure=True) as cluster:
        ident = cluster.config.replicas[0]
        with socket.create_connection((ident.host, ident.port), timeout=5) as s:
            s.sendall(_frame(secure.plain_hello(1)))
            frames = _read_frames(s)
        rejects = [json.loads(f) for f in frames]
        assert rejects and rejects[-1]["type"] == "reject"
        assert "plaintext peer rejected" in rejects[-1]["reason"]


# -- end-to-end secure clusters ----------------------------------------------


@needs_native
def test_secure_cxx_cluster_commits():
    from pbft_tpu.net import LocalCluster, PbftClient

    with LocalCluster(n=4, verifier="cpu", secure=True) as cluster:
        client = PbftClient(cluster.config)
        try:
            req = client.request("over encrypted links")
            assert client.wait_result(req.timestamp, timeout=20) == "awesome!"
        finally:
            client.close()


@needs_native
def test_secure_discovered_cluster_commits():
    """Discovery + encryption together: peers found via multicast beacons
    still complete the signed-ephemeral handshake (identity pubkeys come
    from network.json, never from the unauthenticated beacon channel)."""
    from pbft_tpu.net import LocalCluster, PbftClient

    with LocalCluster(
        n=4,
        verifier="cpu",
        discovery=True,
        secure=True,
        vc_timeout_ms=1500,
    ) as cluster:
        client = PbftClient(cluster.config)
        try:
            assert (
                client.request_with_retry("discovered+encrypted", timeout=30)
                == "awesome!"
            )
        finally:
            client.close()


@needs_native
def test_secure_mixed_runtime_cluster_commits():
    """2 pbftd + 2 asyncio replicas, ALL links encrypted: the handshake and
    AEAD framing interoperate byte-for-byte across the two implementations."""
    from pbft_tpu.net import LocalCluster, PbftClient

    with LocalCluster(
        n=4, verifier="cpu", impl=["cxx", "py", "cxx", "py"], secure=True
    ) as cluster:
        client = PbftClient(cluster.config)
        try:
            reqs = [client.request(f"mixed-secure-{i}") for i in range(3)]
            for r in reqs:
                assert client.wait_result(r.timestamp, timeout=25) == "awesome!"
        finally:
            client.close()
