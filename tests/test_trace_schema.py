"""Tier-1 wiring for the schema lint (scripts/check_trace_schema.py): the
Python and C++ runtimes cannot drift from the event/metric manifest
(pbft_tpu/utils/trace_schema.py) without failing here — the mixed-runtime
schema-parity contract."""

import importlib.util
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "check_trace_schema", REPO / "scripts" / "check_trace_schema.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_emitters_match_manifest():
    errors = _load_lint().check()
    assert errors == [], "\n".join(errors)


def test_native_runtime_names_match_manifest():
    """Runtime half of the parity contract: the names the NATIVE runtime
    compiled in (core/metrics.cc tables via capi.cc) must equal the
    manifest's net.cc sets. Skipped where the native core isn't built —
    the static lint above still covers the sources."""
    from pbft_tpu import native

    if not native.available():
        pytest.skip("native core not built")
    import ctypes

    from pbft_tpu.utils import trace_schema

    lib = native.lib()
    for fn in ("pbft_metric_names", "pbft_trace_event_names"):
        if not hasattr(lib, fn):
            pytest.fail(f"stale libpbftcore.so: missing {fn}; rebuild")

    def names(fn):
        func = getattr(lib, fn)
        func.restype = ctypes.c_size_t
        buf = ctypes.create_string_buffer(8192)
        n = func(buf, len(buf))
        assert 0 < n < len(buf)
        return set(buf.value.decode().split("\n"))

    want_metrics = {
        name
        for name, (_, emitters) in trace_schema.METRIC_SCHEMAS.items()
        if "net.cc" in emitters
    }
    assert names("pbft_metric_names") == want_metrics
    want_events = {
        name
        for name, schema in trace_schema.EVENT_SCHEMAS.items()
        if "net.cc" in schema["emitters"]
    }
    assert names("pbft_trace_event_names") == want_events
