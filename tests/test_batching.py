"""Batched agreement semantics (ISSUE 4): one three-phase instance per
request batch.

Covers the batch state machine end to end in the deterministic core:
duplicate suppression against the OPEN (unsealed) batch, in-batch
execution order with per-client exactly-once + cached replies, the
runtime flush path, empty-batch digests, and a batched pre-prepare
surviving a view change with prepared proofs.
"""

import dataclasses

from pbft_tpu.consensus.config import make_local_cluster
from pbft_tpu.consensus.messages import (
    ClientRequest,
    PrePrepare,
    batch_digest,
    blake2b_256,
)
from pbft_tpu.consensus.replica import Broadcast, Replica
from pbft_tpu.consensus.simulation import Cluster


def _batched_cluster(n=4, batch=4, flush_us=0):
    config, seeds = make_local_cluster(n)
    config = dataclasses.replace(
        config, batch_max_items=batch, batch_flush_us=flush_us
    )
    return Cluster(config=config, seeds=seeds)


# -- batch digest -------------------------------------------------------------


def test_batch_digest_definition():
    a = ClientRequest(operation="a", timestamp=1, client="c:1")
    b = ClientRequest(operation="b", timestamp=2, client="c:2")
    # Batch of one keeps the LEGACY definition (wire compat with 1.1.0).
    assert batch_digest((a,)) == a.digest()
    # Other sizes: Blake2b over the concatenated per-request digests.
    want = blake2b_256(
        bytes.fromhex(a.digest()) + bytes.fromhex(b.digest())
    ).hex()
    assert batch_digest((a, b)) == want
    assert batch_digest(()) == blake2b_256(b"").hex()
    # Order-sensitive: agreement is on an ORDERED batch.
    assert batch_digest((a, b)) != batch_digest((b, a))


def test_batch_of_one_wire_identical_to_legacy():
    """A sealed batch of one must produce the exact legacy pre-prepare
    encoding — singular `request` member, legacy digest — so a
    batch_max_items=1 cluster interoperates with pre-batching peers."""
    config, seeds = make_local_cluster(4)
    r = Replica(config, 0, seeds[0])
    req = ClientRequest(operation="solo", timestamp=1, client="c:1")
    [bcast] = [a for a in r.on_client_request(req) if isinstance(a, Broadcast)]
    pp = bcast.msg
    assert isinstance(pp, PrePrepare)
    assert pp.digest == req.digest()
    d = pp.to_dict()
    assert "request" in d and "requests" not in d


# -- open-batch duplicate suppression ----------------------------------------


def test_duplicate_in_open_batch_suppressed():
    """A retransmission arriving while its first copy sits in the open
    (unsealed) batch must not claim a second batch slot."""
    c = _batched_cluster(batch=4)
    r0 = c.replicas[0]
    c.submit("pay", client="c:9", timestamp=5)
    c.run()
    assert r0.open_batch_size() == 1
    c.submit("pay", client="c:9", timestamp=5)  # exact retransmission
    c.run()
    assert r0.open_batch_size() == 1  # no second slot
    assert r0.counters["duplicate_requests"] >= 1
    # A NEWER request from the same client does take a slot.
    c.submit("pay-again", client="c:9", timestamp=6)
    c.run()
    assert r0.open_batch_size() == 2


def test_flush_open_batch_seals_partial():
    """The runtime's batch_flush_us timer path: a partial batch seals on
    flush_open_batch and the requests commit as one instance."""
    c = _batched_cluster(batch=64)
    reqs = [c.submit(f"op-{i}", client=f"c:{i}") for i in range(3)]
    c.run()
    r0 = c.replicas[0]
    assert r0.open_batch_size() == 3  # far below batch_max_items
    assert all(r.executed_upto == 0 for r in c.replicas)
    c._emit(0, r0.flush_open_batch())
    c.run()
    assert r0.open_batch_size() == 0
    for req in reqs:
        assert c.committed_result(req.timestamp) == "awesome!"
    for r in c.replicas:
        assert r.executed_upto == 1  # ONE sequence number for the batch
        assert r.counters["rounds_executed"] == 1
        assert r.counters["executed"] == 3
    assert len({r.state_digest for r in c.replicas}) == 1


# -- in-batch execution semantics --------------------------------------------


def test_batch_executes_in_order_one_reply_per_request():
    c = _batched_cluster(batch=4)
    reqs = [c.submit(f"op-{i}", client=f"c:{i}") for i in range(4)]
    c.run()  # 4th request seals the batch; one instance commits all four
    for req in reqs:
        assert c.committed_result(req.timestamp) == "awesome!"
    for r in c.replicas:
        assert r.executed_upto == 1
        assert r.counters["rounds_executed"] == 1
        assert r.counters["executed"] == 4
    assert len({r.state_digest for r in c.replicas}) == 1
    # Replies preserve batch order per replica (primary replies first in
    # the simulation's emit order; each replica replied once per request).
    assert len(c.client_replies) == 4 * 4


def test_same_client_twice_in_one_batch_exactly_once():
    """Two requests from ONE client (increasing timestamps) may share a
    batch: both execute, in order, and the reply cache ends at the later
    timestamp."""
    c = _batched_cluster(batch=3)
    c.submit("first", client="c:x", timestamp=1)
    c.submit("second", client="c:x", timestamp=2)
    c.submit("other", client="c:y", timestamp=1)  # seals at 3
    c.run()
    for r in c.replicas:
        assert r.counters["executed"] == 3
        assert r.last_timestamp["c:x"] == 2
        assert r.last_reply["c:x"].timestamp == 2
    # Retransmit the EARLIER one: duplicate — it takes NO batch slot, so
    # the next batch seals on three genuinely new requests.
    c.submit("first", client="c:x", timestamp=1)
    c.submit("n1", client="c:a", timestamp=1)
    c.submit("n2", client="c:b", timestamp=1)
    c.run()
    assert c.replicas[0].open_batch_size() == 2  # duplicate claimed no slot
    c.submit("n3", client="c:c", timestamp=1)  # seals at 3
    c.run()
    for r in c.replicas:
        assert r.counters["executed"] == 6  # only the three new ones


def test_cached_reply_resent_for_executed_batch_member():
    c = _batched_cluster(batch=2)
    c.submit("pay", client="c:m", timestamp=3)
    c.submit("other", client="c:n", timestamp=1)  # seals
    c.run()
    before = len(c.replies_for(3))
    assert before >= 1
    c.submit("pay", client="c:m", timestamp=3)  # retransmission post-exec
    c.run()
    assert len(c.replies_for(3)) == before + 1  # cached reply, no re-exec
    assert all(r.counters["executed"] == 2 for r in c.replicas)


# -- view change with batches -------------------------------------------------


def test_batched_pre_prepare_survives_view_change():
    """A PREPARED (uncommitted) batch must be re-issued whole in the new
    view via the prepared proofs and execute exactly once per request
    (PBFT §4.4 safety, at batch granularity)."""
    c = _batched_cluster(batch=3)
    c.outbound_mutator = lambda src, msg: (
        None if type(msg).__name__ == "Commit" else msg
    )
    reqs = [c.submit(f"op-{i}", client=f"c:{i}") for i in range(3)]
    c.run(max_steps=500)
    assert all(r.executed_upto == 0 for r in c.replicas)
    prepared_somewhere = [r.id for r in c.replicas if r._prepared((0, 1))]
    assert prepared_somewhere, "the batch must have prepared somewhere"
    c.outbound_mutator = None
    c.crash(0)
    c.trigger_view_change([1, 2, 3])
    c.run(max_steps=500)
    live = [c.replicas[i] for i in (1, 2, 3)]
    assert all(r.view == 1 for r in live)
    for req in reqs:
        assert c.committed_result(req.timestamp) == "awesome!"
    for r in live:
        assert r.counters["executed"] == 3  # whole batch, exactly once
        assert r.counters["rounds_executed"] == 1
    assert len({r.state_digest for r in live}) == 1


def test_new_view_gap_filler_is_empty_batch():
    """Sequence gaps in a new view are filled with EMPTY batches whose
    execution is a no-op but still advances the chain — and the chain
    fold matches the legacy null request's, so the encodings agree."""
    config, seeds = make_local_cluster(4)
    config = dataclasses.replace(config, batch_max_items=1)
    replicas = [Replica(config, i, seeds[i]) for i in range(4)]
    # Replica 2 prepares seq 2 in view 0 but seq 1 never prepares
    # anywhere: the new primary must null-fill seq 1.
    primary = replicas[0]
    primary.on_client_request(
        ClientRequest(operation="gap", timestamp=1, client="c:1")
    )
    [pp2_b] = [
        a
        for a in primary.on_client_request(
            ClientRequest(operation="kept", timestamp=1, client="c:2")
        )
        if isinstance(a, Broadcast)
    ]
    pp2 = pp2_b.msg
    from pbft_tpu.consensus.messages import Prepare

    backup = replicas[2]
    backup._dispatch(pp2)
    other = replicas[3]
    backup._dispatch(
        other._sign(Prepare(view=0, seq=2, digest=pp2.digest, replica=3))
    )
    assert backup._prepared((0, 2))
    # View change to view 1 (primary 1) with 2f+1 = 3 participants.
    acts = []
    for rid in (1, 2, 3):
        acts.append((rid, replicas[rid].start_view_change()))
    # Deliver all view-changes to the new primary.
    for rid, alist in acts:
        for a in alist:
            if isinstance(a, Broadcast):
                for dst in (1, 2, 3):
                    if dst != rid:
                        replicas[dst]._dispatch(a.msg)
    nv_pps = [
        pp
        for (v, s), pp in replicas[1].pre_prepares.items()
        if v == 1
    ]
    by_seq = {pp.seq: pp for pp in nv_pps}
    assert by_seq[1].requests == ()  # the gap: an EMPTY batch
    assert by_seq[1].digest == batch_digest(())
    assert [r.operation for r in by_seq[2].requests] == ["kept"]
