"""The consensus bench harness commits what it claims to measure."""

from pbft_tpu.bench import run_config


def test_readme_demo_config():
    res = run_config(0, arm="cpu")
    assert res.replicas == 4 and res.f == 1
    assert res.requests == 1
    assert res.sig_verifications > 0
    assert res.rounds_per_sec > 0


def test_byzantine_config_still_commits():
    res = run_config(4, arm="cpu", requests=2)
    assert res.byzantine
    assert res.replicas == 31 and res.f == 10
    assert res.requests == 2
